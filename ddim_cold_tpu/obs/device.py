"""On-device step telemetry — decode the sampler scans' aux output.

Telemetry-enabled cached samplers (``SamplerConfig(telemetry=True)`` /
``ddim_sample(..., telemetry=True)``) emit a static-shaped aux alongside
the images: per scan step, the cache branch **actually taken** (after the
adaptive drift gate's data-dependent promotion, when the mode is adaptive)
and the gate's drift value. The aux rides the same compiled ``lax.scan``
as the images — same program, same zero-compiles-after-warmup contract —
so cache efficacy is observable per request with no extra dispatches.

This module is the host side: shapes/meaning of the aux and the summary
dict the engine attaches to tickets. It is deliberately numpy-only at
import time (the jax side lives in ``ops/sampling.py`` /
``ops/step_cache.py``); the schedule constants are imported lazily so
``obs`` stays importable without a jax backend.

Aux layout (``StepTelemetry``): ``branch`` — int32 ``(n_steps,)`` branch
index per step (0 = refresh, see ``ops/schedule.py:139``); ``drift`` —
float32 ``(n_steps,)`` batch-max relative drift the adaptive gate computed
(0 for non-adaptive modes, which never compute a drift).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class StepTelemetry(NamedTuple):
    """The sampler scan's stacked per-step aux (device or host arrays)."""

    branch: "np.ndarray"  # (n_steps,) int32 — branch taken, post-gate
    drift: "np.ndarray"   # (n_steps,) float32 — adaptive drift (0 otherwise)


def static_schedule(n_steps: int, cache_interval: int,
                    cache_mode: str = "delta") -> np.ndarray:
    """The branch sequence the STATIC schedule alone would take — what the
    gate's output collapses to at τ=∞ (never promote) and the baseline the
    refresh-promotion count is measured against."""
    from ddim_cold_tpu.ops import schedule

    return np.asarray(
        schedule.cache_branch_sequence(n_steps, cache_interval, cache_mode),
        dtype=np.int32)


def summarize(tel: "StepTelemetry", *, cache_interval: int,
              cache_mode: str, cache_threshold: float = 0.0,
              cache_tokens: int = 0) -> dict:
    """Render a telemetry aux into the per-ticket summary dict.

    ``promoted_refreshes`` counts reuse steps the adaptive gate promoted to
    refresh beyond the static schedule — 0 for non-adaptive modes by
    construction, and exactly the quantity the drift threshold τ trades
    against speed.
    """
    from ddim_cold_tpu.ops import schedule

    branch = np.asarray(tel.branch)
    drift = np.asarray(tel.drift, dtype=np.float64)
    n_steps = int(branch.size)
    refreshes = int(np.sum(branch == schedule.CACHE_REFRESH))
    planned = static_schedule(n_steps, cache_interval, cache_mode)
    planned_refreshes = int(np.sum(planned == schedule.CACHE_REFRESH))
    return {
        "steps": n_steps,
        "cache_mode": cache_mode,
        "cache_interval": cache_interval,
        "cache_threshold": cache_threshold,
        "cache_tokens": cache_tokens,
        "refreshes": refreshes,
        "reuses": n_steps - refreshes,
        "planned_refreshes": planned_refreshes,
        "promoted_refreshes": refreshes - planned_refreshes,
        "refresh_ratio": round(refreshes / n_steps, 4) if n_steps else 0.0,
        "drift_max": float(drift.max()) if n_steps else 0.0,
        "drift_mean": float(drift.mean()) if n_steps else 0.0,
        "branch": branch.tolist(),
        "drift": [round(float(d), 6) for d in drift],
    }
