"""Observability: per-request trace spans, the process metrics registry,
and on-device step telemetry decoding.

* :mod:`ddim_cold_tpu.obs.spans` — trace contexts created at
  ``Router.submit`` / ``Engine.submit``, propagated plan → assemble →
  dispatch → fetch → preview → finish and across hedges/failovers;
  exported as Chrome trace-event JSON (``scripts/obs_report.py``).
* :mod:`ddim_cold_tpu.obs.metrics` — named counters/gauges/histograms the
  serving layers emit into; ``Engine.health()`` / ``Router.health()`` are
  rendered from it.
* :mod:`ddim_cold_tpu.obs.device` — static-shaped sampler-scan aux
  (adaptive-gate decisions, drift) decoded into per-ticket summaries.

``spans`` and ``metrics`` are host-only (jax-free, graftcheck A004);
``device`` imports jax lazily, so ``import ddim_cold_tpu.obs`` is cheap
anywhere the router/fleet layer runs.
"""

from ddim_cold_tpu.obs import device, metrics, spans

__all__ = ["device", "metrics", "spans"]
