"""Observability: per-request trace spans, the process metrics registry,
and on-device step telemetry decoding.

* :mod:`ddim_cold_tpu.obs.spans` — trace contexts created at
  ``Router.submit`` / ``Engine.submit``, propagated plan → assemble →
  dispatch → fetch → preview → finish and across hedges/failovers;
  exported as Chrome trace-event JSON (``scripts/obs_report.py``).
* :mod:`ddim_cold_tpu.obs.metrics` — named counters/gauges/histograms the
  serving layers emit into; ``Engine.health()`` / ``Router.health()`` are
  rendered from it.
* :mod:`ddim_cold_tpu.obs.device` — static-shaped sampler-scan aux
  (adaptive-gate decisions, drift) decoded into per-ticket summaries.
* :mod:`ddim_cold_tpu.obs.attrib` — profiler-trace attribution: device
  time per named scope, flop/byte joins → achieved TFLOP/s, MFU, roofline
  class, fusion candidates (``bench --attrib``, scripts/attrib_report.py).
* :mod:`ddim_cold_tpu.obs.trend` — the BENCH_r*/MULTICHIP_r* trajectory
  loader + noise-banded regression gate (``python -m
  ddim_cold_tpu.obs.trend``).

``spans``, ``metrics``, ``attrib`` and ``trend`` are host-only (jax-free,
graftcheck A004); ``device`` imports jax lazily, so ``import
ddim_cold_tpu.obs`` is cheap anywhere the router/fleet layer runs.
"""

from ddim_cold_tpu.obs import attrib, device, metrics, spans, trend

__all__ = ["attrib", "device", "metrics", "spans", "trend"]
