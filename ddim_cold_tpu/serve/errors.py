"""Typed failure surface of the serving engine.

Every way a request can fail is a distinct exception, and every one reaches
the caller through exactly one of two doors: :meth:`Ticket.result` /
:meth:`Ticket.exception` (the request was admitted, then failed — the
engine-stage exception rides as ``__cause__``), or a raise straight out of
``Engine.submit`` (the request was never admitted: overload, closed
engine). No failure mode leaves a ticket blocking forever — that is the
liveness contract the chaos tests pin.
"""

from __future__ import annotations

from ddim_cold_tpu.utils.faults import TRANSIENT_EXCEPTIONS


class ServeError(Exception):
    """Base class for serving-engine failures."""


class QueueFullError(ServeError):
    """Raised by ``submit`` when the bounded queue is at ``max_queue``
    (admission control: reject-on-overload beats unbounded latency)."""


class DeadlineExceeded(ServeError):
    """The request's deadline elapsed while it was queued or waiting to
    dispatch — it fails fast instead of occupying a bucket."""


class RequestFailedError(ServeError):
    """A pipeline stage (assembly / dispatch / fetch) failed this request's
    batch; the stage exception is attached as ``__cause__``."""


class RequestQuarantinedError(RequestFailedError):
    """Bisection isolated this request as the one that deterministically
    poisons any batch containing it; its batchmates completed."""


class EngineClosedError(ServeError):
    """The engine is draining / drained: queued tickets fail with this and
    new submissions are rejected."""


class EngineStalledError(ServeError):
    """The engine's stall watchdog fired: a device interaction went silent
    past the stall budget (wedged backend). In-flight and queued tickets
    fail with this; batches fetched before the stall keep their results."""


#: Exception classes the dispatch path (and the fleet router's hedging)
#: treats as retryable (capped exponential backoff / one hedged
#: re-placement) rather than deterministic. Built from the fault
#: registry's own transient table plus the real transfer/RPC class, so a
#: new transient fault kind is retryable by construction; anything else
#: goes straight to bisection.
RETRYABLE_EXCEPTIONS: tuple = TRANSIENT_EXCEPTIONS + (ConnectionError,)
