"""Typed failure surface of the serving engine.

Every way a request can fail is a distinct exception, and every one reaches
the caller through exactly one of two doors: :meth:`Ticket.result` /
:meth:`Ticket.exception` (the request was admitted, then failed — the
engine-stage exception rides as ``__cause__``), or a raise straight out of
``Engine.submit`` (the request was never admitted: overload, closed
engine). No failure mode leaves a ticket blocking forever — that is the
liveness contract the chaos tests pin.
"""

from __future__ import annotations

from ddim_cold_tpu.utils.faults import TRANSIENT_EXCEPTIONS


class ServeError(Exception):
    """Base class for serving-engine failures."""


class QueueFullError(ServeError):
    """Raised by ``submit`` when the bounded queue is at ``max_queue``
    (admission control: reject-on-overload beats unbounded latency)."""


class DeadlineExceeded(ServeError):
    """The request's deadline elapsed while it was queued or waiting to
    dispatch — it fails fast instead of occupying a bucket."""


class RequestFailedError(ServeError):
    """A pipeline stage (assembly / dispatch / fetch) failed this request's
    batch; the stage exception is attached as ``__cause__``."""


class RequestQuarantinedError(RequestFailedError):
    """Bisection isolated this request as the one that deterministically
    poisons any batch containing it; its batchmates completed."""


class EngineClosedError(ServeError):
    """The engine is draining / drained: queued tickets fail with this and
    new submissions are rejected."""


class EngineStalledError(ServeError):
    """The engine's stall watchdog fired: a device interaction went silent
    past the stall budget (wedged backend). In-flight and queued tickets
    fail with this; batches fetched before the stall keep their results."""


class RemoteRPCError(ServeError):
    """The replica RPC protocol itself broke (malformed frame, unknown
    method, version skew) — a bug surface, not a load surface; never
    retried blindly."""


class ReplicaUnreachableError(ServeError, ConnectionError):
    """An RPC to an out-of-process replica could not complete (socket
    down, dropped frame, per-call deadline). Subclasses ConnectionError so
    ``RETRYABLE_EXCEPTIONS`` covers it BY CONSTRUCTION: the router treats
    it as "try another replica", never as a request failure."""


class ReplicaCrashedError(EngineClosedError):
    """The replica PROCESS died under this request (exit, SIGKILL, or
    heartbeat loss past the miss budget). Subclasses
    :class:`EngineClosedError` so the router's failover path — not the
    hedge path — re-places the dead replica's tickets onto survivors; the
    message names the replica and the detection cause."""


#: Exception classes the dispatch path (and the fleet router's hedging)
#: treats as retryable (capped exponential backoff / one hedged
#: re-placement) rather than deterministic. Built from the fault
#: registry's own transient table plus the real transfer/RPC class, so a
#: new transient fault kind is retryable by construction; anything else
#: goes straight to bisection.
RETRYABLE_EXCEPTIONS: tuple = TRANSIENT_EXCEPTIONS + (ConnectionError,)


# ---------------------------------------------------------------------------
# wire serialization (serve/remote.py RPC)
# ---------------------------------------------------------------------------

def _wire_types() -> dict:
    """Exception classes a replica server may legally put on the wire,
    by name. Covers this module's whole surface, the fault-injection
    classes (an injected fault crossing the RPC boundary must stay its
    typed self — the chaos tests assert the type, not a string), and the
    builtin failure classes the engine can surface."""
    from ddim_cold_tpu.utils import faults

    classes = [ServeError, QueueFullError, DeadlineExceeded,
               RequestFailedError, RequestQuarantinedError,
               EngineClosedError, EngineStalledError, RemoteRPCError,
               ReplicaUnreachableError, ReplicaCrashedError,
               faults.FaultError, faults.TransientFault,
               faults.PermanentFault,
               TimeoutError, ConnectionError, ValueError, RuntimeError,
               KeyError, TypeError, OSError, AssertionError]
    return {c.__name__: c for c in classes}


def encode_exception(exc: BaseException) -> dict:
    """JSON-able wire form of an exception: type name, message, and the
    ``__cause__`` chain (depth-limited — a cycle-proof flattening)."""
    out: dict = {"type": type(exc).__name__, "message": str(exc)}
    cause = exc.__cause__
    chain = []
    for _ in range(4):
        if cause is None:
            break
        chain.append({"type": type(cause).__name__, "message": str(cause)})
        cause = cause.__cause__
    if chain:
        out["causes"] = chain
    return out


def decode_exception(data: dict) -> BaseException:
    """Rebuild a typed exception from :func:`encode_exception` output.
    Unknown types decode as :class:`RequestFailedError` with the original
    type name embedded — the failure stays typed and debuggable even
    across version skew. The cause chain is re-linked via ``__cause__``."""
    types = _wire_types()

    def build(d: dict) -> BaseException:
        cls = types.get(d.get("type", ""))
        msg = d.get("message", "")
        if cls is None:
            return RequestFailedError(f"[{d.get('type')}] {msg}")
        try:
            return cls(msg)
        except Exception:  # noqa: BLE001 — an exception class with a
            # picky __init__ must not break decoding; wrap it instead
            return RequestFailedError(f"[{d.get('type')}] {msg}")

    exc = build(data)
    node = exc
    for c in data.get("causes", ()):
        cause = build(c)
        node.__cause__ = cause
        node = cause
    return exc
