"""Replica server process — ``python -m ddim_cold_tpu.serve.replica_main``.

The child half of serve/remote.py: connects BACK to the parent's ephemeral
listener (``--connect 127.0.0.1:<port>``; child-connects-to-parent means no
listening socket outlives the fleet), sends a ``hello``, then serves the
RPC methods over one wrapped :class:`~ddim_cold_tpu.serve.fleet.LocalReplica`
— the whole in-process serving stack (engine worker thread, drain
semantics, zero-compile accounting) reused verbatim one process down.

The engine spec arrives via the ``DDIM_COLD_REPLICA_SPEC`` env var (JSON —
see :func:`~ddim_cold_tpu.serve.remote.remote_factory`). Two backends:

* ``"engine"`` — a real jitted Engine, built by serve/backend.py (the one
  jax-touching import, deferred so THIS file stays statically host-only
  for graftcheck A004);
* ``"stub"``  — :class:`StubEngine`, a pure-numpy Engine lookalike whose
  results are a deterministic function of ``(seed, n)`` alone. The RPC
  protocol tests run against it: every wire behavior (framing, typed
  errors, deadlines, crash detection) is exercised without compiling a
  single XLA program.

Threading: the reader thread answers ``ping``/``health``/``submit``/
``start`` inline (all non-blocking), and hands ``warm``/``drain``/``close``
to worker threads — a replica mid-warmup or mid-drain KEEPS answering
heartbeats, so slow is distinguishable from dead. Ticket results push back
as server-initiated ``ticket``/``preview`` events from the engine's
resolver threads, serialized by one send lock.

Chaos: the child arms ``DDIM_COLD_FAULTS`` from ITS OWN environment (the
factory's ``env`` overlay), and fires ``replica.kill`` / ``replica.hang``
on the reader thread before dispatching each WORK request (submit/drain)
— a ``kill`` is a SIGKILL mid-protocol with no goodbye, exactly the crash
the parent's detection must catch; a ``hang`` wedges the reader so pings
go unanswered and the heartbeat miss budget fires.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import threading
import time
from typing import Optional

import numpy as np

from ddim_cold_tpu.serve import fleet
from ddim_cold_tpu.serve import remote
from ddim_cold_tpu.serve.batching import SamplerConfig, Ticket
from ddim_cold_tpu.serve.errors import (DeadlineExceeded, EngineClosedError,
                                        QueueFullError, RemoteRPCError,
                                        encode_exception)
from ddim_cold_tpu.utils import faults

#: RPC methods this server answers — one entry per ``handle`` dispatch arm.
#: graftcheck R001 proves the table matches the arms AND stays set-equal to
#: the client's ``remote.CLIENT_METHODS``.
SERVER_METHODS = ("ping", "health", "start", "submit", "warm", "drain",
                  "close")

#: server-initiated event kinds this process may push — one entry per
#: ``send({"event": ...})`` literal. R001 proves every one has a client
#: dispatch arm (``remote.CLIENT_EVENT_ARMS``).
SERVER_EVENTS = ("hello", "ticket", "preview", "protocol_error")


def stub_rows(seed, n: int, shape: tuple) -> np.ndarray:
    """The stub's entire 'sampler': rows are a pure function of (seed, n)
    — two stub replicas given the same request produce bitwise-identical
    buffers, which is all the failover-equivalence tests need."""
    rng = np.random.RandomState(0 if seed is None else int(seed) % (2**31))
    return rng.standard_normal((int(n),) + tuple(shape)).astype(np.float32)


class StubEngine:
    """Pure-numpy stand-in for serve.engine.Engine behind a LocalReplica:
    the queue/drain/ticket surface is real, the device work is
    :func:`stub_rows` plus an optional ``delay_s`` sleep (how the deadline
    and mid-batch-kill tests make requests take time). Warmup 'compiles'
    are dict inserts, so the zero-compile accounting paths run unchanged.
    """

    def __init__(self, replica_id: str = "stub", *, delay_s: float = 0.0,
                 shape=(8, 8, 3), max_queue: int = 256, buckets=(4, 8)):
        self.replica_id = replica_id
        self.delay_s = float(delay_s)
        self.shape = tuple(shape)
        self.max_queue = int(max_queue)
        self.buckets = tuple(buckets)
        self.stats = {"compiles": 0}
        self._programs: dict = {}
        self.metrics = None  # warmup's getattr(engine, "metrics") contract
        self._lock = threading.Lock()
        self._queue: list = []                          # guarded-by: _lock
        self._closed = False                            # guarded-by: _lock

    # ---- warmup surface --------------------------------------------------
    def ensure_program(self, config, bucket) -> None:
        key = (config, bucket)
        if key not in self._programs:
            self._programs[key] = ("stub", key)
            self.stats["compiles"] += 1

    def prewarm_cache(self, config, bucket) -> None:
        pass

    # ---- serving surface -------------------------------------------------
    def submit(self, seed=None, n=1, *, rng=None, x_init=None, mask=None,
               config=None, deadline_s=None, trace=None, **kwargs) -> Ticket:
        ticket = Ticket(int(n))
        deadline = None if deadline_s is None \
            else time.perf_counter() + float(deadline_s)
        with self._lock:
            if self._closed:
                raise EngineClosedError(
                    f"stub engine {self.replica_id} is closed")
            if len(self._queue) >= self.max_queue:
                raise QueueFullError(
                    f"stub engine {self.replica_id} queue at {self.max_queue}")
            self._queue.append((ticket, seed, int(n), deadline))
        return ticket

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def run(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    return
                ticket, seed, n, deadline = self._queue.pop(0)
            if self.delay_s:
                time.sleep(self.delay_s)
            if deadline is not None and time.perf_counter() > deadline:
                ticket._fail(DeadlineExceeded(
                    f"stub request ({n} rows, seed={seed}) expired "
                    "before dispatch"))
                continue
            ticket._deliver(0, n, stub_rows(seed, n, self.shape))

    def drain(self, timeout: Optional[float] = None) -> dict:
        deadline = None if timeout is None \
            else time.perf_counter() + float(timeout)
        while self.queue_depth():  # flush what we can inside the budget
            if deadline is not None and time.perf_counter() > deadline:
                break
            self.run()
        with self._lock:
            self._closed = True
            leftovers, self._queue = self._queue, []
        for ticket, seed, n, _ in leftovers:
            ticket._fail(EngineClosedError(
                f"stub engine {self.replica_id} drained with a "
                f"{n}-row request still queued"))
        report = self.health()
        report["idle"] = True
        return report

    def health(self) -> dict:
        # field parity with Engine.health() for every key the router and
        # autoscaler read (graftcheck R001): the stub resolves work
        # synchronously in run(), so the live-load fields are honestly zero
        # — but they must EXIST, or the RPC protocol tests would silently
        # exercise a health contract the real engine doesn't have
        with self._lock:
            depth = len(self._queue)
            closed = self._closed
        return {"replica": self.replica_id, "queue_depth": depth,
                "open_tickets": 0,
                "latency_p50_s": 0.0, "latency_p95_s": 0.0,
                "latency_p99_s": 0.0,
                "last_progress_s": 0.0, "quarantined": 0,
                "closed": closed, "stalled": False, "running": not closed,
                "compiles": self.stats["compiles"],
                "max_queue": self.max_queue}


def _jsonable(obj):
    """Clamp a report dict to wire-safe values: numpy arrays pass through
    (the framing layer carries them), tuples become lists, non-string dict
    keys and unserializable leaves (warmup's per-key exception table)
    become their ``str()``."""
    if isinstance(obj, dict):
        return {k if isinstance(k, str) else str(k): _jsonable(v)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.ndarray, str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


class ReplicaServer:
    """One connection, one replica: decode frames, dispatch, push results."""

    #: methods that may carry injected process faults (work, not liveness —
    #: the per-site call counter then indexes submits, so a schedule's
    #: ``at=N`` pins "this replica's N-th work request" exactly)
    WORK_METHODS = ("submit", "drain")

    def __init__(self, conn: socket.socket, replica, replica_id: str):
        self._conn = conn
        self._replica = replica
        self._replica_id = replica_id
        self._send_lock = threading.Lock()

    def send(self, msg: dict) -> None:
        payload = remote.encode_payload(msg)
        if len(payload) > remote.MAX_FRAME_BYTES:
            # raise locally and typed — shipping the frame anyway would be
            # answered by the parent's recv_frame killing the connection
            raise RemoteRPCError(
                f"outbound frame of {len(payload)} bytes exceeds "
                f"MAX_FRAME_BYTES={remote.MAX_FRAME_BYTES}")
        try:
            with self._send_lock:
                self._conn.sendall(struct.pack(">I", len(payload)) + payload)
        except OSError:
            pass  # parent gone; the reader loop will see EOF and exit

    def _recv_request(self) -> dict:
        """recv_frame, except a protocol violation is NOT treated as
        parent-gone: an over-limit frame is drained (its length prefix says
        exactly how many bytes to discard, so the stream stays in sync) and
        answered with a typed error event — one bad request must not kill
        the replica, or a failover would replay it onto every survivor."""
        while True:
            (length,) = struct.unpack(
                ">I", remote._recv_exact(self._conn, 4))
            if length <= remote.MAX_FRAME_BYTES:
                return remote.decode_payload(
                    remote._recv_exact(self._conn, length))
            remaining = length
            while remaining:
                chunk = self._conn.recv(min(remaining, 1 << 20))
                if not chunk:
                    raise ConnectionError("connection closed mid-frame")
                remaining -= len(chunk)
            self.send({"event": "protocol_error",
                       "error": encode_exception(RemoteRPCError(
                           f"inbound frame of {length} bytes exceeds "
                           f"MAX_FRAME_BYTES={remote.MAX_FRAME_BYTES}"))})

    def serve(self) -> None:
        while True:
            try:
                msg = self._recv_request()
            except (RemoteRPCError, ValueError, KeyError, TypeError) as exc:
                # garbage INSIDE a fully consumed frame (bad JSON, bogus
                # dtype, truncated buffers): the stream is still framed —
                # answer typed and keep serving
                try:
                    self.send({"event": "protocol_error",
                               "error": encode_exception(exc)})
                except RemoteRPCError:
                    pass
                continue
            except Exception:  # noqa: BLE001 — EOF/reset: parent is gone,
                break          # so is our reason to exist
            try:
                self.handle(msg)
            except Exception:  # noqa: BLE001 — per-request errors were
                pass           # already answered; never kill the reader
        try:
            self._replica.close()
        finally:
            os._exit(0)

    def handle(self, msg: dict) -> None:
        method = msg.get("method")
        call_id = msg.get("id")
        params = msg.get("params") or {}
        if method in self.WORK_METHODS:
            tag = f"replica:{self._replica_id}|method:{method}|"
            faults.fire("replica.kill", tag=tag)  # SIGKILL: no line after
            faults.fire("replica.hang", tag=tag)  # wedge the reader thread
        try:
            if method == "ping":
                result = {"pid": os.getpid()}
            elif method == "health":
                result = _jsonable(self._replica.health())
            elif method == "start":
                self._replica.start()
                result = {}
            elif method == "submit":
                result = self._submit(params)
            elif method in ("warm", "drain", "close"):
                worker = threading.Thread(
                    target=self._slow, args=(call_id, method, params),
                    name=f"replica-{method}", daemon=True)
                worker.start()
                return
            else:
                raise RemoteRPCError(f"unknown RPC method {method!r}")
        except Exception as exc:  # noqa: BLE001 — every failure crosses
            # back TYPED; the client-side decoder restores the class
            self.send({"id": call_id, "ok": False,
                       "error": encode_exception(exc)})
            return
        self._answer(call_id, result)

    def _answer(self, call_id, result) -> None:
        try:
            self.send({"id": call_id, "ok": True, "result": result})
        except RemoteRPCError as exc:  # response too big for one frame:
            # the caller still gets an answer, just a typed failure
            self.send({"id": call_id, "ok": False,
                       "error": encode_exception(exc)})

    def _submit(self, params: dict) -> dict:
        # the CLIENT owns rid allocation: it registered its ticket under
        # this rid before the submit frame left, so our ticket/preview
        # events can never race ahead of the registration (remote.py)
        rid = params.get("rid")
        if rid is None:
            raise RemoteRPCError("submit without a client-allocated rid")
        cfg = params.get("config")
        if isinstance(cfg, dict):
            cfg = SamplerConfig(**cfg)
        n = int(params.get("n", 1))
        kwargs = dict(params.get("kwargs") or {})
        ticket = self._replica.submit(
            seed=params.get("seed"), n=n, x_init=params.get("x_init"),
            mask=params.get("mask"), config=cfg,
            deadline_s=params.get("deadline_s"), **kwargs)
        ticket.add_preview_callback(
            lambda step, frames, _rid=rid: self.send(
                {"event": "preview", "rid": _rid, "step": int(step),
                 "rows": frames}))
        ticket.add_done_callback(
            lambda t, _rid=rid: self._push_result(_rid, t))
        return {"rid": rid, "n": n}

    def _push_result(self, rid: int, ticket) -> None:
        exc = ticket.exception(timeout=0)
        if exc is None:
            try:
                self.send({"event": "ticket", "rid": rid, "status": "done",
                           "result": ticket.result(timeout=0)})
                return
            except RemoteRPCError as send_exc:  # result too big for one
                exc = send_exc                  # frame: fail the ticket typed
        self.send({"event": "ticket", "rid": rid, "status": "error",
                   "error": encode_exception(exc)})

    def _slow(self, call_id, method: str, params: dict) -> None:
        """warm/drain/close run off the reader thread (they block for
        seconds to minutes; heartbeats must keep flowing meanwhile)."""
        try:
            if method == "warm":
                configs = [SamplerConfig(**c) if isinstance(c, dict) else c
                           for c in params.get("configs") or []]
                buckets = params.get("buckets")
                result = _jsonable(self._replica.warm(
                    configs, tuple(buckets) if buckets else None,
                    **(params.get("kwargs") or {})))
            elif method == "drain":
                result = _jsonable(self._replica.drain(params.get("timeout")))
            else:  # close: ack, then leave — nothing to say after
                self.send({"id": call_id, "ok": True, "result": {}})
                try:
                    self._conn.close()
                finally:
                    os._exit(0)
        except Exception as exc:  # noqa: BLE001 — typed across the wire
            self.send({"id": call_id, "ok": False,
                       "error": encode_exception(exc)})
            return
        self._answer(call_id, result)


def build_replica(replica_id: str, spec: dict):
    """Spec → ReplicaHandle. The persistent compile-cache dir rides in as
    ``spec["cache_dir"]`` and lands in the environment BEFORE any engine
    exists, so a spawned replacement warms from disk — the pre-warmed-spawn
    half of the autoscaler contract."""
    cache_dir = spec.get("cache_dir")
    if cache_dir:
        os.environ.setdefault("DDIM_COLD_COMPILE_CACHE", str(cache_dir))
    if spec.get("backend", "stub") == "stub":
        return fleet.LocalReplica(
            StubEngine(replica_id=replica_id, **(spec.get("stub") or {})))
    from ddim_cold_tpu.serve import backend  # the jax-touching import,

    # deferred: this file must stay statically host-only (A004)
    return backend.build_local_replica(replica_id, spec)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="ddim_cold_tpu replica server (spawned by "
                    "serve.remote.remote_factory)")
    parser.add_argument("--connect", required=True,
                        help="host:port of the parent's listener")
    parser.add_argument("--replica-id", required=True)
    args = parser.parse_args(argv)
    host, _, port = args.connect.rpartition(":")
    spec = json.loads(os.environ.get("DDIM_COLD_REPLICA_SPEC") or "{}")
    faults.arm_from_env()  # the child's OWN chaos schedule (factory env=)
    replica = build_replica(args.replica_id, spec)
    conn = socket.create_connection((host or "127.0.0.1", int(port)),
                                    timeout=30.0)
    conn.settimeout(None)
    server = ReplicaServer(conn, replica, args.replica_id)
    server.send({"event": "hello", "replica_id": args.replica_id,
                 "pid": os.getpid(),
                 "backend": spec.get("backend", "stub")})
    server.serve()


if __name__ == "__main__":
    main()
