"""Request queue → static bucket plans (the batching half of the engine).

XLA compiles one program per input shape, so a naive server recompiles on
every new request count. Here requests are coalesced per sampler config and
packed row-by-row into a small static set of batch buckets (padding the last
batch with zero rows), so the engine only ever dispatches shapes it compiled
at warmup. Requests larger than the biggest bucket simply split across
batches — packing is by ROW RANGE, not whole requests, which is sound because
every sampler row is computed independently of its batchmates (the trunk is
per-row: attention mixes tokens within an image, never across the batch), so
a request's rows are bitwise identical no matter which batch they ride in.

``SamplerConfig`` deliberately has no ``eta``: stochastic DDIM draws
batch-SHAPED per-step noise (``jax.random.normal(key, x.shape)``), whose
per-row values depend on the batch size — coalescing would change every
row. Deterministic sampling (the reference's path) is what serving batches.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

_SAMPLERS = ("ddim", "cold")
_CACHE_MODES = ("delta", "full", "adaptive", "token")
_QUANT_MODES = (None, "xla", "pallas", "w8a8")  # ops/quant.py QUANT_MODES + off
#: workloads.TASKS, duplicated as literals (this module is host-only —
#: graftcheck A004 — and the workloads package imports jax); the two tuples
#: are pinned equal by tests/test_workloads.py
_TASKS = ("sample", "inpaint", "superres", "draft", "interp")
_SP_MODES = ("none", "ulysses", "ring")


@dataclass(frozen=True)
class SamplerConfig:
    """Everything that selects a compiled sampler program (all statics).

    Hashable on purpose: it is half of the engine's program-cache key
    ``(config, bucket)``. Two requests share a batch iff their configs are
    equal — mixed configs never coalesce (in particular quant and non-quant
    requests never share a batch: they run different programs over different
    param trees).
    """

    sampler: str = "ddim"          # "ddim" | "cold"
    k: int = 10                    # DDIM stride (ignored by cold)
    t_start: Optional[int] = None  # guided start level (ddim only)
    levels: int = 6                # cold-diffusion levels (cold only)
    cache_interval: int = 1        # 1 = exact sampler; >1 = step cache
    cache_mode: str = "delta"      # "delta" | "full" | "adaptive" | "token"
    cache_threshold: Optional[float] = None  # "adaptive" only: drift gate τ
    # (≥ 0; 0.0 = refresh every step = bitwise exact). Static — part of the
    # compiled-program key, mirrored by ops/step_cache.cache_spec validation.
    cache_tokens: int = 0          # "token" only: static top-k live tokens
    # per reuse step (≥ 1; = num_patches+1 is bitwise exact — the model-
    # dependent upper bound is enforced at program build, not here: this
    # module is host-only and never sees the model).
    quant: Optional[str] = None    # None = float params; "xla" | "pallas" =
    # the w8a16 trunk (ops/quant.py) over the engine's int8 param tree;
    # "w8a8" additionally feeds int8 activations (per-tensor dynamic scale)
    # — FID-guard gated (eval/fid.quantized_sampler_guard)
    fused: bool = False            # fused sampler-trunk megakernels
    # (models/vit.py fused=True): qkv-dequant → flash → proj as one Pallas
    # kernel plus the fused Mlp kernel. Same param tree as unfused — but a
    # DIFFERENT compiled program, so fused and unfused requests never
    # coalesce. Requires quant != "xla" (pure-XLA mode has no kernels to
    # fuse); f32 results are bitwise the unfused program's (tests pin it).
    task: str = "sample"           # "sample" = plain generation; an editing
    # task name (ddim_cold_tpu/workloads) selects that task's init builder
    # and — for "inpaint" — its per-step-constrained scan. Static: mixed
    # tasks never coalesce, and the inpaint program has a different input
    # signature (known + mask ride the batch).
    preview_every: int = 0         # 0 = final result only; m > 0 streams
    # every m-th intermediate x̂0 frame via Ticket.previews() — the engine
    # then dispatches the SEQUENCE scan variant (a distinct program, part of
    # the warmed set)
    sp_mode: str = "none"          # "none" | "ulysses" | "ring": sequence
    # parallelism for this config's programs. Off by default — the defaults
    # keep every pre-sp config hash-equal to its old self, so sp_degree=1
    # dispatches are bitwise the existing serve path by construction.
    sp_degree: int = 1             # seq-axis size of the (data, seq) mesh
    # the engine builds for this config (its local device count must divide
    # by it). Static: part of the program key — sp and non-sp requests never
    # coalesce, they run differently-sharded programs.
    telemetry: bool = False        # True: the cached DDIM scan also stacks
    # its per-step (branch, drift) aux (ops/step_cache.apply_step_tel) and
    # the engine decodes it into ``Ticket.telemetry`` (obs/device.py).
    # Static: selects a distinct compiled program (one extra warmup entry);
    # images stay bitwise identical with telemetry on or off.
    steps: int = 0                 # 0 = the k-STRIDED family above (the
    # pre-fewstep default — every existing config stays hash-equal to its
    # old self); >= 1 selects the few-step family
    # (ops/sampling.ddim_sample_fewstep): exactly ``steps`` model
    # evaluations along the proportional schedule, the distilled-student
    # serving path (k∈{1,2,4}). ``k`` is ignored when steps > 0; ``t_start``
    # still sets the schedule's start level. Static: part of the program
    # key — fewstep and stride requests never coalesce.
    student: bool = False          # route this config's dispatches through
    # the engine's distilled-student param tree (Engine(student_params=...))
    # instead of the teacher's. Purely a PARAM selection — the compiled
    # program is identical to the teacher's at the same steps (warmup dedup
    # exploits exactly that) — but student and teacher requests must never
    # share a batch, so it is part of the config (and the cache key).

    def __post_init__(self):
        if self.sampler not in _SAMPLERS:
            raise ValueError(f"sampler must be one of {_SAMPLERS}, "
                             f"got {self.sampler!r}")
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")
        if self.levels < 1:
            raise ValueError(f"levels must be >= 1, got {self.levels}")
        if self.cache_interval < 1:
            raise ValueError("cache_interval must be >= 1, "
                             f"got {self.cache_interval}")
        if self.cache_mode not in _CACHE_MODES:
            raise ValueError(f"cache_mode must be one of {_CACHE_MODES}, "
                             f"got {self.cache_mode!r}")
        if self.cache_mode == "adaptive":
            if self.cache_threshold is None:
                raise ValueError(
                    "cache_mode='adaptive' needs cache_threshold=<drift "
                    "gate, ≥ 0.0> (0.0 refreshes every step — bitwise the "
                    "exact sampler)")
            if not float(self.cache_threshold) >= 0.0:  # rejects NaN too
                raise ValueError("cache_threshold must be >= 0.0, "
                                 f"got {self.cache_threshold!r}")
        elif self.cache_threshold is not None:
            raise ValueError(
                "cache_threshold is the 'adaptive' drift gate — meaningless "
                f"under cache_mode={self.cache_mode!r}")
        if self.cache_mode == "token":
            if self.cache_tokens < 1:
                raise ValueError(
                    "cache_mode='token' needs cache_tokens=<static top-k "
                    f"live tokens, >= 1>, got {self.cache_tokens}")
        elif self.cache_tokens != 0:
            raise ValueError(
                "cache_tokens is the 'token' top-k — meaningless under "
                f"cache_mode={self.cache_mode!r}")
        if self.quant not in _QUANT_MODES:
            raise ValueError(f"quant must be one of {_QUANT_MODES}, "
                             f"got {self.quant!r}")
        if self.fused and self.quant == "xla":
            raise ValueError(
                "fused=True requests the Pallas fused trunk kernels but "
                "quant='xla' explicitly opts out of Pallas — use "
                "quant='pallas' or 'w8a8' (or quant=None for the float "
                "fused Mlp alone)")
        if self.task not in _TASKS:
            raise ValueError(f"task must be one of {_TASKS}, "
                             f"got {self.task!r}")
        if self.preview_every < 0:
            raise ValueError(f"preview_every must be >= 0, "
                             f"got {self.preview_every}")
        if self.task == "superres":
            if self.sampler != "cold":
                raise ValueError(
                    "task 'superres' is the cold path (nearest-downsampling "
                    "IS the cold degradation) — pass sampler='cold' with "
                    "levels=<the input's downsampling level>")
        elif self.task != "sample":
            if self.sampler != "ddim":
                raise ValueError(f"task {self.task!r} is a DDIM path, "
                                 f"got sampler={self.sampler!r}")
            if self.task in ("draft", "interp") and self.t_start is None:
                raise ValueError(
                    f"task {self.task!r} decodes from an intermediate noise "
                    "level — t_start= is required")
        # imported lazily: the sp error type lives with the sp kernels, and
        # this module must stay import-free of the (jax-importing) parallel
        # package; any caller constructing a config has serve loaded already
        from ddim_cold_tpu.parallel.ulysses import SeqParallelConfigError
        if self.sp_mode not in _SP_MODES:
            raise SeqParallelConfigError(
                f"sp_mode must be one of {_SP_MODES}, got {self.sp_mode!r}")
        if self.sp_degree < 1:
            raise SeqParallelConfigError(
                f"sp_degree must be >= 1, got {self.sp_degree}")
        if self.sp_mode == "none" and self.sp_degree != 1:
            raise SeqParallelConfigError(
                f"sp_degree={self.sp_degree} needs a strategy — pass "
                "sp_mode='ulysses' (head↔sequence all-to-all; local heads "
                "must divide by sp_degree) or sp_mode='ring' (no head "
                "constraint)")
        if self.sp_mode != "none" and self.sp_degree < 2:
            raise SeqParallelConfigError(
                f"sp_mode={self.sp_mode!r} shards the sequence over "
                "sp_degree >= 2 devices — sp_degree=1 has no seq axis; "
                "drop sp_mode (the default 'none' IS the degree-1 program)")
        if self.sp_degree > 1 and self.cached and self.cache_mode == "adaptive":
            raise SeqParallelConfigError(
                "sequence parallelism cannot compose with the batch-coupled "
                "adaptive cache: the drift gate's batch-max reduction is not "
                "psum'd over the seq axis, so the two sequence shards could "
                "take DIFFERENT refresh branches and desynchronize the "
                "carry — use cache_mode='delta'/'full'/'token' with sp, or "
                "sp_degree=1 for adaptive caching")
        if self.steps < 0:
            raise ValueError(
                f"steps must be >= 0 (0 = the k-strided family, >= 1 = the "
                f"few-step family), got {self.steps}")
        if self.student and self.steps < 1:
            raise ValueError(
                "student=True serves a few-step distilled student — pass "
                "steps=<its evaluation count, e.g. 1/2/4> (student params "
                "under the stride family would silently mis-serve a "
                "teacher-schedule request)")
        if self.steps > 0:
            if self.sampler != "ddim":
                raise ValueError(
                    "steps > 0 is the few-step DDIM family — "
                    f"got sampler={self.sampler!r}")
            if self.task != "sample":
                raise ValueError(
                    "steps > 0 serves plain generation only — task "
                    f"{self.task!r} has no few-step scan variant yet")
            if self.telemetry:
                raise ValueError(
                    "telemetry decodes the CACHED STRIDE scan's step aux — "
                    "it has no few-step variant; drop telemetry or steps")
        if self.telemetry:
            if self.sampler != "ddim" or not self.cached:
                raise ValueError(
                    "telemetry=True decodes the cached DDIM scan's step aux "
                    "— pass sampler='ddim' with cache_interval > 1")
            if self.task != "sample":
                raise ValueError(
                    "telemetry=True is the plain sampling path — task "
                    f"{self.task!r} has no telemetry scan variant")
            if self.preview_every:
                raise ValueError(
                    "telemetry and previews are separate products — the "
                    "telemetry scan is last-only (drop preview_every)")
            if self.sp_mode != "none":
                raise ValueError(
                    "telemetry does not compose with sequence parallelism — "
                    "use sp_degree=1 (default) for telemetry configs")
    @property
    def cached(self) -> bool:
        return self.cache_interval > 1

    @property
    def batch_coupled(self) -> bool:
        """True when one compiled dispatch couples its rows: the adaptive
        drift gate reduces per-row drift with a batch MAX before the
        ``lax.switch`` — a hot batchmate can force a refresh that changes
        every row's arithmetic. Coupled configs must never coalesce or split
        requests (the planner gives each request its own batch; the engine
        pads with row-0 replicas, whose drift equals row 0's and so never
        moves the max) or the bitwise-vs-direct contract breaks. Token mode
        is NOT coupled: its top-k indices are per-row, so it coalesces and
        splits freely — but its bitwise-vs-direct guarantee is per dispatch
        SHAPE (exact-bucket dispatches are bitwise the own-n direct call;
        padded dispatches are bitwise a direct call at the padded shape and
        float-level vs own-n, because the reuse step's gathered
        sub-sequence trunk compiles per batch shape and short-sequence GEMM
        tiling rounds per-row differently across shapes)."""
        return self.cached and self.cache_mode == "adaptive"


class Ticket:
    """Per-request future. The engine delivers row ranges as their batches
    come off the device (a split request completes over several batches);
    ``result()`` blocks until every row has landed — or until the request
    FAILS, in which case it re-raises the failure with the engine-stage
    exception as cause. ``done`` reflects both outcomes (a resolved error
    counts as done), so a caller that saw a ``result(timeout=)`` timeout
    can keep observing the ticket: a late-landing buffer or a late failure
    both flip ``done`` and are readable via ``result()``/``exception()``."""

    def __init__(self, n: int):
        self.n = int(n)
        self.submit_time = time.perf_counter()
        self.done_time: Optional[float] = None
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._buf: Optional[np.ndarray] = None          # guarded-by: _lock
        self._remaining = int(n)                        # guarded-by: _lock
        self._error: Optional[BaseException] = None     # guarded-by: _lock
        # resolution outcome, decided ATOMICALLY under _lock: True once the
        # ticket completed or failed. _event trails it (set in _resolve,
        # outside the lock), so first-resolution-wins races on _resolved,
        # never on the event — a _fail landing in the window between a
        # completing _deliver's lock release and its _event.set() must lose.
        self._resolved = False                          # guarded-by: _lock
        self._health_cb = None  # engine attaches its health snapshot hook
        self._callbacks: list = []                      # guarded-by: _lock
        #: obs root span for this request (obs/spans.py) — set by the engine
        #: or router at submit when tracing is enabled, else None
        self.span = None
        #: per-request step-telemetry summary (obs/device.summarize) — set
        #: at finish for SamplerConfig(telemetry=True) requests, else None
        self.telemetry: Optional[dict] = None
        # streaming previews (SamplerConfig.preview_every): per-step frame
        # assembly (a split request's preview rows land batch by batch, like
        # the result) + completed-frame history. _pcond serializes history
        # and preview-callback registration so no frame is missed or
        # double-fired; history keeps frames alive for late previews() /
        # add_preview_callback consumers.
        self._pcond = threading.Condition()
        # step -> [frame buffer, rows remaining]
        self._pbuf: dict = {}                           # guarded-by: _lock
        self._pdone: set = set()    # hedge dedupe       # guarded-by: _lock
        # completed (step, frames), in order
        self._phistory: list = []                       # guarded-by: _pcond
        self._preview_cbs: list = []                    # guarded-by: _pcond

    def add_done_callback(self, fn) -> None:
        """Call ``fn(ticket)`` once, when the ticket resolves (completed OR
        failed). Fires immediately if already resolved. Callbacks run on the
        resolving thread, outside the ticket lock; exceptions are swallowed
        (a broken observer must not poison engine delivery). The fleet
        router rides this to learn a placement's outcome without a thread
        per ticket."""
        with self._lock:
            if not self._resolved:
                self._callbacks.append(fn)
                return
        self._run_callback(fn)

    def _run_callback(self, fn) -> None:
        try:
            fn(self)
        except Exception:  # noqa: BLE001 — observers must not poison delivery
            pass

    def _resolve(self) -> None:
        """Set the event and fire registered callbacks (resolver thread)."""
        self.done_time = time.perf_counter()
        self._event.set()
        with self._pcond:
            self._pcond.notify_all()  # previews() iterators stop at done
        with self._lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            self._run_callback(fn)

    # ------------------------------------------------------------ previews

    def add_preview_callback(self, fn) -> None:
        """Call ``fn(step, frames)`` for every COMPLETED preview frame (all
        n rows landed), in completion order. Frames that completed before
        registration are replayed first — registration and delivery
        serialize on one lock, so no frame is missed or fired twice.
        Exceptions are swallowed like done-callbacks. The fleet router rides
        this to forward replica previews to its own ticket."""
        with self._pcond:
            self._preview_cbs.append(fn)
            replay = list(self._phistory)
        for step, frames in replay:
            try:
                fn(step, frames)
            except Exception:  # noqa: BLE001 — observers must not poison
                pass

    def _preview(self, step: int, lo: int, hi: int,
                 rows: np.ndarray) -> bool:
        """Engine-side: land preview rows [lo, hi) of trajectory frame
        ``step``. True when that frame just completed. Frames landing after
        the ticket resolved, or for an already-completed step (a hedged
        re-placement re-delivers the schedule), are dropped."""
        step = int(step)
        with self._lock:
            if self._resolved:
                return False
            if step in self._pdone:
                return False
            ent = self._pbuf.get(step)
            if ent is None:
                ent = self._pbuf[step] = [
                    np.empty((self.n,) + rows.shape[1:], rows.dtype),
                    self.n]
            ent[0][lo:hi] = rows
            ent[1] -= hi - lo
            if ent[1] > 0:
                return False
            frames = self._pbuf.pop(step)[0]
            self._pdone.add(step)
        with self._pcond:
            self._phistory.append((step, frames))
            cbs = list(self._preview_cbs)
            self._pcond.notify_all()
        for fn in cbs:
            try:
                fn(step, frames)
            except Exception:  # noqa: BLE001 — observers must not poison
                pass
        return True

    def previews(self, timeout: Optional[float] = None):
        """Iterate completed preview frames as ``(step, frames)`` — frames
        is the (n, H, W, C) intermediate x̂0 prediction after scan step
        ``step`` — blocking up to ``timeout`` between frames (TimeoutError
        on expiry, with the engine health snapshot). The iterator ends when
        the ticket RESOLVES and the history is drained: for a completed
        request that is after the last preview; for a failed one it simply
        stops early (the error surfaces via ``result()``/``exception()``).
        A ticket without ``preview_every`` yields nothing and returns at
        resolution."""
        idx = 0
        while True:
            with self._pcond:
                while len(self._phistory) <= idx and not self._event.is_set():
                    if not self._pcond.wait(timeout):
                        raise TimeoutError(self._timeout_msg(timeout))
                if len(self._phistory) <= idx:
                    return
                step, frames = self._phistory[idx]
                idx += 1
            yield step, frames

    def _deliver(self, lo: int, hi: int, rows: np.ndarray) -> bool:
        """Engine-side: land request rows [lo, hi). True when complete.
        Rows landing after the ticket failed are dropped (the error is the
        outcome; a half-filled buffer must never masquerade as a result)."""
        with self._lock:
            if self._resolved:
                return False
            if self._buf is None:
                self._buf = np.empty((self.n,) + rows.shape[1:], rows.dtype)
            self._buf[lo:hi] = rows
            self._remaining -= hi - lo
            done = self._remaining == 0
            if done:
                self._resolved = True  # claim the resolution under the lock
        if done:
            self._resolve()
        return done

    def _fail(self, exc: BaseException) -> bool:
        """Engine-side: resolve the ticket as failed. First resolution wins
        (a ticket that already completed, or already failed, is untouched);
        returns True when THIS call resolved it. The claim races on
        ``_resolved``, not on ``_event``: a completing ``_deliver`` marks
        ``_resolved`` before releasing the lock but sets the event only
        afterwards, so testing the event here would let a concurrent
        ``_fail`` mask a fully delivered result with an error."""
        with self._lock:
            if self._resolved:
                return False
            self._resolved = True
            self._error = exc
        self._resolve()
        return True

    @property
    def done(self) -> bool:
        """True once the ticket is RESOLVED — completed or failed."""
        return self._event.is_set()

    @property
    def failed(self) -> bool:
        return self._error is not None

    @property
    def latency_s(self) -> Optional[float]:
        if self.done_time is None:
            return None
        return self.done_time - self.submit_time

    def _timeout_msg(self, timeout) -> str:
        base = (f"ticket for {self.n} rows not complete after {timeout}s "
                f"({self._remaining} rows outstanding)")
        if self._health_cb is not None:
            try:
                health = self._health_cb()
                stage = health.get("last_stage")
                if stage is not None:
                    base += (f"; engine last seen at stage {stage!r}, "
                             f"{health.get('stalled_for_s')}s ago")
                return f"{base}; engine health: {health}"
            except Exception:  # noqa: BLE001 — diagnostics must not mask
                return base
        return base + " — no engine attached (did Engine.run() run?)"

    def exception(self, timeout: Optional[float] = None):
        """The request's failure, or None if it completed
        (concurrent.futures semantics: blocks up to ``timeout``, raising
        TimeoutError — with the engine health snapshot — if unresolved)."""
        if not self._event.wait(timeout):
            raise TimeoutError(self._timeout_msg(timeout))
        return self._error

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError(self._timeout_msg(timeout))
        if self._error is not None:
            raise self._error
        return self._buf


@dataclass
class Request:
    """One queued sampling request (internal to the engine; tests build these
    directly for planner coverage). ``key`` is the request's jax PRNG key for
    fresh starts; ``x_init`` the (n, H, W, C) start for guided requests."""

    config: SamplerConfig
    n: int
    key: Optional[object] = None
    x_init: Optional[object] = None
    #: extra per-row batch inputs some tasks ride along with x (host numpy,
    #: leading dim n; the assembly thread slices rows like x_init). The
    #: inpaint task carries {"known": (n,H,W,C), "mask": (n,H,W,1)}.
    extras: Optional[dict] = None
    ticket: Ticket = field(default_factory=lambda: Ticket(0))
    #: engine-assigned id (submit order); fault tags and quarantine records
    #: name requests by it
    rid: int = -1
    #: absolute deadline (time.perf_counter() clock); None = no deadline.
    #: Enforced at plan time and again at dispatch time — an expired request
    #: fails fast with DeadlineExceeded instead of occupying a bucket.
    deadline: Optional[float] = None
    # memo for the assembly thread: the request's full x_init drawn ONCE at
    # its own n (the draw depends on n, slicing does not), shared by every
    # batch the request's rows land in
    _x_full: Optional[object] = None


@dataclass(frozen=True)
class BatchPlan:
    """One device dispatch: ``rows`` real rows padded to ``bucket``.

    ``entries`` = (request, req_lo, req_hi, row_offset): request rows
    [req_lo, req_hi) occupy batch rows [row_offset, row_offset + hi - lo).
    """

    config: SamplerConfig
    bucket: int
    entries: tuple
    rows: int

    @property
    def padded_rows(self) -> int:
        return self.bucket - self.rows


def select_bucket(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket that fits ``n`` whole; None when ``n`` exceeds the
    largest (the planner then splits the request across batches)."""
    fits = [b for b in buckets if b >= n]
    return min(fits) if fits else None


def cover_rows(rows: int, buckets: Sequence[int]) -> list[int]:
    """Bucket multiset covering ``rows`` with minimum padding (ties → fewest
    batches). Greedily peels max-size buckets, then exact DP on the tail:
    the first reachable sum ≥ the remainder has minimal padding, and the DP
    carries the minimum batch count to each sum."""
    bs = sorted({int(b) for b in buckets})
    if not bs or bs[0] <= 0:
        raise ValueError(f"buckets must be positive ints, got {buckets!r}")
    out: list[int] = []
    remaining = int(rows)
    bmax = bs[-1]
    while remaining >= bmax:
        out.append(bmax)
        remaining -= bmax
    if remaining == 0:
        return out
    limit = remaining + bmax  # sum ≥ remaining is reachable by this point
    inf = limit + 1
    count = [inf] * (limit + 1)
    choice = [0] * (limit + 1)
    count[0] = 0
    for s in range(1, limit + 1):
        for b in bs:
            if b <= s and count[s - b] + 1 < count[s]:
                count[s] = count[s - b] + 1
                choice[s] = b
    for s in range(remaining, limit + 1):
        if count[s] <= limit:
            tail = []
            while s:
                tail.append(choice[s])
                s -= choice[s]
            return out + sorted(tail, reverse=True)
    raise AssertionError("unreachable: limit includes a whole bmax")


def plan_batches(requests: Sequence, buckets: Sequence[int]) -> list[BatchPlan]:
    """Coalesce a FIFO request list into bucket-padded batch plans.

    Requests group by config (first-seen order; FIFO within a group) and the
    group's total rows are covered by ``cover_rows``; rows then pack densely
    into the chosen buckets in request order, splitting requests at batch
    boundaries. Only the LAST batch of a group carries padding.

    Batch-coupled configs (``SamplerConfig.batch_coupled`` — the adaptive
    drift gate) are the exception: each request becomes its OWN single
    batch in the smallest bucket that fits it whole (never coalesced with a
    batchmate, never split — either would change the batch the gate's max
    reduction sees and break bitwise-vs-direct). A coupled request larger
    than the biggest bucket is rejected here, which surfaces as a submit
    error.
    """
    groups: dict[SamplerConfig, list] = {}
    for req in requests:
        if req.n < 1:
            raise ValueError(f"request must have n >= 1, got {req.n}")
        groups.setdefault(req.config, []).append(req)

    plans: list[BatchPlan] = []
    for config, reqs in groups.items():
        if config.batch_coupled:
            for req in reqs:
                bucket = select_bucket(req.n, buckets)
                if bucket is None:
                    raise ValueError(
                        f"adaptive-cache request of {req.n} rows exceeds the "
                        f"largest bucket {max(buckets)} — the drift gate "
                        "couples the batch, so the request cannot split; "
                        "submit at most max(buckets) rows per request")
                plans.append(BatchPlan(config=config, bucket=bucket,
                                       entries=((req, 0, req.n, 0),),
                                       rows=req.n))
            continue
        total = sum(r.n for r in reqs)
        sizes = cover_rows(total, buckets)
        it = iter(reqs)
        req, lo = next(it), 0
        for bucket in sizes:
            entries, offset = [], 0
            while offset < bucket and req is not None:
                take = min(req.n - lo, bucket - offset)
                entries.append((req, lo, lo + take, offset))
                offset += take
                lo += take
                if lo == req.n:
                    req, lo = next(it, None), 0
            plans.append(BatchPlan(config=config, bucket=bucket,
                                   entries=tuple(entries), rows=offset))
        assert req is None, "cover_rows under-covered the group"
    return plans
