"""Load-driven fleet autoscaling — the control loop over Router.scale_to.

The ROADMAP's retire/spawn gap, closed: supervision already REPLACES dead
replicas at a fixed target; this module moves the target itself. Each tick
reads one :meth:`Router.health` snapshot — queue pressure (router-queued
requests plus per-replica engine queues, normalized per ready replica) and
the worst per-replica p95 ticket latency (serve/engine.py surfaces the
percentiles from the PR 10 metrics registry's ``engine.latency_s`` series)
— and votes it against two thresholds:

* **overload**  — queue/replica above ``queue_high`` OR p95 above
  ``p95_high_s``;
* **underload** — queue/replica at/below ``queue_low`` AND (when a p95
  floor is configured) p95 below ``p95_low_s``.

Three mechanisms keep the loop from flapping on noisy signals, and the
tests pin each one:

* **hysteresis** — the up and down thresholds are separated bands, and a
  decision needs ``up_ticks`` / ``down_ticks`` CONSECUTIVE votes (one
  noisy p95 spike resets the down-streak, it never triggers a scale-up on
  its own ... unless it persists);
* **cooldown** — after any scale action, both directions hold for
  ``cooldown_s`` (measured on the injectable ``clock``, so the unit tests
  advance time without sleeping);
* **bounds + warm pool** — the target stays in
  ``[min_replicas + warm_pool, max_replicas]``. The warm pool is spare
  serving capacity kept WARM (each spawned replica is warmed from the
  persistent compile cache by the router's spawn path), so replacing a
  crashed replica is a process fork + cache read, not minutes of XLA.

Scale-up asks the router for one more replica; the router's supervision
tick spawns and warms it (``Router._spawn_replica`` asserts the
zero-compile contract via the warmed handle). Scale-down retires the
least-loaded replica through the normal eviction path — queued tickets
fail over, nothing is lost to a scale decision.

Host-only module (graftcheck A004) and a registered host-threaded module
(T-rules): the background thread only ever touches the router OUTSIDE the
autoscaler's own lock, so the lock order autoscale::_lock → router::_lock
never occurs (ranks forbid it).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ddim_cold_tpu.obs import metrics


class Autoscaler:
    """Drive ``router.scale_to`` from load. ``tick()`` is the whole brain
    and is public: the unit tests call it directly with a fake clock;
    :meth:`start` just runs it every ``interval_s`` on a daemon thread."""

    def __init__(self, router, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 queue_high: float = 8.0, queue_low: float = 1.0,
                 p95_high_s: Optional[float] = None,
                 p95_low_s: Optional[float] = None,
                 up_ticks: int = 2, down_ticks: int = 5,
                 cooldown_s: float = 10.0, warm_pool: int = 0,
                 interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas + warm_pool:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= min_replicas + "
                f"warm_pool ({min_replicas} + {warm_pool})")
        if queue_low > queue_high:
            raise ValueError(f"queue_low ({queue_low}) must be <= "
                             f"queue_high ({queue_high}) — the hysteresis "
                             "band would be inverted")
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.p95_high_s = p95_high_s
        self.p95_low_s = p95_low_s
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.cooldown_s = float(cooldown_s)
        self.warm_pool = int(warm_pool)
        self.interval_s = float(interval_s)
        self.clock = clock
        self.metrics = metrics.scope("autoscale")
        # decision state: only the tick path touches these, and ticks are
        # serialized (one thread, or a test driving tick() directly)
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: Optional[float] = None
        self.last_decision: dict = {}
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock

    # ------------------------------------------------------------- signals

    @property
    def floor(self) -> int:
        """Scale-down floor: the configured minimum plus the warm pool."""
        return self.min_replicas + self.warm_pool

    def read_signals(self, health: Optional[dict] = None) -> dict:
        """One load sample from a router health snapshot: total queued
        work (router queue + every replica's engine queue), its per-ready-
        replica normalization, and the worst replica p95."""
        h = health if health is not None else self.router.health()
        replicas = h.get("replicas", {})
        ready = [r for r in replicas.values() if r.get("state") == "ready"]
        router_queued = sum(h.get("pending_by_tenant", {}).values())
        engine_queued = sum(r.get("queue_depth", 0) + r.get("open_tickets", 0)
                            for r in ready)
        total = router_queued + engine_queued
        p95 = max((r.get("latency_p95_s", 0.0) or 0.0 for r in ready),
                  default=0.0)
        n_ready = max(1, len(ready))
        return {"ready": len(ready), "queued": total,
                "queued_per_replica": total / n_ready, "p95_s": p95,
                "target": self.router.target, "closed": h.get("closed")}

    # ---------------------------------------------------------------- tick

    def tick(self, health: Optional[dict] = None) -> dict:
        """One control decision. Returns (and stores on ``last_decision``)
        the signals plus the action taken: ``"up"``, ``"down"``, or
        ``None``."""
        sig = self.read_signals(health)
        self.metrics.inc("autoscale.ticks")
        action = None
        if not sig["closed"]:
            over = sig["queued_per_replica"] > self.queue_high \
                or (self.p95_high_s is not None
                    and sig["p95_s"] > self.p95_high_s)
            under = sig["queued_per_replica"] <= self.queue_low \
                and (self.p95_low_s is None or sig["p95_s"] < self.p95_low_s)
            if over:
                self._up_streak += 1
                self._down_streak = 0
            elif under:
                self._down_streak += 1
                self._up_streak = 0
            else:
                # the dead band between the thresholds: hold, and make any
                # pending streak start over (hysteresis)
                self._up_streak = 0
                self._down_streak = 0
            now = self.clock()
            cooling = (self._last_action_t is not None
                       and now - self._last_action_t < self.cooldown_s)
            target = sig["target"]
            if (over and self._up_streak >= self.up_ticks and not cooling
                    and target < self.max_replicas):
                self.router.scale_to(target + 1)
                self.metrics.inc("autoscale.scale_ups")
                self._last_action_t = now
                self._up_streak = 0
                action = "up"
            elif (under and self._down_streak >= self.down_ticks
                    and not cooling and target > self.floor):
                self.router.scale_to(target - 1)
                self.metrics.inc("autoscale.scale_downs")
                self._last_action_t = now
                self._down_streak = 0
                action = "down"
        self.metrics.gauge("autoscale.target", self.router.target)
        sig["action"] = action
        sig["up_streak"] = self._up_streak
        sig["down_streak"] = self._down_streak
        self.last_decision = sig
        return sig

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Run :meth:`tick` every ``interval_s`` on a daemon thread
        (idempotent). The floor is asserted immediately: a fleet configured
        with a warm pool scales up to it on the first tick rather than
        waiting for load."""
        if self.router.target < self.floor:
            self.router.scale_to(self.floor)
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, name="autoscaler", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a scaling decision must
                pass           # never be load-bearing for serving itself

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(5.0)
