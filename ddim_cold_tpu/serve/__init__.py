"""Sampler serving engine — bucketed continuous batching over the jitted scans.

The ROADMAP north star is serving-scale sampling; ops/sampling.py gives one
fast program per batch shape, and this package turns it into a service loop:

* ``batching``  — request queue → static bucket plans (pad, never recompile)
* ``engine``    — AOT-compiled dispatch with H2D/D2H–compute overlap
* ``warmup``    — compile every (config, bucket) program up front + wire the
                  persistent compilation cache so restarts skip XLA entirely
* ``fleet``     — replica handles (lifecycle unit; in-process backend now,
                  subprocess/host later behind the same interface)
* ``router``    — health-aware placement over N replicas with hedged
                  re-placement, replica replacement, and tenant QoS

Quickstart::

    from ddim_cold_tpu import serve
    eng = serve.Engine(model, params, mesh=None, buckets=(8, 32, 128))
    serve.warmup(eng, [serve.SamplerConfig(k=10)])
    t = eng.submit(seed=0, n=5, k=10)     # → Ticket
    eng.run()                              # drain the queue
    imgs = t.result()                      # (5, H, W, C) in [0, 1]

Engine output is bitwise identical to a direct ``ddim_sample``/``cold_sample``
call with the same rng (padding rows discarded) — see engine.py for why.

The guided-editing workloads (ddim_cold_tpu/workloads: inpaint, superres,
draft, interp) serve through this same machinery as ``SamplerConfig(task=…)``
variants — ``workloads.default_edit_configs()`` is the warmable set, and
``SamplerConfig(preview_every=m)`` streams intermediate x̂0 frames through
``Ticket.previews()``.
"""

from ddim_cold_tpu.serve.autoscale import Autoscaler
from ddim_cold_tpu.serve.batching import (BatchPlan, Request, SamplerConfig,
                                          Ticket, cover_rows, plan_batches,
                                          select_bucket)
from ddim_cold_tpu.serve.engine import Engine
from ddim_cold_tpu.serve.errors import (RETRYABLE_EXCEPTIONS, DeadlineExceeded,
                                        EngineClosedError, EngineStalledError,
                                        QueueFullError, RemoteRPCError,
                                        ReplicaCrashedError,
                                        ReplicaUnreachableError,
                                        RequestFailedError,
                                        RequestQuarantinedError, ServeError)
from ddim_cold_tpu.serve.fleet import LocalReplica, ReplicaHandle, local_factory
from ddim_cold_tpu.serve.remote import (RemoteReplica, remote_factory,
                                        save_params_npz)
from ddim_cold_tpu.serve.router import Router
from ddim_cold_tpu.serve.warmup import warmup

__all__ = [
    "Autoscaler", "BatchPlan", "DeadlineExceeded", "Engine",
    "EngineClosedError", "EngineStalledError", "LocalReplica",
    "QueueFullError", "RemoteReplica", "RemoteRPCError", "ReplicaCrashedError",
    "ReplicaHandle", "ReplicaUnreachableError", "Request",
    "RequestFailedError", "RequestQuarantinedError", "RETRYABLE_EXCEPTIONS",
    "Router", "SamplerConfig", "ServeError", "Ticket", "cover_rows",
    "local_factory", "plan_batches", "remote_factory", "save_params_npz",
    "select_bucket", "warmup",
]
