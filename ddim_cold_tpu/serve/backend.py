"""Child-process engine construction — the jax-touching half of
serve/replica_main.py.

replica_main must stay statically host-only (graftcheck A004: no jax
attribute chains anywhere in the file), but an ``"engine"``-backend replica
obviously needs a model, params, and a jitted Engine. That construction
lives HERE, behind one deferred import, so the A004 boundary stays honest:
everything the parent process imports (remote.py, replica_main.py) is
host-only; the device stack loads only inside the child that serves on it.

Spec fields consumed (see :func:`~ddim_cold_tpu.serve.remote.remote_factory`
for the full grammar): ``model`` (DiffusionViT kwargs with ``dtype`` as a
string and ``img_size`` as a list), ``params_npz`` (a tree saved by
:func:`~ddim_cold_tpu.serve.remote.save_params_npz` — how trained params
cross the process boundary) or ``init_seed`` (deterministic re-init — two
replicas built from the same seed hold bitwise-equal params), ``engine``
(Engine kwargs: buckets, max_queue, ...).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ddim_cold_tpu.models.vit import DiffusionViT
from ddim_cold_tpu.serve.engine import Engine
from ddim_cold_tpu.serve.fleet import LocalReplica
from ddim_cold_tpu.serve.remote import load_params_npz

#: spec-string → jnp dtype (specs are JSON; a dtype object does not travel)
_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
           "float16": jnp.float16}


def build_model(model_spec: dict) -> DiffusionViT:
    kw = dict(model_spec or {})
    dtype = _DTYPES[kw.pop("dtype", "float32")]
    if "img_size" in kw:
        kw["img_size"] = tuple(kw["img_size"])
    return DiffusionViT(dtype=dtype, **kw)


def init_params(model: DiffusionViT, seed: int):
    h, w = tuple(model.img_size)
    x = jnp.zeros((1, h, w, model.in_chans), model.dtype)
    t = jnp.zeros((1,), jnp.int32)
    return model.init(jax.random.PRNGKey(int(seed)), x, t)["params"]


def build_local_replica(replica_id: str, spec: dict) -> LocalReplica:
    model = build_model(spec.get("model"))
    if spec.get("params_npz"):
        params = load_params_npz(spec["params_npz"])
    else:
        params = init_params(model, spec.get("init_seed", 0))
    engine = Engine(model, params, replica_id=replica_id,
                    **(spec.get("engine") or {}))
    return LocalReplica(engine)
