"""Out-of-process replicas — crash isolation behind the ReplicaHandle surface.

:class:`RemoteReplica` drives a replica server living in its OWN OS process
(``python -m ddim_cold_tpu.serve.replica_main``) over a length-prefixed
socket RPC, so a replica dying — SIGKILL, OOM, a wedged backend — is an
event the fleet *observes* instead of one it shares. The handle speaks the
exact :class:`~ddim_cold_tpu.serve.fleet.ReplicaHandle` surface the router
already places onto; nothing above this module knows which side of a
process boundary a replica lives on.

Wire protocol (one frame = one message)::

    [4B big-endian frame length]
    [4B big-endian header length][UTF-8 JSON header][raw array buffers...]

The JSON header carries the message tree with every numpy array replaced by
an ``{"__nd__": i}`` marker plus a parallel ``arrays`` list of
``{shape, dtype}`` descriptors; the buffers follow in marker order. Arrays
therefore cross the boundary at memcpy cost — no base64, no pickling, and
nothing executable on the wire (JSON + raw bytes only).

Failure taxonomy (serve/errors.py, serialized with
``encode_exception``/``decode_exception``):

* a typed failure raised server-side crosses back AS ITS TYPE — an injected
  :class:`~ddim_cold_tpu.utils.faults.TransientFault` stays retryable, a
  :class:`~ddim_cold_tpu.serve.errors.DeadlineExceeded` stays a deadline;
* an RPC that cannot complete (socket gone, dropped frame, per-call
  deadline) raises :class:`~ddim_cold_tpu.serve.errors.ReplicaUnreachableError`
  (retryable by construction — try another replica);
* a process death (exit observed, or ``miss_budget`` consecutive heartbeat
  misses) transitions the handle to ``closed`` and fails every open ticket
  with :class:`~ddim_cold_tpu.serve.errors.ReplicaCrashedError` naming the
  replica — the router's failover path re-places them onto survivors,
  bitwise-identical because placement never changes sampling math.

Chaos sites (utils/faults.py): the client fires ``rpc.drop`` (arm kind
``transient`` — the frame is silently not sent and the call times out) and
``rpc.latency`` around every frame send; the server fires ``replica.kill``
/ ``replica.hang`` per work request. Tags are ``replica:<id>|method:<m>|``
so a schedule can target one replica's n-th submit exactly.

Host-only module (graftcheck A004): no jax anywhere — engine construction
for the child process lives in serve/backend.py, which only the CHILD
imports.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ddim_cold_tpu.obs import metrics
from ddim_cold_tpu.serve import fleet
from ddim_cold_tpu.serve.batching import SamplerConfig, Ticket
from ddim_cold_tpu.serve.errors import (RemoteRPCError, ReplicaCrashedError,
                                        ReplicaUnreachableError,
                                        decode_exception)
from ddim_cold_tpu.utils import faults

#: hard ceiling on one frame (a corrupt length prefix must not look like a
#: 4 GiB allocation request)
MAX_FRAME_BYTES = 1 << 30

#: client→server RPC method kinds on the wire — one entry per ``_call``
#: method literal below. graftcheck R001 proves this table matches the
#: actual call sites AND stays set-equal to the server's
#: ``replica_main.SERVER_METHODS`` (a method sent with no handler, or a
#: handler no client can reach, is a protocol-drift bug).
CLIENT_METHODS = ("ping", "health", "start", "submit", "warm", "drain",
                  "close")

#: server-push event kinds the client has a dispatch arm for (``_dispatch``
#: plus the factory's hello validation). R001 proves every event the server
#: can emit (``replica_main.SERVER_EVENTS``) lands in one of these arms —
#: an unmatched event kind would be silently dropped on the floor.
CLIENT_EVENT_ARMS = ("hello", "ticket", "preview", "protocol_error")


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def encode_payload(msg: dict) -> bytes:
    """Message dict → header + raw array buffers (see module docstring).
    numpy arrays anywhere in the tree are lifted out; numpy scalars fold to
    Python numbers so the header stays pure JSON."""
    arrays: list = []

    def walk(node):
        if isinstance(node, np.ndarray):
            arrays.append(np.ascontiguousarray(node))
            return {"__nd__": len(arrays) - 1}
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return [walk(v) for v in node]
        if isinstance(node, np.integer):
            return int(node)
        if isinstance(node, np.floating):
            return float(node)
        if isinstance(node, np.bool_):
            return bool(node)
        return node

    tree = walk(msg)
    header = json.dumps({
        "msg": tree,
        "arrays": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in arrays],
    }).encode("utf-8")
    parts = [struct.pack(">I", len(header)), header]
    parts.extend(a.tobytes() for a in arrays)
    return b"".join(parts)


def decode_payload(buf: bytes) -> dict:
    """Inverse of :func:`encode_payload`."""
    if len(buf) < 4:
        raise RemoteRPCError(f"truncated payload ({len(buf)} bytes)")
    (hlen,) = struct.unpack(">I", buf[:4])
    if 4 + hlen > len(buf):
        raise RemoteRPCError(f"header length {hlen} exceeds payload")
    header = json.loads(buf[4:4 + hlen].decode("utf-8"))
    arrays = []
    off = 4 + hlen
    for desc in header.get("arrays", ()):
        dtype = np.dtype(desc["dtype"])
        shape = tuple(desc["shape"])
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if off + nbytes > len(buf):
            raise RemoteRPCError("array buffer extends past payload end")
        arrays.append(np.frombuffer(
            buf[off:off + nbytes], dtype=dtype).reshape(shape).copy())
        off += nbytes

    def walk(node):
        if isinstance(node, dict):
            if set(node) == {"__nd__"}:
                return arrays[node["__nd__"]]
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    return walk(header["msg"])


def send_frame(sock: socket.socket, msg: dict) -> None:
    payload = encode_payload(msg)
    if len(payload) > MAX_FRAME_BYTES:
        raise RemoteRPCError(f"frame of {len(payload)} bytes exceeds "
                             f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> dict:
    """Blocking read of one frame; ConnectionError on EOF (the reader
    thread's crash-detection signal), RemoteRPCError on garbage."""
    (length,) = struct.unpack(">I", _recv_exact(sock, 4))
    if length > MAX_FRAME_BYTES:
        raise RemoteRPCError(f"frame length {length} exceeds "
                             f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    return decode_payload(_recv_exact(sock, length))


# ---------------------------------------------------------------------------
# param transfer (parent → child, pure numpy — no orbax, no jax)
# ---------------------------------------------------------------------------

def save_params_npz(path: str, params: dict) -> str:
    """Flatten a nested param tree to an ``.npz`` with ``/``-joined keys.
    Leaves go through ``np.asarray`` so device arrays land as host numpy —
    the child process rebuilds the tree with :func:`load_params_npz`."""
    flat: dict = {}

    def walk(prefix: str, node) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node)

    walk("", params)
    np.savez(path, **flat)
    return path


def load_params_npz(path: str) -> dict:
    params: dict = {}
    with np.load(path) as data:
        for key in data.files:
            node = params
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = data[key]
    return params


class _Waiter:
    """One in-flight RPC: the caller blocks on ``event``; the reader thread
    (or crash handler) fills ``resp``/``error`` and sets it."""

    __slots__ = ("event", "resp", "error")

    def __init__(self):
        self.event = threading.Event()
        self.resp: Optional[dict] = None
        self.error: Optional[BaseException] = None


class RemoteReplica(fleet.ReplicaHandle):
    """ReplicaHandle backend over one replica server process.

    Three daemon threads watch the boundary: a **reader** dispatching
    responses and server-push ticket/preview events, a **heartbeat** firing
    ``ping`` every ``heartbeat_s`` and counting consecutive misses against
    ``miss_budget``, and a **process waiter** blocked in ``Popen.wait``.
    Any of the three detecting death funnels into one idempotent crash
    handler that fails every open ticket typed — the liveness contract:
    no failure mode leaves a ticket blocking forever.
    """

    def __init__(self, conn: socket.socket, proc: subprocess.Popen, *,
                 replica_id: str, spawn_s: float = 0.0,
                 heartbeat_s: float = 0.5, miss_budget: int = 3,
                 rpc_timeout_s: float = 10.0, warm_timeout_s: float = 600.0):
        self.replica_id = replica_id
        self.metrics = metrics.scope("remote")
        self._fleet_metrics = metrics.scope("fleet")
        self._conn = conn
        self._proc = proc
        self.spawn_s = float(spawn_s)
        self.warm_s: Optional[float] = None
        self.warm_report: Optional[dict] = None
        self.heartbeat_s = float(heartbeat_s)
        self.miss_budget = int(miss_budget)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.warm_timeout_s = float(warm_timeout_s)
        self.crash_reason: Optional[str] = None
        #: last typed error the server pushed for a frame it refused to
        #: decode (over-limit or garbage) — there is no call id to fail, so
        #: the breadcrumb lands here and the in-flight call's own deadline
        #: surfaces the failure
        self.last_protocol_error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._next_id = 0                               # guarded-by: _lock
        self._next_rid = 0                              # guarded-by: _lock
        self._pending: dict = {}                        # guarded-by: _lock
        self._tickets: dict = {}                        # guarded-by: _lock
        self._crashed = False                           # guarded-by: _lock
        self._draining = threading.Event()
        self._set_state(fleet.NEW)
        self._reader = threading.Thread(
            target=self._read_loop, name=f"remote-read-{replica_id}",
            daemon=True)
        self._reader.start()
        self._heartbeat = threading.Thread(
            target=self._heartbeat_loop, name=f"remote-hb-{replica_id}",
            daemon=True)
        self._heartbeat.start()
        self._waiter = threading.Thread(
            target=self._proc_wait_loop, name=f"remote-wait-{replica_id}",
            daemon=True)
        self._waiter.start()

    def _set_state(self, state: str) -> None:
        self.state = state
        fleet.record_transition(self._fleet_metrics, state)

    # ----------------------------------------------------------------- RPC

    def _send(self, msg: dict, method: str) -> None:
        """Serialize + send one frame. The two wire-level chaos sites live
        here: ``rpc.drop`` (armed as kind ``transient``; the raise is
        swallowed and the frame never leaves — the caller's deadline turns
        it into ReplicaUnreachableError) and ``rpc.latency``."""
        tag = f"replica:{self.replica_id}|method:{method}|"
        try:
            faults.fire("rpc.drop", tag=tag)
        except faults.FaultError:
            return  # frame dropped on the floor — no send, no error
        faults.fire("rpc.latency", tag=tag)
        payload = encode_payload(msg)
        if len(payload) > MAX_FRAME_BYTES:
            # reject locally and typed (RemoteRPCError is NOT retryable):
            # an oversized frame shipped anyway would be killed by the
            # peer's recv_frame, and a retried/hedged resend would then
            # serially take down every replica it lands on
            raise RemoteRPCError(
                f"replica {self.replica_id}: {method!r} frame of "
                f"{len(payload)} bytes exceeds "
                f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
        try:
            with self._send_lock:
                self._conn.sendall(struct.pack(">I", len(payload)) + payload)
        except OSError as exc:
            raise ReplicaUnreachableError(
                f"replica {self.replica_id}: send of {method!r} failed "
                f"({exc})") from exc

    def _call(self, method: str, params: Optional[dict] = None,
              timeout: Optional[float] = None):
        """One request/response round trip with a per-call deadline."""
        timeout = self.rpc_timeout_s if timeout is None else timeout
        waiter = _Waiter()
        with self._lock:
            if self._crashed:
                raise ReplicaCrashedError(
                    f"replica {self.replica_id} crashed: {self.crash_reason}")
            call_id = self._next_id
            self._next_id += 1
            self._pending[call_id] = waiter
        self.metrics.inc("remote.rpc_calls", key=method)
        try:
            self._send({"id": call_id, "method": method,
                        "params": params or {}}, method)
        except Exception:  # noqa: BLE001 — whatever the send raised is the
            # caller's error; this handler only unregisters the waiter
            with self._lock:
                self._pending.pop(call_id, None)
            raise
        if not waiter.event.wait(timeout):
            with self._lock:
                self._pending.pop(call_id, None)
            raise ReplicaUnreachableError(
                f"replica {self.replica_id}: {method!r} RPC exceeded its "
                f"{timeout}s deadline")
        if waiter.error is not None:
            raise waiter.error
        resp = waiter.resp or {}
        if resp.get("ok"):
            return resp.get("result")
        raise decode_exception(resp.get("error") or
                               {"type": "RemoteRPCError",
                                "message": "malformed error response"})

    # ------------------------------------------------------------- threads

    def _read_loop(self) -> None:
        while True:
            try:
                msg = recv_frame(self._conn)
            except Exception as exc:  # noqa: BLE001 — EOF / reset / garbage
                # all mean the same thing here: the wire is dead
                if not self._draining.is_set():
                    self._on_crash(f"connection lost ({exc})")
                return
            try:
                self._dispatch(msg)
            except Exception:  # noqa: BLE001 — one bad frame must not kill
                pass           # the reader (protocol errors surface per-call)

    def _dispatch(self, msg: dict) -> None:
        if "id" in msg:
            with self._lock:
                waiter = self._pending.pop(msg["id"], None)
            if waiter is not None:
                waiter.resp = msg
                waiter.event.set()
            return
        event = msg.get("event")
        if event == "ticket":
            with self._lock:
                ticket = self._tickets.pop(msg.get("rid"), None)
            if ticket is None:
                return
            if msg.get("status") == "done":
                rows = msg.get("result")
                if isinstance(rows, np.ndarray):
                    ticket._deliver(0, ticket.n, rows)
                else:
                    ticket._fail(RemoteRPCError(
                        f"replica {self.replica_id}: ticket completed "
                        "without a result buffer"))
            else:
                ticket._fail(decode_exception(msg.get("error") or {}))
        elif event == "preview":
            with self._lock:
                ticket = self._tickets.get(msg.get("rid"))
            rows = msg.get("rows")
            if ticket is not None and isinstance(rows, np.ndarray):
                ticket._preview(int(msg.get("step", 0)), 0, ticket.n, rows)
        elif event == "protocol_error":
            # the server refused one of our frames (over-limit, bad JSON)
            # and could not attribute it to a call id — record the typed
            # error so the inevitable per-call deadline has a cause to
            # point at, and count it (a drift here means frame-limit or
            # codec skew between the two processes)
            self.metrics.inc("remote.protocol_errors")
            self.last_protocol_error = decode_exception(
                msg.get("error") or {})

    def _heartbeat_loop(self) -> None:
        misses = 0
        while not self._draining.wait(self.heartbeat_s):
            if self.state == fleet.CLOSED:
                return
            try:
                self._call("ping", timeout=self.heartbeat_s)
                misses = 0
            except ReplicaCrashedError:
                return
            except Exception:  # noqa: BLE001 — any miss counts; the budget
                misses += 1    # decides, not the failure flavor
                self.metrics.inc("remote.heartbeat_misses")
                if misses >= self.miss_budget:
                    self._on_crash(
                        f"heartbeat lost ({misses} consecutive misses, "
                        f"budget {self.miss_budget})")
                    return

    def _proc_wait_loop(self) -> None:
        rc = self._proc.wait()
        if not self._draining.is_set():
            self._on_crash(f"process exited with code {rc}")

    def _on_crash(self, reason: str) -> None:
        """Idempotent death handler: transition to closed, fail every open
        ticket and in-flight RPC typed, and name the replica + cause in the
        message (the failover path's breadcrumb). Tickets resolve OUTSIDE
        the handle lock — a done-callback must be free to call back in."""
        with self._lock:
            if self._crashed:
                return
            self._crashed = True
            self.crash_reason = reason
            tickets = list(self._tickets.values())
            self._tickets.clear()
            pending = list(self._pending.values())
            self._pending.clear()
        self.metrics.inc("remote.crashes")
        self._set_state(fleet.CLOSED)
        err = ReplicaCrashedError(
            f"replica {self.replica_id} crashed: {reason}")
        for waiter in pending:
            waiter.error = err
            waiter.event.set()
        for ticket in tickets:
            ticket._fail(ReplicaCrashedError(
                f"replica {self.replica_id} crashed with this request "
                f"open: {reason}"))
        try:
            self._conn.close()
        except OSError:
            pass
        # A crash detected via heartbeat loss can leave the child ALIVE but
        # wedged, holding the accelerator — a respawned replacement then
        # cannot acquire the device. Kill it; the _proc_wait_loop thread
        # (blocked in wait()) reaps the zombie.
        if self._proc.poll() is None:
            try:
                self._proc.kill()
            except OSError:
                pass

    # ----------------------------------------------------------- lifecycle

    def warm(self, configs, buckets=None, **kwargs) -> dict:
        cfgs = [dataclasses.asdict(c) if isinstance(c, SamplerConfig) else c
                for c in configs]
        t0 = time.perf_counter()
        report = self._call(
            "warm",
            {"configs": cfgs,
             "buckets": list(buckets) if buckets is not None else None,
             "kwargs": dict(kwargs)},
            timeout=self.warm_timeout_s)
        self.warm_s = time.perf_counter() - t0
        self.warm_report = report
        h = self._call("health")
        extra = int(h.get("compiles_after_warmup", 0))
        if extra:
            raise RuntimeError(
                f"replica {self.replica_id}: {extra} compiles AFTER warmup "
                "— the spawn path's zero-compile contract is broken "
                "(unwarmed config, or the persistent cache regressed)")
        self._set_state(fleet.READY)
        return report

    def start(self) -> None:
        self._call("start")

    def submit(self, seed=None, n=1, *, rng=None, x_init=None, mask=None,
               config=None, deadline_s=None, trace=None, **kwargs) -> Ticket:
        if rng is not None:
            raise ValueError("remote replicas take seed=..., not rng keys "
                             "(a PRNG key does not cross a process boundary)")
        if self.state != fleet.READY:
            raise ReplicaCrashedError(
                f"replica {self.replica_id} is {self.state}"
                + (f" ({self.crash_reason})" if self.crash_reason else ""))
        cfg = dataclasses.asdict(config) \
            if isinstance(config, SamplerConfig) else config
        params = {"seed": seed, "n": int(n), "config": cfg,
                  "deadline_s": deadline_s, "kwargs": dict(kwargs)}
        if x_init is not None:
            params["x_init"] = np.asarray(x_init)
        if mask is not None:
            params["mask"] = np.asarray(mask)
        # The CLIENT allocates the rid and registers the ticket BEFORE the
        # submit frame leaves, so a fast-resolving request whose done event
        # races (or beats) the submit response still finds its ticket —
        # _dispatch drops events for unknown rids, and a dropped done event
        # would block result() forever on a healthy replica.
        ticket = Ticket(int(n))
        ticket._health_cb = self.health
        with self._lock:
            if self._crashed:
                raise ReplicaCrashedError(
                    f"replica {self.replica_id} crashed: {self.crash_reason}")
            rid = self._next_rid
            self._next_rid += 1
            self._tickets[rid] = ticket
        params["rid"] = rid
        try:
            self._call("submit", params)
        except Exception:  # noqa: BLE001 — submit never happened server-side
            # (send failed / deadline / typed rejection): unregister so a
            # stray late event cannot touch a ticket the caller never got
            with self._lock:
                self._tickets.pop(rid, None)
            raise
        return ticket

    def health(self) -> dict:
        h = self._call("health", timeout=self.rpc_timeout_s)
        h["state"] = self.state  # the CLIENT's view wins: it sees crashes
        h["spawn_s"] = self.spawn_s
        h["warm_s"] = self.warm_s
        return h

    def drain(self, timeout: Optional[float] = None) -> dict:
        """Graceful stop of the child: server-side engine drain, then
        process shutdown. Draining a crashed replica is a no-op returning
        the crash breadcrumb — the router retires dead replicas through
        this same path."""
        self._draining.set()
        if self.state == fleet.CLOSED:
            # retirement of a crashed replica must not leak the child:
            # _on_crash already sent SIGKILL for the wedged-but-alive case,
            # but make retirement itself the backstop before returning
            if self._proc.poll() is None:
                try:
                    self._proc.kill()
                except OSError:
                    pass
            try:
                self._proc.wait(timeout=self.rpc_timeout_s)
            except subprocess.TimeoutExpired:
                pass
            return {"closed": True, "crashed": True,
                    "reason": self.crash_reason}
        self._set_state(fleet.DRAINING)
        report: dict = {"closed": True}
        try:
            budget = 30.0 if timeout is None else float(timeout)
            report = self._call("drain", {"timeout": timeout},
                                timeout=budget + self.rpc_timeout_s)
            self._call("close")
        except Exception as exc:  # noqa: BLE001 — a replica dying mid-drain
            # is still a completed drain from the fleet's point of view
            report = {"closed": True, "error": str(exc)}
        try:
            self._conn.close()
        except OSError:
            pass
        try:
            self._proc.wait(timeout=self.rpc_timeout_s)
        except subprocess.TimeoutExpired:
            self._proc.kill()
        self._set_state(fleet.CLOSED)
        return report

    def close(self) -> None:
        if self.state != fleet.CLOSED:
            self.drain(self.rpc_timeout_s)

    @property
    def compiles_after_warmup(self) -> int:
        try:
            return int(self.health().get("compiles_after_warmup", 0))
        except Exception:  # noqa: BLE001 — a dead replica has no compiles
            return 0


# ---------------------------------------------------------------------------
# factory
# ---------------------------------------------------------------------------

def remote_factory(spec: dict, *, env: Optional[dict] = None,
                   heartbeat_s: float = 0.5, miss_budget: int = 3,
                   spawn_timeout_s: float = 180.0,
                   rpc_timeout_s: float = 10.0,
                   warm_timeout_s: float = 600.0,
                   on_spawn: Optional[Callable] = None,
                   ) -> Callable[[str], RemoteReplica]:
    """Factory of subprocess replicas for :class:`~.router.Router`.

    ``spec`` describes the child's engine and is shipped via the
    ``DDIM_COLD_REPLICA_SPEC`` env var (see serve/replica_main.py)::

        {"backend": "engine" | "stub",
         "model":      {...DiffusionViT kwargs, dtype as a string...},
         "params_npz": "/path/saved/by/save_params_npz.npz",  # or
         "init_seed":  0,          # re-init deterministically instead
         "engine":     {...Engine kwargs...},
         "cache_dir":  "/path",    # persistent compile cache the child warms
                                   # from — the pre-warmed-spawn accelerant
         "stub":       {"delay_s": 0.0}}

    ``env`` overlays the child environment — the chaos harness uses it to
    arm ``DDIM_COLD_FAULTS`` inside the replica only (the parent's armed
    specs never leak across the fork; the two processes have independent
    fault registries by construction).

    The factory spawns the child, hands it the ephemeral listener port, and
    blocks until the child connects and sends its hello (deadline
    ``spawn_timeout_s``). Spawn wall time lands on the handle as
    ``spawn_s`` and in ``health()``; ``on_spawn(replica_id, spawn_s)`` is
    the bench's hook for the warm-vs-cold spawn table.
    """
    spec = dict(spec)

    def factory(replica_id: str) -> RemoteReplica:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        listener.settimeout(spawn_timeout_s)
        port = listener.getsockname()[1]
        child_env = dict(os.environ)
        if env:
            child_env.update(env)
        child_env["DDIM_COLD_REPLICA_SPEC"] = json.dumps(spec)
        # The child runs `-m ddim_cold_tpu.serve.replica_main` with the
        # parent's cwd, so when the package was imported off a sys.path
        # entry (not installed), the child would not find it. Export the
        # package root the parent actually loaded.
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        existing = child_env.get("PYTHONPATH")
        if pkg_root not in (existing or "").split(os.pathsep):
            child_env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing if existing else ""))
        argv = [sys.executable, "-m", "ddim_cold_tpu.serve.replica_main",
                "--connect", f"127.0.0.1:{port}", "--replica-id", replica_id]
        t0 = time.perf_counter()
        proc = subprocess.Popen(argv, env=child_env)
        try:
            conn, _ = listener.accept()
        except socket.timeout:
            proc.kill()
            raise ReplicaUnreachableError(
                f"replica {replica_id}: no connection within "
                f"{spawn_timeout_s}s of spawn") from None
        finally:
            listener.close()
        # The hello read spends what is LEFT of the spawn budget — a child
        # that connects but wedges before its hello (hung device init) must
        # not block the factory, and through it fleet-wide supervision,
        # forever. Only a validated hello earns a deadline-free socket.
        remaining = spawn_timeout_s - (time.perf_counter() - t0)
        conn.settimeout(max(1.0, remaining))
        try:
            hello = recv_frame(conn)
        except Exception as exc:  # noqa: BLE001 — timeout, EOF, garbage:
            # the child never completed its half of the handshake
            proc.kill()
            try:
                conn.close()
            except OSError:
                pass
            raise ReplicaUnreachableError(
                f"replica {replica_id}: connected but sent no valid hello "
                f"within the {spawn_timeout_s}s spawn budget ({exc})"
            ) from exc
        conn.settimeout(None)
        if hello.get("event") != "hello":
            proc.kill()
            raise RemoteRPCError(
                f"replica {replica_id}: expected hello, got {hello!r}")
        spawn_s = time.perf_counter() - t0
        if on_spawn is not None:
            try:
                on_spawn(replica_id, spawn_s)
            except Exception:  # noqa: BLE001 — observers must not block spawn
                pass
        return RemoteReplica(
            conn, proc, replica_id=replica_id, spawn_s=spawn_s,
            heartbeat_s=heartbeat_s, miss_budget=miss_budget,
            rpc_timeout_s=rpc_timeout_s, warm_timeout_s=warm_timeout_s)

    return factory
