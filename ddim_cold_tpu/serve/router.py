"""Health-aware replica router: fleet-scale serving over N engine replicas.

The fault-tolerant engine (serve/engine.py) is a single-process unit — a
stalled or poisoned replica still takes its whole queue down with it. The
router makes the replica the blast radius instead of the fleet:

* **Failure-aware placement** — ``submit()`` queues at the router; the
  control loop places each request onto the least-loaded healthy replica
  (by ``health()`` queue depth + open tickets), skipping replicas that are
  stalled, closed, draining, or quarantine-heavy. Placement is itself a
  fault site (``router.place``) so chaos schedules can break the act of
  routing, not just the replicas.

* **Capped hedged re-placement** — a ticket that fails with a retryable
  cause (``errors.RETRYABLE_EXCEPTIONS``, e.g. an assembly-stage transient
  the engine does not retry internally) is transparently re-submitted once
  to a DIFFERENT replica. The request's rng/x_init ride along unchanged, so
  the hedged result is bitwise-equal to direct sampling — the engine's own
  contract, inherited. :class:`~.errors.RequestQuarantinedError` is
  terminal and never hedged: bisection already proved the request itself
  is the poison, and a hedge would just poison the next replica.

* **Replica lifecycle** — the control loop retires a replica whose health
  snapshot shows it stalled/closed/quarantine-heavy (or wedged by
  ``last_progress_s``), drains it (its queued engine tickets fail with
  ``EngineClosedError`` → the router fails them over to survivors via the
  ``router.failover`` site), and spawns a warmed replacement from the same
  ``(SamplerConfig, bucket)`` set — so zero-compiles-after-warmup holds
  across replacement, per replica against its own warm (statically provable
  via graftcheck J006: the sweep's programs are trace-hash-stable across
  independently built worlds, and a replacement is exactly such a world).

* **Tenant QoS** — ``submit(..., tenant=, priority=)`` with weighted
  fair-share admission: with declared tenant weights, each tenant's
  admitted-but-unresolved requests are capped at
  ``max(1, max_pending * w / W)``; a flooding tenant exhausts only its own
  share (``QueueFullError``) while others keep theirs. Within the control
  loop, placement is weighted round-robin over per-tenant priority queues.

* **Sequence-parallel placement** — configs with ``sp_degree > 1``
  (serve/engine.py's (data, seq)-mesh programs) route exactly like any
  other config: every replica warms the SAME ``(SamplerConfig, bucket)``
  set, so each replica owns the per-degree meshes, sp model clones, and
  re-placed param trees for every sp config the deployment serves, and an
  sp ticket fails over to a survivor — or to a freshly spawned
  replacement — without a serve-time compile or param placement. The
  router never inspects the mesh: sp-ness is static config identity, and
  the placement/hedging/failover invariants above are sharding-blind.

Liveness contract (same as the engine's): no admitted ticket blocks
forever — every path ends in delivery or a typed failure naming the
replica it happened on.

This module is host-only (graftcheck A004): routing must never touch a
device array — requests carry opaque rng/x_init/mask payloads straight
through to the replica's ``submit`` (editing workloads route like plain
sampling; preview frames come back through the replica ticket's
preview-callback hook, host numpy end to end).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np

from ddim_cold_tpu.obs import metrics, spans
from ddim_cold_tpu.serve import fleet
from ddim_cold_tpu.serve.batching import SamplerConfig, Ticket
from ddim_cold_tpu.serve.errors import (RETRYABLE_EXCEPTIONS, DeadlineExceeded,
                                        EngineClosedError, EngineStalledError,
                                        QueueFullError, RequestFailedError,
                                        RequestQuarantinedError)
from ddim_cold_tpu.utils import faults


@dataclass
class _FleetRequest:
    """Router-side state of one admitted request: the frozen replica
    ``submit()`` call (hedges re-issue it verbatim — that is what keeps the
    result bitwise), plus placement history and the caller's ticket."""

    fid: int
    n: int
    tenant: str
    priority: int
    call: dict
    deadline: Optional[float]
    ticket: Ticket
    hedges: int = 0
    failovers: int = 0
    tried: set = field(default_factory=set)
    placed_on: Optional[str] = None
    resolved: bool = False
    #: obs root span of this request's trace (None with tracing disabled).
    #: Every placement attempt — hedges included — is a child of it, so the
    #: whole multi-replica life of the request shares ONE trace_id.
    span: object = None


class Router:
    """N replicas behind one ``submit()``.

    ::

        factory = fleet.local_factory(model, params, buckets=(4, 8))
        router = Router(factory, replicas=2, configs=[SamplerConfig(k=10)])
        t = router.submit(seed=0, n=4, config=SamplerConfig(k=10),
                          tenant="web", priority=1)
        imgs = t.result(timeout=60)
        router.drain()

    ``factory(replica_id)`` builds a :class:`~.fleet.ReplicaHandle`; the
    router warms each new replica with ``configs`` (× ``buckets``, default
    the replica's own) before placing onto it. ``auto_start=False`` defers
    the control loop (admission still works — deterministic QoS tests use
    this) until :meth:`start`.
    """

    def __init__(self, factory: Callable[[str], "fleet.ReplicaHandle"],
                 replicas: int = 2,
                 configs: Sequence[SamplerConfig] = (SamplerConfig(),),
                 buckets: Optional[Sequence[int]] = None, *,
                 tenants: Optional[dict] = None, default_weight: int = 1,
                 max_pending: Optional[int] = None,
                 max_hedges: int = 1, max_failovers: int = 3,
                 quarantine_limit: int = 2,
                 wedge_after_s: Optional[float] = None,
                 drain_timeout_s: float = 30.0, tick_s: float = 0.02,
                 warm_kwargs: Optional[dict] = None,
                 auto_start: bool = True):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1 or None, "
                             f"got {max_pending}")
        self._factory = factory
        self._configs = tuple(configs)
        self._buckets = tuple(buckets) if buckets is not None else None
        self._tenant_weights = dict(tenants or {})
        self._default_weight = max(1, int(default_weight))
        self.max_pending = max_pending
        self.max_hedges = int(max_hedges)
        self.max_failovers = int(max_failovers)
        self.quarantine_limit = int(quarantine_limit)
        self.wedge_after_s = wedge_after_s
        self.drain_timeout_s = float(drain_timeout_s)
        self.tick_s = float(tick_s)
        self._warm_kwargs = dict(warm_kwargs or {})
        self._lock = threading.RLock()
        # rid -> active ReplicaHandle
        self._replicas: dict = {}                       # guarded-by: _lock
        # drained handles (health still summed)
        self._retired: list = []                        # guarded-by: _lock
        self._target = int(replicas)
        # tenant -> heap of (-prio, seq, freq)
        self._queues: dict = {}                         # guarded-by: _lock
        # tenant -> admitted-unresolved count
        self._outstanding: dict = {}                    # guarded-by: _lock
        # (freq, rid, exc) failure reports
        self._events: deque = deque()                   # guarded-by: _lock
        self._seq = itertools.count()
        self._next_fid = 0                              # guarded-by: _lock
        self._next_rep = 0                              # guarded-by: _lock
        self._closed = False                            # guarded-by: _lock
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: obs emit handle (``router#N``): the counters the hand-rolled
        #: stats dict used to hold now live in the process metrics registry
        #: (obs/metrics.py); :attr:`stats` is a read-only view over it.
        self.metrics = metrics.scope("router")
        # the initial fleet: a spawn failure here is fatal (chaos specs
        # targeting replica.spawn at cold start surface immediately)
        for _ in range(self._target):
            self._spawn_replica()
        if auto_start:
            self.start()

    @property
    def stats(self) -> dict:
        """Legacy router counters, rendered from the metrics registry."""
        m = self.metrics
        return {
            "submitted": m.value("router.submitted"),
            "completed": m.value("router.completed"),
            "failed": m.value("router.failed"),
            "rejected": m.value("router.rejected"),
            "rejected_by_tenant": m.by_key("router.rejected_by_tenant"),
            "placements": m.value("router.placements"),
            "hedges": m.value("router.hedges"),
            "failovers": m.value("router.failovers"),
            "replicas_spawned": m.value("router.replicas_spawned"),
            "replicas_retired": m.value("router.replicas_retired"),
            "spawn_failures": m.value("router.spawn_failures"),
            "loop_errors": m.value("router.loop_errors"),
        }

    # -------------------------------------------------------------- replicas

    def _spawn_replica(self):
        """Build + warm + start one replica (the ``replica.spawn`` fault
        site fires first, so chaos can break the spawn path itself)."""
        with self._lock:
            rid = f"r{self._next_rep}"
            self._next_rep += 1
        faults.fire("replica.spawn", tag=f"replica:{rid}|")
        rep = self._factory(rid)
        # the FULL config set, sp included — a replacement replica that
        # skipped an sp config would compile at its first failover ticket
        rep.warm(self._configs, self._buckets, **self._warm_kwargs)
        rep.start()
        with self._lock:
            self._replicas[rid] = rep
        self.metrics.inc("router.replicas_spawned")
        # replica lifetime span: its own trace, closed at retirement — a
        # chaos run's trace export shows exactly when each replica lived
        rep._obs_span = spans.begin("replica.lifetime", replica=rid) or None
        return rep

    def _retire(self, rid: str, rep) -> None:
        """Pull a bad replica out of rotation and drain it. Its queued
        engine tickets fail with EngineClosedError; their done-callbacks
        push failover events, which the next loop pass re-places onto
        survivors."""
        with self._lock:
            self._replicas.pop(rid, None)
            self._retired.append(rep)
        self.metrics.inc("router.replicas_retired")
        sp = getattr(rep, "_obs_span", None)
        if sp is not None:
            sp.end(retired=True)
        try:
            rep.drain(self.drain_timeout_s)
        except Exception:  # noqa: BLE001 — a broken drain must not stop
            pass           # supervision; the handle is out of rotation

    def _supervise(self) -> None:
        """Retire replicas whose snapshot shows them unhealthy, then spawn
        back up to the target count (a failed spawn leaves the deficit for
        the next tick — capped retry via the tick cadence)."""
        with self._lock:
            reps = list(self._replicas.items())
            closed = self._closed
        for rid, rep in reps:
            if rep.state in (fleet.DRAINING, fleet.CLOSED):
                # the replica left READY on its own — a subprocess handle
                # that detected its process dead self-transitions to closed
                # (crash detection), and an in-process replica can be
                # drained behind the router's back. Either way it can never
                # serve again (lifecycle is one-way): retire the
                # bookkeeping so a replacement spawns below.
                self._retire(rid, rep)
                continue
            if rep.state != fleet.READY:
                continue
            try:
                h = rep.health()
            except Exception:  # noqa: BLE001 — an unreachable replica is
                self._retire(rid, rep)  # by definition unhealthy
                continue
            wedged = (self.wedge_after_s is not None
                      and h.get("open_tickets", 0) > 0
                      and h.get("last_progress_s", 0.0) > self.wedge_after_s)
            if (h.get("stalled") or h.get("closed") or wedged
                    or h.get("quarantined", 0) >= self.quarantine_limit):
                self._retire(rid, rep)
        if closed:
            return
        while True:
            with self._lock:
                if len(self._replicas) >= self._target:
                    return
            try:
                self._spawn_replica()
            except Exception:  # noqa: BLE001 — injected or real spawn
                # failure: count it, retry on the next tick
                self.metrics.inc("router.spawn_failures")
                return

    # -------------------------------------------------------------- scaling

    @property
    def target(self) -> int:
        """The replica count supervision converges the fleet to."""
        return self._target

    def scale_to(self, n: int) -> int:
        """Move the supervision target to ``n`` (the autoscaler's one
        lever). Scale-DOWN retires the least-loaded ready replicas
        immediately (their queued tickets fail over through the normal
        eviction path — no request is lost to a scale decision);
        scale-UP is left to the next supervision tick, which already owns
        spawn-with-retry. Returns the clamped target."""
        n = max(1, int(n))
        with self._lock:
            if self._closed:
                return self._target
            self._target = n
            # excess counts READY replicas only: a crashed/DRAINING handle
            # still in the dict is already leaving (supervision retires it)
            # and must not cost an extra ready victim its place
            ready = [(rid, rep) for rid, rep in self._replicas.items()
                     if rep.state == fleet.READY]
            excess = len(ready) - n
        if excess > 0:
            scored = []
            for rid, rep in ready:
                try:
                    h = rep.health()
                except Exception:  # noqa: BLE001 — unreachable sorts first
                    scored.append((-1, rid, rep))
                    continue
                scored.append((h.get("queue_depth", 0)
                               + h.get("open_tickets", 0), rid, rep))
            scored.sort(key=lambda s: (s[0], s[1]))
            victims = [(rid, rep) for _, rid, rep in scored[:excess]]
            for rid, rep in victims:
                self._retire(rid, rep)
        self._kick.set()
        return n

    # -------------------------------------------------------------- admission

    def _weight(self, tenant: str) -> int:
        return self._tenant_weights.get(tenant, self._default_weight)

    def _share(self, tenant: str) -> Optional[int]:
        """This tenant's admitted-unresolved cap: its weighted slice of
        ``max_pending`` over the declared tenant set (an undeclared tenant
        joins at ``default_weight``). No declared tenants → one shared
        pool."""
        if self.max_pending is None:
            return None
        if not self._tenant_weights:
            return self.max_pending
        w = self._weight(tenant)
        total_w = sum(self._tenant_weights.values())
        if tenant not in self._tenant_weights:
            total_w += w
        return max(1, (self.max_pending * w) // total_w)

    def submit(self, seed: Optional[int] = None, n: int = 1, *,
               rng=None, x_init=None, mask=None,
               config: Optional[SamplerConfig] = None,
               tenant: str = "default", priority: int = 0,
               deadline_s: Optional[float] = None, **kwargs) -> Ticket:
        """Queue a request with the fleet; returns a :class:`Ticket` with
        the engine ticket's exact surface (``result``/``exception``/
        ``done``; timeout messages embed the ROUTER health snapshot).

        Editing workloads submit exactly like at the engine: ``config.task``
        picks the task, ``x_init`` carries its image input, ``mask=`` the
        inpaint pixel selector (see ``Engine.submit``). With
        ``config.preview_every`` set, the replica's completed preview frames
        are forwarded to THIS ticket's ``previews()`` stream — a hedged
        re-placement re-delivers its schedule, deduped per step.

        ``tenant`` scopes fair-share admission; higher ``priority`` places
        first within a tenant. Raises :class:`QueueFullError` when the
        tenant is at its share and :class:`EngineClosedError` after
        :meth:`drain`.
        """
        if config is None:
            config = SamplerConfig(**kwargs)
        elif kwargs:
            raise ValueError(
                f"pass config OR keyword options, not both: {kwargs}")
        task = config.task
        if mask is not None and task != "inpaint":
            raise ValueError(
                f"mask= is the inpaint task's input (config.task={task!r})")
        if task != "sample" and x_init is None:
            raise ValueError(f"task {task!r} needs x_init= — its image "
                             "input (see Engine.submit)")
        if task == "inpaint" and mask is None:
            raise ValueError("inpaint needs mask= (binary, 1 = known pixel)")
        if x_init is not None:
            x_init = np.asarray(x_init, np.float32)
            if task != "interp":
                # interp keeps the caller's n (the path length); everything
                # else takes its row count from the batch input
                n = x_init.shape[0] if x_init.ndim == 4 else 1
        needs_key = (task in ("inpaint", "draft", "interp")
                     or (task == "sample" and x_init is None))
        if needs_key and seed is None and rng is None:
            raise ValueError("this request's init/noise draw is keyed — "
                             "pass seed= or rng=")
        n = int(n)
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        deadline = (time.perf_counter() + deadline_s
                    if deadline_s is not None else None)
        call = {"seed": seed, "n": n, "rng": rng, "x_init": x_init,
                "mask": mask, "config": config}
        with self._lock:
            if self._closed:
                raise EngineClosedError(
                    "router is drained — no new requests accepted")
            share = self._share(tenant)
            if share is not None:
                cur = self._outstanding.get(tenant, 0)
                total = sum(self._outstanding.values())
                if cur >= share or total >= self.max_pending:
                    self.metrics.inc("router.rejected")
                    self.metrics.inc("router.rejected_by_tenant", key=tenant)
                    raise QueueFullError(
                        f"tenant {tenant!r} at its fair share "
                        f"({cur}/{share} of max_pending={self.max_pending}, "
                        f"weight {self._weight(tenant)}) — request rejected; "
                        "other tenants keep their share")
            ticket = Ticket(n)
            ticket._health_cb = self.health
            freq = _FleetRequest(fid=self._next_fid, n=n, tenant=tenant,
                                 priority=int(priority), call=call,
                                 deadline=deadline, ticket=ticket)
            self._next_fid += 1
            if spans.enabled():
                # ONE trace per fleet request: every placement attempt —
                # hedges and failovers included — is a child of this span
                freq.span = spans.begin("router.request", fid=freq.fid,
                                        tenant=tenant, n=n) or None
                ticket.span = freq.span
            self._enqueue(freq)
            self._outstanding[tenant] = self._outstanding.get(tenant, 0) + 1
        self.metrics.inc("router.submitted")
        self._kick.set()
        return ticket

    def _enqueue(self, freq: _FleetRequest) -> None:  # requires: _lock
        heapq.heappush(self._queues.setdefault(freq.tenant, []),
                       (-freq.priority, next(self._seq), freq))

    # -------------------------------------------------------------- placement

    def _candidates(self, freq: _FleetRequest) -> list:
        """Healthy replicas, least-loaded first; replicas this request
        already failed on are skipped while an untried one exists (the
        hedge must land somewhere else)."""
        with self._lock:
            cands = [(rid, rep) for rid, rep in self._replicas.items()
                     if rep.state == fleet.READY]
        fresh = [(rid, rep) for rid, rep in cands if rid not in freq.tried]
        if fresh:
            cands = fresh
        scored = []
        for rid, rep in cands:
            try:
                h = rep.health()
            except Exception:  # noqa: BLE001 — unreachable ≠ placeable;
                continue       # supervision will retire it
            if h.get("stalled") or h.get("closed"):
                continue
            if h.get("quarantined", 0) >= self.quarantine_limit:
                continue
            load = h.get("queue_depth", 0) + h.get("open_tickets", 0)
            scored.append((load, rid, rep))
        scored.sort(key=lambda s: (s[0], s[1]))
        return [(rid, rep) for _, rid, rep in scored]

    def _try_place(self, freq: _FleetRequest) -> bool:
        """One placement attempt over the healthy candidates. Returns True
        when the queue entry is consumed (placed OR terminally failed);
        False leaves the request for the next tick."""
        if freq.deadline is not None:
            remaining = freq.deadline - time.perf_counter()
            if remaining <= 0:
                self._fail_freq(freq, DeadlineExceeded(
                    f"request {freq.fid} (tenant {freq.tenant!r}) missed "
                    "its deadline while queued at the router"))
                return True
        for rid, rep in self._candidates(freq):
            try:
                faults.fire(
                    "router.place",
                    tag=f"replica:{rid}|freq:{freq.fid}|"
                        f"tenant:{freq.tenant}|")
            except RETRYABLE_EXCEPTIONS:
                continue  # transient placement fault: next candidate
            except Exception as exc:  # noqa: BLE001 — injected permanent
                # placement fault: this request cannot be routed
                err = RequestFailedError(
                    f"placement of request {freq.fid} onto replica {rid!r} "
                    f"failed: {exc!r}")
                err.__cause__ = exc
                self._fail_freq(freq, err)
                return True
            deadline_s = None
            if freq.deadline is not None:
                deadline_s = max(0.0,
                                 freq.deadline - time.perf_counter())
            # per-attempt child span: the replica's engine parents ITS
            # request span under this ctx, so a hedged ticket's attempts
            # share one trace across replicas (freq.call stays untouched —
            # hedges re-issue it verbatim)
            att = (freq.span.child("router.attempt", replica=rid)
                   if freq.span is not None else None)
            try:
                t = rep.submit(deadline_s=deadline_s,
                               trace=att.ctx if att is not None else None,
                               **freq.call)
            except (QueueFullError, EngineClosedError):
                if att is not None:
                    att.end(outcome="backpressure")
                continue  # replica-level backpressure: next candidate
            except RETRYABLE_EXCEPTIONS:
                # transient boundary failure (unreachable RPC replica,
                # dropped frame): the request is NOT consumed — try the
                # next candidate, supervision decides the replica's fate
                if att is not None:
                    att.end(outcome="unreachable")
                continue
            except Exception as exc:  # noqa: BLE001 — a replica whose
                # submit breaks outright cannot hold the request
                if att is not None:
                    att.end(outcome="submit_error")
                err = RequestFailedError(
                    f"replica {rid!r} rejected request {freq.fid}: {exc!r}")
                err.__cause__ = exc
                self._fail_freq(freq, err)
                return True
            freq.tried.add(rid)
            freq.placed_on = rid
            self.metrics.inc("router.placements")
            if freq.call["config"].preview_every:
                # forward completed replica frames to the router ticket;
                # its per-step dedupe absorbs a hedge's re-delivery
                t.add_preview_callback(
                    lambda step, frames, f=freq:
                        f.ticket._preview(step, 0, f.n, frames))
            t.add_done_callback(
                lambda t_, f=freq, r=rid, a=att: self._on_ticket(f, r, t_, a))
            return True
        return False  # no healthy candidate right now: stay queued

    def _place_round(self) -> None:
        """Weighted round-robin placement: each pass gives every tenant
        with queued work up to ``weight`` placements, until nothing can be
        placed (no healthy replica, or queues empty)."""
        progress = True
        while progress and not self._stop.is_set():
            progress = False
            with self._lock:
                tenants = sorted(t for t, q in self._queues.items() if q)
            for tenant in tenants:
                for _ in range(self._weight(tenant)):
                    with self._lock:
                        q = self._queues.get(tenant)
                        if not q:
                            break
                        _, _, freq = heapq.heappop(q)
                    if freq.resolved:
                        continue
                    if self._try_place(freq):
                        progress = True
                    else:
                        with self._lock:
                            self._enqueue(freq)
                        break

    # ---------------------------------------------------- outcome handling

    def _on_ticket(self, freq: _FleetRequest, rid: str, t: Ticket,
                   att=None) -> None:
        """Done-callback of a placed engine ticket (runs on the replica's
        worker thread — keep it cheap: deliveries resolve inline, failures
        queue an event for the control thread's hedging logic)."""
        if t.failed:
            if att is not None:
                att.end(outcome="failed")
            with self._lock:
                self._events.append((freq, rid, t.exception(0)))
            self._kick.set()
            return
        if att is not None:
            att.end(outcome="completed")
        self._complete(freq, t.result(0))

    def _complete(self, freq: _FleetRequest, rows) -> None:
        with self._lock:
            if freq.resolved:
                return
            freq.resolved = True
            self._outstanding[freq.tenant] -= 1
        if freq.ticket._deliver(0, freq.n, rows):
            self.metrics.inc("router.completed")
            if freq.span is not None:
                freq.span.end(hedges=freq.hedges, failovers=freq.failovers)

    def _fail_freq(self, freq: _FleetRequest, exc: BaseException) -> None:
        with self._lock:
            if freq.resolved:
                return
            freq.resolved = True
            self._outstanding[freq.tenant] -= 1
        if freq.ticket._fail(exc):
            self.metrics.inc("router.failed")
            if freq.span is not None:
                freq.span.end(error=type(exc).__name__,
                              hedges=freq.hedges, failovers=freq.failovers)

    def _drain_events(self) -> None:
        while True:
            with self._lock:
                if not self._events:
                    return
                freq, rid, exc = self._events.popleft()
            self._handle_failure(freq, rid, exc)

    def _handle_failure(self, freq: _FleetRequest, rid: str,
                        exc: BaseException) -> None:
        """Decide a failed placement's fate: hedge (retryable cause, once),
        fail over (the replica died under it), or fail through with the
        replica-naming error."""
        if freq.resolved:
            return
        if isinstance(exc, RequestQuarantinedError):
            # bisection proved the REQUEST is the poison — hedging it would
            # just quarantine it again on the next replica
            self._fail_freq(freq, exc)
            return
        cause = exc.__cause__ if exc.__cause__ is not None else exc
        retryable = isinstance(exc, RETRYABLE_EXCEPTIONS) \
            or isinstance(cause, RETRYABLE_EXCEPTIONS)
        evicted = isinstance(exc, (EngineClosedError, EngineStalledError))
        if retryable and freq.hedges < self.max_hedges:
            kind = "hedge"
            freq.hedges += 1
            self.metrics.inc("router.hedges")
        elif evicted and freq.failovers < self.max_failovers:
            kind = "failover"
            freq.failovers += 1
            self.metrics.inc("router.failovers")
        else:
            self._fail_freq(freq, exc)
            return
        if self._closed:
            # no re-placement after drain started — fail through typed
            self._fail_freq(freq, exc)
            return
        try:
            faults.fire("router.failover",
                        tag=f"replica:{rid}|freq:{freq.fid}|kind:{kind}|")
        except Exception as fexc:  # noqa: BLE001 — injected failover fault:
            # the re-placement path itself is broken, fail through
            err = RequestFailedError(
                f"fleet {kind} of request {freq.fid} away from replica "
                f"{rid!r} failed: {fexc!r}")
            err.__cause__ = fexc
            self._fail_freq(freq, err)
            return
        with self._lock:
            freq.placed_on = None
            self._enqueue(freq)
        self._kick.set()

    # ---------------------------------------------------------- control loop

    def start(self) -> None:
        """Start the control loop (idempotent). Placement, hedging,
        supervision, and replacement all happen here — one thread, so
        replica bookkeeping needs no cross-thread coordination."""
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(target=self._loop, name="router",
                                            daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.tick_s)
            self._kick.clear()
            try:
                self._drain_events()
                self._supervise()
                self._place_round()
            except Exception:  # noqa: BLE001 — the control loop must
                # survive anything; a dead loop would strand every ticket
                self.metrics.inc("router.loop_errors")

    # ------------------------------------------------------------- shutdown

    def drain(self, timeout: Optional[float] = None) -> dict:
        """Graceful fleet shutdown: stop admission, let the control loop
        finish placing/hedging what is in flight (bounded by ``timeout``),
        drain every replica, then fail anything still queued with
        :class:`EngineClosedError`. Returns the final health snapshot."""
        with self._lock:
            self._closed = True
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        while True:
            with self._lock:
                busy = (any(self._queues.values())
                        or any(c > 0 for c in self._outstanding.values())
                        or bool(self._events))
            if not busy:
                break
            if deadline is not None and time.perf_counter() > deadline:
                break
            self._kick.set()
            time.sleep(self.tick_s)
        self._stop.set()
        self._kick.set()
        thread = self._thread
        if thread is not None:
            thread.join(5.0)
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            try:
                rep.drain(self.drain_timeout_s)
            except Exception:  # noqa: BLE001 — best-effort shutdown
                pass
            sp = getattr(rep, "_obs_span", None)
            if sp is not None:
                sp.end(retired=False)
        # replica drains may have produced final failure events; with the
        # fleet closed, _handle_failure fails them through typed
        self._drain_events()
        with self._lock:
            leftovers = [f for q in self._queues.values() for _, _, f in q]
            for q in self._queues.values():
                q.clear()
        for freq in leftovers:
            self._fail_freq(freq, EngineClosedError(
                f"router drained with request {freq.fid} "
                f"(tenant {freq.tenant!r}) still queued"))
        return self.health()

    def close(self) -> dict:
        return self.drain(self.drain_timeout_s)

    # --------------------------------------------------------------- health

    def health(self) -> dict:
        """Fleet snapshot: per-replica health (active AND retired — a
        retired replica's compile counter still counts against the fleet
        zero-compile contract), queue/outstanding by tenant, and the
        router's own counters. ``compiles_after_warmup`` sums every
        replica's per-own-warm count, replacement included."""
        with self._lock:
            reps = list(self._replicas.items())
            retired = [(r.replica_id, r) for r in self._retired]
            pending = {t: len(q) for t, q in self._queues.items() if q}
            outstanding = {t: c for t, c in self._outstanding.items() if c}
            closed = self._closed
        rep_health = {}
        compiles_after_warmup = 0
        for rid, rep in reps + retired:
            try:
                h = rep.health()
            except Exception:  # noqa: BLE001 — an unreachable replica
                h = {"state": rep.state, "unreachable": True}
            rep_health[rid] = h
            compiles_after_warmup += h.get("compiles_after_warmup", 0)
        return {
            "replicas": rep_health,
            "active_replicas": len(reps),
            "retired_replicas": len(retired),
            "pending_by_tenant": pending,
            "outstanding_by_tenant": outstanding,
            "closed": closed,
            "compiles_after_warmup": compiles_after_warmup,
            **self.stats,
        }
