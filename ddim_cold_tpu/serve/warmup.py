"""Startup warmup: compile every serving program before the first request.

Two layers, matching the two restart costs:

* **Process-level** — ``warmup(engine, configs)`` AOT-compiles every
  (config, bucket) pair through ``Engine.ensure_program``, so the first
  request pays zero compile latency and the engine's compile counter is
  frozen for the lifetime of the process (the compile-count guard tests
  assert exactly this).

* **Restart-level** — JAX's persistent compilation cache
  (utils/platform.enable_compile_cache) is wired first, so the XLA
  executables land on disk and the NEXT process's warmup is a disk read,
  not minutes of XLA. Cache failure is non-fatal (purely an accelerant).

Warmup cost is O(configs × buckets) compiles per replica, but many served
configs lower to the SAME program — ``preview_every`` values beyond the
on/off bit never reach the trace, a ``student`` config runs the teacher's
executable on different params, and the few-step ``k`` field is dead when
``steps`` is set. Warmup therefore fingerprints each key before compiling
(``Engine.program_fingerprint`` — trace-only, milliseconds) and ALIASES a
key whose fingerprint was already compiled this call
(``Engine.adopt_program``) instead of paying XLA again. The fingerprint
pairs the constant-blind ``signature_hash`` with a digest of the traced
constants, so two programs only alias when both the structure and every
baked coefficient table match byte-for-byte — aliasing can never change
an output bit.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ddim_cold_tpu.serve.batching import SamplerConfig
from ddim_cold_tpu.utils.platform import enable_compile_cache


def warmup(engine, configs: Sequence[SamplerConfig],
           buckets: Optional[Sequence[int]] = None, *,
           persistent_cache: bool = True,
           cache_dir: Optional[str] = None,
           dedup: bool = True,
           tolerate_errors: bool = False) -> dict:
    """Compile every (config, bucket) program the engine may dispatch.

    ``configs`` is the exact set of :class:`SamplerConfig` the deployment
    serves (an unlisted config would compile lazily at serve time — counted,
    and caught by the guard test). Editing workloads are ordinary configs
    here — ``workloads.default_edit_configs()`` is the ready-made set
    covering every task (preview-enabled variants are distinct programs:
    warm them with the ``preview_every`` you serve). Returns a report with
    the number of new compiles, total resident programs, and the
    persistent-cache directory (None when disabled or the running JAX lacks
    the feature).

    ``dedup=True`` (the default) fingerprints each uncompiled (config,
    bucket) key first and aliases it to an executable already built this
    call when the fingerprints match (see the module docstring) — the
    report's ``deduped`` counts the compiles avoided, and
    ``new_compiles + deduped`` equals the number of keys warmed fresh.
    ``dedup=False`` restores one compile per key (the fingerprint trace
    itself is skipped too).

    ``tolerate_errors=True`` keeps warming the remaining programs when one
    compile fails (degraded startup beats no startup: a config whose compile
    is broken will fail at its own dispatch, not take the deployment down);
    the per-program exceptions land in ``report["errors"]``.

    Sequence-parallel configs (``sp_degree > 1``) warm like any other: the
    first ``ensure_program`` that needs a degree builds its (data, seq)
    mesh, the sp model clone, AND the param tree re-placed on that mesh, so
    a warmed engine serves sp requests with zero serve-time compiles and
    zero serve-time param placements. Cached configs additionally get their
    spare step-cache carry pre-allocated on the config's mesh
    (:meth:`Engine.prewarm_cache`), so the first dispatch donates a
    pool-owned buffer instead of paying the allocation inline. The report's
    ``sp_meshes`` lists the geometries built (``{degree: {axis: size}}``).
    """
    buckets = tuple(buckets) if buckets is not None else engine.buckets
    active_dir = enable_compile_cache(cache_dir) if persistent_cache else None
    before = engine.stats["compiles"]
    errors: dict = {}
    deduped = 0
    seen: dict = {}  # fingerprint -> (config, bucket) that compiled it
    can_dedup = dedup and hasattr(engine, "program_fingerprint")
    for config in configs:
        for bucket in buckets:
            key = (config, bucket)
            try:
                fp = None
                if can_dedup and key not in engine._programs:
                    try:
                        fp = engine.program_fingerprint(config, bucket)
                    except Exception:  # noqa: BLE001 — trace-only accelerant:
                        fp = None      # let the compile path raise its error
                src = seen.get(fp) if fp is not None else None
                if src is not None:
                    engine.adopt_program(config, bucket, src)
                    deduped += 1
                else:
                    engine.ensure_program(config, bucket)
                    if fp is not None:
                        seen[fp] = key
                if config.cached:
                    engine.prewarm_cache(config, bucket)
            except Exception as exc:  # noqa: BLE001 — optionally isolated
                if not tolerate_errors:
                    raise
                errors[key] = exc
    m = getattr(engine, "metrics", None)
    if m is not None:
        m.inc("warmup.new_compiles", engine.stats["compiles"] - before)
        m.inc("warmup.deduped", deduped)
        m.gauge("warmup.programs", len(engine._programs))
    return {
        "new_compiles": engine.stats["compiles"] - before,
        "deduped": deduped,
        "programs": len(engine._programs),
        "buckets": buckets,
        "configs": len(set(configs)),
        "cache_dir": active_dir,
        "sp_meshes": {d: dict(m.shape)
                      for d, m in getattr(engine, "_sp_meshes", {}).items()},
        "errors": errors,
    }
