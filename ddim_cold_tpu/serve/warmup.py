"""Startup warmup: compile every serving program before the first request.

Two layers, matching the two restart costs:

* **Process-level** — ``warmup(engine, configs)`` AOT-compiles every
  (config, bucket) pair through ``Engine.ensure_program``, so the first
  request pays zero compile latency and the engine's compile counter is
  frozen for the lifetime of the process (the compile-count guard tests
  assert exactly this).

* **Restart-level** — JAX's persistent compilation cache
  (utils/platform.enable_compile_cache) is wired first, so the XLA
  executables land on disk and the NEXT process's warmup is a disk read,
  not minutes of XLA. Cache failure is non-fatal (purely an accelerant).
"""

from __future__ import annotations

from typing import Optional, Sequence

from ddim_cold_tpu.serve.batching import SamplerConfig
from ddim_cold_tpu.utils.platform import enable_compile_cache


def warmup(engine, configs: Sequence[SamplerConfig],
           buckets: Optional[Sequence[int]] = None, *,
           persistent_cache: bool = True,
           cache_dir: Optional[str] = None,
           tolerate_errors: bool = False) -> dict:
    """Compile every (config, bucket) program the engine may dispatch.

    ``configs`` is the exact set of :class:`SamplerConfig` the deployment
    serves (an unlisted config would compile lazily at serve time — counted,
    and caught by the guard test). Editing workloads are ordinary configs
    here — ``workloads.default_edit_configs()`` is the ready-made set
    covering every task (preview-enabled variants are distinct programs:
    warm them with the ``preview_every`` you serve). Returns a report with
    the number of new compiles, total resident programs, and the
    persistent-cache directory (None when disabled or the running JAX lacks
    the feature).

    ``tolerate_errors=True`` keeps warming the remaining programs when one
    compile fails (degraded startup beats no startup: a config whose compile
    is broken will fail at its own dispatch, not take the deployment down);
    the per-program exceptions land in ``report["errors"]``.

    Sequence-parallel configs (``sp_degree > 1``) warm like any other: the
    first ``ensure_program`` that needs a degree builds its (data, seq)
    mesh, the sp model clone, AND the param tree re-placed on that mesh, so
    a warmed engine serves sp requests with zero serve-time compiles and
    zero serve-time param placements. Cached configs additionally get their
    spare step-cache carry pre-allocated on the config's mesh
    (:meth:`Engine.prewarm_cache`), so the first dispatch donates a
    pool-owned buffer instead of paying the allocation inline. The report's
    ``sp_meshes`` lists the geometries built (``{degree: {axis: size}}``).
    """
    buckets = tuple(buckets) if buckets is not None else engine.buckets
    active_dir = enable_compile_cache(cache_dir) if persistent_cache else None
    before = engine.stats["compiles"]
    errors: dict = {}
    for config in configs:
        for bucket in buckets:
            try:
                engine.ensure_program(config, bucket)
                if config.cached:
                    engine.prewarm_cache(config, bucket)
            except Exception as exc:  # noqa: BLE001 — optionally isolated
                if not tolerate_errors:
                    raise
                errors[(config, bucket)] = exc
    m = getattr(engine, "metrics", None)
    if m is not None:
        m.inc("warmup.new_compiles", engine.stats["compiles"] - before)
        m.gauge("warmup.programs", len(engine._programs))
    return {
        "new_compiles": engine.stats["compiles"] - before,
        "programs": len(engine._programs),
        "buckets": buckets,
        "configs": len(set(configs)),
        "cache_dir": active_dir,
        "sp_meshes": {d: dict(m.shape)
                      for d, m in getattr(engine, "_sp_meshes", {}).items()},
        "errors": errors,
    }
