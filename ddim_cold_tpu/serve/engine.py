"""AOT-compiled dispatch loop: the engine half of the serving subsystem.

Throughput comes from three structural moves, none of which touch the math:

* **Zero serve-time compiles** — every (config, bucket) pair is compiled
  ahead of time via the jitted scans' AOT path (``.lower(...).compile()``)
  and dispatch only ever calls those executables. A compiled executable can
  NOT retrace — a shape it wasn't built for raises instead of silently
  recompiling — so "no compiles after warmup" is structural, not hopeful.
  ``stats["compiles"]`` counts program builds; after ``warmup()`` it must
  not move.

* **Transfer/compute overlap** — batch assembly (per-request init draws, the
  guided path's H2D upload, padding, mesh placement) runs ``depth`` batches
  ahead in a background thread (the ``device_prefetch`` machinery from
  data/loader.py), while the main loop keeps a small in-flight window of
  dispatched batches and fetches batch n−w (D2H) while the device scans
  batch n. JAX dispatch is async, so the three phases pipeline.

* **Buffer donation** — the scans donate ``x_init`` and the step-cache
  carry (ops/sampling.py), so a dispatch peaks at one x-sized buffer, and
  the engine recycles the returned cache as the next batch's donated
  ``cache0`` (legal: the cache schedule's step 0 always refreshes, so stale
  contents are never read) — cached serving allocates its cache once per
  bucket, ever.

**Bitwise contract.** Engine output rows are bitwise identical to a direct
``ddim_sample``/``cold_sample``/``sample_from`` call with the same request
rng: the engine draws each request's init at the request's OWN ``n`` with the
request's own key (exactly the draw the direct call makes — the values depend
on ``n``), and row slices of that draw keep their bits; every sampler row is
then computed independently of its batchmates (per-row trunk), so neither
coalescing, padding, nor splitting changes a single bit. This holds for the
deterministic samplers only — which is why ``SamplerConfig`` has no ``eta``
(batch-shaped noise draws break row invariance) — and exactly per-backend
(a mesh reduces in a different order than one device; same as training).
A quant config keeps the same contract against a direct call on the
quantized model/params pair (``model.clone(quant=...)`` +
``quant.quantize_params(params)`` — the deterministic transform the engine
itself applies).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.data.loader import device_prefetch
from ddim_cold_tpu.ops import sampling, step_cache
from ddim_cold_tpu.parallel.mesh import batch_sharding, data_axis_size, shard_params
from ddim_cold_tpu.serve.batching import (BatchPlan, Request, SamplerConfig,
                                          Ticket, plan_batches)
from ddim_cold_tpu.utils.profiling import latency_summary


class Engine:
    """Bucketed continuous-batching sampler server.

    ::

        eng = Engine(model, params, mesh=mesh, buckets=(8, 32, 128))
        serve.warmup(eng, [SamplerConfig(k=10)])
        tickets = [eng.submit(seed=s, n=5) for s in range(40)]
        eng.run()
        imgs = tickets[0].result()   # (5, H, W, C) in [0, 1]

    ``submit`` is thread-safe and returns immediately; ``run`` drains the
    queue (requests submitted mid-run join the next planning round).
    """

    def __init__(self, model, params, mesh=None,
                 buckets: Sequence[int] = (8, 32, 128), *,
                 prefetch_depth: int = 2, inflight: int = 2):
        self.model = model
        self.mesh = mesh
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets!r}")
        shards = data_axis_size(mesh)
        bad = [b for b in self.buckets if b % shards]
        if bad:
            raise ValueError(
                f"buckets {bad} do not divide the mesh data axis ({shards}); "
                "sharded placement needs even divisibility")
        self.params = shard_params(params, mesh) if mesh is not None else params
        self.prefetch_depth = int(prefetch_depth)
        self.inflight = max(1, int(inflight))
        # any key works here: the deterministic scans never read noise_rng
        # (eta is pinned to 0.0 at program build — see module docstring)
        self._key0 = jax.random.PRNGKey(0)
        self._programs: dict = {}
        self._spare_caches: dict = {}  # bucket -> recycled step-cache carry
        # w8a16 serving (ops/quant.py): the int8 tree is built ONCE from the
        # float params on the first quant config and shipped/pinned like the
        # float tree — every quant dispatch reuses the same device buffers
        # (≈4× fewer trunk-param bytes over the link than the float tree).
        self._qparams = None
        self._quant_models: dict = {}  # quant mode -> model clone (hash key)
        self._pending: list[Request] = []
        self._lock = threading.Lock()
        self.stats = {"compiles": 0, "dispatches": 0, "rows": 0,
                      "padded_rows": 0, "max_queue_depth": 0,
                      "latencies_s": [], "param_bytes": None,
                      "param_bytes_quant": None}

    # ---------------------------------------------------------------- submit

    def submit(self, seed: Optional[int] = None, n: int = 1, *,
               rng: Optional[jax.Array] = None,
               x_init: Optional[np.ndarray] = None,
               config: Optional[SamplerConfig] = None, **kwargs) -> Ticket:
        """Queue a sampling request; returns its :class:`Ticket`.

        Fresh starts pass ``seed`` (or a jax ``rng`` key) — the engine draws
        the same init the direct sampler would from that key. Guided requests
        pass ``x_init`` (an (n, H, W, C) or (H, W, C) encoded start; pair it
        with ``t_start`` — the ``sample_from`` path). Sampler options go in
        ``config`` or as keyword args (``k=, t_start=, cache_interval=, …``).
        """
        if config is None:
            config = SamplerConfig(**kwargs)
        elif kwargs:
            raise ValueError(f"pass config OR keyword options, not both: {kwargs}")
        if x_init is not None:
            if config.sampler != "ddim":
                raise ValueError("guided starts (x_init) are a DDIM path; "
                                 "cold sampling has no encoded-start analogue")
            x_init = np.asarray(x_init, np.float32)
            if x_init.ndim == 3:
                x_init = x_init[None]
            if x_init.ndim != 4:
                raise ValueError(f"x_init must be (n, H, W, C) or (H, W, C), "
                                 f"got shape {x_init.shape}")
            n = x_init.shape[0]
            key = None
        else:
            if rng is None:
                if seed is None:
                    raise ValueError("fresh requests need seed= or rng=")
                rng = jax.random.PRNGKey(int(seed))
            key = rng
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        req = Request(config=config, n=int(n), key=key, x_init=x_init,
                      ticket=Ticket(n))
        with self._lock:
            self._pending.append(req)
            depth = len(self._pending)
        self.stats["max_queue_depth"] = max(self.stats["max_queue_depth"], depth)
        return req.ticket

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # ------------------------------------------------------------- programs

    def ensure_program(self, config: SamplerConfig, bucket: int):
        """The ONLY compile site. Dispatch calls this too — a serve-time miss
        (a config/bucket warmup didn't cover) compiles and is counted, so
        ``stats['compiles']`` staying flat after warmup proves zero serve-time
        compiles."""
        key = (config, bucket)
        prog = self._programs.get(key)
        if prog is None:
            prog = self._build_program(config, bucket)
            self._programs[key] = prog
            self.stats["compiles"] += 1
        return prog

    def _model_for(self, config: SamplerConfig):
        """The model variant a config's programs trace: ``quant`` is a field
        of the (hash-by-value) module, so quant and float programs can never
        collide in jit/AOT caches."""
        if not config.quant:
            return self.model
        model = self._quant_models.get(config.quant)
        if model is None:
            model = self._quant_models[config.quant] = self.model.clone(
                quant=config.quant)
        return model

    def _params_for(self, config: SamplerConfig):
        if not config.quant:
            return self.params
        if self._qparams is None:
            from ddim_cold_tpu.ops import quant

            qp = quant.quantize_params(self.params)
            self._qparams = (shard_params(qp, self.mesh)
                             if self.mesh is not None else qp)
            self.stats["param_bytes"] = quant.param_bytes(self.params)
            self.stats["param_bytes_quant"] = quant.param_bytes(self._qparams)
        return self._qparams

    def _x_struct(self, bucket: int):
        H, W = self.model.img_size
        sharding = batch_sharding(self.mesh) if self.mesh is not None else None
        return jax.ShapeDtypeStruct((bucket, H, W, self.model.in_chans),
                                    jnp.float32, sharding=sharding)

    def _cache_struct(self, bucket: int):
        shape = (bucket, self.model.num_patches + 1, self.model.embed_dim)
        sharding = batch_sharding(self.mesh) if self.mesh is not None else None
        s = jax.ShapeDtypeStruct(shape, self.model.dtype, sharding=sharding)
        return (s, s)

    def _build_program(self, config: SamplerConfig, bucket: int):
        """AOT-compile the scan for this (config, bucket): trace with shape
        structs (no dummy allocation), compile, return the executable. The
        executable is called with the NON-static args only (params, x, …)."""
        x = self._x_struct(bucket)
        model, params = self._model_for(config), self._params_for(config)
        if config.sampler == "cold":
            if config.cached:
                return _cold_cached_lower(model, params, x,
                                          self._cache_struct(bucket), config)
            return sampling._cold_scan.lower(
                model, params, x, levels=config.levels,
                return_sequence=False).compile()
        if config.cached:
            return _ddim_cached_lower(model, params, x, self._key0,
                                      self._cache_struct(bucket), config)
        return sampling._ddim_scan_last.lower(
            model, params, x, self._key0, k=config.k,
            t_start=config.t_start, eta=0.0).compile()

    # ------------------------------------------------------------- assembly

    def _request_init(self, req: Request) -> jax.Array:
        """The request's full init, drawn once at the request's own n —
        bitwise the direct sampler's draw (which depends on n); batches then
        take row slices (which don't)."""
        if req._x_full is None:
            H, W = self.model.img_size
            C = self.model.in_chans
            if req.x_init is not None:
                req._x_full = jnp.asarray(req.x_init, jnp.float32)
            elif req.config.sampler == "cold":
                color = jax.random.normal(req.key, (req.n, 1, 1, C),
                                          jnp.float32)
                req._x_full = jnp.broadcast_to(color, (req.n, H, W, C))
            else:
                req._x_full = jax.random.normal(req.key, (req.n, H, W, C),
                                                jnp.float32)
        return req._x_full

    def _assemble(self, plan: BatchPlan):
        """Background-thread H2D stage: build the padded bucket batch on
        device (init draws dispatch async; guided numpy starts upload here,
        overlapping the main loop's compute)."""
        parts = [self._request_init(req)[lo:hi]
                 for req, lo, hi, _ in plan.entries]
        if plan.padded_rows:
            H, W = self.model.img_size
            parts.append(jnp.zeros((plan.padded_rows, H, W,
                                    self.model.in_chans), jnp.float32))
        x = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        if self.mesh is not None:
            x = jax.device_put(x, batch_sharding(self.mesh))
        return plan, x

    # ------------------------------------------------------------- dispatch

    def _take_cache(self, bucket: int):
        cache = self._spare_caches.pop(bucket, None)
        if cache is None:
            cache = step_cache.init_cache(bucket, self.model.num_patches + 1,
                                          self.model.embed_dim,
                                          self.model.dtype)
            cache = step_cache.shard_cache(cache, self.mesh)
        return cache

    def _dispatch(self, plan: BatchPlan, x: jax.Array):
        prog = self.ensure_program(plan.config, plan.bucket)
        params = self._params_for(plan.config)
        if plan.config.sampler == "cold":
            if plan.config.cached:
                out, cache_out = prog(params, x,
                                      self._take_cache(plan.bucket))
                self._spare_caches[plan.bucket] = cache_out
            else:
                out = prog(params, x)
        elif plan.config.cached:
            out, cache_out = prog(params, x, self._key0,
                                  self._take_cache(plan.bucket))
            self._spare_caches[plan.bucket] = cache_out
        else:
            out = prog(params, x, self._key0)
        self.stats["dispatches"] += 1
        self.stats["rows"] += plan.rows
        self.stats["padded_rows"] += plan.padded_rows
        return out

    def _finish(self, plan: BatchPlan, out) -> None:
        """D2H + delivery: one blocking fetch per batch, rows copied into
        each ticket's buffer; padding rows are simply never read."""
        host = np.asarray(out)
        for req, lo, hi, offset in plan.entries:
            if req.ticket._deliver(lo, hi, host[offset:offset + (hi - lo)]):
                self.stats["latencies_s"].append(req.ticket.latency_s)

    # ------------------------------------------------------------------ run

    def run(self) -> dict:
        """Drain the queue: plan → assemble (background) → dispatch → fetch,
        pipelined. Returns a report for this drain (throughput over real
        rows — padding is excluded from img/s by construction)."""
        t0 = time.perf_counter()
        compiles0 = self.stats["compiles"]
        rows = padded = batches = 0
        completed: list[float] = []
        n_lat0 = len(self.stats["latencies_s"])
        while True:
            with self._lock:
                pending, self._pending = self._pending, []
            if not pending:
                break
            plans = plan_batches(pending, self.buckets)
            inflight: deque = deque()
            for plan, x in device_prefetch(plans, lambda p: self._assemble(p),
                                           depth=self.prefetch_depth):
                inflight.append((plan, self._dispatch(plan, x)))
                rows += plan.rows
                padded += plan.padded_rows
                batches += 1
                while len(inflight) > self.inflight:
                    self._finish(*inflight.popleft())
            while inflight:
                self._finish(*inflight.popleft())
        wall = time.perf_counter() - t0
        completed = self.stats["latencies_s"][n_lat0:]
        return {
            "batches": batches,
            "rows": rows,
            "padded_rows": padded,
            "wall_s": wall,
            "img_per_sec": rows / wall if wall > 0 else 0.0,
            "latency": latency_summary(completed),
            "compiles": self.stats["compiles"] - compiles0,
            "max_queue_depth": self.stats["max_queue_depth"],
        }


def _ddim_cached_lower(model, params, x, key, cache, config: SamplerConfig):
    return sampling._ddim_scan_cached.lower(
        model, params, x, key, cache, k=config.k, t_start=config.t_start,
        eta=0.0, cache_interval=config.cache_interval,
        cache_mode=config.cache_mode, sequence=False).compile()


def _cold_cached_lower(model, params, x, cache, config: SamplerConfig):
    return sampling._cold_scan_cached.lower(
        model, params, x, cache, levels=config.levels, return_sequence=False,
        cache_interval=config.cache_interval,
        cache_mode=config.cache_mode).compile()
