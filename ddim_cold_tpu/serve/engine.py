"""AOT-compiled dispatch loop: the engine half of the serving subsystem.

Throughput comes from three structural moves, none of which touch the math:

* **Zero serve-time compiles** — every (config, bucket) pair is compiled
  ahead of time via the jitted scans' AOT path (``.lower(...).compile()``)
  and dispatch only ever calls those executables. A compiled executable can
  NOT retrace — a shape it wasn't built for raises instead of silently
  recompiling — so "no compiles after warmup" is structural, not hopeful.
  ``stats["compiles"]`` counts program builds; after ``warmup()`` it must
  not move.

* **Transfer/compute overlap** — batch assembly (per-request init draws, the
  guided path's H2D upload, padding, mesh placement) runs ``depth`` batches
  ahead in a background thread (the ``device_prefetch`` machinery from
  data/loader.py), while the main loop keeps a small in-flight window of
  dispatched batches and fetches batch n−w (D2H) while the device scans
  batch n. JAX dispatch is async, so the three phases pipeline.

* **Buffer donation** — the scans donate ``x_init`` and the step-cache
  carry (ops/sampling.py), so a dispatch peaks at one x-sized buffer, and
  the engine recycles the returned cache as the next batch's donated
  ``cache0`` (legal: the cache schedule's step 0 always refreshes, so stale
  contents are never read) — cached serving allocates its cache once per
  bucket, ever.

**Failure isolation.** Every pipeline stage (assembly → dispatch → fetch) is
wrapped so an exception fails only the tickets of the batch it struck — the
engine keeps serving subsequent batches. Retryable faults (the transfer/RPC
class, ``errors.RETRYABLE_EXCEPTIONS``) get capped exponential backoff with
the donated input rebuilt per attempt; a batch that fails deterministically
is BISECTED on request boundaries — each half re-assembles (padded to the
same compiled bucket, so recovery never compiles) and re-dispatches until
the poisoned request is isolated and quarantined
(:class:`~.errors.RequestQuarantinedError`, stage exception as cause) while
its innocent batchmates complete. Admission control bounds the queue
(``max_queue`` → :class:`~.errors.QueueFullError` at submit) and per-request
deadlines are enforced at plan AND dispatch time (expired requests fail fast
with :class:`~.errors.DeadlineExceeded` instead of occupying a bucket).
:meth:`Engine.drain` stops admission, flushes in-flight batches, and
deterministically fails queued tickets. A soft-mode
:class:`~ddim_cold_tpu.utils.watchdog.StallWatchdog` bounds every silent
device window (a wedged tunnel hangs native calls with NO exception to
catch — the r03/r05 lesson): on stall it fails in-flight and queued tickets
(partial results already fetched stand) instead of hanging every waiter.
Chaos coverage injects faults at the ``serve.*`` sites
(utils/faults.py); with faults disarmed the fast path executes
byte-identical device code.

**Bitwise contract.** Engine output rows are bitwise identical to a direct
``ddim_sample``/``cold_sample``/``sample_from`` call with the same request
rng: the engine draws each request's init at the request's OWN ``n`` with the
request's own key (exactly the draw the direct call makes — the values depend
on ``n``), and row slices of that draw keep their bits; every sampler row is
then computed independently of its batchmates (per-row trunk), so neither
coalescing, padding, splitting, nor bisection recovery changes a single bit.
This holds for the deterministic samplers only — which is why
``SamplerConfig`` has no ``eta`` (batch-shaped noise draws break row
invariance) — and exactly per-backend (a mesh reduces in a different order
than one device; same as training). A quant config keeps the same contract
against a direct call on the quantized model/params pair
(``model.clone(quant=...)`` + ``quant.quantize_params(params)`` — the
deterministic transform the engine itself applies).

**Sequence parallelism.** A config with ``sp_degree > 1`` compiles its
programs against a per-degree ``(data, seq)`` mesh over the local devices
(``make_mesh({"data": n_dev // sp_degree, "seq": sp_degree})``) with the
model cloned to run its attention through ``ulysses_self_attention`` /
``ring_self_attention`` (patch tokens sequence-sharded inside the
shard_map, the CLS/time conditioning replicated like every other
non-sequence activation). The registry key is unchanged — ``(config,
bucket)`` — because ``sp_mode``/``sp_degree`` are fields of the hashed
config, so sp and non-sp programs can never collide and never coalesce
into one batch. ``sp_mode='ulysses'`` falls back to the ring when the
head count does not divide by the seq axis (Ulysses' structural
requirement; the ring has none). Contract-wise: the degenerate
``sp_degree=1`` IS the default config (``SamplerConfig`` rejects
``sp_mode != 'none'`` at degree 1), so degree-1 dispatches are bitwise
the existing serve path by identity, not by luck; ``sp_degree > 1``
output matches the degree-1 program at float tolerance only — the
seq-axis collectives reduce in a different order, same caveat as the
data mesh vs one device.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.data.loader import device_prefetch
from ddim_cold_tpu.obs import device as obs_device
from ddim_cold_tpu.obs import metrics, spans
from ddim_cold_tpu.ops import sampling, step_cache
from ddim_cold_tpu.parallel.mesh import (batch_sharding, data_axis_size,
                                         make_mesh, shard_params)
from ddim_cold_tpu.serve.batching import (BatchPlan, Request, SamplerConfig,
                                          Ticket, plan_batches)
from ddim_cold_tpu.serve.errors import (RETRYABLE_EXCEPTIONS, DeadlineExceeded,
                                        EngineClosedError, EngineStalledError,
                                        QueueFullError, RequestFailedError,
                                        RequestQuarantinedError)
from ddim_cold_tpu.utils import faults
from ddim_cold_tpu.utils.platform import watchdog_stall_s
from ddim_cold_tpu.workloads import preview as workload_preview
from ddim_cold_tpu.workloads import tasks as workload_tasks
from ddim_cold_tpu.utils.profiling import latency_summary
from ddim_cold_tpu.utils.watchdog import StallWatchdog

#: per-task batch inputs that ride along with x through assembly — sliced
#: per request row range, zero-padded, and placed exactly like the init
#: batch (Request.extras carries the host arrays; order here is the
#: program's positional argument order after x)
_EXTRA_INPUTS = {"inpaint": ("known", "mask")}


def _need_key(seed, rng) -> jax.Array:
    if rng is None:
        if seed is None:
            raise ValueError("this request's init/noise draw is keyed — "
                             "pass seed= or rng=")
        rng = jax.random.PRNGKey(int(seed))
    return rng


class Engine:
    """Bucketed continuous-batching sampler server.

    ::

        eng = Engine(model, params, mesh=mesh, buckets=(8, 32, 128))
        serve.warmup(eng, [SamplerConfig(k=10)])
        tickets = [eng.submit(seed=s, n=5) for s in range(40)]
        eng.run()
        imgs = tickets[0].result()   # (5, H, W, C) in [0, 1]

    ``submit`` is thread-safe and returns immediately; ``run`` drains the
    queue (requests submitted mid-run join the next planning round).
    ``drain()`` closes admission and fails anything still queued.
    """

    def __init__(self, model, params, mesh=None,
                 buckets: Sequence[int] = (8, 32, 128), *,
                 student_params=None,
                 prefetch_depth: int = 2, inflight: int = 2,
                 max_queue: Optional[int] = None,
                 max_retries: int = 2, retry_base_s: float = 0.05,
                 retry_cap_s: float = 1.0,
                 stall_s: Optional[float] = None,
                 replica_id: str = ""):
        self.model = model
        self.mesh = mesh
        # fleet identity: names this engine in fault tags ("replica:r0|" —
        # chaos specs can target one replica), failure messages, and the
        # health snapshot, so fleet-level failures are attributable
        self.replica_id = str(replica_id)
        self._rname = (f"replica {self.replica_id!r}" if self.replica_id
                       else "engine")
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets!r}")
        shards = data_axis_size(mesh)
        bad = [b for b in self.buckets if b % shards]
        if bad:
            raise ValueError(
                f"buckets {bad} do not divide the mesh data axis ({shards}); "
                "sharded placement needs even divisibility")
        self.params = shard_params(params, mesh) if mesh is not None else params
        # distilled few-step student (train/distill.py): same architecture,
        # different weights — shipped/pinned exactly like the teacher tree.
        # config.student routes _params_for here; the PROGRAM is shared with
        # the teacher at equal steps (params are a runtime argument), which
        # is what lets warmup dedup alias student configs for free.
        self.student_params = (shard_params(student_params, mesh)
                               if mesh is not None and student_params
                               is not None else student_params)
        self.prefetch_depth = int(prefetch_depth)
        self.inflight = max(1, int(inflight))
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 or None, got {max_queue}")
        self.max_queue = max_queue
        self.max_retries = int(max_retries)
        self.retry_base_s = float(retry_base_s)
        self.retry_cap_s = float(retry_cap_s)
        # stall budget for silent device windows; shared arm-condition with
        # the evidence scripts (0 on a local cpu backend unless the env
        # overrides — no tunnel to wedge there)
        self.stall_s = (watchdog_stall_s("DDIM_COLD_SERVE_STALL_S", 900.0)
                        if stall_s is None else float(stall_s))
        # any key works here: the deterministic scans never read noise_rng
        # (eta is pinned to 0.0 at program build — see module docstring)
        self._key0 = jax.random.PRNGKey(0)
        self._programs: dict = {}
        # (bucket, kind) -> recycled step-cache carry; kind per _cache_kind
        # (sp configs get their own kinds — a carry placed on a (data, seq)
        # mesh cannot be donated to a program compiled for another mesh)
        self._spare_caches: dict = {}
        # sequence parallelism: per-degree (data, seq) meshes over this
        # engine's devices, the sp model clones traced against them, and the
        # param trees re-placed on them (AOT executables are sharding-strict
        # — an sp program must see params on ITS mesh, not the engine's)
        self._sp_meshes: dict = {}   # sp_degree -> Mesh
        self._sp_models: dict = {}   # (mode, degree, quant) -> model clone
        self._sp_params: dict = {}   # (degree, quantized?, student?) -> tree
        # w8a16 serving (ops/quant.py): the int8 tree is built ONCE from the
        # float params on the first quant config and shipped/pinned like the
        # float tree — every quant dispatch reuses the same device buffers
        # (≈4× fewer trunk-param bytes over the link than the float tree).
        self._qparams = None
        self._qparams_student = None
        self._quant_models: dict = {}  # (quant, fused) -> model clone
        self._pending: list[Request] = []               # guarded-by: _lock
        # rid -> unresolved Request (stall fail set)
        self._open: dict = {}                           # guarded-by: _lock
        self._lock = threading.Lock()
        self._next_rid = 0                              # guarded-by: _lock
        self._closed = False                            # guarded-by: _lock
        self._stalled = False
        self._running = False
        self._wd: Optional[StallWatchdog] = None
        self._idle = threading.Event()
        self._idle.set()
        self._t0 = time.monotonic()
        # (monotonic time, label) of the last pipeline beacon — health()
        # surfaces its age as last_progress_s so a router can spot a wedged
        # replica from the snapshot alone, before the watchdog fires
        self._last_mark = (self._t0, "init")
        self.quarantined: list[int] = []  # rids bisection isolated
        #: obs emit handle (scope id ``engine#N`` — per instance, so a
        #: multi-replica fleet's counters never alias): every counter the
        #: old hand-rolled stats dict tracked now lives in the process
        #: metrics registry (obs/metrics.py); :attr:`stats` is a read-only
        #: legacy view rendered from it. Public so warmup() reports its
        #: compile counts under the engine it warmed.
        self.metrics = metrics.scope("engine")

    @property
    def stats(self) -> dict:
        """Legacy stats surface, rendered from the metrics registry — the
        same keys/semantics the hand-maintained dict had (``param_bytes``
        is None until the quant tree is built; ``latencies_s`` is the raw
        per-ticket sample list)."""
        m = self.metrics
        return {
            "compiles": m.value("engine.compiles"),
            "program_aliases": m.value("engine.program_aliases"),
            "dispatches": m.value("engine.dispatches"),
            "rows": m.value("engine.rows"),
            "padded_rows": m.value("engine.padded_rows"),
            "max_queue_depth": int(m.raw("engine.max_queue_depth") or 0),
            "preview_frames": m.value("engine.preview_frames"),
            "latencies_s": m.samples("engine.latency_s"),
            "param_bytes": m.raw("engine.param_bytes"),
            "param_bytes_quant": m.raw("engine.param_bytes_quant"),
            "retries": m.value("engine.retries"),
            "failed_batches": m.value("engine.failed_batches"),
            "failed_tickets": m.value("engine.failed_tickets"),
            "quarantined": m.value("engine.quarantined"),
            "deadline_expired": m.value("engine.deadline_expired"),
            "rejected": m.value("engine.rejected"),
            "skipped_batches": m.value("engine.skipped_batches"),
            "stalls": m.value("engine.stalls"),
        }

    # ---------------------------------------------------------------- submit

    def submit(self, seed: Optional[int] = None, n: int = 1, *,
               rng: Optional[jax.Array] = None,
               x_init: Optional[np.ndarray] = None,
               mask: Optional[np.ndarray] = None,
               config: Optional[SamplerConfig] = None,
               deadline_s: Optional[float] = None,
               trace=None, **kwargs) -> Ticket:
        """Queue a sampling request; returns its :class:`Ticket`.

        Fresh starts pass ``seed`` (or a jax ``rng`` key) — the engine draws
        the same init the direct sampler would from that key. Guided requests
        pass ``x_init`` (an (n, H, W, C) or (H, W, C) encoded start; pair it
        with ``t_start`` — the ``sample_from`` path). Sampler options go in
        ``config`` or as keyword args (``k=, t_start=, cache_interval=, …``).

        Editing workloads (``config.task`` in workloads.EDIT_TASKS) reuse
        ``x_init`` as the task's image input: the known image (``inpaint``,
        with ``mask=`` selecting the pixels to preserve), the upsampled
        low-res start (``superres`` — see ``workloads.superres_init``), the
        draft to forward-noise (``draft``), or the (2, H, W, C) endpoint pair
        (``interp``, where ``n`` stays the path length). ``inpaint``,
        ``draft`` and ``interp`` also need ``seed``/``rng`` — their noise
        draw is keyed exactly like the direct workloads.* call, which is what
        keeps the bitwise contract.

        ``deadline_s`` bounds the request's total time in the engine: past
        it, the request fails fast with :class:`DeadlineExceeded` instead of
        occupying a bucket. Raises :class:`QueueFullError` when the bounded
        queue is at ``max_queue`` and :class:`EngineClosedError` after
        :meth:`drain`.

        ``trace`` (an ``obs.spans`` TraceContext/Span, or None) parents this
        request's span when tracing is enabled — the fleet router passes its
        placement-attempt span here so hedged attempts land in ONE trace.
        With no parent, the request starts a fresh trace.
        """
        if config is None:
            config = SamplerConfig(**kwargs)
        elif kwargs:
            raise ValueError(f"pass config OR keyword options, not both: {kwargs}")
        task = config.task
        if mask is not None and task != "inpaint":
            raise ValueError(
                f"mask= is the inpaint task's input (config.task={task!r})")
        extras = None
        if task == "sample":
            if x_init is not None:
                if config.sampler != "ddim":
                    raise ValueError(
                        "guided starts (x_init) are a DDIM path; "
                        "cold sampling has no encoded-start analogue")
                x_init = self._as_batch(x_init)
                n = x_init.shape[0]
                key = None
            else:
                key = _need_key(seed, rng)
        else:
            if x_init is None:
                raise ValueError(
                    f"task {task!r} needs x_init= — its image input "
                    "(inpaint: known image; superres: upsampled low-res; "
                    "draft: the draft; interp: the (2, H, W, C) endpoints)")
            x_init = self._as_batch(x_init)
            if task == "interp":
                # n stays the caller's path length; x_init is the pair
                if x_init.shape[0] != 2:
                    raise ValueError(
                        "interp x_init is the endpoint PAIR (2, H, W, C) — "
                        f"n= is the path length; got shape {x_init.shape}")
            else:
                n = x_init.shape[0]
            key = None if task == "superres" else _need_key(seed, rng)
            if task == "inpaint":
                if mask is None:
                    raise ValueError(
                        "inpaint needs mask= (binary, 1 = known pixel — "
                        "see workloads.normalize_mask)")
                extras = {"known": np.ascontiguousarray(x_init),
                          "mask": workload_tasks.normalize_mask(
                              mask, int(n), self.model.img_size)}
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        deadline = (time.perf_counter() + deadline_s
                    if deadline_s is not None else None)
        req = Request(config=config, n=int(n), key=key, x_init=x_init,
                      ticket=Ticket(n), deadline=deadline, extras=extras)
        req.ticket._health_cb = self.health
        with self._lock:
            if self._closed:
                raise EngineClosedError(
                    "engine is drained — no new requests accepted")
            if self.max_queue is not None and len(self._pending) >= self.max_queue:
                self.metrics.inc("engine.rejected")
                raise QueueFullError(
                    f"queue at max_queue={self.max_queue} "
                    f"({len(self._pending)} pending) — request rejected "
                    "(overload backpressure; retry later or raise max_queue)")
            req.rid = self._next_rid
            self._next_rid += 1
            self._pending.append(req)
            self._open[req.rid] = req
            depth = len(self._pending)
        self.metrics.gauge(
            "engine.max_queue_depth",
            max(int(self.metrics.raw("engine.max_queue_depth") or 0), depth))
        if spans.enabled():
            req.ticket.span = spans.begin(
                "engine.request", parent=trace, rid=req.rid, n=req.n,
                replica=self.replica_id) or None
        return req.ticket

    @staticmethod
    def _as_batch(x_init) -> np.ndarray:
        x_init = np.asarray(x_init, np.float32)
        if x_init.ndim == 3:
            x_init = x_init[None]
        if x_init.ndim != 4:
            raise ValueError(f"x_init must be (n, H, W, C) or (H, W, C), "
                             f"got shape {x_init.shape}")
        return x_init

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # ------------------------------------------------------------- programs

    def ensure_program(self, config: SamplerConfig, bucket: int):
        """The ONLY compile site. Dispatch calls this too — a serve-time miss
        (a config/bucket warmup didn't cover) compiles and is counted, so
        ``stats['compiles']`` staying flat after warmup proves zero serve-time
        compiles."""
        key = (config, bucket)
        prog = self._programs.get(key)
        if prog is None:
            if config.sp_degree > 1:
                shards = data_axis_size(self._sp_mesh(config.sp_degree))
                if bucket % shards:
                    raise ValueError(
                        f"bucket {bucket} does not divide the sp config's "
                        f"data axis ({shards} = {self._n_devices()} devices "
                        f"/ sp_degree {config.sp_degree}); pick buckets that "
                        "divide it, or a larger sp_degree (which shrinks the "
                        "data axis)")
            faults.fire("serve.compile", tag=f"bucket:{bucket}|")
            self._mark(f"compile bucket={bucket}", budget_s=4 * self.stall_s)
            prog = self._build_program(config, bucket)
            self._programs[key] = prog
            self.metrics.inc("engine.compiles")
        return prog

    # -------------------------------------------------- sequence parallelism

    def _devices(self) -> list:
        """The devices sp meshes are built over: the engine mesh's devices
        when one was given (sp subdivides the same hardware), else every
        local device."""
        if self.mesh is not None:
            return list(self.mesh.devices.flat)
        return jax.local_devices()

    def _n_devices(self) -> int:
        return len(self._devices())

    def _sp_mesh(self, degree: int):
        """The (data, seq) mesh for one sp_degree — built once, shared by
        every config at that degree (data-major, so each seq group is a
        contiguous ICI neighborhood)."""
        mesh = self._sp_meshes.get(degree)
        if mesh is None:
            devices = self._devices()
            if len(devices) % degree:
                raise ValueError(
                    f"sp_degree={degree} does not divide the "
                    f"{len(devices)} local device(s) — the (data, seq) mesh "
                    "needs a whole data axis; pick an sp_degree from the "
                    "divisors of the device count")
            mesh = make_mesh({"data": len(devices) // degree, "seq": degree},
                             devices=np.asarray(devices))
            self._sp_meshes[degree] = mesh
        return mesh

    def _mesh_for(self, config: SamplerConfig):
        """The mesh a config's programs run on: the engine's own mesh for
        the degree-1 (default) configs — the existing path, untouched — else
        the per-degree (data, seq) mesh."""
        if config.sp_degree == 1:
            return self.mesh
        return self._sp_mesh(config.sp_degree)

    def _sharding_for(self, config: SamplerConfig):
        """Batch sharding for a config's inputs, or None off-mesh."""
        mesh = self._mesh_for(config)
        return batch_sharding(mesh) if mesh is not None else None

    def _sp_attn_mode(self, config: SamplerConfig) -> str:
        """Resolve the attention strategy: 'ulysses' needs the head count
        divisible by the seq axis (it reshards heads<->sequence with
        all-to-alls — parallel/ulysses.py raises SeqParallelConfigError
        otherwise), so it falls back to the ring, which has no head
        constraint, instead of failing the warmup."""
        if (config.sp_mode == "ulysses"
                and self.model.num_heads % config.sp_degree):
            return "ring"
        return config.sp_mode

    def _model_for(self, config: SamplerConfig):
        """The model variant a config's programs trace: ``quant``, ``fused``,
        the sp mesh, and the sp axis names are all fields of the
        (hash-by-value) module, so quant/float, fused/unfused and sp/non-sp
        programs can never collide in jit/AOT caches. sp composes with quant
        and fused: the sp clone starts from the quant/fused clone (under sp
        the fused attention falls back in-model, but the fused Mlp still
        applies)."""
        base = self.model
        if config.quant or config.fused:
            key = (config.quant, config.fused)
            base = self._quant_models.get(key)
            if base is None:
                base = self._quant_models[key] = self.model.clone(
                    quant=config.quant, fused=config.fused)
        if config.sp_degree == 1:
            return base
        key = (config.sp_mode, config.sp_degree, config.quant, config.fused)
        model = self._sp_models.get(key)
        if model is None:
            from ddim_cold_tpu.models.vit import sp_clone

            model = self._sp_models[key] = sp_clone(
                base, self._sp_mesh(config.sp_degree),
                sp_mode=config.sp_mode)
        return model

    def _params_for(self, config: SamplerConfig):
        if config.student:
            if self.student_params is None:
                raise ValueError(
                    "config.student=True but this engine holds no student "
                    "tree — pass student_params= at construction (the "
                    "distilled checkpoint from train/distill.py)")
            float_tree = self.student_params
        else:
            float_tree = self.params
        if not config.quant:
            base = float_tree
        else:
            # one int8 tree per weight set (teacher / student), built lazily
            # on the first quant config that needs it and pinned for reuse
            attr = "_qparams_student" if config.student else "_qparams"
            base = getattr(self, attr)
            if base is None:
                from ddim_cold_tpu.ops import quant

                qp = quant.quantize_params(float_tree)
                base = (shard_params(qp, self.mesh)
                        if self.mesh is not None else qp)
                setattr(self, attr, base)
                if not config.student:
                    self.metrics.gauge("engine.param_bytes",
                                       quant.param_bytes(float_tree))
                    self.metrics.gauge("engine.param_bytes_quant",
                                       quant.param_bytes(base))
        if config.sp_degree == 1:
            return base
        # re-place (replicated) on the config's (data, seq) mesh, once per
        # (degree, quantization, weight set) — the sp executable rejects
        # params committed to a different mesh
        key = (config.sp_degree, bool(config.quant), bool(config.student))
        placed = self._sp_params.get(key)
        if placed is None:
            placed = self._sp_params[key] = shard_params(
                base, self._sp_mesh(config.sp_degree))
        return placed

    def _x_struct(self, bucket: int, config: SamplerConfig):
        H, W = self.model.img_size
        return jax.ShapeDtypeStruct((bucket, H, W, self.model.in_chans),
                                    jnp.float32,
                                    sharding=self._sharding_for(config))

    def _cache_struct(self, bucket: int, config: SamplerConfig):
        shape = (bucket, self.model.num_patches + 1, self.model.embed_dim)
        sharding = self._sharding_for(config)
        s = jax.ShapeDtypeStruct(shape, self.model.dtype, sharding=sharding)
        if config.cache_mode == "adaptive":
            # the drift gate's reference image rides the carry (f32,
            # x-shaped) — see ops/step_cache.init_cache
            H, W = self.model.img_size
            x_ref = jax.ShapeDtypeStruct(
                (bucket, H, W, self.model.in_chans), jnp.float32,
                sharding=sharding)
            return (s, s, x_ref)
        return (s, s)

    def _mask_struct(self, bucket: int, config: SamplerConfig):
        H, W = self.model.img_size
        return jax.ShapeDtypeStruct((bucket, H, W, 1), jnp.float32,
                                    sharding=self._sharding_for(config))

    def _program_spec(self, config: SamplerConfig, bucket: int):
        """The ``(jitted scan, positional args, static kwargs)`` triple this
        (config, bucket) lowers — the single source of program identity.
        :meth:`_build_program` compiles the triple; :meth:`program_fingerprint`
        traces the SAME triple to a jaxpr for warmup dedup, so the two can
        never disagree about what a key would compile.

        ``preview_every > 0`` selects the sequence-returning variant of the
        SAME scan — trajectory frames are the preview stream and the final
        frame is the result (bitwise the last-only output), so previews cost
        one program per (config, bucket) like everything else and zero extra
        compiles at serve time. ``task`` picks the scan family: inpaint has
        its own constrained scan; the other tasks reuse the plain programs
        (their task-ness lives entirely in the init, so e.g. draft and
        guided-sample configs with equal fields share an executable).
        ``steps > 0`` picks the few-step family (ops/sampling.py): one scan
        over the explicit step-index schedule per k, the final jump-to-clean
        update outside the scan — so k=1 lowers scan-free."""
        x = self._x_struct(bucket, config)
        model, params = self._model_for(config), self._params_for(config)
        seq = config.preview_every > 0
        if config.task == "inpaint":
            if config.cached:
                return _inpaint_cached_spec(
                    model, params, x, self._mask_struct(bucket, config),
                    self._key0, self._cache_struct(bucket, config), config,
                    seq)
            fn = (sampling._ddim_scan_inpaint_seq if seq
                  else sampling._ddim_scan_inpaint)
            return fn, (model, params, x, x,
                        self._mask_struct(bucket, config), self._key0), dict(
                k=config.k, t_start=config.t_start, eta=0.0, sequence=seq)
        if config.sampler == "cold":
            if config.cached:
                return _cold_cached_spec(model, params, x,
                                         self._cache_struct(bucket, config),
                                         config, seq)
            fn = sampling._cold_scan_seq if seq else sampling._cold_scan
            return fn, (model, params, x), dict(levels=config.levels,
                                                return_sequence=seq)
        if config.steps > 0:
            if config.cached:
                return _fewstep_cached_spec(
                    model, params, x, self._key0,
                    self._cache_struct(bucket, config), config, seq)
            fn = (sampling._ddim_scan_fewstep_seq if seq
                  else sampling._ddim_scan_fewstep)
            return fn, (model, params, x, self._key0), dict(
                steps=config.steps, t_start=config.t_start, eta=0.0,
                sequence=seq)
        if config.cached:
            if config.telemetry:
                return _ddim_cached_tel_spec(
                    model, params, x, self._key0,
                    self._cache_struct(bucket, config), config)
            return _ddim_cached_spec(model, params, x, self._key0,
                                     self._cache_struct(bucket, config),
                                     config, seq)
        fn = sampling._ddim_scan_sequence if seq else sampling._ddim_scan_last
        return fn, (model, params, x, self._key0), dict(
            k=config.k, t_start=config.t_start, eta=0.0)

    def _build_program(self, config: SamplerConfig, bucket: int):
        """AOT-compile the scan for this (config, bucket): trace with shape
        structs (no dummy allocation), compile, return the executable. The
        executable is called with the NON-static args only (params, x, …)."""
        fn, args, kwargs = self._program_spec(config, bucket)
        return fn.lower(*args, **kwargs).compile()

    def program_fingerprint(self, config: SamplerConfig, bucket: int):
        """Trace-only program identity: the constant-blind ``signature_hash``
        over the traced jaxpr + input avals, paired with a digest of every
        captured constant's bytes. Two (config, bucket) keys with equal
        fingerprints lower the SAME program — warmup dedups on this instead
        of compiling both (tracing costs milliseconds; XLA costs seconds).
        The consts digest is load-bearing: ``signature_hash`` is constant-
        blind by design (J006 uses that), but two configs whose scans bake
        different coefficient tables must NOT alias."""
        import hashlib

        from ddim_cold_tpu.analysis.jaxpr_checks import (iter_consts,
                                                         signature_hash)

        fn, args, kwargs = self._program_spec(config, bucket)
        traced = fn.trace(*args, **kwargs)
        sig = signature_hash(traced.jaxpr, traced.in_avals)
        h = hashlib.sha256()
        for c in iter_consts(traced.jaxpr):
            a = np.asarray(c)
            h.update(f"{a.dtype}{a.shape}".encode())
            h.update(a.tobytes())
        return sig, h.hexdigest()

    def adopt_program(self, config: SamplerConfig, bucket: int,
                      src_key) -> None:
        """Alias an already-compiled executable under a second (config,
        bucket) key — warmup's dedup path, valid only when both keys'
        :meth:`program_fingerprint` match. Does not bump ``compiles``
        (nothing compiled); counted under ``engine.program_aliases``."""
        self._programs[(config, bucket)] = self._programs[src_key]
        self.metrics.inc("engine.program_aliases")

    # ------------------------------------------------------------- assembly

    def _request_init(self, req: Request) -> jax.Array:
        """The request's full init, drawn once at the request's own n —
        bitwise the direct sampler's draw (which depends on n); batches then
        take row slices (which don't). Editing tasks route through the SAME
        init builders the direct workloads.* functions use (one definition —
        the bitwise contract is structural)."""
        if req._x_full is None:
            H, W = self.model.img_size
            C = self.model.in_chans
            task = req.config.task
            if task == "draft":
                req._x_full = workload_tasks.draft_init(
                    req.key, jnp.asarray(req.x_init, jnp.float32),
                    req.config.t_start, self.model.total_steps)
            elif task == "interp":
                pair = jnp.asarray(req.x_init, jnp.float32)
                req._x_full = workload_tasks.interp_init(
                    req.key, pair[0], pair[1], req.n, req.config.t_start,
                    self.model.total_steps)
            elif task == "inpaint":
                # fresh noise start — x_init (the known image) rides along
                # as a batch extra, it is not the scan's initial state
                req._x_full = jax.random.normal(req.key, (req.n, H, W, C),
                                                jnp.float32)
            elif req.x_init is not None:
                req._x_full = jnp.asarray(req.x_init, jnp.float32)
            elif req.config.sampler == "cold":
                color = jax.random.normal(req.key, (req.n, 1, 1, C),
                                          jnp.float32)
                req._x_full = jnp.broadcast_to(color, (req.n, H, W, C))
            else:
                req._x_full = jax.random.normal(req.key, (req.n, H, W, C),
                                                jnp.float32)
        return req._x_full

    def _tag(self, plan: BatchPlan) -> str:
        """Fault/beacon tag: ``|``-separated fields naming the replica (when
        fleet-owned), the bucket, and every request in the batch
        (``match="req:3|"`` targets request 3; ``match="replica:r0|"``
        targets every batch of one replica)."""
        reqs = {id(req): req for req, *_ in plan.entries}
        head = f"replica:{self.replica_id}|" if self.replica_id else ""
        return (head + f"bucket:{plan.bucket}|"
                + "".join(f"req:{r.rid}|" for r in reqs.values()))

    def _assemble(self, plan: BatchPlan):
        """Background-thread H2D stage: build the padded bucket batch on
        device (init draws dispatch async; guided numpy starts upload here,
        overlapping the main loop's compute). Returns ``(plan, xs)`` with
        ``xs`` a tuple: the init batch first, then any per-task extras
        (``_EXTRA_INPUTS`` — inpaint's known/mask ride along, sliced and
        padded exactly like x; zero-padding rows carry mask 0, so they pass
        through the projection untouched).

        Batch-coupled (adaptive-gate) plans pad with ROW-0 REPLICAS of every
        input instead of zeros: the pad rows then evolve bit-identically to
        row 0, so their per-row drift equals row 0's and the gate's batch-max
        reduction is exactly what the direct unpadded call computes — the
        bitwise-vs-direct contract survives padding."""
        self._mark(f"assemble bucket={plan.bucket}")
        t0 = spans.now() if spans.enabled() else 0.0
        faults.fire("serve.assemble", tag=self._tag(plan))
        coupled = plan.config.batch_coupled

        def _pad(real_parts):
            first = real_parts[0]
            if coupled:
                return jnp.broadcast_to(
                    first[:1], (plan.padded_rows,) + first.shape[1:])
            return jnp.zeros((plan.padded_rows,) + first.shape[1:],
                             jnp.float32)

        parts = [self._request_init(req)[lo:hi]
                 for req, lo, hi, _ in plan.entries]
        if plan.padded_rows:
            parts.append(_pad(parts))
        x = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        sharding = self._sharding_for(plan.config)
        if sharding is not None:
            x = jax.device_put(x, sharding)
        xs = [x]
        for name in _EXTRA_INPUTS.get(plan.config.task, ()):
            cols = [jnp.asarray(req.extras[name][lo:hi], jnp.float32)
                    for req, lo, hi, _ in plan.entries]
            if plan.padded_rows:
                cols.append(_pad(cols))
            e = cols[0] if len(cols) == 1 else jnp.concatenate(cols, axis=0)
            if sharding is not None:
                e = jax.device_put(e, sharding)
            xs.append(e)
        self._record_stage(plan, "assemble", t0)
        return plan, tuple(xs)

    def _record_stage(self, plan: BatchPlan, name: str, t0: float,
                      **attrs) -> None:
        """Attribute one per-batch pipeline stage to every request riding
        the batch: a retroactive closed span (same measured window) under
        each request's trace — so a split request's trace shows the stage
        once per batch it rode, and a coalesced batch's window appears under
        every participant. No-op with tracing disabled."""
        if not spans.enabled():
            return
        t1 = spans.now()
        for req in {id(r): r for r, *_ in plan.entries}.values():
            spans.record(req.ticket.span, name, t0, t1,
                         bucket=plan.bucket, **attrs)

    def _assemble_safe(self, plan: BatchPlan):
        """Assembly with the exception CAPTURED, not raised — the prefetch
        generator must keep producing the other plans when one batch's
        assembly fails (device_prefetch forwards a raise to the consumer and
        stops, which would strand every later batch)."""
        try:
            plan, xs = self._assemble(plan)
            return plan, xs, None
        except Exception as exc:  # noqa: BLE001 — isolated per batch
            return plan, None, exc

    # ------------------------------------------------------------- dispatch

    def _cache_kind(self, config: SamplerConfig):
        """Spare-cache pool key suffix: delta/full/token all share the
        two-leaf (B, N+1, E) carry structure ("pair" — a recycled carry is
        interchangeable between them because every schedule's step 0
        refreshes before reading), while adaptive's third x_ref leaf needs
        its own pool. sp configs extend the key with their (mode, degree)
        identity: a carry committed to one mesh cannot be donated to a
        program compiled for another."""
        kind = "adaptive" if config.cache_mode == "adaptive" else "pair"
        if config.sp_degree > 1:
            return (kind, config.sp_mode, config.sp_degree)
        return kind

    def _take_cache(self, bucket: int, config: SamplerConfig):
        cache = self._spare_caches.pop((bucket, self._cache_kind(config)),
                                       None)
        if cache is None:
            H, W = self.model.img_size
            cache = step_cache.init_cache(bucket, self.model.num_patches + 1,
                                          self.model.embed_dim,
                                          self.model.dtype,
                                          mode=config.cache_mode,
                                          img_shape=(H, W,
                                                     self.model.in_chans))
            cache = step_cache.shard_cache(cache, self._mesh_for(config))
        return cache

    def _recycle_cache(self, bucket: int, config: SamplerConfig,
                       cache_out) -> None:
        self._spare_caches[(bucket, self._cache_kind(config))] = cache_out

    def prewarm_cache(self, config: SamplerConfig, bucket: int) -> None:
        """Pre-allocate the spare step-cache carry for a cached (config,
        bucket) on the config's mesh — warmup calls this next to
        ``ensure_program`` so the first cached dispatch donates a pool-owned
        buffer instead of paying the allocation inline (sp configs get their
        per-mesh carries prebuilt the same way; no-op when the pool already
        holds a compatible carry)."""
        if not config.cached:
            return
        key = (bucket, self._cache_kind(config))
        if key not in self._spare_caches:
            self._spare_caches[key] = self._take_cache(bucket, config)

    def _dispatch(self, plan: BatchPlan, xs):
        prog = self.ensure_program(plan.config, plan.bucket)
        params = self._params_for(plan.config)
        self._mark(f"dispatch bucket={plan.bucket}")
        t0 = spans.now() if spans.enabled() else 0.0
        faults.fire("serve.dispatch", tag=self._tag(plan))
        if plan.config.task == "inpaint":
            x, known, m = xs
            if plan.config.cached:
                out, cache_out = prog(
                    params, x, known, m, self._key0,
                    self._take_cache(plan.bucket, plan.config))
                self._recycle_cache(plan.bucket, plan.config, cache_out)
            else:
                out = prog(params, x, known, m, self._key0)
        elif plan.config.sampler == "cold":
            x, = xs
            if plan.config.cached:
                out, cache_out = prog(
                    params, x, self._take_cache(plan.bucket, plan.config))
                self._recycle_cache(plan.bucket, plan.config, cache_out)
            else:
                out = prog(params, x)
        elif plan.config.cached:
            x, = xs
            if plan.config.telemetry:
                out, cache_out, aux = prog(
                    params, x, self._key0,
                    self._take_cache(plan.bucket, plan.config))
                out = (out, aux)
            else:
                out, cache_out = prog(
                    params, x, self._key0,
                    self._take_cache(plan.bucket, plan.config))
            self._recycle_cache(plan.bucket, plan.config, cache_out)
        else:
            x, = xs
            out = prog(params, x, self._key0)
        self.metrics.inc("engine.dispatches")
        self.metrics.inc("engine.rows", plan.rows)
        self.metrics.inc("engine.padded_rows", plan.padded_rows)
        self._record_stage(plan, "dispatch", t0)
        return out

    def _dispatch_retry(self, plan: BatchPlan, xs):
        """Dispatch with capped exponential backoff on the retryable fault
        class. The donated input is rebuilt per attempt when the failed call
        already consumed it (donation deletes the buffer even on error; only
        ``xs[0]`` — the scan state — is ever donated, the conditioning extras
        are not)."""
        delay = self.retry_base_s
        for attempt in range(self.max_retries + 1):
            try:
                return self._dispatch(plan, xs)
            except RETRYABLE_EXCEPTIONS:
                if attempt == self.max_retries:
                    raise
                self.metrics.inc("engine.retries")
                time.sleep(min(delay, self.retry_cap_s))
                delay = min(delay * 2, self.retry_cap_s)
                if getattr(xs[0], "is_deleted", lambda: False)():
                    _, xs, err = self._assemble_safe(plan)
                    if err is not None:
                        raise err
        raise AssertionError("unreachable: loop returns or raises")

    def _subplan(self, plan: BatchPlan, entries) -> BatchPlan:
        """A sub-batch of ``entries`` repacked densely at the SAME bucket —
        bisection recovery reuses the compiled program, it never compiles."""
        packed, offset = [], 0
        for req, lo, hi, _ in entries:
            packed.append((req, lo, hi, offset))
            offset += hi - lo
        return BatchPlan(config=plan.config, bucket=plan.bucket,
                         entries=tuple(packed), rows=offset)

    def _dispatch_safe(self, plan: BatchPlan, xs) -> list:
        """Dispatch with full failure isolation; returns the list of
        (plan, out) that actually went to the device.

        Deadlines are re-checked here (plan-time admission already filtered,
        but a request can expire while earlier batches run): expired entries
        fail fast, and a batch with no live entries left skips the device
        entirely. A deterministic batch failure bisects on request
        boundaries — halves re-assemble at the same bucket and recurse;
        a single-request batch that still fails is the poisoned one:
        quarantined, with the stage exception as cause."""
        now = time.perf_counter()
        for req, *_ in plan.entries:
            if req.deadline is not None and now > req.deadline \
                    and not req.ticket.done:
                self.metrics.inc("engine.deadline_expired", key="dispatch")
                self._fail_request(req, DeadlineExceeded(
                    f"request {req.rid} missed its deadline before dispatch "
                    f"on {self._rname} (expired {now - req.deadline:.3f}s "
                    "ago waiting for a bucket) — failing fast instead of "
                    "occupying one"))
        if all(req.ticket.failed for req, *_ in plan.entries):
            self.metrics.inc("engine.skipped_batches")
            return []
        try:
            return [(plan, self._dispatch_retry(plan, xs))]
        except Exception as exc:  # noqa: BLE001 — isolate, bisect, quarantine
            self.metrics.inc("engine.failed_batches", key="dispatch")
            reqs = list({id(r): r for r, *_ in plan.entries}.values())
            if len(reqs) == 1:
                req = reqs[0]
                if not req.ticket.done:
                    err = RequestQuarantinedError(
                        f"request {req.rid} deterministically fails its "
                        f"batch (bucket {plan.bucket}) on {self._rname} — "
                        "quarantined by bisection; batchmates completed "
                        "separately")
                    err.__cause__ = exc
                    self.quarantined.append(req.rid)
                    self.metrics.inc("engine.quarantined")
                    self._fail_request(req, err)
                return []
            results = []
            mid = len(reqs) // 2
            for part in (reqs[:mid], reqs[mid:]):
                ids = {id(r) for r in part}
                sub = self._subplan(
                    plan, [e for e in plan.entries if id(e[0]) in ids])
                sub, sx, err = self._assemble_safe(sub)
                if err is not None:
                    self._fail_plan(sub, err, "assembly (bisect)")
                    continue
                results += self._dispatch_safe(sub, sx)
            return results

    # ---------------------------------------------------------------- fetch

    def _finish(self, plan: BatchPlan, out) -> None:
        """D2H + delivery: one blocking fetch per batch, rows copied into
        each ticket's buffer; padding rows are simply never read. A fetch
        failure fails only this batch's tickets.

        Preview-enabled configs fetch the whole trajectory: the scheduled
        intermediate x̂0 frames stream to each ticket's preview buffer
        (``Ticket.previews()``) before the FINAL frame — bitwise the
        last-only program's output — is delivered as the result.

        Telemetry configs (``SamplerConfig.telemetry``) arrive here as
        ``(images, (branch, drift))``: the static-shaped step aux is fetched
        with the batch, decoded once (``obs.device.summarize``), attached to
        every participating ticket BEFORE delivery (a ``result()`` waiter
        wakes to a populated ``Ticket.telemetry``), and its refresh/reuse
        step counts emitted. Batch == request for the coupled adaptive case;
        the static modes' aux is identical for every batchmate anyway."""
        try:
            self._mark(f"fetch bucket={plan.bucket}")
            t0 = spans.now() if spans.enabled() else 0.0
            aux = None
            if plan.config.telemetry:
                out, (br, dr) = out
                aux = (np.asarray(br), np.asarray(dr))
            host = np.asarray(out)
            host = faults.fire("serve.fetch", tag=self._tag(plan),
                               payload=host)
        except Exception as exc:  # noqa: BLE001 — isolated per batch
            self._fail_plan(plan, exc, "fetch")
            return
        self._record_stage(plan, "fetch", t0)
        if aux is not None:
            cfg = plan.config
            summary = obs_device.summarize(
                obs_device.StepTelemetry(branch=aux[0], drift=aux[1]),
                cache_interval=cfg.cache_interval, cache_mode=cfg.cache_mode,
                cache_threshold=cfg.cache_threshold or 0.0,
                cache_tokens=cfg.cache_tokens)
            self.metrics.inc("engine.cache_refresh_steps",
                             summary["refreshes"])
            self.metrics.inc("engine.cache_reuse_steps", summary["reuses"])
            for req in {id(r): r for r, *_ in plan.entries}.values():
                req.ticket.telemetry = summary
        every = plan.config.preview_every
        if every:
            try:
                t0 = spans.now() if spans.enabled() else 0.0
                faults.fire("serve.preview", tag=self._tag(plan))
                steps = host.shape[0] - 1  # frame 0 is the init
                for j in workload_preview.preview_indices(steps, every):
                    frame = host[j]
                    for req, lo, hi, offset in plan.entries:
                        if req.ticket._preview(
                                j, lo, hi, frame[offset:offset + (hi - lo)]):
                            self.metrics.inc("engine.preview_frames")
            except Exception as exc:  # noqa: BLE001 — isolated per batch
                self._fail_plan(plan, exc, "preview")
                return
            self._record_stage(plan, "preview", t0)
            host = host[-1]
        for req, lo, hi, offset in plan.entries:
            if req.ticket._deliver(lo, hi, host[offset:offset + (hi - lo)]):
                self.metrics.observe("engine.latency_s",
                                     req.ticket.latency_s)
                sp = req.ticket.span
                if sp is not None:
                    sp.end(rows=req.n, latency_s=req.ticket.latency_s)
                with self._lock:
                    self._open.pop(req.rid, None)

    # -------------------------------------------------------------- failure

    def _fail_request(self, req: Request, exc: BaseException) -> None:
        with self._lock:
            self._open.pop(req.rid, None)
        if req.ticket._fail(exc):
            self.metrics.inc("engine.failed_tickets")
            sp = req.ticket.span
            if sp is not None:
                sp.end(error=type(exc).__name__)

    def _fail_plan(self, plan: BatchPlan, exc: BaseException,
                   stage: str) -> None:
        """Fail exactly this batch's tickets, the stage exception as cause."""
        self.metrics.inc("engine.failed_batches", key="plan")
        for req in {id(r): r for r, *_ in plan.entries}.values():
            if req.ticket.done:
                continue
            err = RequestFailedError(
                f"batch {stage} failed for request {req.rid} "
                f"(bucket {plan.bucket}, {self._rname}): {exc!r}")
            err.__cause__ = exc
            self._fail_request(req, err)

    # ----------------------------------------------------- watchdog / drain

    def _mark(self, label: str, budget_s: Optional[float] = None) -> None:
        self._last_mark = (time.monotonic(), label)
        wd = self._wd
        if wd is not None:
            wd.mark(label, budget_s)

    def _on_stall(self, label: str, silent: float) -> None:
        """Soft watchdog abort: a device interaction went silent past the
        stall budget (wedged backend — no exception will ever surface). Fail
        every unresolved ticket so no waiter hangs; batches fetched before
        the stall keep their delivered results."""
        self._stalled = True
        self.metrics.inc("engine.stalls")
        err = EngineStalledError(
            f"{self._rname} made no progress for {silent:.1f}s after "
            f"{label!r} — wedged backend; in-flight and queued tickets "
            "failed, results fetched before the stall stand")
        with self._lock:
            open_reqs = list(self._open.values())
        for req in open_reqs:
            self._fail_request(req, err)

    def drain(self, timeout: Optional[float] = None) -> dict:
        """Graceful shutdown: stop admission (``submit`` raises
        :class:`EngineClosedError`), let an active :meth:`run` flush its
        in-flight batches, then deterministically fail everything still
        queued. Returns the final health snapshot plus ``"idle"``.

        When the idle wait TIMES OUT (``idle: False``) a :meth:`run` is
        still mid-flight, so the queued-request sweep is skipped — failing
        requests while their batches are on the device would race delivery
        and could resolve a ticket the pipeline is about to complete. The
        caller decides: wait again, or escalate (the fleet router treats a
        non-idle drain as a wedged replica).

        Idle-race audit (graftcheck T-rules): this sweep cannot double-fail
        or lose a request even when a :meth:`run` starts concurrently —
        both sides take the queue by SWAPPING ``_pending`` under ``_lock``
        (each request appears in exactly one swap), ``submit`` rejects once
        ``_closed`` is set under the same lock (nothing lands after either
        sweep), and a run() racing the idle wait fails its own swapped list
        through the same first-resolution-wins ``Ticket._fail`` path."""
        with self._lock:
            self._closed = True
        idle = self._idle.wait(timeout)
        if idle:
            with self._lock:
                pending, self._pending = self._pending, []
            for req in pending:
                self._fail_request(req, EngineClosedError(
                    f"{self._rname} drained with request {req.rid} "
                    "still queued"))
        report = self.health()
        report["idle"] = idle
        return report

    def health(self) -> dict:
        """Live health snapshot (also rendered into Ticket timeout
        messages): queue/engine state, failure counters (read from the
        obs metrics registry — this dict is a view, not a second source of
        truth), and realized fault injections by site. ``last_stage`` /
        ``stalled_for_s`` name the last pipeline beacon and its age — the
        structured "where is it stuck" answer a timed-out waiter needs."""
        with self._lock:
            depth = len(self._pending)
            open_n = len(self._open)
            mark_t, mark_label = self._last_mark
        now = time.monotonic()
        s = self.stats
        lat = latency_summary(s["latencies_s"])
        return {
            "replica": self.replica_id,
            "queue_depth": depth,
            "open_tickets": open_n,
            # per-ticket submit→deliver latency percentiles — the load
            # signal the fleet autoscaler scales on (serve/autoscale.py)
            "latency_p50_s": lat["p50_s"],
            "latency_p95_s": lat["p95_s"],
            "latency_p99_s": lat["p99_s"],
            "max_queue": self.max_queue,
            "uptime_s": now - self._t0,
            "last_progress_s": now - mark_t,
            "last_stage": mark_label,
            "stalled_for_s": round(now - mark_t, 3),
            "running": self._running,
            "closed": self._closed,
            "stalled": self._stalled,
            "compiles": s["compiles"],
            "dispatches": s["dispatches"],
            "retries": s["retries"],
            "failed_batches": s["failed_batches"],
            "failed_tickets": s["failed_tickets"],
            "quarantined": s["quarantined"],
            "deadline_expired": s["deadline_expired"],
            "rejected": s["rejected"],
            "skipped_batches": s["skipped_batches"],
            "stalls": s["stalls"],
            "faults_by_site": faults.snapshot()["by_site"],
        }

    # ------------------------------------------------------------------ run

    def run(self) -> dict:
        """Drain the queue: plan → assemble (background) → dispatch → fetch,
        pipelined. Returns a report for this drain (throughput over real
        rows — padding is excluded from img/s by construction). Failures
        never escape a batch: see the module docstring's isolation story."""
        t0 = time.perf_counter()
        s0 = self.stats
        compiles0 = s0["compiles"]
        counters0 = {k: s0[k] for k in
                     ("retries", "failed_tickets", "quarantined")}
        rows = padded = batches = 0
        n_lat0 = self.metrics.count("engine.latency_s")
        self._stalled = False
        self._running = True
        self._idle.clear()
        wd = None
        if self.stall_s > 0:
            wd = StallWatchdog(self.stall_s, exit_code=None,
                               on_abort=self._on_stall, name="engine")
            self._wd = wd
            wd.start()
        try:
            while not self._stalled:
                with self._lock:
                    pending, self._pending = self._pending, []
                    closed = self._closed
                if closed:
                    for req in pending:
                        self._fail_request(req, EngineClosedError(
                            f"{self._rname} drained with request {req.rid} "
                            "still queued"))
                    break
                if not pending:
                    break
                live = self._admit(pending)
                if not live:
                    continue
                self._mark(f"plan {len(live)} requests")
                tp = spans.now() if spans.enabled() else 0.0
                plans = plan_batches(live, self.buckets)
                if spans.enabled():
                    tp1 = spans.now()
                    for req in live:
                        spans.record(req.ticket.span, "plan", tp, tp1,
                                     batches=len(plans))
                inflight: deque = deque()
                for plan, xs, err in device_prefetch(
                        plans, self._assemble_safe,
                        depth=self.prefetch_depth):
                    if self._stalled:
                        break
                    if err is not None:
                        self._fail_plan(plan, err, "assembly")
                        continue
                    for item in self._dispatch_safe(plan, xs):
                        inflight.append(item)
                        batches += 1
                        rows += item[0].rows
                        padded += item[0].padded_rows
                    while len(inflight) > self.inflight:
                        self._finish(*inflight.popleft())
                while inflight:
                    self._finish(*inflight.popleft())
        finally:
            self._running = False
            if wd is not None:
                wd.done()
                self._wd = None
            self._idle.set()
        wall = time.perf_counter() - t0
        s1 = self.stats
        completed = self.metrics.samples("engine.latency_s")[n_lat0:]
        return {
            "batches": batches,
            "rows": rows,
            "padded_rows": padded,
            "wall_s": wall,
            "img_per_sec": rows / wall if wall > 0 else 0.0,
            "latency": latency_summary(completed),
            "compiles": s1["compiles"] - compiles0,
            "max_queue_depth": s1["max_queue_depth"],
            "stalled": self._stalled,
            **{k: s1[k] - v0 for k, v0 in counters0.items()},
        }

    def _admit(self, pending) -> list:
        """Plan-time deadline gate: expired requests fail fast HERE, before
        they cost a bucket slot or an assembly."""
        now = time.perf_counter()
        live = []
        for req in pending:
            if req.deadline is not None and now > req.deadline:
                self.metrics.inc("engine.deadline_expired", key="plan")
                self._fail_request(req, DeadlineExceeded(
                    f"request {req.rid} missed its deadline while queued "
                    f"on {self._rname} (expired {now - req.deadline:.3f}s "
                    "before planning)"))
            else:
                live.append(req)
        return live


def _ddim_cached_spec(model, params, x, key, cache, config: SamplerConfig,
                      seq: bool = False):
    fn = (sampling._ddim_scan_cached_seq if seq
          else sampling._ddim_scan_cached)
    return fn, (model, params, x, key, cache), dict(
        k=config.k, t_start=config.t_start,
        eta=0.0, cache_interval=config.cache_interval,
        cache_mode=config.cache_mode,
        cache_threshold=config.cache_threshold,
        cache_tokens=config.cache_tokens or None, sequence=seq)


def _ddim_cached_tel_spec(model, params, x, key, cache,
                          config: SamplerConfig):
    return sampling._ddim_scan_cached_tel, (model, params, x, key, cache), \
        dict(k=config.k, t_start=config.t_start,
             eta=0.0, cache_interval=config.cache_interval,
             cache_mode=config.cache_mode,
             cache_threshold=config.cache_threshold,
             cache_tokens=config.cache_tokens or None)


def _fewstep_cached_spec(model, params, x, key, cache,
                         config: SamplerConfig, seq: bool = False):
    fn = (sampling._ddim_scan_fewstep_cached_seq if seq
          else sampling._ddim_scan_fewstep_cached)
    return fn, (model, params, x, key, cache), dict(
        steps=config.steps, t_start=config.t_start, eta=0.0,
        cache_interval=config.cache_interval,
        cache_mode=config.cache_mode,
        cache_threshold=config.cache_threshold,
        cache_tokens=config.cache_tokens or None, sequence=seq)


def _cold_cached_spec(model, params, x, cache, config: SamplerConfig,
                      seq: bool = False):
    fn = (sampling._cold_scan_cached_seq if seq
          else sampling._cold_scan_cached)
    return fn, (model, params, x, cache), dict(
        levels=config.levels, return_sequence=seq,
        cache_interval=config.cache_interval,
        cache_mode=config.cache_mode,
        cache_threshold=config.cache_threshold,
        cache_tokens=config.cache_tokens or None)


def _inpaint_cached_spec(model, params, x, mask, key, cache,
                         config: SamplerConfig, seq: bool = False):
    # known shares x's struct: both are (bucket, H, W, C) f32 batch-sharded
    fn = (sampling._ddim_scan_inpaint_cached_seq if seq
          else sampling._ddim_scan_inpaint_cached)
    return fn, (model, params, x, x, mask, key, cache), dict(
        k=config.k, t_start=config.t_start, eta=0.0,
        cache_interval=config.cache_interval,
        cache_mode=config.cache_mode,
        cache_threshold=config.cache_threshold,
        cache_tokens=config.cache_tokens or None, sequence=seq)
