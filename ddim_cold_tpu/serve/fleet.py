"""Replica handles — the fleet's unit of lifecycle management.

A :class:`ReplicaHandle` is what the router needs from one serving replica:
warm it, hand it requests, read its health, drain it, kill it. The surface
is deliberately narrow and host-typed (dicts, numpy-backed tickets) so a
subprocess or remote-host backend can slot in behind the same interface —
the router never sees an Engine, a mesh, or a device array.

:class:`LocalReplica` is the in-process backend: one
:class:`~ddim_cold_tpu.serve.engine.Engine` plus a worker thread that runs
the engine's dispatch loop whenever the queue is non-empty, so ``submit``
returns immediately and N replicas serve concurrently inside one process
(their device work still serializes on one backend — the point here is
failure isolation and lifecycle, not extra FLOPs; a subprocess backend
buys the parallelism later without touching the router).

Lifecycle is a one-way street::

    new --warm()--> ready --drain()--> draining --> closed

The router only places onto ``ready`` replicas; ``drain()`` stops the
worker after the engine's own graceful drain (which fails still-queued
tickets with :class:`~ddim_cold_tpu.serve.errors.EngineClosedError` — the
router's cue to fail those requests over to surviving replicas).

This module is host-only (graftcheck A004): no jax imports — the engine
and warmup are imported lazily inside :func:`local_factory` so importing
the fleet layer never initializes a backend.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

from ddim_cold_tpu.obs import metrics

#: replica lifecycle states (a handle only ever moves forward through these)
NEW, READY, DRAINING, CLOSED = "new", "ready", "draining", "closed"


def record_transition(scope, state: str) -> None:
    """The ONE emit site for replica lifecycle transitions (graftcheck
    A005 allows a metric name at one site) — every ReplicaHandle backend
    (local thread, subprocess RPC) funnels its state changes through here,
    so a chaos run's replica churn is countable without scraping router
    internals."""
    scope.inc("fleet.replica_transitions", key=state)


class ReplicaHandle:
    """The router's view of one replica. Subclass per backend; every method
    is called from the router's control thread (plus ``submit`` from the
    router under its own lock), so implementations need to be thread-safe
    against their OWN worker, not against concurrent router calls."""

    replica_id: str = ""
    state: str = NEW

    def warm(self, configs, buckets=None, **kwargs) -> dict:
        """Compile every (config, bucket) program; flips state to ready.
        After this, ``health()['compiles_after_warmup']`` must stay 0 for
        the replica's lifetime — the fleet-wide zero-compile contract."""
        raise NotImplementedError

    def start(self) -> None:
        """Begin serving (idempotent)."""
        raise NotImplementedError

    def submit(self, *args, **kwargs):
        """Queue one request; returns its Ticket. Raises the engine's
        admission errors (QueueFullError / EngineClosedError)."""
        raise NotImplementedError

    def health(self) -> dict:
        """Engine health snapshot plus ``state`` and
        ``compiles_after_warmup`` (the two fleet-level fields)."""
        raise NotImplementedError

    def drain(self, timeout: Optional[float] = None) -> dict:
        """Graceful stop: engine drain (queued tickets fail typed), worker
        stopped, state → closed. Returns the drain report."""
        raise NotImplementedError

    def close(self) -> None:
        """Hard stop (drain with a short timeout)."""
        raise NotImplementedError


class LocalReplica(ReplicaHandle):
    """In-process replica: an Engine plus its serving thread.

    The worker loop polls the engine queue every ``poll_s`` (and wakes
    immediately on ``submit``), calling :meth:`Engine.run` whenever work is
    pending — requests submitted mid-run join the run's next planning
    round, so the loop is a thin liveness shim, not a scheduler.
    """

    def __init__(self, engine, *, poll_s: float = 0.02, join_s: float = 5.0):
        self.engine = engine
        self.replica_id = engine.replica_id
        self.metrics = metrics.scope("fleet")
        self._set_state(NEW)
        self.poll_s = float(poll_s)
        self.join_s = float(join_s)
        self.warmup_compiles = 0
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock
        self._lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle

    def _set_state(self, state: str) -> None:
        """The one state-write site: every lifecycle transition lands in the
        obs registry keyed by the state entered (via the module-level
        single emit site shared with the subprocess backend)."""
        self.state = state
        record_transition(self.metrics, state)

    def warm(self, configs, buckets=None, **kwargs) -> dict:
        from ddim_cold_tpu.serve.warmup import warmup

        report = warmup(self.engine, configs, buckets, **kwargs)
        self.warmup_compiles = self.engine.stats["compiles"]
        self._set_state(READY)
        return report

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, name=f"replica-{self.replica_id}",
                daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._work.wait(self.poll_s)
            self._work.clear()
            if self.engine.queue_depth():
                try:
                    self.engine.run()
                except Exception:  # noqa: BLE001 — run() isolates failures
                    # per batch; anything escaping it must not kill the
                    # worker (the router retires the replica via health())
                    pass

    def drain(self, timeout: Optional[float] = None) -> dict:
        self._set_state(DRAINING)
        report = self.engine.drain(timeout)
        self._stop.set()
        self._work.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            # bounded join: a wedged engine (report["idle"] False) can pin
            # the worker forever — it is a daemon thread, leave it behind
            thread.join(self.join_s)
        self._set_state(CLOSED)
        return report

    def close(self) -> None:
        if self.state != CLOSED:
            self.drain(self.join_s)

    # -------------------------------------------------------------- serving

    def submit(self, *args, **kwargs):
        # Guard the health()-snapshot → submit() window: a replica that
        # drained between the router's candidate scan and its placement must
        # raise the TYPED eviction error (the router's cue to try the next
        # candidate), never a raw engine RuntimeError. The engine's own
        # closed-check rides behind this for the race where drain lands
        # mid-call.
        if self.state != READY:
            from ddim_cold_tpu.serve.errors import EngineClosedError

            raise EngineClosedError(
                f"replica {self.replica_id} is {self.state}, not ready — "
                "placement raced a drain; retry on another replica")
        ticket = self.engine.submit(*args, **kwargs)
        self._work.set()
        return ticket

    def queue_depth(self) -> int:
        return self.engine.queue_depth()

    @property
    def compiles_after_warmup(self) -> int:
        """Program builds since this replica's own warmup — the per-replica
        zero-compile contract (a replacement replica proves 0 against its
        OWN warm, not the fleet's first)."""
        return self.engine.stats["compiles"] - self.warmup_compiles

    def health(self) -> dict:
        h = self.engine.health()
        h["state"] = self.state
        h["compiles_after_warmup"] = self.compiles_after_warmup
        return h


def local_factory(model, params, *, mesh=None,
                  **engine_kwargs) -> Callable[[str], LocalReplica]:
    """Factory of in-process replicas for :class:`~.router.Router`:
    ``factory(replica_id)`` builds an Engine (with that id threaded into
    its fault tags and failure messages) wrapped in a started-on-demand
    :class:`LocalReplica`. All replicas share the caller's ``params``
    (jax arrays are immutable — sharing is safe and keeps N replicas at
    one param footprint)."""
    def factory(replica_id: str) -> LocalReplica:
        from ddim_cold_tpu.serve.engine import Engine

        return LocalReplica(Engine(model, params, mesh=mesh,
                                   replica_id=replica_id, **engine_kwargs))
    return factory
