#!/usr/bin/env python
"""Inference CLI: ``python ViT.py --sample_n 256 --acc_k 1``.

Preserves the reference CLI surface (ViT.py:258-316): renders the k=100
denoise-sequence figure and a 16×16 sample grid from the OxfordFlower config.
Device selection is automatic (TPU when present — the north-star "dispatch to
TPU backend when no GPU"). Additions: ``--config`` to pick any model config,
``--checkpoint`` to point at a torch ``.pkl`` or an orbax directory, and
``--init-random`` for smoke runs without weights (the reference hard-requires
``Saved_Models/OxfordFlower.pkl``, which is absent from the upstream snapshot).
"""

import os
import sys

import click

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)


@click.command()
@click.option("--sample_n", default=256, help="Number of samples you'll get.")
@click.option("--acc_k", default=1, help="Number of steps jumped during sampling.")
@click.option("--config", "config_name", default="oxford_flower_64",
              help="Model config name (see ddim_cold_tpu.models.MODEL_CONFIGS).")
@click.option("--checkpoint", default=None,
              help="Weights: torch .pkl or orbax dir "
                   "[default: Saved_Models/OxfordFlower.pkl].")
@click.option("--init-random", is_flag=True,
              help="Use random init instead of a checkpoint (smoke runs).")
@click.option("--seed", default=0, help="Sampling rng seed.")
@click.option("--eta", default=0.0,
              help="Stochastic-DDIM noise scale (DDIM paper interpolation; "
                   "0 = the reference's deterministic sampler).")
def main(sample_n, acc_k, config_name, checkpoint, init_random, seed, eta):
    """Batch sampling + denoise-sequence figure (reference ViT.py main)."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.ops import sampling
    from ddim_cold_tpu.utils import checkpoint as ckpt
    from ddim_cold_tpu.utils.platform import (
        enable_compile_cache, honor_env_platform, require_accelerator_or_exit,
    )

    honor_env_platform()
    require_accelerator_or_exit()  # wedged tunnel: exit 3, never hang
    enable_compile_cache()  # repeat CLI runs reuse compiled XLA programs
    from ddim_cold_tpu.utils.image import get_next_path, grid_shape, save_grid

    model = DiffusionViT(total_steps=2000, **MODEL_CONFIGS[config_name])
    saved = os.path.join(HERE, "Saved_Models")
    os.makedirs(saved, exist_ok=True)

    if init_random:
        params = model.init(
            jax.random.PRNGKey(seed),
            jnp.zeros((1, *model.img_size, 3)), jnp.zeros((1,), jnp.int32),
        )["params"]
    else:
        path = checkpoint or os.path.join(saved, "OxfordFlower.pkl")
        if os.path.isdir(path):
            target = model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, *model.img_size, 3)), jnp.zeros((1,), jnp.int32),
            )["params"]
            params = ckpt.restore_checkpoint(path, target)
        else:
            params = ckpt.load_torch_pkl(path, model.patch_size)

    print(f"devices: {jax.devices()}")
    # multi-chip hosts shard the sample batch over a data mesh automatically
    # (the reference sampler is single-GPU; SPMD sampling is free here)
    mesh = None
    if jax.device_count() > 1 and sample_n % jax.device_count() == 0:
        from ddim_cold_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": jax.device_count()})

    n_seq = 6
    seq = sampling.ddim_sample(model, params, jax.random.PRNGKey(seed), k=100,
                               n=n_seq, return_sequence=True, eta=eta)
    # rows = samples, cols = trajectory frames (reference figure layout)
    frames = jnp.swapaxes(seq, 0, 1).reshape(-1, *seq.shape[2:])
    out = save_grid(frames, get_next_path(os.path.join(saved, "denoise_sequence.png")),
                    nrows=n_seq, ncols=seq.shape[0])
    print(f"wrote {out}")

    img = sampling.ddim_sample(model, params, jax.random.PRNGKey(seed + 1),
                               k=acc_k, n=sample_n, mesh=mesh, eta=eta)
    nrows, ncols = grid_shape(sample_n)
    out = save_grid(img, get_next_path(os.path.join(saved, "samples.png")),
                    nrows=nrows, ncols=ncols)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
