#!/usr/bin/env python
"""Cold-sampling + zero-shot application entry point: ``python ViT_draft2drawing.py``.

Preserves the reference script's surface (ViT_draft2drawing.py:331-419): loads
the vit_tiny checkpoint from ``Saved_Models/20220822vit_tiny_diffusion/``,
renders the 6-level cold-diffusion sequence figure, then — given a draft image
— runs the zero-shot draft→drawing pipeline: encode the draft to each noise
level t_start ∈ range(1599, 2000, 50), DDIM-denoise with k=10, and tile the
nine variants into ``draft2img.png``. The slerp interpolation the reference
keeps commented out (ViT_draft2drawing.py:422-476) is live here behind
``--interpolate A B``.

Additions over the reference: ``--config/--checkpoint/--init-random`` (the
upstream snapshot ships no weights), ``--draft`` to point at any sketch, and
automatic TPU dispatch.
"""

import math
import os
import sys

import click

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)


def img2tensor(path: str, img_size):
    """Load an image file → NHWC float array in [−1, 1] (reference
    ViT_draft2drawing.py:331-339: resize then scale, no crop)."""
    import jax.numpy as jnp
    import numpy as np

    from ddim_cold_tpu.data.datasets import pil_loader
    from ddim_cold_tpu.data.resize import resize_bilinear

    img = np.asarray(pil_loader(path), np.float32) / 255.0
    img = resize_bilinear(img, tuple(img_size))
    return jnp.asarray(img * 2.0 - 1.0)[None]


@click.command()
@click.option("--config", "config_name", default="vit_tiny",
              help="Model config name (reference uses vit_tiny).")
@click.option("--checkpoint", default=None,
              help="Weights: torch .pkl or orbax dir "
                   "[default: Saved_Models/20220822vit_tiny_diffusion/bestloss.pkl].")
@click.option("--init-random", is_flag=True,
              help="Use random init instead of a checkpoint (smoke runs).")
@click.option("--draft", default=None,
              help="Draft/sketch image for the draft→drawing app.")
@click.option("--interpolate", nargs=2, default=None,
              help="Two images to slerp-interpolate between (C25).")
@click.option("--cold-n", default=49, help="Samples in the cold grid.")
@click.option("--seed", default=0, help="Sampling rng seed.")
@click.option("--eta", default=0.0,
              help="Stochastic-DDIM noise scale for the draft2img restarts "
                   "and the --interpolate decode (0 = the reference's "
                   "deterministic sampler).")
def main(config_name, checkpoint, init_random, draft, interpolate, cold_n,
         seed, eta):
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.ops import sampling
    from ddim_cold_tpu.utils import checkpoint as ckpt
    from ddim_cold_tpu.utils.platform import (
        enable_compile_cache, honor_env_platform, require_accelerator_or_exit,
    )

    honor_env_platform()
    require_accelerator_or_exit()  # wedged tunnel: exit 3, never hang
    enable_compile_cache()  # repeat CLI runs reuse compiled XLA programs
    from ddim_cold_tpu.utils.image import get_next_path, grid_shape, save_grid

    model = DiffusionViT(total_steps=2000, **MODEL_CONFIGS[config_name])
    saved = os.path.join(HERE, "Saved_Models")
    run_dir = os.path.join(saved, "20220822vit_tiny_diffusion")
    os.makedirs(run_dir, exist_ok=True)

    if init_random:
        params = model.init(
            jax.random.PRNGKey(seed),
            jnp.zeros((1, *model.img_size, 3)), jnp.zeros((1,), jnp.int32),
        )["params"]
    else:
        path = checkpoint or os.path.join(run_dir, "bestloss.pkl")
        if os.path.isdir(path):
            target = model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, *model.img_size, 3)), jnp.zeros((1,), jnp.int32),
            )["params"]
            params = ckpt.restore_checkpoint(path, target)
        else:
            params = ckpt.load_torch_pkl(path, model.patch_size)

    print(f"devices: {jax.devices()}")

    # --- cold-diffusion sequence figure (reference :364-376) -----------------
    # levels follow the model's own size (t ∈ [1, log2(H)]): 6 for the
    # reference's 64px configs, 7 for 200px via the additive --config flag
    levels = int(math.log2(model.img_size[0]))
    seq = sampling.cold_sample(model, params, jax.random.PRNGKey(seed),
                               n=cold_n, levels=levels, return_sequence=True)
    frames = jnp.swapaxes(seq, 0, 1).reshape(-1, *seq.shape[2:])
    out = save_grid(frames, get_next_path(os.path.join(saved, "cold_sequence.png")),
                    nrows=cold_n, ncols=seq.shape[0])
    print(f"wrote {out}")

    grid = sampling.cold_sample(model, params, jax.random.PRNGKey(seed + 1),
                                n=cold_n, levels=levels)
    nrows, ncols = grid_shape(cold_n)
    out = save_grid(grid, get_next_path(os.path.join(saved, "cold_samples.png")),
                    nrows=nrows, ncols=ncols)
    print(f"wrote {out}")

    # --- zero-shot draft→drawing (reference :378-419) ------------------------
    if draft is not None:
        x = img2tensor(draft, model.img_size)
        variants = []
        t_starts = list(range(1599, 2000, 50))  # 9 restart levels (:393)
        for i, t_start in enumerate(t_starts):
            noisy = sampling.forward_noise(
                jax.random.PRNGKey(seed + 100 + i), x, t_start, model.total_steps)
            variants.append(sampling.sample_from(
                model, params, noisy, t_start=t_start, k=10, eta=eta,
                rng=jax.random.PRNGKey(seed + 200 + i))[0])
        tiles = jnp.stack([(x[0] + 1.0) / 2.0] + variants)
        out = save_grid(tiles, get_next_path(os.path.join(saved, "draft2img.png")),
                        nrows=2, ncols=5)
        print(f"wrote {out}")

    # --- slerp interpolation (reference :422-476, dormant upstream) ----------
    if interpolate:
        a = img2tensor(interpolate[0], model.img_size)[0]
        b = img2tensor(interpolate[1], model.img_size)[0]
        frames = sampling.slerp_interpolate(
            model, params, jax.random.PRNGKey(seed + 500), a, b,
            n_interp=8, t_start=1800, k=10, eta=eta)
        out = save_grid(frames, get_next_path(os.path.join(saved, "interpolation.png")),
                        nrows=1, ncols=8)
        print(f"wrote {out}")


if __name__ == "__main__":
    main()
