// Native host-side data pipeline for ddim_cold_tpu.
//
// TPU-native equivalent of the machinery the reference reaches through torch's
// DataLoader worker *processes* (multi_gpu_trainer.py:63: num_workers=8 — PIL
// decode + torchvision resize running in forked CPython interpreters). Under
// SPMD there is one process per host, so the decode parallelism moves into
// this C++ library: libjpeg/libpng decode, torch-`F.interpolate`-convention
// resizes, the cold degradation operator D(x,t) (diffusion_loader.py:79-83),
// and a std::thread batch assembler that fills caller-owned float32 buffers —
// zero Python in the per-image path, fully outside the GIL.
//
// Resize conventions mirror ddim_cold_tpu/data/resize.py EXACTLY (they are
// observable in training targets):
//   nearest : src = floor(dst * in/out), clamped
//   bilinear: half-pixel centers, src=(dst+0.5)*scale-0.5, clamp at 0,
//             i0 = min(floor(src), in-1), i1 = min(i0+1, in-1), frac = src-i0
//
// Build: g++ -O3 -fPIC -shared ddim_data.cc -o libddim_data.so -ljpeg -lpng
// Python binding: ddim_cold_tpu/data/native.py (ctypes).

#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------------------
// decode: file → RGB8 (H, W, 3)
// ---------------------------------------------------------------------------

struct JpegErrorMgr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErrorMgr* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

void jpeg_silent(j_common_ptr, int) {}

// Decompression-bomb cap: a 4 KB file whose header claims 65535x65535 would
// otherwise commit a ~12.9 GB buffer which libjpeg's premature-EOF padding
// then touches page by page. Set to exactly 2x PIL's MAX_IMAGE_PIXELS — the
// threshold where PIL escalates its DecompressionBombWarning to an error —
// so no image the PIL tier would accept ever loses native acceleration, and
// every file this cap rejects is one the PIL fallback refuses too
// (DecompressionBombError, surfaced with the offending path by
// data/datasets.py pil_loader).
constexpr size_t kMaxPixels = 2 * 89478485ull;

// Decode a JPEG file to RGB8. Returns nullptr on any decode error (caller
// falls back to the PIL path). Defaults (islow DCT, fancy upsampling) match
// PIL's, which wraps the same libjpeg.
uint8_t* decode_jpeg(FILE* f, int* out_h, int* out_w) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_error_exit;
  jerr.mgr.emit_message = jpeg_silent;
  // volatile: assigned between setjmp and a possible longjmp — without it the
  // error path would free an indeterminate register copy.
  uint8_t* volatile buf = nullptr;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(buf);
    return nullptr;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // YCbCr/gray → RGB in-library
  jpeg_start_decompress(&cinfo);
  const int h = cinfo.output_height, w = cinfo.output_width;
  const int c = cinfo.output_components;
  if (c != 3) {  // out_color_space=JCS_RGB should guarantee 3
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  if (h <= 0 || w <= 0 ||
      static_cast<size_t>(h) * static_cast<size_t>(w) > kMaxPixels) {
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  buf = static_cast<uint8_t*>(std::malloc(static_cast<size_t>(h) * w * 3));
  if (!buf) {
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = buf + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out_h = h;
  *out_w = w;
  return buf;
}

// Decode a PNG file to RGB8 via the libpng simplified API (handles palette
// expansion and gray→RGB replication, both of which match PIL convert("RGB")
// exactly). PNGs with an alpha channel (incl. tRNS) or 16-bit depth are
// REJECTED → PIL fallback: libpng's simplified API composites/linearizes them
// differently from PIL, which would silently break the byte-parity contract.
uint8_t* decode_png(FILE* f, int* out_h, int* out_w) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_stdio(&image, f)) return nullptr;
  if (image.format & (PNG_FORMAT_FLAG_ALPHA | PNG_FORMAT_FLAG_LINEAR)) {
    png_image_free(&image);
    return nullptr;
  }
  image.format = PNG_FORMAT_RGB;
  if (image.height == 0 || image.width == 0 ||
      static_cast<size_t>(image.height) * image.width > kMaxPixels) {
    png_image_free(&image);
    return nullptr;
  }
  const size_t sz = PNG_IMAGE_SIZE(image);
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(sz));
  if (!buf) {
    png_image_free(&image);
    return nullptr;
  }
  if (!png_image_finish_read(&image, nullptr, buf, 0, nullptr)) {
    png_image_free(&image);
    std::free(buf);
    return nullptr;
  }
  *out_h = static_cast<int>(image.height);
  *out_w = static_cast<int>(image.width);
  return buf;
}

// Sniff format by magic bytes (extensions lie; unknown formats fail → the
// Python side redoes that slot via PIL).
uint8_t* decode_rgb8(const char* path, int* h, int* w) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  uint8_t magic[8] = {0};
  const size_t n = std::fread(magic, 1, sizeof(magic), f);
  std::rewind(f);
  uint8_t* buf = nullptr;
  if (n >= 3 && magic[0] == 0xFF && magic[1] == 0xD8 && magic[2] == 0xFF) {
    buf = decode_jpeg(f, h, w);
  } else if (n >= 8 && png_sig_cmp(magic, 0, 8) == 0) {
    buf = decode_png(f, h, w);
  }
  std::fclose(f);
  return buf;
}

// ---------------------------------------------------------------------------
// resize (torch F.interpolate conventions — see resize.py)
// ---------------------------------------------------------------------------

void nearest_indices(int out_size, int in_size, int* idx) {
  const double scale = static_cast<double>(in_size) / out_size;
  for (int i = 0; i < out_size; ++i) {
    int v = static_cast<int>(std::floor(i * scale));
    idx[i] = v < in_size - 1 ? v : in_size - 1;
  }
}

struct BilinearAxis {
  std::vector<int> i0, i1;
  std::vector<float> frac;
};

BilinearAxis bilinear_weights(int out_size, int in_size) {
  BilinearAxis ax;
  ax.i0.resize(out_size);
  ax.i1.resize(out_size);
  ax.frac.resize(out_size);
  const double scale = static_cast<double>(in_size) / out_size;
  for (int i = 0; i < out_size; ++i) {
    double src = (i + 0.5) * scale - 0.5;
    if (src < 0.0) src = 0.0;
    int i0 = static_cast<int>(std::floor(src));
    if (i0 > in_size - 1) i0 = in_size - 1;
    int i1 = i0 + 1 < in_size - 1 ? i0 + 1 : in_size - 1;
    ax.i0[i] = i0;
    ax.i1[i] = i1;
    // NOTE: frac is computed against the *clamped* i0 (resize.py order) and
    // in float32 to match `(src - i0).astype(np.float32)`.
    ax.frac[i] = static_cast<float>(src - i0);
  }
  return ax;
}

// (in_h, in_w, C) float32 → (out_h, out_w, C) float32, bilinear
// (align_corners=False, no antialias).
void resize_bilinear_f32(const float* in, int in_h, int in_w, int c,
                         int out_h, int out_w, float* out) {
  const BilinearAxis ay = bilinear_weights(out_h, in_h);
  const BilinearAxis axw = bilinear_weights(out_w, in_w);
  for (int y = 0; y < out_h; ++y) {
    const float fy = ay.frac[y];
    const float* top = in + static_cast<size_t>(ay.i0[y]) * in_w * c;
    const float* bot = in + static_cast<size_t>(ay.i1[y]) * in_w * c;
    float* dst = out + static_cast<size_t>(y) * out_w * c;
    for (int x = 0; x < out_w; ++x) {
      const float fx = axw.frac[x];
      const float* tl = top + static_cast<size_t>(axw.i0[x]) * c;
      const float* tr = top + static_cast<size_t>(axw.i1[x]) * c;
      const float* bl = bot + static_cast<size_t>(axw.i0[x]) * c;
      const float* br = bot + static_cast<size_t>(axw.i1[x]) * c;
      for (int ch = 0; ch < c; ++ch) {
        // match resize.py's operation order: rows = top·(1−fy)+bot·fy, then
        // left·(1−fx)+right·fx — float32 throughout for bit parity.
        const float left = tl[ch] * (1.0f - fy) + bl[ch] * fy;
        const float right = tr[ch] * (1.0f - fy) + br[ch] * fy;
        dst[x * c + ch] = left * (1.0f - fx) + right * fx;
      }
    }
  }
}

void resize_nearest_f32(const float* in, int in_h, int in_w, int c,
                        int out_h, int out_w, float* out) {
  std::vector<int> iy(out_h), ix(out_w);
  nearest_indices(out_h, in_h, iy.data());
  nearest_indices(out_w, in_w, ix.data());
  for (int y = 0; y < out_h; ++y) {
    const float* row = in + static_cast<size_t>(iy[y]) * in_w * c;
    float* dst = out + static_cast<size_t>(y) * out_w * c;
    for (int x = 0; x < out_w; ++x)
      std::memcpy(dst + static_cast<size_t>(x) * c,
                  row + static_cast<size_t>(ix[x]) * c, sizeof(float) * c);
  }
}

// ---------------------------------------------------------------------------
// item pipelines
// ---------------------------------------------------------------------------

// decode → /255 → bilinear(out_h, out_w) → ·2−1  (datasets.py _load_base /
// reference diffusion_loader.py:47-49 order). out: (out_h, out_w, 3) f32.
int load_base_impl(const char* path, int out_h, int out_w, float* out) {
  int h = 0, w = 0;
  uint8_t* rgb = decode_rgb8(path, &h, &w);
  if (!rgb) return 1;
  std::vector<float> unit(static_cast<size_t>(h) * w * 3);
  const size_t n = unit.size();
  // divide (not multiply-by-reciprocal): bit parity with numpy's `/ 255.0`
  for (size_t i = 0; i < n; ++i) unit[i] = rgb[i] / 255.0f;
  std::free(rgb);
  resize_bilinear_f32(unit.data(), h, w, 3, out_h, out_w, out);
  const size_t m = static_cast<size_t>(out_h) * out_w * 3;
  for (size_t i = 0; i < m; ++i) out[i] = out[i] * 2.0f - 1.0f;
  return 0;
}

// D(x, 2^t): nearest down to max(⌊size/2^t⌋, 1), nearest back up.
void cold_degrade_impl(const float* img, int size, int c, int level_scale,
                       float* out) {
  int target = size / level_scale;  // floor for positive ints
  if (target < 1) target = 1;
  if (target == size) {  // s=1 identity
    std::memcpy(out, img, sizeof(float) * static_cast<size_t>(size) * size * c);
    return;
  }
  std::vector<float> small(static_cast<size_t>(target) * target * c);
  resize_nearest_f32(img, size, size, c, target, target, small.data());
  resize_nearest_f32(small.data(), target, target, c, size, size, out);
}

// One cold-dataset item: (D(x,t), D(x,t−1) | x₀, t) — diffusion_loader.py:84-97.
int cold_item_impl(const char* path, int size, int t, int chain, float* noisy,
                   float* target) {
  std::vector<float> base(static_cast<size_t>(size) * size * 3);
  if (load_base_impl(path, size, size, base.data())) return 1;
  cold_degrade_impl(base.data(), size, 3, 1 << t, noisy);
  if (chain) {
    cold_degrade_impl(base.data(), size, 3, 1 << (t - 1), target);
  } else {
    std::memcpy(target, base.data(), sizeof(float) * base.size());
  }
  return 0;
}

// Simple work-stealing-free parallel for: threads pull indices off an atomic.
template <typename Fn>
int parallel_items(int n, int num_threads, Fn&& fn) {
  if (num_threads < 1) num_threads = 1;
  if (num_threads > n) num_threads = n;
  std::atomic<int> next(0), failures(0);
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1))
      if (fn(i)) failures.fetch_add(1);
  };
  if (num_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(num_threads);
    for (int i = 0; i < num_threads; ++i) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

}  // namespace

// ---------------------------------------------------------------------------
// C API (ctypes surface)
// ---------------------------------------------------------------------------

extern "C" {

const char* ddim_native_version() { return "ddim_data 1"; }

// file → (out_h, out_w, 3) float32 in [−1, 1]. Returns 0 on success.
int ddim_load_base(const char* path, int out_h, int out_w, float* out) {
  return load_base_impl(path, out_h, out_w, out);
}

// (size, size, c) float32 → D(x, level_scale) into out (same shape).
void ddim_cold_degrade(const float* img, int size, int c, int level_scale,
                       float* out) {
  cold_degrade_impl(img, size, c, level_scale, out);
}

int ddim_cold_item(const char* path, int size, int t, int chain, float* noisy,
                   float* target) {
  return cold_item_impl(path, size, t, chain, noisy, target);
}

// Batch of cold items into pre-allocated (n, size, size, 3) float32 buffers.
// Returns the number of FAILED items (0 = all good); failed slots are
// untouched and `failed`, when non-null, is an n-int32 mask the Python side
// uses to re-do stragglers via PIL.
int ddim_cold_batch(const char** paths, const int32_t* ts, int n, int size,
                    int chain, int num_threads, float* noisy, float* target,
                    int32_t* failed) {
  const size_t stride = static_cast<size_t>(size) * size * 3;
  if (failed) std::memset(failed, 0, sizeof(int32_t) * n);
  return parallel_items(n, num_threads, [&](int i) -> int {
    const int rc = cold_item_impl(paths[i], size, ts[i], chain,
                                  noisy + stride * i, target + stride * i);
    if (rc && failed) failed[i] = 1;
    return rc;
  });
}

// Batch of cold pairs computed from ALREADY-DECODED base images (the
// decoded-image cache's warm-epoch path): bases is (n, size, size, 3) float32
// in [−1,1]; writes (D(x,t), D(x,t−1) | x₀) into the output buffers.
void ddim_cold_pair_batch(const float* bases, const int32_t* ts, int n,
                          int size, int chain, int num_threads, float* noisy,
                          float* target) {
  const size_t stride = static_cast<size_t>(size) * size * 3;
  parallel_items(n, num_threads, [&](int i) -> int {
    const float* base = bases + stride * i;
    cold_degrade_impl(base, size, 3, 1 << ts[i], noisy + stride * i);
    if (chain) {
      cold_degrade_impl(base, size, 3, 1 << (ts[i] - 1), target + stride * i);
    } else {
      std::memcpy(target + stride * i, base, sizeof(float) * stride);
    }
    return 0;
  });
}

// Batch of decoded+resized base images ([−1,1]) — the shared front half of
// the Gaussian dataset (noise stays in numpy for Philox-stream parity).
int ddim_base_batch(const char** paths, int n, int out_h, int out_w,
                    int num_threads, float* out, int32_t* failed) {
  const size_t stride = static_cast<size_t>(out_h) * out_w * 3;
  if (failed) std::memset(failed, 0, sizeof(int32_t) * n);
  return parallel_items(n, num_threads, [&](int i) -> int {
    const int rc = load_base_impl(paths[i], out_h, out_w, out + stride * i);
    if (rc && failed) failed[i] = 1;
    return rc;
  });
}

// Batch of RAW RGB8 decodes for the uint8 transfer path: a slot succeeds only
// when the file decodes AND its native size is exactly (out_h, out_w) — no
// resize happens here, so the bytes are the exact pre-normalization pixels
// and (u8/255)·2−1 on device reproduces load_base bit-for-bit. Size-mismatch
// or decode-error slots set `failed` and the Python side falls back to the
// float path (which resizes).
int ddim_decode_batch(const char** paths, int n, int out_h, int out_w,
                      int num_threads, uint8_t* out, int32_t* failed) {
  const size_t stride = static_cast<size_t>(out_h) * out_w * 3;
  if (failed) std::memset(failed, 0, sizeof(int32_t) * n);
  return parallel_items(n, num_threads, [&](int i) -> int {
    int h = 0, w = 0;
    uint8_t* buf = decode_rgb8(paths[i], &h, &w);
    int rc = 1;
    if (buf && h == out_h && w == out_w) {
      std::memcpy(out + stride * i, buf, stride);
      rc = 0;
    }
    std::free(buf);
    if (rc && failed) failed[i] = 1;
    return rc;
  });
}

}  // extern "C"
