"""utils/platform: env-over-site-pin and the wedged-tunnel backend guard."""

import os

import pytest

from ddim_cold_tpu.utils import platform as plat


@pytest.fixture(autouse=True)
def _no_probe_cache(tmp_path, monkeypatch):
    """Point the probe's TTL marker at a fresh dir so tests never see (or
    leave) a cached success."""
    monkeypatch.setenv("TMPDIR", str(tmp_path))
    import tempfile

    monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))


def _force_platform(monkeypatch, value):
    """The guard resolves from jax.config first (conftest pins 'cpu' there);
    route it through the env instead for these tests."""
    import jax

    monkeypatch.setattr(
        type(jax.config), "jax_platforms",
        property(lambda self: value), raising=False)


def test_ensure_live_backend_skips_when_cpu_pinned():
    # conftest pins jax.config.jax_platforms = "cpu" — the common CLI case
    assert plat.ensure_live_backend()[0] == "default"


def test_ensure_live_backend_probe_success(monkeypatch):
    _force_platform(monkeypatch, "axon,cpu")
    got, reason = plat.ensure_live_backend(timeout_s=30, _probe_code="pass")
    assert got == "default" and reason == "probe ok"


def test_ensure_live_backend_caches_success(monkeypatch):
    _force_platform(monkeypatch, "axon,cpu")
    assert plat.ensure_live_backend(timeout_s=30, _probe_code="pass")[1] == "probe ok"
    got, reason = plat.ensure_live_backend(
        timeout_s=30, _probe_code="raise SystemExit(9)")
    assert got == "default" and "cached" in reason  # probe not re-run


def test_ensure_live_backend_times_out_to_cpu(monkeypatch):
    """A probe that never finishes (the wedged-tunnel claim loop) must pin
    this process to CPU instead of letting the caller hang forever."""
    import jax

    _force_platform(monkeypatch, "axon,cpu")
    update = jax.config.update
    seen = {}
    monkeypatch.setattr(
        type(jax.config), "update",
        lambda self, k, v: seen.update({k: v}) or update(k, v), raising=False)
    got, reason = plat.ensure_live_backend(
        timeout_s=1.0, _probe_code="import time; time.sleep(60)")
    assert got == "cpu" and "hung" in reason
    assert seen.get("jax_platforms") == "cpu"


def test_ensure_live_backend_reports_crash_not_timeout(monkeypatch):
    _force_platform(monkeypatch, "axon,cpu")
    got, reason = plat.ensure_live_backend(
        timeout_s=30,
        _probe_code="import sys; print('boom-detail', file=sys.stderr); sys.exit(3)")
    assert got == "cpu"
    assert "rc=3" in reason and "boom-detail" in reason and "hung" not in reason


def test_ensure_live_backend_passes_effective_platform_to_probe(monkeypatch):
    """The probe must validate the PARENT's effective platform (jax.config —
    site hooks write there), not whatever its own site hook would re-pin."""
    _force_platform(monkeypatch, "fakeplat")
    code = ("import os, sys\n"
            "sys.exit(0 if os.environ.get('DDIM_COLD_PROBE_PLATFORMS') == "
            "'fakeplat' else 7)")
    got, reason = plat.ensure_live_backend(timeout_s=30, _probe_code=code)
    assert got == "default", reason


def test_ensure_live_backend_retries_then_succeeds(tmp_path, monkeypatch):
    """A transiently-failing probe must be retried (with backoff) before the
    guard gives up the round's hardware record to a CPU fallback."""
    _force_platform(monkeypatch, "axon,cpu")
    flag = tmp_path / "second_attempt_flag"
    code = ("import os, sys\n"
            f"p = {str(flag)!r}\n"
            "if os.path.exists(p): sys.exit(0)\n"
            "open(p, 'w').close(); sys.exit(5)\n")
    got, reason = plat.ensure_live_backend(
        timeout_s=30, attempts=2, backoff_s=0.01, _probe_code=code)
    assert got == "default" and "attempt 2" in reason


def test_ensure_live_backend_exhausts_attempts(monkeypatch):
    _force_platform(monkeypatch, "axon,cpu")
    got, reason = plat.ensure_live_backend(
        timeout_s=30, attempts=3, backoff_s=0.01,
        _probe_code="import sys; sys.exit(2)")
    assert got == "cpu" and "3 attempts" in reason


def test_marker_path_is_per_user(monkeypatch, tmp_path):
    """The probe-success cache must not be shareable across users — a foreign
    stale marker would skip the probe against a wedged tunnel."""
    _force_platform(monkeypatch, "axon,cpu")
    plat.ensure_live_backend(timeout_s=30, _probe_code="pass")
    markers = [p for p in os.listdir(tmp_path)
               if p.startswith("ddim_cold_backend_ok_")]
    assert markers == [f"ddim_cold_backend_ok_{os.getuid()}_axon"]


def test_watch_tpu_probe_once():
    """scripts/watch_tpu.py probe primitive: live backend → ALIVE; a
    nonexistent platform → down with the subprocess rc, not a hang."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "watch_tpu", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "watch_tpu.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    alive, detail = mod.probe_once("cpu", timeout_s=60)
    assert alive, detail
    alive, detail = mod.probe_once("no_such_platform", timeout_s=60)
    assert not alive and detail.startswith("rc=")


def test_honor_env_platform_reapplies_env(monkeypatch):
    import jax

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    jax.config.update("jax_platforms", "cpu")  # conftest state; idempotent
    plat.honor_env_platform()
    assert (jax.config.jax_platforms or "").split(",")[0] == "cpu"
    monkeypatch.delenv("JAX_PLATFORMS")
    plat.honor_env_platform()  # unset env → no-op
    assert (jax.config.jax_platforms or "").split(",")[0] == "cpu"


def test_require_accelerator_or_exit(monkeypatch):
    """The CLI guard: pass-through when the backend is live or cpu-pinned,
    SystemExit(3) when an accelerator was configured but unreachable."""
    monkeypatch.setattr(plat, "ensure_live_backend",
                        lambda attempts=3: ("default", "probe ok"))
    plat.require_accelerator_or_exit()  # no raise

    monkeypatch.setattr(plat, "ensure_live_backend",
                        lambda attempts=3: ("cpu", "backend init probe hung"))
    # single-host TPU_WORKER_HOSTNAMES values (the axon image sets
    # 'localhost') must NOT disable the guard
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    with pytest.raises(SystemExit) as e:
        plat.require_accelerator_or_exit()
    assert e.value.code == 3

    # coordinated multi-host launches stand the guard down — a lone probe
    # cannot rendezvous a pod slice and would fail on healthy hardware
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1")
    plat.require_accelerator_or_exit()  # no raise
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    plat.require_accelerator_or_exit()  # no raise


def test_enable_compile_cache_env_off(monkeypatch, tmp_path):
    import jax

    monkeypatch.setenv("DDIM_COLD_COMPILE_CACHE", "off")
    assert plat.enable_compile_cache() is None
    monkeypatch.setenv("DDIM_COLD_COMPILE_CACHE", str(tmp_path / "cc"))
    assert plat.enable_compile_cache() == str(tmp_path / "cc")
    # restore the suite-wide cache dir (conftest.py) for later tests
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))
