"""Torch-free reader of torch zip checkpoints (utils/torch_pickle.py) —
parity against real ``torch.load``/``torch.save`` output (SURVEY.md §7 hard
part: conversion without torch installed)."""

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from ddim_cold_tpu.utils import torch_pickle  # noqa: E402


def test_reads_plain_tensor_dict(tmp_path):
    """dtypes, shapes, non-contiguous tensors, 0-dim tensors, nesting."""
    t = {
        "f32": torch.arange(6, dtype=torch.float32).reshape(2, 3),
        "noncontig": torch.arange(6, dtype=torch.float32).reshape(2, 3).t(),
        "f16": torch.full((3,), 1.5, dtype=torch.float16),
        "bf16": torch.full((4,), 0.25, dtype=torch.bfloat16),
        "i64": torch.arange(4),
        "u8": torch.arange(5, dtype=torch.uint8),
        "scalar": torch.tensor(7.0),
        "nested": {"x": torch.ones(2, 2)},
        "plain": 3,
        "lst": [torch.zeros(1), "s"],
    }
    p = str(tmp_path / "t.pkl")
    torch.save(t, p)
    got = torch_pickle.load(p)
    assert got["plain"] == 3 and got["lst"][1] == "s"
    for key, want in [("f32", t["f32"]), ("noncontig", t["noncontig"]),
                      ("f16", t["f16"]), ("i64", t["i64"]), ("u8", t["u8"]),
                      ("scalar", t["scalar"]), ("nested", t["nested"]["x"])]:
        g = got["nested"]["x"] if key == "nested" else got[key]
        np.testing.assert_array_equal(np.asarray(g, dtype=np.float64)
                                      if g.dtype != np.uint8 else g,
                                      want.numpy().astype(np.float64)
                                      if key != "u8" else want.numpy())
    assert got["bf16"].dtype.name == "bfloat16"
    np.testing.assert_array_equal(got["bf16"].astype(np.float32),
                                  t["bf16"].float().numpy())


def test_model_state_dict_parity_with_torch_load(tmp_path):
    """A real model state_dict round-trips identically through the torch-free
    reader and torch.load → the exact Flax tree either way."""
    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    import jax

    model = DiffusionViT(**MODEL_CONFIGS["vit_tiny"])
    params = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1, 64, 64, 3), np.float32), np.zeros((1,), np.int32)
    )["params"]
    p = str(tmp_path / "best.pkl")
    ckpt.save_torch_pkl(params, p, patch_size=8)

    via_torch = torch.load(p, map_location="cpu", weights_only=False)
    via_native = torch_pickle.load(p)
    assert set(via_native) == set(via_torch)
    for k in via_torch:
        np.testing.assert_array_equal(np.asarray(via_native[k]),
                                      via_torch[k].numpy())

    a = ckpt.flax_from_torch_state_dict(via_native, patch_size=8)
    b = ckpt.flax_from_torch_state_dict(
        {k: v.numpy() for k, v in via_torch.items()}, patch_size=8)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


def test_lastepoch_style_dict(tmp_path):
    """The reference's lastepoch layout: nested dict with non-tensor leaves
    and a DDP-prefixed state_dict (multi_gpu_trainer.py:155-163)."""
    sd = {"module.head.weight": torch.randn(4, 8),
          "module.head.bias": torch.zeros(4)}
    obj = {"epoch": 3, "steps": 1536, "loss_rec": 0.123, "metric": 0.05,
           "state_dict": sd}
    p = str(tmp_path / "last.pkl")
    torch.save(obj, p)
    got = torch_pickle.load(p)
    assert got["epoch"] == 3 and got["steps"] == 1536
    np.testing.assert_allclose(got["state_dict"]["module.head.weight"],
                               sd["module.head.weight"].numpy())


def test_load_torch_pkl_falls_back_without_torch(tmp_path, monkeypatch):
    """checkpoint.load_torch_pkl produces the same Flax tree when torch is
    unimportable (simulated) as when it is present."""
    import builtins

    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    import jax

    model = DiffusionViT(**MODEL_CONFIGS["vit_tiny"])
    params = model.init(
        jax.random.PRNGKey(1),
        np.zeros((1, 64, 64, 3), np.float32), np.zeros((1,), np.int32)
    )["params"]
    p = str(tmp_path / "best.pkl")
    ckpt.save_torch_pkl(params, p, patch_size=8)

    with_torch = ckpt.load_torch_pkl(p, patch_size=8)

    real_import = builtins.__import__

    def no_torch(name, *args, **kwargs):
        if name == "torch" or name.startswith("torch."):
            raise ImportError("torch disabled for this test")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_torch)
    without_torch = ckpt.load_torch_pkl(p, patch_size=8)
    monkeypatch.undo()

    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        with_torch, without_torch)


def test_non_zip_file_raises_clearly(tmp_path):
    p = str(tmp_path / "legacy.pkl")
    with open(p, "wb") as f:
        f.write(b"\x80\x02not a zip")
    with pytest.raises(Exception, match="[Zz]ip|torch"):
        torch_pickle.load(p)


def test_rejects_non_checkpoint_globals(tmp_path):
    """A pickle that reaches for a non-torch global (the classic os.system
    reduce) is refused instead of executed — pickle's default find_class
    would import and invoke it."""
    import io
    import pickle
    import zipfile

    class Evil:
        def __reduce__(self):
            import os
            return (os.system, ("true",))

    buf = io.BytesIO()
    pickle.dump({"x": Evil()}, buf)
    p = str(tmp_path / "evil.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
    with pytest.raises(pickle.UnpicklingError, match="refusing"):
        torch_pickle.load(p)
