"""Torch-free reader of torch zip checkpoints (utils/torch_pickle.py) —
parity against real ``torch.load``/``torch.save`` output (SURVEY.md §7 hard
part: conversion without torch installed)."""

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from ddim_cold_tpu.utils import torch_pickle  # noqa: E402


def test_reads_plain_tensor_dict(tmp_path):
    """dtypes, shapes, non-contiguous tensors, 0-dim tensors, nesting."""
    t = {
        "f32": torch.arange(6, dtype=torch.float32).reshape(2, 3),
        "noncontig": torch.arange(6, dtype=torch.float32).reshape(2, 3).t(),
        "f16": torch.full((3,), 1.5, dtype=torch.float16),
        "bf16": torch.full((4,), 0.25, dtype=torch.bfloat16),
        "i64": torch.arange(4),
        "u8": torch.arange(5, dtype=torch.uint8),
        "scalar": torch.tensor(7.0),
        "nested": {"x": torch.ones(2, 2)},
        "plain": 3,
        "lst": [torch.zeros(1), "s"],
    }
    p = str(tmp_path / "t.pkl")
    torch.save(t, p)
    got = torch_pickle.load(p)
    assert got["plain"] == 3 and got["lst"][1] == "s"
    for key, want in [("f32", t["f32"]), ("noncontig", t["noncontig"]),
                      ("f16", t["f16"]), ("i64", t["i64"]), ("u8", t["u8"]),
                      ("scalar", t["scalar"]), ("nested", t["nested"]["x"])]:
        g = got["nested"]["x"] if key == "nested" else got[key]
        np.testing.assert_array_equal(np.asarray(g, dtype=np.float64)
                                      if g.dtype != np.uint8 else g,
                                      want.numpy().astype(np.float64)
                                      if key != "u8" else want.numpy())
    assert got["bf16"].dtype.name == "bfloat16"
    np.testing.assert_array_equal(got["bf16"].astype(np.float32),
                                  t["bf16"].float().numpy())


def test_model_state_dict_parity_with_torch_load(tmp_path):
    """A real model state_dict round-trips identically through the torch-free
    reader and torch.load → the exact Flax tree either way."""
    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    import jax

    model = DiffusionViT(**MODEL_CONFIGS["vit_tiny"])
    params = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1, 64, 64, 3), np.float32), np.zeros((1,), np.int32)
    )["params"]
    p = str(tmp_path / "best.pkl")
    ckpt.save_torch_pkl(params, p, patch_size=8)

    via_torch = torch.load(p, map_location="cpu", weights_only=False)
    via_native = torch_pickle.load(p)
    assert set(via_native) == set(via_torch)
    for k in via_torch:
        np.testing.assert_array_equal(np.asarray(via_native[k]),
                                      via_torch[k].numpy())

    a = ckpt.flax_from_torch_state_dict(via_native, patch_size=8)
    b = ckpt.flax_from_torch_state_dict(
        {k: v.numpy() for k, v in via_torch.items()}, patch_size=8)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


def test_lastepoch_style_dict(tmp_path):
    """The reference's lastepoch layout: nested dict with non-tensor leaves
    and a DDP-prefixed state_dict (multi_gpu_trainer.py:155-163)."""
    sd = {"module.head.weight": torch.randn(4, 8),
          "module.head.bias": torch.zeros(4)}
    obj = {"epoch": 3, "steps": 1536, "loss_rec": 0.123, "metric": 0.05,
           "state_dict": sd}
    p = str(tmp_path / "last.pkl")
    torch.save(obj, p)
    got = torch_pickle.load(p)
    assert got["epoch"] == 3 and got["steps"] == 1536
    np.testing.assert_allclose(got["state_dict"]["module.head.weight"],
                               sd["module.head.weight"].numpy())


def test_load_torch_pkl_falls_back_without_torch(tmp_path, monkeypatch):
    """checkpoint.load_torch_pkl produces the same Flax tree when torch is
    unimportable (simulated) as when it is present."""
    import builtins

    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    import jax

    model = DiffusionViT(**MODEL_CONFIGS["vit_tiny"])
    params = model.init(
        jax.random.PRNGKey(1),
        np.zeros((1, 64, 64, 3), np.float32), np.zeros((1,), np.int32)
    )["params"]
    p = str(tmp_path / "best.pkl")
    ckpt.save_torch_pkl(params, p, patch_size=8)

    with_torch = ckpt.load_torch_pkl(p, patch_size=8)

    real_import = builtins.__import__

    def no_torch(name, *args, **kwargs):
        if name == "torch" or name.startswith("torch."):
            raise ImportError("torch disabled for this test")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_torch)
    without_torch = ckpt.load_torch_pkl(p, patch_size=8)
    monkeypatch.undo()

    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        with_torch, without_torch)


def test_non_zip_file_raises_clearly(tmp_path):
    p = str(tmp_path / "legacy.pkl")
    with open(p, "wb") as f:
        f.write(b"\x80\x02not a zip")
    with pytest.raises(Exception, match="[Zz]ip|torch"):
        torch_pickle.load(p)


def test_rejects_non_checkpoint_globals(tmp_path):
    """A pickle that reaches for a non-torch global (the classic os.system
    reduce) is refused instead of executed — pickle's default find_class
    would import and invoke it."""
    import io
    import pickle
    import zipfile

    class Evil:
        def __reduce__(self):
            import os
            return (os.system, ("true",))

    buf = io.BytesIO()
    pickle.dump({"x": Evil()}, buf)
    p = str(tmp_path / "evil.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
    with pytest.raises(pickle.UnpicklingError, match="refusing"):
        torch_pickle.load(p)


def test_torch_free_writer_read_by_real_torch(tmp_path):
    """save() output loads with real torch.load (weights_only both ways) and
    with this module's own reader."""
    import ml_dtypes

    obj = {"state_dict": {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                          "b": np.zeros(3, np.float16),
                          "bf": np.full((2,), 0.5, dtype=ml_dtypes.bfloat16)},
           "epoch": 5}
    p = str(tmp_path / "native.pkl")
    torch_pickle.save(obj, p)
    for weights_only in (False, True):
        got = torch.load(p, map_location="cpu", weights_only=weights_only)
        assert got["epoch"] == 5
        np.testing.assert_array_equal(got["state_dict"]["w"].numpy(),
                                      obj["state_dict"]["w"])
        np.testing.assert_array_equal(
            got["state_dict"]["bf"].float().numpy(),
            obj["state_dict"]["bf"].astype(np.float32))
    rt = torch_pickle.load(p)
    np.testing.assert_array_equal(rt["state_dict"]["w"],
                                  obj["state_dict"]["w"])


def test_save_torch_pkl_falls_back_without_torch(tmp_path, monkeypatch):
    """A torch-less host still exports a bestloss.pkl that REAL torch.load
    opens to the same state_dict the torch writer produces."""
    import builtins

    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    import jax

    model = DiffusionViT(**MODEL_CONFIGS["vit_tiny"])
    params = model.init(
        jax.random.PRNGKey(2),
        np.zeros((1, 64, 64, 3), np.float32), np.zeros((1,), np.int32)
    )["params"]
    p_torch = str(tmp_path / "via_torch.pkl")
    ckpt.save_torch_pkl(params, p_torch, patch_size=8)

    real_import = builtins.__import__

    def no_torch(name, *args, **kwargs):
        if name == "torch" or name.startswith("torch."):
            raise ImportError("torch disabled for this test")
        return real_import(name, *args, **kwargs)

    p_native = str(tmp_path / "via_native.pkl")
    monkeypatch.setattr(builtins, "__import__", no_torch)
    ckpt.save_torch_pkl(params, p_native, patch_size=8)
    monkeypatch.undo()

    a = torch.load(p_torch, map_location="cpu", weights_only=False)
    b = torch.load(p_native, map_location="cpu", weights_only=False)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k].numpy(), b[k].numpy())
        assert a[k].dtype == b[k].dtype


def test_numpy_metadata_and_parameters_load(tmp_path):
    """Checkpoint metadata carrying numpy scalars (common in lastepoch-style
    dicts) and nn.Parameter leaves both load on the torch-free path."""
    obj = {"loss_rec": np.float64(0.123), "metric": np.float32(0.05),
           "state_dict": {"w": torch.nn.Parameter(torch.ones(2, 3))}}
    p = str(tmp_path / "meta.pkl")
    torch.save(obj, p)
    got = torch_pickle.load(p)
    assert got["loss_rec"] == pytest.approx(0.123)
    np.testing.assert_array_equal(got["state_dict"]["w"], np.ones((2, 3)))


def test_loaded_arrays_are_writable_and_owned(tmp_path):
    """load() must hand back writable arrays that own their memory — a
    read-only view over the zip record bytes breaks in-place callers and
    pins whole storage buffers alive."""
    torch.save({"w": torch.ones(4, 4)}, str(tmp_path / "w.pkl"))
    got = torch_pickle.load(str(tmp_path / "w.pkl"))
    arr = got["w"]
    assert arr.flags.writeable and arr.flags.owndata
    arr *= 2  # must not raise
    np.testing.assert_array_equal(arr, 2 * np.ones((4, 4)))


_NT = __import__("collections").namedtuple("_NT", ["a", "b"])


def test_writer_refuses_namedtuples(tmp_path):
    """A namedtuple pickles as a GLOBAL of its defining module, which the
    strict reader refuses — the writer rejects it up front (write/read
    symmetry) with a conversion hint."""
    with pytest.raises(ValueError, match="not round-trippable"):
        torch_pickle.save({"cfg": _NT(np.zeros(2, np.float32), 1)},
                          str(tmp_path / "nt.pkl"))


def test_writer_edge_dtypes_and_shapes(tmp_path):
    """0-dim arrays keep their shape through real torch.load; explicitly
    big-endian dtypes are normalized (not silently byte-swapped on disk);
    unsupported dtypes fail with a clear error."""
    p = str(tmp_path / "edge.pkl")
    torch_pickle.save({"z": np.full((), 7.0, np.float32),  # true 0-dim
                       "be": np.arange(4, dtype=">f4")}, p)
    got = torch.load(p, map_location="cpu", weights_only=False)
    assert got["z"].shape == torch.Size([])
    assert float(got["z"]) == 7.0
    np.testing.assert_array_equal(got["be"].numpy(), np.arange(4, dtype=np.float32))
    with pytest.raises(ValueError, match="unsupported numpy dtype"):
        torch_pickle.save({"w": np.zeros(2, np.uint16)}, str(tmp_path / "u.pkl"))


def test_writer_refuses_unreadable_values(tmp_path):
    """save() rejects leaves its own load() couldn't read back (write/read
    symmetry): a set would pickle via a builtins global the strict reader
    refuses."""
    with pytest.raises(ValueError, match="unsupported value"):
        torch_pickle.save({"tags": {"a", "b"}}, str(tmp_path / "s.pkl"))
    # numpy scalar metadata is written as a plain Python scalar so even
    # torch>=2.6's default weights_only=True load accepts the file
    p = str(tmp_path / "m.pkl")
    torch_pickle.save({"loss": np.float64(0.5)}, p)
    assert torch_pickle.load(p)["loss"] == pytest.approx(0.5)
    got = torch.load(p, map_location="cpu", weights_only=True)
    assert got["loss"] == pytest.approx(0.5) and isinstance(got["loss"], float)


def test_oob_tensor_metadata_rejected(tmp_path):
    """size/stride/offset come from the pickle stream independently of the
    storage length — crafted values must raise, never read out of bounds."""
    import io
    import pickle
    import zipfile

    from ddim_cold_tpu.utils.torch_pickle import (_FakeGlobal,
                                                  _PersistentStorage,
                                                  _TorchPickler)

    def craft(size, stride, offset=0):
        pid = _PersistentStorage(
            ("storage", _FakeGlobal("torch", "FloatStorage"), "0", "cpu", 2))
        proxy_args = (pid, offset, size, stride, False,
                      __import__("collections").OrderedDict())

        class Raw:
            def __reduce__(self):
                return (_FakeGlobal("torch._utils", "_rebuild_tensor_v2"),
                        proxy_args)

        buf = io.BytesIO()
        _TorchPickler(buf, protocol=2).dump({"w": Raw()})
        p = str(tmp_path / "crafted.pkl")
        with zipfile.ZipFile(p, "w") as zf:
            zf.writestr("archive/data.pkl", buf.getvalue())
            zf.writestr("archive/data/0", np.ones(2, np.float32).tobytes())
        return p

    with pytest.raises(ValueError, match="corrupt tensor metadata"):
        torch_pickle.load(craft(size=(10**6,), stride=(1,)))
    with pytest.raises(ValueError, match="corrupt tensor metadata"):
        torch_pickle.load(craft(size=(2,), stride=(-1,), offset=1))
    # sane metadata over the same storage still loads
    got = torch_pickle.load(craft(size=(2,), stride=(1,)))
    np.testing.assert_array_equal(got["w"], np.ones(2, np.float32))


def test_tied_storages_share_one_read(tmp_path):
    """Two tensors over one storage (tied weights) load correctly and the
    storage record is materialized once."""
    base = torch.arange(6, dtype=torch.float32)
    t = {"a": base.view(2, 3), "b": base.view(3, 2)}
    p = str(tmp_path / "tied.pkl")
    torch.save(t, p)
    got = torch_pickle.load(p)
    np.testing.assert_array_equal(got["a"], base.view(2, 3).numpy())
    np.testing.assert_array_equal(got["b"], base.view(3, 2).numpy())


def test_unknown_rebuild_flavor_raises(tmp_path):
    """A rebuild function this reader doesn't implement must surface the
    'load with torch' escape hatch, not silently return a stub."""
    import io
    import pickle
    import zipfile

    from ddim_cold_tpu.utils.torch_pickle import _FakeGlobal, _TorchPickler

    class Raw:
        def __reduce__(self):
            return (_FakeGlobal("torch._utils", "_rebuild_qtensor"), (1,))

    buf = io.BytesIO()
    _TorchPickler(buf, protocol=2).dump({"w": Raw()})
    p = str(tmp_path / "q.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
    with pytest.raises(pickle.UnpicklingError, match="load with torch"):
        torch_pickle.load(p)


def test_writer_dedups_shared_arrays(tmp_path):
    """The same ndarray object written twice produces one storage record,
    and torch.load returns tensors sharing storage (tied weights survive)."""
    import zipfile

    w = np.arange(8, dtype=np.float32)
    p = str(tmp_path / "tied_w.pkl")
    torch_pickle.save({"a": w, "b": w}, p)
    with zipfile.ZipFile(p) as zf:
        assert [n for n in zf.namelist() if "/data/" in n] == ["archive/data/0"]
    got = torch.load(p, map_location="cpu", weights_only=False)
    assert got["a"].data_ptr() == got["b"].data_ptr()  # tie preserved


def test_materialization_cap_rejects_expand_bombs(tmp_path):
    """0-stride/huge-size metadata (cheap view under torch.load, full copy
    here) must hit the byte cap, not attempt a TiB allocation."""
    import io
    import zipfile

    from ddim_cold_tpu.utils.torch_pickle import (_FakeGlobal,
                                                  _PersistentStorage,
                                                  _TorchPickler)

    pid = _PersistentStorage(
        ("storage", _FakeGlobal("torch", "FloatStorage"), "0", "cpu", 2))

    class Raw:
        def __reduce__(self):
            return (_FakeGlobal("torch._utils", "_rebuild_tensor_v2"),
                    (pid, 0, (10**12,), (0,), False,
                     __import__("collections").OrderedDict()))

    buf = io.BytesIO()
    _TorchPickler(buf, protocol=2).dump({"w": Raw()})
    p = str(tmp_path / "bomb.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
        zf.writestr("archive/data/0", np.ones(2, np.float32).tobytes())
    with pytest.raises(ValueError, match="materialization cap"):
        torch_pickle.load(p)


def test_writer_validates_dict_keys(tmp_path):
    """Keys get the same conversion/refusal as values: numpy-scalar keys
    become Python scalars (weights_only-safe); non-round-trippable keys are
    refused."""
    p = str(tmp_path / "k.pkl")
    torch_pickle.save({np.int64(3): np.ones(1, np.float32)}, p)
    got = torch.load(p, map_location="cpu", weights_only=True)
    assert list(got) == [3] and isinstance(list(got)[0], int)
    assert torch_pickle.load(p)[3] is not None
    with pytest.raises(ValueError, match="unsupported value"):
        torch_pickle.save({frozenset({"a"}): 1}, str(tmp_path / "fk.pkl"))


def test_writer_payload_byte_sizing(tmp_path):
    """The zip record length must be the byte count, not the element count,
    for multi-byte dtypes (zipfile's zip64 sizing reads len())."""
    import zipfile

    p = str(tmp_path / "f64.pkl")
    torch_pickle.save({"w": np.arange(10, dtype=np.float64)}, p)
    with zipfile.ZipFile(p) as zf:
        assert zf.getinfo("archive/data/0").file_size == 80
    np.testing.assert_array_equal(
        torch.load(p, weights_only=True)["w"].numpy(),
        np.arange(10, dtype=np.float64))


def test_tensor_subclasses_load_as_base_arrays(tmp_path):
    """nn.Buffer / tensor subclasses (pickled via _rebuild_from_type_v2)
    load as their underlying arrays — never as silent stubs."""
    p = str(tmp_path / "buf.pkl")
    torch.save({"w": torch.nn.Buffer(torch.ones(2, 2))}, p)
    got = torch_pickle.load(p)
    np.testing.assert_array_equal(got["w"], np.ones((2, 2), np.float32))


def test_empty_bytes_round_trip(tmp_path):
    """Empty bytes pickle as the bytes global itself (non-empty go via
    _codecs.encode) — both must round-trip through the torch-free pair."""
    p = str(tmp_path / "eb.pkl")
    torch_pickle.save({"empty": b"", "tag": b"abc"}, p)
    got = torch_pickle.load(p)
    assert got["empty"] == b"" and got["tag"] == b"abc"


def test_conflicting_pids_on_shared_key_rejected(tmp_path):
    """A second persistent id reusing a storage key with different dtype or
    numel must be validated, not ride the first pid's cache entry."""
    import io
    import zipfile

    from ddim_cold_tpu.utils.torch_pickle import (_FakeGlobal,
                                                  _PersistentStorage,
                                                  _TorchPickler)

    def tensor_raw(storage_name, numel, size):
        pid = _PersistentStorage(
            ("storage", _FakeGlobal("torch", storage_name), "0", "cpu", numel))

        class Raw:
            def __reduce__(self):
                return (_FakeGlobal("torch._utils", "_rebuild_tensor_v2"),
                        (pid, 0, size, (1,), False,
                         __import__("collections").OrderedDict()))

        return Raw()

    buf = io.BytesIO()
    _TorchPickler(buf, protocol=2).dump(
        {"a": tensor_raw("FloatStorage", 2, (2,)),
         "b": tensor_raw("LongStorage", 99, (1,))})
    p = str(tmp_path / "conflict.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
        zf.writestr("archive/data/0", np.ones(2, np.float32).tobytes())
    with pytest.raises(ValueError, match="conflicting persistent ids"):
        torch_pickle.load(p)


def test_save_is_atomic(tmp_path, monkeypatch):
    """A crash mid-write must leave no truncated zip at the destination (a
    corrupt warm-start file would crash every later run)."""
    import zipfile as zf_mod

    p = str(tmp_path / "atomic.pkl")
    torch_pickle.save({"w": np.ones(2, np.float32)}, p)  # good file exists

    real_writestr = zf_mod.ZipFile.writestr
    calls = {"n": 0}

    def crashing_writestr(self, *a, **k):
        calls["n"] += 1
        if calls["n"] >= 3:
            raise OSError("disk full")
        return real_writestr(self, *a, **k)

    monkeypatch.setattr(zf_mod.ZipFile, "writestr", crashing_writestr)
    with pytest.raises(OSError, match="disk full"):
        torch_pickle.save({"w": np.zeros(4, np.float32)}, p)
    monkeypatch.undo()
    assert not os.path.exists(p + ".writing")
    got = torch_pickle.load(p)  # previous good file intact
    np.testing.assert_array_equal(got["w"], np.ones(2, np.float32))


def test_ndarray_allocation_bomb_rejected(tmp_path):
    """REDUCE(numpy.ndarray, ((2**40,),)) in a crafted stream must hit the
    materialization cap, not allocate terabytes."""
    import io
    import pickle
    import zipfile

    buf = io.BytesIO()
    buf.write(b"\x80\x02")                    # PROTO 2
    buf.write(b"cnumpy\nndarray\n")           # GLOBAL numpy ndarray
    buf.write(b"\x8a\x08" + (2 ** 40).to_bytes(8, "little"))  # LONG1 2**40
    buf.write(b"\x85")                        # TUPLE1 → (2**40,)  the shape
    buf.write(b"\x85")                        # TUPLE1 → ((2**40,),) REDUCE args
    buf.write(b"R")                           # REDUCE → ndarray((2**40,))
    buf.write(b".")                           # STOP
    p = str(tmp_path / "bomb2.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
    with pytest.raises(Exception, match="materialization cap|refusing"):
        torch_pickle.load(p)


def test_reconstruct_allocation_bomb_rejected(tmp_path):
    """REDUCE(numpy _reconstruct, (ndarray, (2**40,), b'b')) — the bootstrap
    numpy itself uses — must hit the cap, not allocate at the C level."""
    import io
    import zipfile

    buf = io.BytesIO()
    buf.write(b"\x80\x02")
    buf.write(b"cnumpy._core.multiarray\n_reconstruct\n")
    buf.write(b"cnumpy\nndarray\n")
    buf.write(b"\x8a\x08" + (2 ** 40).to_bytes(8, "little"))
    buf.write(b"\x85")                        # (2**40,)
    buf.write(b"C\x01b")                      # SHORT_BINBYTES b'b'
    buf.write(b"\x87")                        # TUPLE3 args
    buf.write(b"R.")                          # REDUCE, STOP
    p = str(tmp_path / "bomb3.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
    with pytest.raises(Exception, match="materialization cap"):
        torch_pickle.load(p)


def test_reconstruct_large_itemsize_bomb_rejected(tmp_path):
    """A crafted huge-itemsize dtype must not stretch an in-cap element
    count into a huge allocation."""
    import io
    import zipfile

    buf = io.BytesIO()
    buf.write(b"\x80\x02")
    buf.write(b"cnumpy._core.multiarray\n_reconstruct\n")
    buf.write(b"cnumpy\nndarray\n")
    buf.write(b"M\x00\x04\x85")               # (1024,) — in-cap element count
    buf.write(b"cnumpy\ndtype\n")
    buf.write(b"U\x0aV100000000\x85R")        # dtype('V100000000')
    buf.write(b"\x87R.")                      # TUPLE3, REDUCE, STOP
    p = str(tmp_path / "bomb4.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
    with pytest.raises(Exception, match="materialization cap"):
        torch_pickle.load(p)


def test_setstate_allocation_bomb_and_object_dtype_rejected(tmp_path):
    """BUILD-opcode state must not re-allocate past the cap (list payloads
    skip numpy's length check) and object dtypes are refused outright."""
    import io
    import zipfile

    def crafted(shape_bytes, dtype_bytes):
        buf = io.BytesIO()
        buf.write(b"\x80\x02")
        buf.write(b"cnumpy._core.multiarray\n_reconstruct\n")
        buf.write(b"cnumpy\nndarray\nK\x00\x85C\x01b\x87R")  # bootstrap
        buf.write(b"(K\x01")                                 # MARK, version 1
        buf.write(shape_bytes)                               # shape tuple
        buf.write(b"cnumpy\ndtype\n" + dtype_bytes + b"\x85R")
        buf.write(b"\x89")                                   # False
        buf.write(b"]K\x01a")                                # [1]
        buf.write(b"t")                                      # TUPLE (state)
        buf.write(b"b.")                                     # BUILD, STOP
        p = str(tmp_path / "sb.pkl")
        with zipfile.ZipFile(p, "w") as zf:
            zf.writestr("archive/data.pkl", buf.getvalue())
        return p

    big_shape = b"\x8a\x05" + (10 ** 10).to_bytes(5, "little") + b"\x85"
    with pytest.raises(Exception, match="materialization cap|object-dtype"):
        torch_pickle.load(crafted(big_shape, b"U\x02i8"))
    small_shape = b"K\x01\x85"
    with pytest.raises(Exception, match="object-dtype"):
        torch_pickle.load(crafted(small_shape, b"U\x01O"))


def test_non_torch_packages_refused_not_stubbed(tmp_path):
    """torchvision/torch_* globals must hit the loud refusal, not a silent
    stub (stubs are for torch-proper passive singletons only)."""
    import io
    import pickle
    import zipfile

    buf = io.BytesIO()
    buf.write(b"\x80\x02ctorchvision.transforms\nCompose\n)R.")
    p = str(tmp_path / "tv.pkl")
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", buf.getvalue())
    with pytest.raises(pickle.UnpicklingError, match="refusing"):
        torch_pickle.load(p)


def test_stale_writing_dir_cleared(tmp_path):
    """A leftover '<path>.writing' DIRECTORY (crashed orbax save with the
    same suffix) must be cleared, not crash every later save."""
    p = str(tmp_path / "w.pkl")
    os.makedirs(p + ".writing/sub")
    torch_pickle.save({"w": np.ones(2, np.float32)}, p)
    assert not os.path.exists(p + ".writing")
    np.testing.assert_array_equal(torch_pickle.load(p)["w"],
                                  np.ones(2, np.float32))
