"""Native C++ data-pipeline parity (native/ddim_data.cc via data/native.py).

The native path must be a pure accelerator: byte-for-byte the same tensors as
the PIL/numpy reference path (datasets.py / resize.py) on the formats it
handles, and a graceful fallback everywhere else. JPEG decode parity is exact
because PIL wraps the same libjpeg with the same defaults; the resize math is
written to match resize.py's float32 operation order.
"""

import os

import numpy as np
import pytest
from PIL import Image

from ddim_cold_tpu.data import native, resize
from ddim_cold_tpu.data.datasets import (
    ColdDownSampleDataset,
    DiffusionDataset,
    _load_base,
)
from ddim_cold_tpu.data.loader import ShardedLoader

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@pytest.fixture(scope="module")
def mixed_image_dir(tmp_path_factory):
    """jpg + png + bmp (bmp exercises the PIL fallback inside native batches)."""
    root = tmp_path_factory.mktemp("mixed_imgs")
    rs = np.random.RandomState(7)
    for i, ext in enumerate(["jpg", "jpg", "png", "png", "bmp", "jpg"]):
        arr = rs.randint(0, 255, size=(70 + i, 90 - i, 3), dtype=np.uint8)
        Image.fromarray(arr).save(root / f"{i}.{ext}")
    # a grayscale png (native must replicate channels like PIL convert("RGB"))
    Image.fromarray(rs.randint(0, 255, size=(50, 40), dtype=np.uint8)).save(
        root / "9_gray.png")
    return str(root)


def test_load_base_parity(mixed_image_dir):
    for name in sorted(os.listdir(mixed_image_dir)):
        path = os.path.join(mixed_image_dir, name)
        via_pil = _load_base(path, (64, 64), use_native=False)
        via_native = native.load_base(path, (64, 64))
        if os.path.splitext(name)[1] == ".bmp":
            assert via_native is None  # unsupported → caller falls back
            continue
        assert via_native is not None, name
        np.testing.assert_array_equal(via_native, via_pil.astype(np.float32),
                                      err_msg=name)


def test_png_alpha_and_16bit_rejected(tmp_path):
    """PNGs whose PIL conversion libpng can't reproduce exactly (alpha
    composite, 16-bit scaling) must be REJECTED → PIL fallback, not silently
    decoded differently."""
    rs = np.random.RandomState(3)
    rgba = tmp_path / "a.png"
    Image.fromarray(rs.randint(0, 255, (32, 32, 4), dtype=np.uint8), "RGBA").save(rgba)
    i16 = tmp_path / "b.png"
    Image.fromarray(rs.randint(0, 65535, (32, 32), dtype=np.uint16)).save(i16)
    for path in (rgba, i16):
        assert native.load_base(str(path), (16, 16)) is None
        # and the dataset path still produces the PIL result
        got = _load_base(str(path), (16, 16))
        want = _load_base(str(path), (16, 16), use_native=False)
        np.testing.assert_array_equal(got, want)


def test_cold_degrade_parity(rng):
    img = rng.randn(64, 64, 3).astype(np.float32)
    for t in range(1, 7):
        want = resize.cold_degrade(img, 2**t, 64)
        got = native.cold_degrade(img, 2**t)
        np.testing.assert_array_equal(got, want, err_msg=f"t={t}")


@pytest.mark.parametrize("mode", ["chain", "direct"])
def test_cold_item_and_batch_parity(mixed_image_dir, mode):
    ds_native = ColdDownSampleDataset(mixed_image_dir, (64, 64), target_mode=mode)
    ds_pil = ColdDownSampleDataset(mixed_image_dir, (64, 64), target_mode=mode,
                                   use_native=False)
    n = len(ds_native)
    # per-item parity (same seed ⇒ same t draws)
    for i in range(n):
        a_noisy, a_target, a_t = ds_native[i]
        b_noisy, b_target, b_t = ds_pil[i]
        assert a_t == b_t
        np.testing.assert_array_equal(a_noisy, b_noisy)
        np.testing.assert_array_equal(a_target, b_target)
    # batch fast path (includes the bmp fallback slot)
    batch = ds_native.get_batch(list(range(n)))
    assert batch is not None
    noisy, target, ts = batch
    for i in range(n):
        b_noisy, b_target, b_t = ds_pil[i]
        assert int(ts[i]) == b_t
        np.testing.assert_array_equal(noisy[i], b_noisy)
        np.testing.assert_array_equal(target[i], b_target)


def test_gaussian_batch_parity(synthetic_image_dir):
    ds_native = DiffusionDataset(synthetic_image_dir, (32, 32), max_step=100)
    ds_pil = DiffusionDataset(synthetic_image_dir, (32, 32), max_step=100,
                              use_native=False)
    batch = ds_native.get_batch(list(range(len(ds_native))))
    assert batch is not None
    noisy, target, ts = batch
    for i in range(len(ds_pil)):
        b_noisy, b_target, b_t = ds_pil[i]
        assert int(ts[i]) == b_t
        np.testing.assert_array_equal(noisy[i], b_noisy)
        np.testing.assert_array_equal(target[i], b_target)


def test_loader_uses_native_batches(mixed_image_dir):
    """End-to-end: the loader's batches are identical with and without the
    native backend (shuffle order is loader-side, decode is dataset-side)."""
    kwargs = dict(batch_size=3, shuffle=True, seed=5, drop_last=True)
    l_native = ShardedLoader(ColdDownSampleDataset(mixed_image_dir, (64, 64)), **kwargs)
    l_pil = ShardedLoader(
        ColdDownSampleDataset(mixed_image_dir, (64, 64), use_native=False), **kwargs)
    for (an, at, att), (bn, bt, btt) in zip(l_native, l_pil):
        np.testing.assert_array_equal(an, bn)
        np.testing.assert_array_equal(at, bt)
        np.testing.assert_array_equal(att, btt)


def test_env_kill_switch(monkeypatch, synthetic_image_dir):
    """DDIM_COLD_NO_NATIVE force-disables the library; the batch path then
    degrades to the PIL tier inline with identical bytes."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_lib_failed", False)
    monkeypatch.setenv("DDIM_COLD_NO_NATIVE", "1")
    assert not native.available()
    assert native.decode_batch(["x.jpg"], (8, 8)) is None
    ds = DiffusionDataset(synthetic_image_dir, (32, 32))
    got = ds.get_batch([0, 1])
    assert got is not None  # PIL tier fills the batch when the lib is off
    pil_ds = DiffusionDataset(synthetic_image_dir, (32, 32), use_native=False)
    items = [pil_ds[0], pil_ds[1]]
    np.testing.assert_array_equal(got[0], np.stack([it[0] for it in items]))
    np.testing.assert_array_equal(got[1], np.stack([it[1] for it in items]))
    np.testing.assert_array_equal(got[2], np.asarray([it[2] for it in items]))
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_lib_failed", False)


@pytest.mark.parametrize("mode", ["chain", "direct"])
def test_cold_pair_batch_parity(rng, mode):
    """Warm-cache C++ degrade path == numpy degrade, bit for bit."""
    if not native.available():
        pytest.skip("native library unavailable")
    bases = rng.randn(5, 64, 64, 3).astype(np.float32)
    ts = [1, 3, 6, 2, 4]
    pair = native.cold_pair_batch(bases, ts, chain=(mode == "chain"))
    if pair is None:
        pytest.skip("stale .so without ddim_cold_pair_batch")
    noisy, target = pair
    for j, t in enumerate(ts):
        np.testing.assert_array_equal(noisy[j], resize.cold_degrade(bases[j], 2**t, 64))
        want_t = (resize.cold_degrade(bases[j], 2 ** (t - 1), 64)
                  if mode == "chain" else bases[j])
        np.testing.assert_array_equal(target[j], want_t)
