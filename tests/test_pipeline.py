"""Pipeline parallelism on the 8-virtual-device mesh (parallel/pipeline.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.parallel import make_mesh, make_pipelined_apply, pipeline_param_specs

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)

CFG = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=4, num_heads=4)


@pytest.fixture(scope="module")
def scanned_model_and_params():
    model = DiffusionViT(scan_blocks=True, **CFG)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 16, 16, 3), jnp.float32)
    t = jnp.array([1, 5, 9, 100, 400, 1999, 0, 7], jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), x, t)["params"]
    return model, params, x, t


@pytest.mark.parametrize("mesh_shape,n_micro", [
    ({"data": 2, "pipe": 4}, 2),
    ({"pipe": 2}, 4),
    ({"data": 4, "pipe": 2}, 2),
])
def test_pipelined_forward_matches_scanned(scanned_model_and_params, mesh_shape, n_micro):
    model, params, x, t = scanned_model_and_params
    n_dev = int(np.prod(list(mesh_shape.values())))
    mesh = make_mesh(mesh_shape, devices=jax.devices()[:n_dev])
    pf = make_pipelined_apply(model, mesh, n_microbatch=n_micro)
    want = np.asarray(jax.jit(model.apply)({"params": params}, x, t))
    got = np.asarray(jax.jit(pf)({"params": params}, x, t))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pipelined_composes_with_tp(scanned_model_and_params):
    """pipe×tp (VERDICT r4 weak #6, previously refused): {data, pipe, model}
    mesh, stage kernels Megatron-split over the GSPMD-auto 'model' axis via
    pipeline_param_specs(tensor_axes=...). Forward AND grads must match the
    plain scanned model, and the param shardings must actually carry both
    the stage and the tensor split."""
    from jax.sharding import NamedSharding

    model, params, x, t = scanned_model_and_params
    mesh = make_mesh({"data": 2, "pipe": 2, "model": 2})
    specs = pipeline_param_specs(params, tensor_axes=("model",))
    qkv_spec = specs["blocks"]["attn"]["qkv"]["kernel"]
    assert tuple(qkv_spec) == ("pipe", None, "model"), qkv_spec
    sharded = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs)
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)

    want = np.asarray(jax.jit(model.apply)({"params": params}, x, t))
    got = np.asarray(jax.jit(pf)({"params": sharded}, x, t))
    np.testing.assert_allclose(got, want, atol=1e-5)

    ga = jax.jit(jax.grad(
        lambda p: jnp.mean(model.apply({"params": p}, x, t) ** 2)))(params)
    gb = jax.jit(jax.grad(
        lambda p: jnp.mean(pf({"params": p}, x, t) ** 2)))(sharded)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipelined_remat_grads_match(scanned_model_and_params):
    """remat=True wraps each stage block in jax.checkpoint INSIDE the
    manual region — gradients must equal the non-remat pipeline (remat is
    a memory/flops trade, never a math change), including under pipe×sp
    where the recomputation replays the ring collectives."""
    model, params, x, t = scanned_model_and_params
    rmodel = DiffusionViT(scan_blocks=True, remat=True, **CFG)
    mesh = make_mesh({"data": 2, "pipe": 2, "seq": 2})
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)
    rpf = make_pipelined_apply(rmodel, mesh, n_microbatch=2)
    ga = jax.jit(jax.grad(
        lambda p: jnp.mean(pf({"params": p}, x, t) ** 2)))(params)
    gb = jax.jit(jax.grad(
        lambda p: jnp.mean(rpf({"params": p}, x, t) ** 2)))(params)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipelined_steps_per_dispatch_step(scanned_model_and_params):
    """The grouped multi-step dispatch (one lax.scan over n optimizer
    steps) composes with the pipelined apply_fn — the network-attached-host
    lever and the depth lever together."""
    from ddim_cold_tpu.parallel import shard_batch, shard_train_state
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    model, params, x, t = scanned_model_and_params
    mesh = make_mesh({"data": 2, "pipe": 4})
    batch = (x, x, t)
    state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                               total_steps=10, sample_batch=batch)
    state = shard_train_state(state, mesh, pipeline_param_specs(state.params))
    step = make_train_step(
        model, make_pipelined_apply(model, mesh, n_microbatch=2),
        steps_per_dispatch=2)
    grouped = jax.tree.map(lambda a: jnp.stack([a, a]), batch)
    state, loss, _ = step(state, shard_batch(grouped, mesh, grouped=True),
                          jax.random.PRNGKey(1), jnp.float32(5.0))
    assert np.isfinite(float(loss)), loss
    assert int(state.step) == 2


def test_pipelined_grads_match(scanned_model_and_params):
    model, params, x, t = scanned_model_and_params
    mesh = make_mesh({"data": 2, "pipe": 4})
    pf = make_pipelined_apply(model, mesh, n_microbatch=4)

    # jit the grads: eager transform dispatch on the 8-device CPU mesh is the
    # suite's single slowest test otherwise (~30s vs ~8s)
    ga = jax.jit(jax.grad(
        lambda p: jnp.mean(model.apply({"params": p}, x, t) ** 2)))(params)
    gb = jax.jit(jax.grad(
        lambda p: jnp.mean(pf({"params": p}, x, t) ** 2)))(params)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipelined_training_mode_finite(scanned_model_and_params):
    model, params, x, t = scanned_model_and_params
    mesh = make_mesh({"data": 2, "pipe": 4})
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)
    y = jax.jit(lambda p, x, t: pf(
        {"params": p}, x, t, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(3)}))(params, x, t)
    assert bool(jnp.isfinite(y).all())


def test_pipeline_param_specs_shard_blocks_only(scanned_model_and_params):
    _, params, _, _ = scanned_model_and_params
    specs = pipeline_param_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    from jax.sharding import PartitionSpec as P

    for path, spec in flat:
        names = [getattr(k, "key", str(k)) for k in path]
        if names[0] == "blocks":
            assert spec == P("pipe"), names
        else:
            assert spec == P(), names


def test_pipeline_rejects_bad_shapes(scanned_model_and_params):
    model, params, x, t = scanned_model_and_params
    mesh = make_mesh({"pipe": 3}, devices=jax.devices()[:3])  # depth 4 % 3 != 0
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)
    with pytest.raises(ValueError, match="divisible"):
        pf({"params": params}, x, t)
    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    pf = make_pipelined_apply(model, mesh, n_microbatch=3)  # 8 % 3 != 0
    with pytest.raises(ValueError, match="divisible"):
        pf({"params": params}, x, t)


@pytest.mark.isolated
def test_pipeline_training_end_to_end(tmp_path, synthetic_image_dir):
    """Full trainer run on mesh {data:2, pipe:2}: pipelined step + stage-
    sharded optimizer state + checkpoints."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="pp", framework="pipe", batch_size=2, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=2, head=2,
        mesh={"data": 2, "pipe": 2}, microbatches=2,
    )
    result = run(cfg, str(tmp_path), max_steps=2)
    assert np.isfinite(result.best_loss)
    import os

    assert os.path.isdir(os.path.join(result.run_dir, "lastepoch.ckpt"))


@pytest.mark.isolated
def test_pipeline_trainer_composes_with_tp(synthetic_image_dir, tmp_path):
    """YAML mesh {model, pipe} trains end to end (previously rejected):
    layout_for_mesh hands pipeline_param_specs the tensor axes and the
    executor leaves 'model' in GSPMD auto mode."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="ppx", framework="pipe", batch_size=4, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=2, head=2,
        mesh={"model": 2, "pipe": 2}, microbatches=2,
    )
    result = run(cfg, str(tmp_path), max_steps=2)
    assert np.isfinite(result.best_loss)


def test_pipelined_composes_with_moe():
    """pipe×MoE (the last composition gap, VERDICT r4 weak #6 — previously
    refused because the stage body dropped sown collections): outputs match
    the plain MoE model, and the re-sown aux equals the plain path's sown
    leaf averaged per microbatch (pipe-only mesh ⇒ identical router stats:
    the pipeline's Switch router sees B/M samples per call, so the reference
    is the plain model applied per microbatch, mean over layer×microbatch)."""
    model = DiffusionViT(scan_blocks=True, num_experts=2, **CFG)
    x = jnp.asarray(np.random.RandomState(1).randn(8, 16, 16, 3), jnp.float32)
    t = jnp.array([1, 5, 9, 100, 400, 1999, 0, 7], jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), x, t)["params"]
    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    M = 2
    pf = make_pipelined_apply(model, mesh, n_microbatch=M)
    assert getattr(pf, "supports_losses", False)

    want = np.asarray(jax.jit(model.apply)({"params": params}, x, t))
    got, got_vars = jax.jit(
        lambda p, xx, tt: pf({"params": p}, xx, tt, mutable=["losses"]))(
            params, x, t)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    aux_ref = []
    for mb in range(M):
        sl = slice(mb * (8 // M), (mb + 1) * (8 // M))
        _, v = model.apply({"params": params}, x[sl], t[sl],
                           mutable=["losses"])
        aux_ref.append(np.mean(np.asarray(
            jax.tree.leaves(v["losses"])[0], np.float32)))
    aux = np.asarray(jax.tree.leaves(got_vars["losses"])[0], np.float32)
    np.testing.assert_allclose(aux.mean(), np.mean(aux_ref), rtol=1e-5)

    # the losses-free call path stays exactly as before
    plain = np.asarray(jax.jit(pf)({"params": params}, x, t))
    np.testing.assert_allclose(plain, want, atol=1e-5)


def test_pipelined_moe_grads_with_aux_finite():
    """Reverse-mode through the pipelined MoE apply WITH the aux term in the
    loss (the train step's composed objective): grads exist for router and
    expert banks and are finite — the aux path is differentiable through
    the schedule scan's masking."""
    model = DiffusionViT(scan_blocks=True, num_experts=2, **CFG)
    x = jnp.asarray(np.random.RandomState(2).randn(4, 16, 16, 3), jnp.float32)
    t = jnp.array([1, 5, 9, 100], jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), x, t)["params"]
    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)

    def loss(p):
        out, aux_vars = pf({"params": p}, x, t, mutable=["losses"])
        aux = jax.tree.leaves(aux_vars["losses"])[0]
        return jnp.mean(out ** 2) + 0.01 * jnp.sum(aux)

    val, grads = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(val))
    leaves = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    moe_grads = jax.tree.leaves(
        jax.tree.map(lambda g: g, grads["blocks"]["moe"]))
    assert any(float(np.abs(np.asarray(g)).max()) > 0 for g in moe_grads)


def test_pipelined_moe_mutable_forms_and_sp_refusal():
    """Edge contracts: every flax-legal ``mutable`` form keeps the 2-tuple
    arity (or fails loud for collections the pipeline can't thread), and the
    pp×sp×MoE TRIPLE is refused — the stage body would give each seq shard
    its own Switch capacity/priority, silently diverging from the unsharded
    routing every other layout reproduces."""
    model = DiffusionViT(scan_blocks=True, num_experts=2, **CFG)
    x = jnp.asarray(np.random.RandomState(3).randn(4, 16, 16, 3), jnp.float32)
    t = jnp.array([1, 5, 9, 100], jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), x, t)["params"]
    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)

    out, v = pf({"params": params}, x, t, mutable="losses")  # str form
    assert "moe_aux" in v["losses"]
    out_b, v_b = pf({"params": params}, x, t, mutable=True)  # bool form
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out), atol=1e-6)
    out_e, v_e = pf({"params": params}, x, t, mutable=[])  # empty: arity kept
    assert v_e == {}
    with pytest.raises(ValueError, match="only the 'losses'"):
        pf({"params": params}, x, t, mutable=["losses", "intermediates"])

    sp_model = DiffusionViT(scan_blocks=True, num_experts=2, **CFG)
    sp_mesh = make_mesh({"pipe": 2, "seq": 2}, devices=jax.devices()[:4])
    spf = make_pipelined_apply(sp_model, sp_mesh)
    with pytest.raises(ValueError, match="shard-local Switch capacity"):
        spf({"params": params}, x, t)


@pytest.mark.isolated
def test_pipeline_trainer_composes_with_moe(synthetic_image_dir, tmp_path):
    """YAML mesh {pipe, expert} with num_experts=2 trains end to end
    (previously rejected): layout_for_mesh hands pipeline_param_specs the
    'expert' tensor axis (banks Megatron-shard in GSPMD auto mode inside
    the manual pipe region) and the pipelined apply threads the aux loss
    into the step's objective."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="ppm", framework="pipe", batch_size=4, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=2, head=2,
        mesh={"pipe": 2, "expert": 2}, microbatches=2, num_experts=2,
    )
    result = run(cfg, str(tmp_path), max_steps=2)
    assert np.isfinite(result.best_loss)


def test_pipelined_composes_with_sp(scanned_model_and_params):
    """pipe×sp: tokens sharded over a manual 'seq' axis inside each stage,
    attention via the inner ring kernel (17 tokens over sp=2 exercises the
    pad+mask path). Forward AND grads must match the plain scanned model."""
    model, params, x, t = scanned_model_and_params
    mesh = make_mesh({"data": 2, "pipe": 2, "seq": 2})
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)

    want = np.asarray(jax.jit(model.apply)({"params": params}, x, t))
    got = np.asarray(jax.jit(pf)({"params": params}, x, t))
    np.testing.assert_allclose(got, want, atol=1e-5)

    ga = jax.jit(jax.grad(
        lambda p: jnp.mean(model.apply({"params": p}, x, t) ** 2)))(params)
    gb = jax.jit(jax.grad(
        lambda p: jnp.mean(pf({"params": p}, x, t) ** 2)))(params)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipelined_composes_with_sp_and_tp(scanned_model_and_params):
    """The full stack on one mesh — {pipe, seq, model}: stages manual over
    pipe, ring attention manual over seq, tensor parallelism GSPMD-auto over
    model via the param specs. Forward parity against the plain model."""
    from jax.sharding import NamedSharding

    model, params, x, t = scanned_model_and_params
    mesh = make_mesh({"pipe": 2, "seq": 2, "model": 2})
    specs = pipeline_param_specs(params, tensor_axes=("model",))
    sharded = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs)
    pf = make_pipelined_apply(model, mesh, n_microbatch=4)
    want = np.asarray(jax.jit(model.apply)({"params": params}, x, t))
    got = np.asarray(jax.jit(pf)({"params": sharded}, x, t))
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.isolated
def test_pipeline_trainer_composes_with_sp(synthetic_image_dir, tmp_path):
    """YAML mesh {seq, pipe} trains end to end under BOTH sp strategies
    (previously rejected outright): ring rotation and the ulysses
    all-to-all, each as the stage's manual attention kernel."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="pps", framework="pipe", batch_size=4, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=2, head=2,
        mesh={"seq": 2, "pipe": 2}, microbatches=2,
    )
    result = run(cfg, str(tmp_path), max_steps=2)
    assert np.isfinite(result.best_loss)

    ul = ExperimentConfig(
        exp_name="ppu", framework="pipe", batch_size=4, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=2, head=2,
        mesh={"seq": 2, "pipe": 2}, microbatches=2, sp_mode="ulysses",
    )
    result = run(ul, str(tmp_path / "ul"), max_steps=2)
    assert np.isfinite(result.best_loss)


@pytest.mark.parametrize("impl", [False, "xla"])
def test_pipelined_composes_with_ulysses_sp(scanned_model_and_params, impl):
    """pipe×sp with the ulysses strategy: the stage attention all-to-alls
    its local heads over the manual 'seq' axis (17 tokens over sp=2
    exercises the pad-slice between the two all-to-alls). impl='xla' runs
    the blockwise local attention there — the config that needs the
    check_vma exemption (its scan carry inits are unvarying)."""
    model, params, x, t = scanned_model_and_params
    ul_model = DiffusionViT(scan_blocks=True, sp_mode="ulysses",
                            use_flash=impl, **CFG)
    mesh = make_mesh({"data": 2, "pipe": 2, "seq": 2})
    pf = make_pipelined_apply(ul_model, mesh, n_microbatch=2)
    want = np.asarray(jax.jit(model.apply)({"params": params}, x, t))
    got = np.asarray(jax.jit(pf)({"params": params}, x, t))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pipelined_dropout_independent_across_data_shards(scanned_model_and_params):
    """Identical samples placed on different data shards must draw different
    dropout/stochastic-depth masks (regression: the rng was folded only by
    step and layer, so every dp row masked its batch identically)."""
    model, params, _, _ = scanned_model_and_params
    mesh = make_mesh({"data": 2, "pipe": 4})
    pf = make_pipelined_apply(model, mesh, n_microbatch=2)
    x = jnp.broadcast_to(
        jnp.asarray(np.random.RandomState(6).randn(1, 16, 16, 3), jnp.float32),
        (8, 16, 16, 3))
    t = jnp.full((8,), 42, jnp.int32)
    y = np.asarray(jax.jit(lambda p, x, t: pf(
        {"params": p}, x, t, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(11)}))(params, x, t))
    # rows 0..3 live on data shard 0, rows 4..7 on shard 1; same position in
    # each shard must NOT be identical
    assert not np.allclose(y[0], y[4])
    assert not np.allclose(y[1], y[5])
