"""Analytic FLOP accounting (utils/flops.py) — the MFU denominator must be
trustworthy or every reported MFU is fiction."""

import numpy as np

from ddim_cold_tpu.models import MODEL_CONFIGS
from ddim_cold_tpu.utils import flops


def test_vit_forward_flops_counts_matmuls_exactly():
    """Hand-count for a tiny config: per block 6·N·D² + 2·N²·D MACs
    (qkv 3ND², proj ND², mlp 2ND² at ratio 1, attention 2N²D), plus the
    patch-embed and head GEMMs (N·P²C·D each); FLOPs = 2·MACs."""
    img, p, d, depth, ratio = (8, 8), 4, 16, 3, 1.0
    n = (8 // 4) * (8 // 4) + 1  # 5 tokens
    per_block = 6 * n * d * d + 2 * n * n * d
    embed_head = 2 * n * (p * p * 3) * d
    want = 2.0 * (depth * per_block + embed_head)
    got = flops.vit_forward_flops(img_size=img, patch_size=p, embed_dim=d,
                                  depth=depth, num_heads=2, mlp_ratio=ratio)
    assert got == want


def test_train_step_is_three_forwards():
    fwd = flops.vit_forward_flops(mlp_ratio=1.0, **MODEL_CONFIGS["vit_tiny"])
    assert flops.train_step_flops(32, mlp_ratio=1.0,
                                  **MODEL_CONFIGS["vit_tiny"]) == 3 * 32 * fwd


def test_vit_tiny_magnitude():
    """vit_tiny (7.2M params, 65 tokens) forward ≈ 0.87 GF — the PERF.md
    number; order-of-magnitude pin against accidental unit slips."""
    fwd = flops.vit_forward_flops(mlp_ratio=1.0, **MODEL_CONFIGS["vit_tiny"])
    assert 0.5e9 < fwd < 1.5e9


def test_peak_lookup_prefix_match():
    assert flops.peak_tflops("TPU v5 lite") == 197.0
    assert flops.peak_tflops("TPU v5p") == 459.0
    assert flops.peak_tflops("TPU v4") == 275.0
    assert flops.peak_tflops("TPU v6 lite") == 918.0
    assert flops.peak_tflops("cpu") is None


def test_mfu_math():
    # 1 TFLOP of work in 10 ms on a 100-TFLOP/s chip → 100 TF/s·s⁻¹... :
    # mfu = 1e12 / (0.01 · 100e12) = 1.0 exactly at peak
    assert np.isclose(flops.mfu(1e12, 0.01, "TPU v5 lite"),
                      1e12 / (0.01 * 197e12))
    assert flops.mfu(1e12, 0.0, "TPU v5 lite") is None
    assert flops.mfu(1e12, 0.01, "unknown-chip") is None
