"""Deterministic-interleave stress tests for the graftcheck T-rule hot
sites — the dynamic half of the thread-safety story (tests/test_analysis.py
proves the locking discipline statically; this file hammers the three
top-audited sites from many threads and asserts the invariants the locks
exist to keep).

The scheduler-yield shim: ``sys.setswitchinterval`` is dropped to ~10 µs so
the interpreter preempts threads mid-critical-path orders of magnitude more
often than the 5 ms default, and every worker starts behind a barrier with
a SEEDED random micro-stagger — each round explores a different (but
reproducible) interleaving instead of the one the OS happens to pick.

Sites under stress, matching the static audit:

1. ``Ticket._deliver`` vs ``Ticket._fail`` — the hedged re-placement race.
   First resolution must win atomically: exactly one winner per ticket, a
   fully delivered result is never masked by a late failure, and every
   done-callback fires exactly once.
2. ``Ticket._preview`` delivery vs ``add_preview_callback`` registration —
   hedge twins re-deliver the same frame schedule; no frame may be missed,
   double-fired, or double-counted.
3. ``obs.metrics`` emit vs render — snapshots racing emitters must be
   atomic views (a counter never appears without its by_key breakdown) and
   the final registry view must equal the arithmetic total.
4. ``Engine.submit``/``run`` vs ``Engine.drain`` — the idle-race audit:
   every admitted ticket resolves exactly once (result or
   EngineClosedError), none is lost or double-failed.
"""

import random
import sys
import threading
import time

import numpy as np
import pytest

from ddim_cold_tpu.obs import metrics
from ddim_cold_tpu.serve.batching import Ticket

THREADS = 8


@pytest.fixture(autouse=True)
def _fine_grained_switching():
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    yield
    sys.setswitchinterval(old)


def _spawn(fns, seed):
    """Run ``fns`` concurrently behind a barrier with a seeded per-thread
    micro-stagger; re-raise the first worker exception."""
    rng = random.Random(seed)
    staggers = [rng.random() * 1e-4 for _ in fns]
    barrier = threading.Barrier(len(fns))
    errors = []

    def runner(fn, stagger):
        barrier.wait()
        time.sleep(stagger)
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — reported to the test
            errors.append(e)

    threads = [threading.Thread(target=runner, args=(fn, st))
               for fn, st in zip(fns, staggers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]


# --------------------------------------------- site 1: _deliver vs _fail


def test_ticket_resolution_race_first_wins():
    rows = np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
    for round_ in range(100):
        t = Ticket(4)
        wins: list = []
        cb_counts = [0, 0]

        def register(i, t=t, cb_counts=cb_counts):
            def cb(_tk, i=i):
                cb_counts[i] += 1
            t.add_done_callback(cb)

        def deliver(lo, t=t, wins=wins):
            if t._deliver(lo, lo + 1, rows[lo:lo + 1]):
                wins.append("deliver")

        def fail(i, t=t, wins=wins):
            if t._fail(RuntimeError(f"hedge-cancel-{i}")):
                wins.append("fail")

        # 8 threads: 4 row-shard deliverers + 2 hedge failers + 2 registrars
        _spawn([lambda lo=lo: deliver(lo) for lo in range(4)]
               + [lambda i=i: fail(i) for i in range(2)]
               + [lambda i=i: register(i) for i in range(2)],
               seed=round_)

        # exactly one resolution won, and the ticket is observably resolved
        assert wins in (["deliver"], ["fail"]), wins
        err = t.exception(timeout=5.0)
        if wins == ["deliver"]:
            # a completed delivery is never masked as a failure
            assert err is None and not t.failed
            assert np.array_equal(t.result(0), rows)
        else:
            assert isinstance(err, RuntimeError)
            with pytest.raises(RuntimeError):
                t.result(0)
        # both callbacks fired exactly once (pre- or post-resolution
        # registration both count) — none lost, none doubled
        assert cb_counts == [1, 1]


# ----------------------------------- site 2: previews vs registration


def test_preview_delivery_vs_registration_no_miss_no_double():
    steps = 20
    frame = np.ones((2, 3), np.float32)
    for round_ in range(30):
        t = Ticket(2)
        seen = [dict() for _ in range(4)]

        def register(d, t=t):
            def cb(step, frames, d=d):
                d[step] = d.get(step, 0) + 1
            t.add_preview_callback(cb)

        def produce(t=t):  # a hedge twin re-delivers the whole schedule
            for step in range(steps):
                t._preview(step, 0, 2, frame)

        _spawn([lambda d=d: register(d) for d in seen]
               + [produce] * 4, seed=1000 + round_)

        # each frame completed exactly once (hedge dedupe), in step order
        # per producer, and every registrant saw every frame exactly once
        # whether it registered before or after completion (replay)
        history = [s for s, _f in t._phistory]
        assert sorted(history) == list(range(steps))
        assert len(set(history)) == steps
        for d in seen:
            assert d == {s: 1 for s in range(steps)}, d
        # late registration replays the full history, still exactly once
        late: dict = {}
        t.add_preview_callback(
            lambda step, frames, d=late: d.__setitem__(
                step, d.get(step, 0) + 1))
        assert late == {s: 1 for s in range(steps)}


# --------------------------------------- site 3: metrics emit vs render


def test_metrics_emit_vs_render_atomic_views():
    reg = metrics.Registry()
    sc = reg.scope("engine")
    n_per, emitters = 200, 6
    stop = threading.Event()
    torn: list = []

    def emit():
        for j in range(n_per):
            sc.inc("engine.rows", 1)
            sc.inc("engine.failed_batches", 1,
                   key="dispatch" if j % 2 else "plan")
            sc.observe("engine.latency_s", 0.001 * j)

    def render():
        while not stop.is_set():
            snap = reg.snapshot().get(sc.sid, {})
            total = snap.get("engine.failed_batches")
            by_key = snap.get("engine.failed_batches/by_key")
            if total is not None:
                # atomicity: the counter is only ever emitted WITH a key,
                # so its rendered total must equal its keyed breakdown in
                # every snapshot — a torn (mid-emit) view breaks this
                if by_key is None or total != sum(by_key.values()):
                    torn.append((total, by_key))
            sc.by_key("engine.failed_batches")
            sc.samples("engine.latency_s")

    renderers = [threading.Thread(target=render) for _ in range(2)]
    for r in renderers:
        r.start()
    try:
        _spawn([emit] * emitters, seed=7)
    finally:
        stop.set()
        for r in renderers:
            r.join()

    assert torn == []
    # registry-view equality: every read surface agrees with arithmetic
    expect = emitters * n_per
    assert sc.value("engine.rows") == expect
    assert sc.value("engine.failed_batches") == expect
    assert sc.by_key("engine.failed_batches") == {
        "dispatch": emitters * (n_per // 2),
        "plan": emitters * (n_per - n_per // 2)}
    assert sc.count("engine.latency_s") == expect
    snap = reg.snapshot()[sc.sid]
    assert snap["engine.rows"] == expect
    assert snap["engine.failed_batches"] == expect


# ------------------------------------- site 4: submit/run vs drain race


def test_engine_submit_drain_race_no_lost_tickets():
    """The Engine.drain idle-race audit, dynamically: submitters, a run
    loop, and a drain all race; every admitted ticket must resolve exactly
    once — completed or EngineClosedError — and none may hang."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu import serve
    from ddim_cold_tpu.models import DiffusionViT

    from tests.test_serve import K, TINY

    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    eng = serve.Engine(model, params, buckets=(4,))
    cfg = serve.SamplerConfig(k=K)
    serve.warmup(eng, [cfg], persistent_cache=False)

    tickets: list = []
    tlock = threading.Lock()
    rejected = [0]
    drained = threading.Event()

    def submitter(seed):
        rng = random.Random(seed)
        for i in range(4):
            if i:  # first submit races the run loop, not the drain
                time.sleep(rng.random() * 0.02)
            try:
                t = eng.submit(seed=seed * 100 + i, n=1, config=cfg)
            except serve.EngineClosedError:
                rejected[0] += 1
                continue
            with tlock:
                tickets.append(t)

    def runner():
        while True:
            eng.run()
            if drained.is_set():
                return
            time.sleep(0.001)

    def drainer():
        time.sleep(0.03)
        report = eng.drain(timeout=60.0)
        assert report["idle"], report
        drained.set()

    _spawn([lambda s=s: submitter(s) for s in range(5)]
           + [runner, drainer], seed=42)
    # one final sweep: requests admitted between the drain sweep and the
    # last run() exit are failed by run()'s own closed-path sweep
    eng.run()

    assert tickets, "no ticket was admitted before the drain"
    completed = failed = 0
    for t in tickets:
        err = t.exception(timeout=60.0)  # raises TimeoutError if LOST
        if err is None:
            assert t.result(0).shape == (1, 16, 16, 3)
            completed += 1
        else:
            assert isinstance(err, serve.EngineClosedError), err
            failed += 1
    assert completed + failed == len(tickets)
    assert len(tickets) + rejected[0] == 5 * 4
