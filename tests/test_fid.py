"""FID subsystem: Fréchet math vs closed forms/scipy, streaming stats vs
numpy, InceptionV3 forward + torch-layout weight conversion."""

import os

import numpy as np
import pytest

from ddim_cold_tpu.eval import fid
from ddim_cold_tpu.eval import inception

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_frechet_identical_is_zero(rng):
    x = rng.randn(500, 8)
    mu, sigma = x.mean(0), np.cov(x, rowvar=False)
    assert abs(fid.frechet_distance(mu, sigma, mu, sigma)) < 1e-8


def test_frechet_diagonal_closed_form():
    """For commuting (diagonal) covariances the distance is
    ‖Δμ‖² + Σᵢ (√s1ᵢ − √s2ᵢ)²."""
    mu1, mu2 = np.array([0.0, 0.0]), np.array([3.0, 4.0])
    s1, s2 = np.diag([1.0, 4.0]), np.diag([9.0, 1.0])
    want = 25.0 + (1 - 3) ** 2 + (2 - 1) ** 2
    assert abs(fid.frechet_distance(mu1, s1, mu2, s2) - want) < 1e-10


def test_trace_sqrt_product_vs_scipy(rng):
    import scipy.linalg

    a = rng.randn(16, 16)
    b = rng.randn(16, 16)
    s1, s2 = a @ a.T + 0.1 * np.eye(16), b @ b.T + 0.1 * np.eye(16)
    want = np.trace(scipy.linalg.sqrtm(s1 @ s2)).real
    assert abs(fid.trace_sqrt_product(s1, s2) - want) < 1e-8


def test_streaming_stats_match_numpy(rng):
    x = rng.randn(333, 12).astype(np.float32)
    stats = fid.ActivationStats(12)
    for chunk in np.array_split(x, 7):
        stats.update(chunk)
    np.testing.assert_allclose(stats.mean, x.mean(0), atol=1e-6)
    np.testing.assert_allclose(stats.cov, np.cov(x, rowvar=False), atol=1e-6)
    # shard merge (per-host accumulators)
    a, b = fid.ActivationStats(12), fid.ActivationStats(12)
    a.update(x[:100])
    b.update(x[100:])
    merged = a.merge(b)
    np.testing.assert_allclose(merged.cov, stats.cov, atol=1e-6)


def test_fid_separates_distributions(rng):
    """Same-distribution FID ≈ small; shifted distribution FID ≫."""
    d = 6
    same1, same2 = rng.randn(2000, d), rng.randn(2000, d)
    far = rng.randn(2000, d) + 5.0
    s = [fid.ActivationStats(d) for _ in range(3)]
    for acc, data in zip(s, (same1, same2, far)):
        acc.update(data)
    near = fid.fid_from_stats(s[0], s[1])
    far_d = fid.fid_from_stats(s[0], s[2])
    assert near < 1.0 < far_d
    assert far_d > 100.0


@pytest.fixture(scope="module")
def small_variables():
    import jax

    return inception.init_variables(jax.random.PRNGKey(0))


def test_inception_forward_shape(small_variables):
    import jax.numpy as jnp

    model, variables = small_variables
    x = jnp.zeros((2, inception.INCEPTION_SIZE, inception.INCEPTION_SIZE, 3))
    feats = model.apply(variables, x)
    assert feats.shape == (2, inception.FEATURE_DIM)
    assert bool(jnp.isfinite(feats).all())


def test_torch_conversion_roundtrip(small_variables):
    """Build a torch-layout state_dict from the flax variables, convert back,
    and check the tree is identical — the layout transform is its own test
    (torchvision itself is not installed)."""
    import jax

    model, variables = small_variables

    # flax tree → torch-key state_dict (inverse of flax_from_torch_inception)
    sd = {}

    def walk(tree, prefix, is_stats):
        for key, value in tree.items():
            path = prefix + [key]
            if isinstance(value, dict):
                walk(value, path, is_stats)
                continue
            v = np.asarray(value)
            mod, leaf = path[:-1], path[-1]
            name = ".".join(mod)
            if leaf == "kernel":
                sd[name + ".weight"] = v.transpose(3, 2, 0, 1)
            elif leaf == "scale":
                sd[name + ".weight"] = v
            elif leaf == "bias":
                sd[name + ".bias"] = v
            elif leaf == "mean":
                sd[name + ".running_mean"] = v
            elif leaf == "var":
                sd[name + ".running_var"] = v
            else:
                raise AssertionError(leaf)

    walk(variables["params"], [], False)
    walk(variables["batch_stats"], [], True)
    sd["fc.weight"] = np.zeros((1000, 2048), np.float32)  # ignored heads
    sd["AuxLogits.conv0.conv.weight"] = np.zeros((1,), np.float32)

    converted = inception.flax_from_torch_inception(sd)
    flat_a = jax.tree_util.tree_leaves_with_path(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]})
    flat_b = jax.tree_util.tree_leaves_with_path(converted)
    assert len(flat_a) == len(flat_b)
    for (pa, va), (pb, vb) in zip(flat_a, flat_b):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_fid_between_images(rng):
    """End-to-end on tiny images with the random-init extractor: a stream
    compared against itself gives (near-)zero; against noise it does not.
    (Small batches: each 299×299 InceptionV3 forward is ~seconds on CPU —
    8 images over 3 forwards keeps the path covered without dominating the
    suite's wall time.)"""
    imgs = rng.rand(8, 32, 32, 3).astype(np.float32)
    other = rng.rand(4, 32, 32, 3).astype(np.float32) * 0.2
    import jax

    feature_fn, dim = fid.make_feature_fn(*inception.init_variables(jax.random.PRNGKey(1)))
    a = fid.stats_for_batches([imgs[:4], imgs[4:]], feature_fn, dim)
    b = fid.stats_for_batches([imgs[:4], imgs[4:]], feature_fn, dim)
    c = fid.stats_for_batches([other], feature_fn, dim)
    assert abs(fid.fid_from_stats(a, b)) < 1e-6
    assert fid.fid_from_stats(a, c) > fid.fid_from_stats(a, b)


def test_fid_trend_collect_points(tmp_path):
    """scripts/fid_trend.py point assembly: random anchor first, snapshot
    epochs sorted + evenly thinned with first/last kept, best last."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fid_trend", os.path.join(REPO, "scripts", "fid_trend.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    run = tmp_path
    snap = run / "snapshots"
    snap.mkdir()
    for ep in (3, 1, 21, 7, 11, 15, 9):
        (snap / f"epoch_{ep}").mkdir()
    (snap / "epoch_5.tmp").mkdir()  # in-flight copy: must be ignored
    (run / "bestloss.ckpt").mkdir()

    pts = mod.collect_points(str(run), max_points=4)
    labels = [p[0] for p in pts]
    assert labels[0] == "random" and labels[-1] == "best"
    epochs = [p[1] for p in pts[1:-1]]
    assert epochs == sorted(epochs) and len(epochs) <= 4
    assert epochs[0] == 1 and epochs[-1] == 21  # first/last survive thinning
    assert pts[0][2] is None and pts[-1][2].endswith("bestloss.ckpt")

    # no snapshots, no best → still a valid 1-point (random) trend
    empty = tmp_path / "empty_run"
    empty.mkdir()
    assert [p[0] for p in mod.collect_points(str(empty), 4)] == ["random"]


def test_random_extractor_features_do_not_collapse(rng):
    """Regression: with default lecun conv init the 94-conv stack attenuates
    activations to ~1e-4 std and every FID computes as ≈0; init_variables
    applies the √2 ReLU gain so seeded-random features stay discriminative."""
    import jax
    import jax.numpy as jnp

    feature_fn, _ = fid.make_feature_fn(*inception.init_variables(jax.random.PRNGKey(0)))
    imgs = rng.rand(4, 32, 32, 3).astype(np.float32)
    feats = np.asarray(feature_fn(jnp.asarray(imgs)))
    assert feats.std() > 0.05, f"collapsed features: std={feats.std()}"
