"""Malformed-input fuzz for the native C++ decode path (native/ddim_data.cc)
— VERDICT r4 item 9: the decoder longjmps out of libjpeg on malformed input;
prove the error path is actually safe (no crash, no fd leak, no
decompression bomb) rather than assuming it.

Every call goes through the ctypes binding in-process: a segfault in the
error path would kill pytest itself, which IS the detection. Failure
contract under fuzz: ``load_base`` returns either a well-formed (H, W, 3)
float32 array or None — never raises from the C side, never leaks the FILE*
(fd-count check), never allocates past the kMaxPixels bomb cap.
"""

import io
import os
import struct
import zlib

import numpy as np
import pytest

from ddim_cold_tpu.data import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native decode library unavailable")


def _valid_jpeg(px=48) -> bytes:
    from PIL import Image

    r = np.random.RandomState(3)
    img = Image.fromarray(r.randint(0, 256, (px, px, 3), dtype=np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=85)
    return buf.getvalue()


def _valid_png(px=32) -> bytes:
    from PIL import Image

    r = np.random.RandomState(4)
    img = Image.fromarray(r.randint(0, 256, (px, px, 3), dtype=np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


def _decode(tmp_path, blob: bytes, name="f.jpg"):
    p = tmp_path / name
    p.write_bytes(blob)
    out = native.load_base(str(p), (64, 64))
    if out is not None:
        assert out.shape == (64, 64, 3) and out.dtype == np.float32
        assert np.isfinite(out).all()
    return out


def _open_fds() -> int:
    return len(os.listdir("/proc/self/fd"))


def test_valid_files_still_decode(tmp_path):
    assert _decode(tmp_path, _valid_jpeg()) is not None
    assert _decode(tmp_path, _valid_png(), "f.png") is not None


def test_fuzz_jpeg_truncations_no_crash_no_fd_leak(tmp_path):
    blob = _valid_jpeg()
    before = _open_fds()
    for cut in range(0, len(blob), 23):
        _decode(tmp_path, blob[:cut])
    # libjpeg pads premature EOF with gray — either outcome (None or a
    # well-formed array) is fine; the FILE* must be closed on every path
    assert _open_fds() == before


def test_fuzz_jpeg_bitflips_no_crash(tmp_path):
    blob = _valid_jpeg()
    r = np.random.RandomState(17)
    before = _open_fds()
    for _ in range(250):
        mutated = bytearray(blob)
        pos = int(r.randint(len(blob)))
        mutated[pos] = (mutated[pos] + 1 + r.randint(255)) % 256
        _decode(tmp_path, bytes(mutated))
    assert _open_fds() == before


def test_fuzz_garbage_with_jpeg_magic(tmp_path):
    """Random bytes behind a real SOI marker reach deep into the libjpeg
    header parser (the magic sniff passes) — every one must come back
    None/array, never crash."""
    r = np.random.RandomState(23)
    before = _open_fds()
    for size in (0, 1, 16, 300, 5000):
        for _ in range(20):
            body = bytes(r.randint(0, 256, size=size, dtype=np.uint8))
            _decode(tmp_path, b"\xff\xd8\xff" + body)
    assert _open_fds() == before


def _patch_jpeg_dims(blob: bytes, h: int, w: int) -> bytes:
    """Rewrite the SOF0/SOF2 frame header's dimension fields in place."""
    i = 2
    b = bytearray(blob)
    while i + 4 <= len(b):
        assert b[i] == 0xFF, "marker scan desynced"
        marker = b[i + 1]
        seglen = struct.unpack(">H", bytes(b[i + 2:i + 4]))[0]
        if marker in (0xC0, 0xC2):  # SOF0/SOF2: [len][prec][H:2][W:2]...
            b[i + 5:i + 7] = struct.pack(">H", h)
            b[i + 7:i + 9] = struct.pack(">H", w)
            return bytes(b)
        i += 2 + seglen
    raise AssertionError("no SOF marker found")


def test_jpeg_dimension_bomb_rejected(tmp_path):
    """A 1 KB file whose frame header claims 65500x65500 (12.9 GB RGB) must
    be rejected by the kMaxPixels cap (PIL's MAX_IMAGE_PIXELS default) —
    before this guard the decoder would malloc and page-touch the full
    claimed buffer from a file that fits in one disk sector."""
    bomb = _patch_jpeg_dims(_valid_jpeg(), 65500, 65500)
    assert _decode(tmp_path, bomb) is None


def test_bomb_pil_fallback_names_the_file(tmp_path):
    """The tier behind the native reject is PIL, whose bomb guard raises at
    the same threshold (native cap = 2x MAX_IMAGE_PIXELS = PIL's
    warning→error escalation point) — and the terminal error must carry the
    offending path, not just PIL's internal buffer repr."""
    from ddim_cold_tpu.data.datasets import pil_loader

    bomb = _patch_jpeg_dims(_valid_jpeg(), 65500, 65500)
    p = tmp_path / "bomb.jpg"
    p.write_bytes(bomb)
    with pytest.raises(Exception, match="bomb.jpg"):
        pil_loader(str(p))


def test_jpeg_zero_dims_rejected(tmp_path):
    # libjpeg itself errors on 0-dim frames, but the guard must hold even
    # if the library tolerates it
    bomb = _patch_jpeg_dims(_valid_jpeg(), 0, 0)
    assert _decode(tmp_path, bomb) is None


def _patch_png_dims(blob: bytes, w: int, h: int) -> bytes:
    """Rewrite IHDR dims and fix its CRC (libpng verifies the CRC before
    the dimensions are visible to the caller)."""
    assert blob[12:16] == b"IHDR"
    b = bytearray(blob)
    b[16:20] = struct.pack(">I", w)
    b[20:24] = struct.pack(">I", h)
    crc = zlib.crc32(bytes(b[12:29])) & 0xFFFFFFFF
    b[29:33] = struct.pack(">I", crc)
    return bytes(b)


def test_png_dimension_bomb_rejected(tmp_path):
    bomb = _patch_png_dims(_valid_png(), 100000, 100000)
    assert _decode(tmp_path, bomb, "f.png") is None


def test_fuzz_png_bitflips_no_crash(tmp_path):
    blob = _valid_png()
    r = np.random.RandomState(29)
    before = _open_fds()
    for _ in range(150):
        mutated = bytearray(blob)
        pos = int(r.randint(len(blob)))
        mutated[pos] = (mutated[pos] + 1 + r.randint(255)) % 256
        _decode(tmp_path, bytes(mutated), "f.png")
    assert _open_fds() == before


def test_decode_batch_mixed_valid_and_malformed(tmp_path):
    """The batch entry point (no-GIL loop over slots) with a mix of valid,
    truncated, and bomb files: valid slots decode, bad slots report failure
    for the PIL fallback, and slot results never bleed into each other."""
    if not native.has_decode_batch():
        pytest.skip("batch entry point absent")
    good = _valid_jpeg()
    paths, kinds = [], []
    for i, (name, blob) in enumerate((
            ("good0.jpg", good),
            ("trunc.jpg", good[: len(good) // 3]),
            ("bomb.jpg", _patch_jpeg_dims(good, 65500, 65500)),
            ("good1.jpg", good),
            ("garbage.jpg", b"\xff\xd8\xff" + b"\x00" * 64),
    )):
        p = tmp_path / name
        p.write_bytes(blob)
        paths.append(str(p))
        kinds.append(name.split(".")[0].rstrip("01"))
    out, failed = native.decode_batch(paths, (48, 48))
    good_ref = None
    for i, kind in enumerate(kinds):
        if kind == "good":
            assert not failed[i], f"slot {i} ({kind}) should decode"
            if good_ref is None:
                good_ref = np.asarray(out[i]).copy()
            else:
                np.testing.assert_array_equal(out[i], good_ref)
        elif kind in ("bomb", "garbage"):
            assert failed[i], f"{kind} slot must fail for PIL fallback"
