"""Autoscaler tests (serve/autoscale.py + Router.scale_to): hysteresis /
cooldown / min-max units on a stub router with an injectable clock (no
sleeping, no threads), scale-up under synthetic queue-depth pressure
through a real Router over stub replicas, and no-flapping under a noisy
p95 signal. The control decisions are pure functions of (health snapshot,
clock), so every test drives ``tick()`` directly and asserts the exact
``scale_to`` call sequence."""

import time

import pytest

from ddim_cold_tpu.serve import fleet
from ddim_cold_tpu.serve.autoscale import Autoscaler
from ddim_cold_tpu.serve.router import Router


class FakeRouter:
    """Health-programmable router: the autoscaler only reads ``health()``/
    ``target`` and calls ``scale_to`` — three knobs, no threads."""

    def __init__(self, target=2):
        self.target = target
        self.calls = []
        self.replicas = {f"r{i}": {"state": "ready", "queue_depth": 0,
                                   "open_tickets": 0, "latency_p95_s": 0.0}
                         for i in range(target)}
        self.pending = {}
        self.closed = False

    def set_load(self, queue_depth=0, p95_s=0.0, pending=0):
        for r in self.replicas.values():
            r["queue_depth"] = queue_depth
            r["latency_p95_s"] = p95_s
        self.pending = {"default": pending} if pending else {}

    def health(self):
        return {"replicas": {k: dict(v) for k, v in self.replicas.items()},
                "pending_by_tenant": dict(self.pending),
                "closed": self.closed}

    def scale_to(self, n):
        self.calls.append(n)
        self.target = n
        return n


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _scaler(router, **kw):
    kw.setdefault("clock", FakeClock())
    kw.setdefault("cooldown_s", 0.0)
    return Autoscaler(router, **kw)


# ------------------------------------------------------------- hysteresis


def test_scale_up_needs_consecutive_overload_ticks():
    """One pressure sample is noise; up_ticks consecutive samples are a
    trend. The target moves exactly once, on the up_ticks-th tick."""
    r = FakeRouter(target=2)
    a = _scaler(r, max_replicas=4, queue_high=2.0, up_ticks=3)
    r.set_load(queue_depth=5)
    assert a.tick()["action"] is None
    assert a.tick()["action"] is None
    assert a.tick()["action"] == "up"
    assert r.calls == [3]


def test_scale_down_needs_consecutive_underload_ticks():
    r = FakeRouter(target=3)
    a = _scaler(r, max_replicas=4, queue_low=1.0, down_ticks=3)
    r.set_load(queue_depth=0)
    assert [a.tick()["action"] for _ in range(3)] == [None, None, "down"]
    assert r.calls == [2]


def test_dead_band_resets_streaks():
    """A sample between the thresholds restarts BOTH streaks — load
    oscillating in and out of the overload band never accumulates to an
    action (the hysteresis contract)."""
    r = FakeRouter(target=2)
    a = _scaler(r, max_replicas=4, queue_low=1.0, queue_high=8.0, up_ticks=2)
    for _ in range(4):
        r.set_load(queue_depth=20)   # overload: streak 1
        assert a.tick()["action"] is None
        r.set_load(queue_depth=4)    # dead band: streak back to 0
        assert a.tick()["action"] is None
    assert r.calls == []


def test_noisy_p95_does_not_flap():
    """p95 spiking above the threshold every other tick (queue mid-band)
    never scales — and neither direction ever fires, so the fleet holds."""
    r = FakeRouter(target=2)
    a = _scaler(r, max_replicas=4, queue_low=1.0, queue_high=8.0,
                p95_high_s=1.0, up_ticks=2, down_ticks=2)
    for i in range(12):
        r.set_load(queue_depth=4, p95_s=2.5 if i % 2 else 0.1)
        a.tick()
    assert r.calls == []


def test_sustained_p95_scales_up():
    """The same spike SUSTAINED is a real signal — p95 pressure alone
    (queue idle) drives a scale-up."""
    r = FakeRouter(target=2)
    a = _scaler(r, max_replicas=4, queue_high=100.0, p95_high_s=1.0,
                up_ticks=2)
    r.set_load(queue_depth=0, p95_s=2.5)
    assert [a.tick()["action"] for _ in range(2)] == [None, "up"]


# ---------------------------------------------------------------- cooldown


def test_cooldown_blocks_consecutive_actions():
    clock = FakeClock()
    r = FakeRouter(target=1)
    a = _scaler(r, max_replicas=5, queue_high=1.0, up_ticks=1,
                cooldown_s=100.0, clock=clock)
    r.set_load(queue_depth=10)
    assert a.tick()["action"] == "up"
    for clock.t in (1.0, 10.0, 99.0):
        assert a.tick()["action"] is None, "action inside the cooldown"
    clock.t = 150.0
    assert a.tick()["action"] == "up"
    assert r.calls == [2, 3]


# ------------------------------------------------------------------ bounds


def test_max_replicas_caps_scale_up():
    r = FakeRouter(target=2)
    a = _scaler(r, max_replicas=2, queue_high=1.0, up_ticks=1)
    r.set_load(queue_depth=50)
    for _ in range(5):
        assert a.tick()["action"] is None
    assert r.calls == []


def test_warm_pool_raises_the_scale_down_floor():
    """min_replicas=1 + warm_pool=1 → the fleet never drops below 2: the
    spare is the seconds-not-minutes replacement capacity."""
    r = FakeRouter(target=3)
    a = _scaler(r, min_replicas=1, max_replicas=4, warm_pool=1,
                down_ticks=1, queue_low=1.0)
    assert a.floor == 2
    r.set_load(queue_depth=0)
    assert a.tick()["action"] == "down"       # 3 → 2
    for _ in range(5):
        assert a.tick()["action"] is None     # 2 == floor: hold
    assert r.calls == [2]


def test_validation():
    r = FakeRouter()
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(r, min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        Autoscaler(r, min_replicas=2, max_replicas=2, warm_pool=1)
    with pytest.raises(ValueError, match="queue_low"):
        Autoscaler(r, queue_low=5.0, queue_high=1.0)


def test_closed_router_never_scales():
    r = FakeRouter(target=2)
    r.closed = True
    a = _scaler(r, queue_high=1.0, up_ticks=1)
    r.set_load(queue_depth=50)
    assert a.tick()["action"] is None
    assert r.calls == []


# ----------------------------------------------------------------- signals


def test_read_signals_normalizes_per_ready_replica():
    r = FakeRouter(target=2)
    r.replicas["r0"].update(queue_depth=3, open_tickets=1,
                            latency_p95_s=0.2)
    r.replicas["r1"].update(queue_depth=5, latency_p95_s=0.8)
    r.replicas["r2"] = {"state": "closed", "queue_depth": 99,
                        "latency_p95_s": 9.9}  # dead: excluded
    r.pending = {"default": 7}
    a = _scaler(r)
    sig = a.read_signals()
    assert sig["ready"] == 2
    assert sig["queued"] == 3 + 1 + 5 + 7
    assert sig["queued_per_replica"] == pytest.approx(8.0)
    assert sig["p95_s"] == pytest.approx(0.8)


def test_start_asserts_warm_pool_floor_then_stops():
    r = FakeRouter(target=1)
    a = _scaler(r, min_replicas=1, max_replicas=4, warm_pool=2,
                interval_s=0.01)
    a.start()
    try:
        assert r.calls[:1] == [3]  # floor asserted immediately, not on load
    finally:
        a.stop()


# ------------------------------------------------- Router.scale_to units


class StubReplica(fleet.ReplicaHandle):
    """Health-programmable replica (same shape as test_fleet's)."""

    def __init__(self, rid):
        self.replica_id = rid
        self.state = fleet.NEW
        self.drained = False
        self.h = {"stalled": False, "closed": False, "quarantined": 0,
                  "queue_depth": 0, "open_tickets": 0,
                  "last_progress_s": 0.0, "compiles_after_warmup": 0}

    def warm(self, configs, buckets=None, **kwargs):
        self.state = fleet.READY
        return {"new_compiles": 0}

    def start(self):
        pass

    def health(self):
        return dict(self.h, state=self.state, replica=self.replica_id)

    def drain(self, timeout=None):
        self.drained = True
        self.state = fleet.CLOSED
        return self.health()

    def close(self):
        self.state = fleet.CLOSED


def test_router_scale_to_down_retires_least_loaded():
    """Scale-down takes the replicas with the least queued work — the busy
    replica keeps serving, the idle ones drain through the normal path."""
    reps = {}

    def factory(rid):
        reps[rid] = StubReplica(rid)
        return reps[rid]

    router = Router(factory, replicas=3, configs=(), auto_start=False)
    reps["r1"].h["queue_depth"] = 9  # the busy one
    assert router.scale_to(1) == 1
    assert router.target == 1
    h = router.health()
    assert h["active_replicas"] == 1 and h["retired_replicas"] == 2
    assert not reps["r1"].drained, "scale-down retired the BUSY replica"
    assert reps["r0"].drained and reps["r2"].drained


def test_router_scale_to_excess_counts_ready_replicas_only():
    """Scale-down while one replica is crashed (CLOSED in the dict, not
    yet retired by supervision) must not take extra READY capacity: excess
    is measured against READY replicas, the dead one is already leaving."""
    reps = {}

    def factory(rid):
        reps[rid] = StubReplica(rid)
        return reps[rid]

    router = Router(factory, replicas=3, configs=(), auto_start=False)
    reps["r0"].state = fleet.CLOSED  # crashed behind the router's back
    assert router.scale_to(2) == 2
    ready = [r for r in reps.values() if r.state == fleet.READY]
    assert len(ready) == 2, \
        "scale-down retired READY capacity the dead replica already freed"
    assert not any(r.drained for r in ready)


def test_router_scale_up_spawns_on_supervision_tick():
    reps = {}

    def factory(rid):
        reps[rid] = StubReplica(rid)
        return reps[rid]

    router = Router(factory, replicas=1, configs=(), tick_s=0.01)
    router.scale_to(3)
    deadline = time.time() + 10
    while time.time() < deadline:
        if router.health()["active_replicas"] == 3:
            break
        time.sleep(0.02)
    h = router.drain(timeout=2)
    assert h["replicas_spawned"] == 3 and h["retired_replicas"] == 0


def test_router_scale_to_clamps_and_ignores_when_closed():
    reps = {}

    def factory(rid):
        reps[rid] = StubReplica(rid)
        return reps[rid]

    router = Router(factory, replicas=2, configs=(), auto_start=False)
    assert router.scale_to(0) == 1  # floor of one serving replica
    router.drain(timeout=1)
    before = router.target
    assert router.scale_to(5) == before  # closed fleet: target frozen


def test_autoscaler_scales_real_router_under_queue_pressure():
    """End to end over a real Router: synthetic queue-depth pressure on
    stub replicas drives tick() → scale_to → supervision spawning, and the
    fleet converges on the new target without flapping past it."""
    reps = {}

    def factory(rid):
        reps[rid] = StubReplica(rid)
        return reps[rid]

    router = Router(factory, replicas=2, configs=(), tick_s=0.01)
    a = Autoscaler(router, min_replicas=1, max_replicas=3, queue_high=2.0,
                   up_ticks=2, cooldown_s=0.0, clock=FakeClock())
    for rep in reps.values():
        rep.h["queue_depth"] = 10
    assert a.tick()["action"] is None
    assert a.tick()["action"] == "up"
    deadline = time.time() + 10
    while time.time() < deadline:
        if router.health()["active_replicas"] == 3:
            break
        time.sleep(0.02)
    assert router.health()["active_replicas"] == 3
    # pressure gone → nothing further happens inside the streak window
    for rep in reps.values():
        rep.h["queue_depth"] = 0
    assert a.tick()["action"] is None
    h = router.drain(timeout=2)
    assert h["replicas_spawned"] == 3 and h["retired_replicas"] == 0
