"""Fault-injection registry (utils/faults.py): determinism, replay, scoping,
the env grammar — and the non-serve fault sites (``ckpt.save`` crash windows,
``data.next``).

The registry's whole value is that chaos is REPRODUCIBLE: same specs + same
call order → same injections, and a realized plan replays itself exactly.
These tests pin that, then use the ``ckpt.save`` site to kill
``save_checkpoint`` inside every crash window and assert the two-rename swap
never loses the last loadable checkpoint.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ddim_cold_tpu.utils import faults
from ddim_cold_tpu.utils.faults import (FaultSpec, PermanentFault,
                                        TransientFault, parse_specs)

pytestmark = pytest.mark.usefixtures("clean_faults")


@pytest.fixture()
def clean_faults():
    """Chaos must not leak between tests: every scope exits via the context
    manager, so here we only ASSERT the invariant rather than repair it."""
    assert not faults.active(), "a previous test leaked an armed fault scope"
    yield
    assert not faults.active(), "this test leaked an armed fault scope"


# ---------------------------------------------------------------- registry


def test_disarmed_fire_is_identity():
    buf = np.arange(6.0)
    out = faults.fire("serve.dispatch", tag="bucket:8|", payload=buf)
    assert out is buf  # not even a copy on the fast path
    assert faults.current_plan() is None


def test_spec_validation():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec("serve.nope")
    with pytest.raises(ValueError, match="kind"):
        FaultSpec("serve.dispatch", "explode")
    with pytest.raises(ValueError, match="rate"):
        FaultSpec("serve.dispatch", rate=1.5)


def _drive(spec, calls=40, site="serve.dispatch"):
    """Fire ``site`` ``calls`` times under ``spec``; return the call indices
    that raised."""
    hits = []
    with faults.inject(spec):
        for i in range(calls):
            try:
                faults.fire(site, tag=f"req:{i}|")
            except (TransientFault, PermanentFault):
                hits.append(i)
    return hits


def test_seeded_schedule_is_deterministic():
    spec = FaultSpec("serve.dispatch", "transient", rate=0.3, seed=7)
    first = _drive(spec)
    assert first, "rate=0.3 over 40 calls must fire at least once"
    for _ in range(3):  # scope exit resets counters: exact repetition
        assert _drive(spec) == first
    # a different seed is a different schedule
    assert _drive(FaultSpec("serve.dispatch", "transient",
                            rate=0.3, seed=8)) != first


def test_match_restricts_to_tagged_calls():
    spec = FaultSpec("serve.dispatch", "permanent", match="req:3|")
    assert _drive(spec, calls=12) == [3]  # and NOT req:33 etc. (trailing |)


def test_max_fires_caps_injections():
    spec = FaultSpec("serve.dispatch", "transient", rate=1.0, max_fires=2)
    assert _drive(spec, calls=10) == [0, 1]


def test_at_overrides_dice():
    spec = FaultSpec("serve.dispatch", "transient", at=(2, 5))
    assert _drive(spec, calls=10) == [2, 5]


def test_latency_sleeps_and_records():
    spec = FaultSpec("serve.dispatch", "latency", latency_s=0.15, max_fires=1)
    with faults.inject(spec) as plan:
        t0 = time.perf_counter()
        faults.fire("serve.dispatch")
        dt = time.perf_counter() - t0
    assert dt >= 0.15
    assert plan.realized[0]["kind"] == "latency"


def test_corrupt_flips_one_element_copy_not_caller():
    buf = np.zeros(32, np.float32)
    spec = FaultSpec("serve.fetch", "corrupt", seed=5, max_fires=1)
    with faults.inject(spec) as plan:
        out = faults.fire("serve.fetch", payload=buf)
    assert np.isnan(out).sum() == 1
    assert not np.isnan(buf).any()  # caller's buffer untouched
    idx = plan.realized[0]["detail"]["index"]
    assert np.isnan(out[idx])
    # int payloads corrupt too (saturate, not NaN)
    ibuf = np.zeros(8, np.int32)
    with faults.inject(FaultSpec("serve.fetch", "corrupt", seed=5)):
        iout = faults.fire("serve.fetch", payload=ibuf)
    assert (iout == np.iinfo(np.int32).max).sum() == 1


def test_plan_records_and_replays_exactly():
    spec = FaultSpec("serve.dispatch", "transient", rate=0.3, seed=7)
    with faults.inject(spec) as plan:
        hits = []
        for i in range(30):
            try:
                faults.fire("serve.dispatch", tag=f"req:{i}|")
            except TransientFault:
                hits.append(i)
        realized = [(r["site"], r["call"], r["kind"]) for r in plan.realized]
        replay_specs = plan.replay()
    assert [c for _, c, _ in realized] == hits
    # the replay specs re-fire at exactly the same call indices — dice retired
    assert replay_specs[0].at == tuple(hits)
    assert _drive(replay_specs[0], calls=30) == hits
    assert plan.by_site() == {"serve.dispatch": len(hits)}


def test_scopes_stack_and_reset():
    outer = FaultSpec("serve.dispatch", "transient", at=(1,))
    inner = FaultSpec("serve.fetch", "transient", at=(0,))
    with faults.inject(outer) as plan:
        faults.fire("serve.dispatch")  # call 0: no hit
        with faults.inject(inner):
            assert faults.current_plan() is plan  # shared plan, not nested
            with pytest.raises(TransientFault):
                faults.fire("serve.fetch")
        with pytest.raises(TransientFault):
            faults.fire("serve.dispatch")  # call 1: counters NOT reset by
            # the inner scope's exit
        assert plan.by_site() == {"serve.fetch": 1, "serve.dispatch": 1}
    assert faults.current_plan() is None  # last scope out: full reset


def test_snapshot_shape():
    assert faults.snapshot() == {"armed": 0, "injected": 0, "by_site": {}}
    with faults.inject(FaultSpec("serve.dispatch", "transient", at=(0,))):
        with pytest.raises(TransientFault):
            faults.fire("serve.dispatch")
        snap = faults.snapshot()
    assert snap["armed"] == 1 and snap["injected"] == 1
    assert snap["by_site"] == {"serve.dispatch": 1}


# ------------------------------------------------------------- env grammar


def test_parse_specs_grammar_round_trip():
    specs = parse_specs(
        "serve.dispatch:transient:rate=0.2,seed=7;"
        "serve.fetch:latency:latency_s=0.5;"
        "ckpt.save:permanent:match=window:mid-swap|,max_fires=1;"
        "data.next:corrupt:at=0+3")
    assert [s.site for s in specs] == ["serve.dispatch", "serve.fetch",
                                      "ckpt.save", "data.next"]
    assert specs[0].rate == 0.2 and specs[0].seed == 7
    assert specs[1].latency_s == 0.5
    assert specs[2].match == "window:mid-swap|" and specs[2].max_fires == 1
    assert specs[3].at == (0, 3)
    with pytest.raises(ValueError, match="site:kind"):
        parse_specs("serve.dispatch")
    with pytest.raises(ValueError, match="unknown fault spec key"):
        parse_specs("serve.dispatch:transient:boom=1")


def test_env_var_arms_in_subprocess():
    """The env path is process-lifetime state — exercised in a subprocess so
    this process's registry stays clean."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = """
import sys; sys.path.insert(0, {repo!r})
from ddim_cold_tpu.utils import faults
try:
    faults.fire("serve.dispatch")
except faults.TransientFault:
    print("armed-from-env")
print("injected", faults.snapshot()["injected"])
""".format(repo=repo)
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=60, env=dict(
            os.environ, JAX_PLATFORMS="cpu",
            DDIM_COLD_FAULTS="serve.dispatch:transient:at=0"))
    assert proc.returncode == 0, proc.stderr
    assert "armed-from-env" in proc.stdout
    assert "injected 1" in proc.stdout


# ---------------------------------------------- ckpt.save crash windows


#: every window the two-rename swap can die in (the tags save_checkpoint
#: fires at, in sequence)
CKPT_WINDOWS = ("pre-write", "post-write", "mid-swap", "post-swap")


@pytest.mark.parametrize("window", CKPT_WINDOWS)
def test_ckpt_save_crash_window_never_loses_checkpoint(tmp_path, window):
    """Kill the save inside each crash window: after recover_swap, a
    loadable checkpoint ALWAYS survives — v1 (crash before the new data
    committed) or v2 (crash after) — never a torn state."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    p = str(tmp_path / "state.ckpt")
    v1 = {"a": np.arange(3), "epoch": np.asarray(1)}
    v2 = {"a": np.arange(3) + 10, "epoch": np.asarray(2)}
    ckpt.save_checkpoint(p, v1)
    with faults.inject(FaultSpec("ckpt.save", "permanent",
                                 match=f"window:{window}|")):
        with pytest.raises(PermanentFault):
            ckpt.save_checkpoint(p, v2)
    ckpt.recover_swap(p)  # what the trainer's resume path runs
    got = ckpt.restore_checkpoint(p)
    assert int(got["epoch"]) in (1, 2), "torn checkpoint"
    want = v1 if int(got["epoch"]) == 1 else v2
    np.testing.assert_array_equal(got["a"], want["a"])
    # the NEXT save must heal leftovers and fully succeed
    v3 = {"a": np.arange(3) + 20, "epoch": np.asarray(3)}
    ckpt.save_checkpoint(p, v3)
    np.testing.assert_array_equal(ckpt.restore_checkpoint(p)["a"], v3["a"])
    assert not os.path.isdir(p + ".writing") and not os.path.isdir(p + ".old")


def test_ckpt_save_transient_window_heals_on_retry(tmp_path):
    """A transient fault mid-swap (the realistic NFS hiccup): the very next
    save_checkpoint call recovers the swap itself and overwrites cleanly."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    p = str(tmp_path / "state.ckpt")
    ckpt.save_checkpoint(p, {"a": np.arange(3)})
    with faults.inject(FaultSpec("ckpt.save", "transient",
                                 match="window:mid-swap|", max_fires=1)):
        with pytest.raises(TransientFault):
            ckpt.save_checkpoint(p, {"a": np.arange(4)})
        ckpt.save_checkpoint(p, {"a": np.arange(5)})  # retry inside scope
    np.testing.assert_array_equal(ckpt.restore_checkpoint(p)["a"],
                                  np.arange(5))


# ------------------------------------------------------------- data.next


def test_data_next_site_fires_in_loader():
    from ddim_cold_tpu.data.loader import ShardedLoader

    class Toy:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            x = np.full((4, 4, 3), float(i), np.float32)
            return x, x, i

    loader = ShardedLoader(Toy(), batch_size=4, shuffle=False,
                           num_threads=1)
    with faults.inject(FaultSpec("data.next", "transient", at=(1,))):
        it = iter(loader)
        next(it)  # batch 0 fine
        with pytest.raises(TransientFault):
            next(it)  # batch 1 killed — surfaces at the consumer
    # disarmed: the loader iterates clean (threaded path too)
    loader2 = ShardedLoader(Toy(), batch_size=4, shuffle=False,
                            num_threads=2)
    assert sum(1 for _ in loader2) == 2


# ------------------------------------------------------- retry taxonomy


def test_retryable_exceptions_match_transient_fault_kinds():
    """The router's hedging predicate (errors.RETRYABLE_EXCEPTIONS) must
    agree with this module's fault taxonomy: every raising kind is
    classified, transients (and only transients) are retryable, and the
    classification is derived — not a hand-copied list that drifts when a
    kind is added."""
    from ddim_cold_tpu.serve.errors import RETRYABLE_EXCEPTIONS

    # every fault kind that raises has a classification entry
    raising = set(faults.KIND_EXCEPTIONS)
    assert raising == {"transient", "permanent"}
    assert set(faults.KINDS) >= raising  # latency/corrupt perturb, not raise
    # transients are exactly the retryable fault classes...
    assert set(faults.TRANSIENT_EXCEPTIONS) == \
        {faults.KIND_EXCEPTIONS["transient"]}
    fault_retryables = tuple(e for e in RETRYABLE_EXCEPTIONS
                             if issubclass(e, faults.FaultError))
    assert fault_retryables == faults.TRANSIENT_EXCEPTIONS
    # ...and permanents are terminal
    assert not issubclass(PermanentFault,
                          tuple(RETRYABLE_EXCEPTIONS))
    # the classification is live: each raising kind raises its mapped class
    for kind, exc_type in faults.KIND_EXCEPTIONS.items():
        with faults.inject(FaultSpec("serve.dispatch", kind, rate=1.0)):
            with pytest.raises(exc_type):
                faults.fire("serve.dispatch")
