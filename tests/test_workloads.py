"""Guided-editing workload tests (ddim_cold_tpu/workloads).

Three contracts, per task:

* **bitwise-vs-direct** — a served ``SamplerConfig(task=…)`` request returns
  bit-for-bit the direct ``workloads.*`` call with the same rng, at BOTH
  warmed buckets (the engine contract of ISSUE-2, inherited because every
  init builder is shared code drawn at the request's own n);
* **zero compiles after warmup** — the edit configs coalesce into the same
  AOT machinery, so the compile counter is frozen across every submission
  (including preview-enabled variants);
* **mask idempotence** — inpainting preserves the known pixels EXACTLY
  (the final output is the last projected x̂0).

Plus the streaming-preview surface: ``Ticket.previews()`` frames are a
bitwise prefix of the direct trajectory, and at least one frame lands
BEFORE the ticket resolves.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu import serve, workloads
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import degrade, sampling
from ddim_cold_tpu.serve import fleet
from ddim_cold_tpu.serve.router import Router

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
            num_heads=4, total_steps=2000)
K = 500       # 4 reverse steps
T_START = 1200  # 3-step suffix for draft/interp


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


def _configs():
    return {
        "inpaint": serve.SamplerConfig(task="inpaint", k=K),
        "superres": serve.SamplerConfig(task="superres", sampler="cold",
                                        levels=3),
        "draft": serve.SamplerConfig(task="draft", k=K, t_start=T_START),
        "interp": serve.SamplerConfig(task="interp", k=K, t_start=T_START),
        "draft_pv": serve.SamplerConfig(task="draft", k=K, t_start=T_START,
                                        preview_every=1),
    }


@pytest.fixture(scope="module")
def edit_warmed(model_and_params):
    """One engine warmed with every edit config at two buckets."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4, 8))
    cfgs = _configs()
    report = serve.warmup(eng, list(cfgs.values()), persistent_cache=False)
    assert report["new_compiles"] == 2 * len(cfgs)
    return eng, cfgs


@pytest.fixture(scope="module")
def images(model_and_params):
    """Deterministic [-1, 1] reference images + a half-image mask."""
    model, _ = model_and_params
    H, W = model.img_size
    rs = np.random.RandomState(7)
    imgs = rs.uniform(-1.0, 1.0, (5, H, W, 3)).astype(np.float32)
    mask = np.zeros((H, W), np.float32)
    mask[: H // 2] = 1.0
    return imgs, mask


# ----------------------------------------------------------------- registry


def test_task_registry_pinned():
    """serve/batching.py keeps a literal copy of the task tuple (host-only
    module) — it must stay equal to the workloads registry."""
    from ddim_cold_tpu.serve import batching

    assert batching._TASKS == workloads.TASKS
    assert workloads.TASKS == ("sample",) + workloads.EDIT_TASKS


def test_normalize_mask_shapes(model_and_params):
    model, _ = model_and_params
    H, W = model.img_size
    flat = np.ones((H, W), np.float32)
    for shaped in (flat, flat[..., None], flat[None], flat[None, ..., None]):
        m = workloads.normalize_mask(shaped, 3, (H, W))
        assert m.shape == (3, H, W, 1) and m.dtype == np.float32
    with pytest.raises(ValueError, match="binary"):
        workloads.normalize_mask(flat * 0.5, 1, (H, W))
    with pytest.raises(ValueError, match="batch"):
        workloads.normalize_mask(np.ones((2, H, W), np.float32), 3, (H, W))
    with pytest.raises(ValueError, match="mask must be"):
        workloads.normalize_mask(np.ones((H + 1, W), np.float32), 1, (H, W))


# ------------------------------------------------------------------ inpaint


def test_inpaint_mask_idempotence(model_and_params, images):
    """Known pixels of the result are (known+1)/2 bit-exactly; the
    synthesized half actually differs from the reference."""
    model, params = model_and_params
    imgs, mask = images
    known = imgs[:2]
    out = np.asarray(workloads.inpaint(model, params, jax.random.PRNGKey(1),
                                       known, mask, k=K))
    sel = mask.astype(bool)
    assert np.array_equal(out[:, sel], ((known[:, sel] + 1.0) / 2.0))
    assert not np.allclose(out[:, ~sel], (known[:, ~sel] + 1.0) / 2.0)


def test_inpaint_engine_bitwise_two_buckets(edit_warmed, images):
    eng, cfgs = edit_warmed
    model, params = eng.model, eng.params
    imgs, mask = images
    c0 = eng.stats["compiles"]
    tickets = {}
    for seed, n in ((11, 3), (12, 5)):  # buckets 4 and 8
        tickets[seed] = eng.submit(seed=seed, x_init=imgs[:n], mask=mask,
                                   config=cfgs["inpaint"])
    eng.run()
    for seed, n in ((11, 3), (12, 5)):
        direct = np.asarray(workloads.inpaint(
            model, params, jax.random.PRNGKey(seed), imgs[:n], mask, k=K))
        assert np.array_equal(tickets[seed].result(), direct)
    assert eng.stats["compiles"] == c0


# ----------------------------------------------------------------- superres


def test_superres_matches_cold_sample(model_and_params):
    """A 1×1 constant input at the full level count IS cold sampling: the
    upsampled start equals the broadcast constant-color init bitwise."""
    model, params = model_and_params
    color = np.asarray(jax.random.normal(jax.random.PRNGKey(3), (2, 1, 1, 3),
                                         jnp.float32))
    direct = np.asarray(sampling.cold_sample(model, params,
                                             jax.random.PRNGKey(3), n=2,
                                             levels=4))
    sr = np.asarray(workloads.super_resolve(model, params, color, level=4))
    assert np.array_equal(sr, direct)


def test_superres_engine_bitwise_two_buckets(edit_warmed, images):
    eng, cfgs = edit_warmed
    model, params = eng.model, eng.params
    imgs, _ = images
    H = model.img_size[0]
    c0 = eng.stats["compiles"]
    tickets = {}
    for n in (3, 5):
        low = imgs[:n, ::8, ::8]  # 2×2 inputs → level 3
        tickets[n] = eng.submit(x_init=workloads.superres_init(low, H),
                                config=cfgs["superres"])
    eng.run()
    for n in (3, 5):
        low = imgs[:n, ::8, ::8]
        direct = np.asarray(workloads.super_resolve(model, params, low,
                                                    level=3))
        assert np.array_equal(tickets[n].result(), direct)
    assert eng.stats["compiles"] == c0


def test_upsample_nearest_roundtrips_downsample():
    """upsample∘downsample is the cold degradation D(x, level): idempotent
    on already-degraded images (the degradation-operator property the
    superres task leans on)."""
    from ddim_cold_tpu.data import resize

    rs = np.random.RandomState(0)
    x = rs.uniform(-1, 1, (2, 16, 16, 3)).astype(np.float32)
    iy = resize.nearest_indices(4, 16)
    down = x[:, iy][:, :, iy]
    up = np.asarray(degrade.upsample_nearest(down, 16))
    down2 = up[:, iy][:, :, iy]
    assert np.array_equal(down, down2)


# -------------------------------------------------------------------- draft


def test_draft_engine_bitwise_two_buckets(edit_warmed, images):
    eng, cfgs = edit_warmed
    model, params = eng.model, eng.params
    imgs, _ = images
    c0 = eng.stats["compiles"]
    tickets = {}
    for seed, n in ((21, 3), (22, 5)):
        tickets[seed] = eng.submit(seed=seed, x_init=imgs[:n],
                                   config=cfgs["draft"])
    eng.run()
    for seed, n in ((21, 3), (22, 5)):
        direct = np.asarray(workloads.draft_to_drawing(
            model, params, jax.random.PRNGKey(seed), imgs[:n],
            t_start=T_START, k=K))
        assert np.array_equal(tickets[seed].result(), direct)
    assert eng.stats["compiles"] == c0


def test_sample_from_forwards_sequence_and_mesh(model_and_params, images):
    """Satellite fix: sample_from used to drop return_sequence/mesh on the
    floor — the trajectory form must come back (steps+1, n, H, W, C)."""
    model, params = model_and_params
    imgs, _ = images
    enc = workloads.draft_init(jax.random.PRNGKey(2),
                               jnp.asarray(imgs[:2]), T_START)
    seq = sampling.sample_from(model, params, enc, T_START, k=K,
                               return_sequence=True, mesh=None)
    steps = T_START // K + 1  # the scan visits t_start down through 0
    assert seq.shape == (steps + 1, 2) + model.img_size + (3,)
    last = sampling.sample_from(model, params, enc, T_START, k=K)
    assert last.shape == (2,) + model.img_size + (3,)


# ------------------------------------------------------------------- interp


def test_interpolate_end_to_end(model_and_params, images):
    model, params = model_and_params
    imgs, _ = images
    out = np.asarray(workloads.interpolate(
        model, params, jax.random.PRNGKey(4), imgs[0], imgs[1],
        n_interp=5, t_start=T_START, k=K))
    assert out.shape == (5,) + model.img_size + (3,)
    assert np.isfinite(out).all()
    assert not np.array_equal(out[0], out[-1])  # path actually moves


def test_interp_engine_bitwise_two_buckets(edit_warmed, images):
    eng, cfgs = edit_warmed
    model, params = eng.model, eng.params
    imgs, _ = images
    pair = imgs[:2]
    c0 = eng.stats["compiles"]
    tickets = {}
    for seed, n in ((31, 3), (32, 5)):  # n is the PATH length here
        tickets[seed] = eng.submit(seed=seed, n=n, x_init=pair,
                                   config=cfgs["interp"])
    eng.run()
    for seed, n in ((31, 3), (32, 5)):
        direct = np.asarray(workloads.interpolate(
            model, params, jax.random.PRNGKey(seed), pair[0], pair[1],
            n_interp=n, t_start=T_START, k=K))
        assert np.array_equal(tickets[seed].result(), direct)
    assert eng.stats["compiles"] == c0


# ----------------------------------------------------------------- previews


def test_previews_stream_before_completion(edit_warmed, images):
    """preview_every=1 on the 3-step draft config: frames 1 and 2 stream,
    each a bitwise row-slice of the direct trajectory, delivered BEFORE the
    ticket resolves; the final result is the trajectory's last frame."""
    eng, cfgs = edit_warmed
    model, params = eng.model, eng.params
    imgs, _ = images
    c0 = eng.stats["compiles"]
    t = eng.submit(seed=41, x_init=imgs[:3], config=cfgs["draft_pv"])
    seen = []
    t.add_preview_callback(lambda step, frames: seen.append((step, t.done)))
    eng.run()
    assert eng.stats["compiles"] == c0
    assert seen and all(not done for _, done in seen)

    direct_seq = np.asarray(workloads.draft_to_drawing(
        model, params, jax.random.PRNGKey(41), imgs[:3],
        t_start=T_START, k=K, return_sequence=True))
    frames = list(t.previews(timeout=5))
    assert [s for s, _ in frames] == [1, 2]
    for step, frame in frames:
        assert np.array_equal(frame, direct_seq[step])
    assert np.array_equal(t.result(), direct_seq[-1])


def test_previews_iterator_empty_without_opt_in(edit_warmed, images):
    eng, cfgs = edit_warmed
    imgs, _ = images
    t = eng.submit(seed=42, x_init=imgs[:3], config=cfgs["draft"])
    eng.run()
    t.result()
    assert list(t.previews(timeout=1)) == []


def test_router_forwards_previews_and_keeps_bitwise(model_and_params,
                                                   images):
    """The fleet path: an edit task routed through Router completes bitwise
    and its preview frames surface on the ROUTER ticket."""
    model, params = model_and_params
    imgs, mask = images
    cfg = serve.SamplerConfig(task="inpaint", k=K, preview_every=2)
    factory = fleet.local_factory(model, params, buckets=(4,))
    router = Router(factory, replicas=1, configs=[cfg],
                    warm_kwargs={"persistent_cache": False})
    try:
        t = router.submit(seed=51, x_init=imgs[:3], mask=mask, config=cfg)
        rows = t.result(timeout=120)
        direct_seq = np.asarray(workloads.inpaint(
            model, params, jax.random.PRNGKey(51), imgs[:3], mask, k=K,
            return_sequence=True))
        assert np.array_equal(rows, direct_seq[-1])
        frames = list(t.previews(timeout=5))
        assert [s for s, _ in frames] == [2]  # 4 steps, every=2
        assert np.array_equal(frames[0][1], direct_seq[2])
    finally:
        router.drain(5.0)


# --------------------------------------------------------------- validation


def test_submit_validation(edit_warmed, images):
    eng, cfgs = edit_warmed
    imgs, mask = images
    with pytest.raises(ValueError, match="mask= is the inpaint"):
        eng.submit(seed=0, x_init=imgs[:2], mask=mask, config=cfgs["draft"])
    with pytest.raises(ValueError, match="needs x_init"):
        eng.submit(seed=0, config=cfgs["draft"])
    with pytest.raises(ValueError, match="needs mask"):
        eng.submit(seed=0, x_init=imgs[:2], config=cfgs["inpaint"])
    with pytest.raises(ValueError, match="keyed"):
        eng.submit(x_init=imgs[:2], mask=mask, config=cfgs["inpaint"])
    with pytest.raises(ValueError, match="endpoint PAIR"):
        eng.submit(seed=0, n=4, x_init=imgs[:3], config=cfgs["interp"])


def test_config_validation():
    with pytest.raises(ValueError, match="task"):
        serve.SamplerConfig(task="sharpen")
    with pytest.raises(ValueError, match="cold"):
        serve.SamplerConfig(task="superres")  # superres is the cold path
    with pytest.raises(ValueError, match="t_start"):
        serve.SamplerConfig(task="draft", k=K)
    # inpaint + step cache became a served product in the adaptive-cache PR
    assert serve.SamplerConfig(task="inpaint", k=K, cache_interval=2).cached
    with pytest.raises(ValueError, match="preview_every"):
        serve.SamplerConfig(k=K, preview_every=-1)


def test_default_edit_configs_cover_every_task():
    cfgs = workloads.default_edit_configs(k=K, t_start=T_START, sr_level=3)
    assert sorted(c.task for c in cfgs) == sorted(workloads.EDIT_TASKS)
