"""Out-of-process replica tests (serve/remote.py + serve/replica_main.py).

The wire protocol and exception codec are tested in-process; the process
tests spawn the STUB backend (serve/replica_main.py's StubEngine — the full
warmup/submit/drain surface minus jax, deterministic rows per seed) so a
child boots in well under a second and the whole file fits the tier-1
budget. The chaos recipes mirror bench --fleet-proc: ``replica.kill`` is a
real SIGKILL inside the child, ``replica.hang`` wedges its reader thread
(heartbeat-loss retire), ``rpc.drop`` eats frames on the parent side.
"""

import socket
import struct
import subprocess
import threading
import time

import numpy as np
import pytest

from ddim_cold_tpu.serve import fleet, remote, replica_main
from ddim_cold_tpu.serve.batching import SamplerConfig
from ddim_cold_tpu.serve.errors import (DeadlineExceeded, EngineClosedError,
                                        RemoteRPCError, ReplicaCrashedError,
                                        ReplicaUnreachableError,
                                        RequestFailedError, decode_exception,
                                        encode_exception)
from ddim_cold_tpu.serve.router import Router
from ddim_cold_tpu.utils import faults

pytestmark = pytest.mark.usefixtures("no_leaked_faults")

CFG = SamplerConfig(k=50)
STUB_SHAPE = (8, 8, 3)


@pytest.fixture()
def no_leaked_faults():
    assert not faults.active(), "a previous test leaked an armed fault scope"
    yield
    assert not faults.active(), "this test leaked an armed fault scope"


@pytest.fixture()
def reaper():
    """Track spawned handles; guarantee no child process outlives a test
    (a hung child would otherwise linger for its full hang_s)."""
    handles = []
    yield handles
    for rep in handles:
        try:
            rep.close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        try:
            rep._proc.kill()
        except Exception:  # noqa: BLE001 — already gone is fine
            pass


def _spawn(reaper, spec=None, env=None, **kw):
    kw.setdefault("heartbeat_s", 0.3)
    kw.setdefault("miss_budget", 3)
    kw.setdefault("rpc_timeout_s", 10.0)
    factory = remote.remote_factory(
        dict({"backend": "stub"}, **(spec or {})), env=env, **kw)
    rep = factory("rk")
    reaper.append(rep)
    return rep


def _poll(fn, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


# ------------------------------------------------------------ wire protocol


def test_payload_round_trip_with_arrays():
    msg = {"id": 3, "method": "submit",
           "params": {"seed": 7, "x_init": np.arange(12, dtype=np.float32)
                      .reshape(3, 4),
                      "mask": np.ones((2, 2), dtype=bool),
                      "nested": {"w": np.float64(2.5), "k": np.int64(9)},
                      "plain": [1, "two", None, 3.0]}}
    back = remote.decode_payload(remote.encode_payload(msg))
    assert back["id"] == 3 and back["method"] == "submit"
    np.testing.assert_array_equal(back["params"]["x_init"],
                                  msg["params"]["x_init"])
    assert back["params"]["x_init"].dtype == np.float32
    np.testing.assert_array_equal(back["params"]["mask"],
                                  msg["params"]["mask"])
    # numpy scalars cross as plain python numbers, not zero-d arrays
    assert back["params"]["nested"] == {"w": 2.5, "k": 9}
    assert back["params"]["plain"] == [1, "two", None, 3.0]


def test_frames_over_a_socket_and_eof_is_connection_error():
    a, b = socket.socketpair()
    try:
        remote.send_frame(a, {"event": "ticket",
                              "rows": np.zeros((2, 4), np.float32)})
        msg = remote.recv_frame(b)
        assert msg["event"] == "ticket" and msg["rows"].shape == (2, 4)
        a.close()
        with pytest.raises(ConnectionError):
            remote.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_exception_round_trip_typed_with_cause():
    exc = DeadlineExceeded("ticket blew its 3s budget")
    exc.__cause__ = TimeoutError("socket timed out")
    back = decode_exception(encode_exception(exc))
    assert isinstance(back, DeadlineExceeded)
    assert "3s budget" in str(back)
    assert isinstance(back.__cause__, TimeoutError)


def test_exception_round_trip_unknown_type_degrades_typed():
    back = decode_exception({"type": "WeirdVendorError", "message": "boom"})
    assert isinstance(back, RequestFailedError)
    assert "[WeirdVendorError]" in str(back) and "boom" in str(back)


def test_params_npz_round_trip(tmp_path):
    params = {"encoder": {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                          "b": np.zeros((3,), np.float32)},
              "head": {"scale": np.float32(0.5)}}
    path = remote.save_params_npz(str(tmp_path / "p.npz"), params)
    back = remote.load_params_npz(path)
    np.testing.assert_array_equal(back["encoder"]["w"],
                                  params["encoder"]["w"])
    np.testing.assert_array_equal(back["head"]["scale"], 0.5)


# ----------------------------------------------- drain-race satellite (local)


def test_local_replica_submit_after_drain_is_typed_not_runtime_error():
    """The Router snapshots health, then places — a replica draining in
    that window must raise the typed failover class (EngineClosedError →
    Router tries the next candidate), never a raw RuntimeError."""
    rep = fleet.LocalReplica(replica_main.StubEngine("local"))
    rep.warm([CFG], buckets=(4,), persistent_cache=False)
    rep.start()
    rep.drain(timeout=5)
    with pytest.raises(EngineClosedError, match="retry"):
        rep.submit(seed=0, n=1)


# -------------------------------------------------------- subprocess replica


def test_stub_subprocess_serves_bitwise_and_reports_health(reaper):
    rep = _spawn(reaper, spec={"stub": {"shape": list(STUB_SHAPE)}})
    rep.warm([CFG], buckets=(4, 8), persistent_cache=False)
    rep.start()
    with pytest.raises(ValueError, match="seed"):
        rep.submit(rng=object())
    t = rep.submit(seed=7, n=3)
    rows = t.result(timeout=15)
    np.testing.assert_array_equal(rows,
                                  replica_main.stub_rows(7, 3, STUB_SHAPE))
    h = rep.health()
    assert h["state"] == fleet.READY
    assert h["compiles_after_warmup"] == 0
    assert h["spawn_s"] > 0 and h["warm_s"] > 0
    rep.drain(timeout=10)
    assert rep.state == fleet.CLOSED
    assert rep._proc.poll() is not None, "drained child still running"


def test_kill_mid_batch_fails_queued_tickets_typed(reaper):
    """SIGKILL inside the child while two tickets sit queued: the in-flight
    RPC and both tickets all resolve typed, naming the replica — nothing
    blocks forever (the liveness contract)."""
    rep = _spawn(reaper, spec={"stub": {"delay_s": 0.5}},
                 env={"DDIM_COLD_FAULTS": "replica.kill:kill:at=2"})
    rep.warm([CFG], buckets=(4,), persistent_cache=False)
    rep.start()
    t1 = rep.submit(seed=1, n=2)
    t2 = rep.submit(seed=2, n=2)
    with pytest.raises((ReplicaCrashedError, ReplicaUnreachableError)):
        rep.submit(seed=3, n=1)  # the 3rd work frame pulls the trigger
    e1 = t1.exception(timeout=15)
    e2 = t2.exception(timeout=15)
    for e in (e1, e2):
        assert isinstance(e, ReplicaCrashedError), e
        assert "rk" in str(e), f"cause does not name the replica: {e}"
    assert _poll(lambda: rep.state == fleet.CLOSED)
    # whichever watcher won the race — reader EOF or the process waiter —
    # left its breadcrumb
    assert ("exited" in rep.crash_reason
            or "connection lost" in rep.crash_reason)
    report = rep.drain(timeout=5)  # retiring a corpse is a typed no-op
    assert report.get("crashed") is True


def test_heartbeat_loss_retires_hung_replica(reaper):
    """replica.hang wedges the child's reader thread (the process is alive
    but deaf): pings go unanswered, the miss budget empties, and the handle
    self-transitions to closed with the heartbeat breadcrumb."""
    rep = _spawn(reaper, spec={"stub": {}},
                 env={"DDIM_COLD_FAULTS": "replica.hang:hang:at=0,hang_s=60"},
                 heartbeat_s=0.15, miss_budget=3)
    rep.warm([CFG], buckets=(4,), persistent_cache=False)
    rep.start()
    with pytest.raises(ReplicaCrashedError, match="heartbeat"):
        rep.submit(seed=0, n=1)  # first work frame trips the wedge
    assert rep.state == fleet.CLOSED
    assert "heartbeat lost" in rep.crash_reason
    # the wedged child is ALIVE when the heartbeat budget empties — crash
    # handling must kill it, not just close the socket (a leaked child
    # would hold the accelerator against the respawned replacement)
    assert _poll(lambda: rep._proc.poll() is not None), \
        "heartbeat-loss crash leaked a live child process"
    rep.drain(timeout=5)  # retiring the corpse reaps it
    assert rep._proc.poll() is not None


def test_deadline_enforced_across_the_rpc_boundary(reaper):
    """deadline_s crosses the wire, expires inside the child, and the
    child's DeadlineExceeded comes back as the same type."""
    rep = _spawn(reaper, spec={"stub": {"delay_s": 0.5}})
    rep.warm([CFG], buckets=(4,), persistent_cache=False)
    rep.start()
    t = rep.submit(seed=0, n=1, deadline_s=0.05)
    exc = t.exception(timeout=15)
    assert isinstance(exc, DeadlineExceeded), exc
    rep.drain(timeout=10)


def test_rpc_drop_turns_into_unreachable_at_the_deadline(reaper):
    rep = _spawn(reaper, spec={"stub": {}}, rpc_timeout_s=0.5)
    rep.warm([CFG], buckets=(4,), persistent_cache=False)
    rep.start()
    with faults.inject(faults.FaultSpec(site="rpc.drop", kind="transient",
                                        match="method:health")):
        with pytest.raises(ReplicaUnreachableError, match="deadline"):
            rep.health()
    assert rep.health()["state"] == fleet.READY  # drop was the fault, not us
    rep.drain(timeout=10)


# ---------------------------------------------- protocol races and limits


class _FakeProc:
    """Popen lookalike for driving a RemoteReplica against a socketpair."""

    def __init__(self):
        self._dead = threading.Event()

    def wait(self, timeout=None):
        if not self._dead.wait(timeout):
            raise subprocess.TimeoutExpired("fake-replica", timeout)
        return 0

    def poll(self):
        return 0 if self._dead.is_set() else None

    def kill(self):
        self._dead.set()


def test_done_event_racing_ahead_of_submit_response_still_resolves():
    """The server's ticket done event can hit the wire BEFORE the submit
    RPC response (add_done_callback fires from the resolver thread for a
    fast request). The client registers the rid before the submit frame
    leaves, so the early event finds its ticket — an unknown-rid drop here
    would leave result() blocking forever on a healthy replica."""
    parent, child = socket.socketpair()
    proc = _FakeProc()
    rep = remote.RemoteReplica(parent, proc, replica_id="race",
                               heartbeat_s=60.0)
    try:
        rep.state = fleet.READY  # the fake server has no warm step
        rows = replica_main.stub_rows(3, 2, STUB_SHAPE)

        def server():
            msg = remote.recv_frame(child)
            rid = msg["params"]["rid"]
            # the racy interleaving, made deterministic: done event first,
            # submit response second
            remote.send_frame(child, {"event": "ticket", "rid": rid,
                                      "status": "done", "result": rows})
            remote.send_frame(child, {"id": msg["id"], "ok": True,
                                      "result": {"rid": rid, "n": 2}})

        th = threading.Thread(target=server, daemon=True)
        th.start()
        t = rep.submit(seed=3, n=2)
        np.testing.assert_array_equal(t.result(timeout=10), rows)
        th.join(5)
    finally:
        proc.kill()
        parent.close()
        child.close()


def test_oversized_submit_rejected_locally_replica_survives(
        reaper, monkeypatch):
    """An over-MAX_FRAME_BYTES submit raises typed at the CLIENT send site
    (RemoteRPCError — not retryable, so a hedge cannot replay it), and the
    replica it never reached keeps serving."""
    rep = _spawn(reaper, spec={"stub": {"shape": list(STUB_SHAPE)}})
    rep.warm([CFG], buckets=(4,), persistent_cache=False)
    rep.start()
    monkeypatch.setattr(remote, "MAX_FRAME_BYTES", 4096)
    with pytest.raises(RemoteRPCError, match="MAX_FRAME_BYTES"):
        rep.submit(seed=0, n=1,
                   x_init=np.zeros((1, 64, 64, 3), np.float32))
    monkeypatch.setattr(remote, "MAX_FRAME_BYTES", 1 << 30)
    assert rep.health()["state"] == fleet.READY
    t = rep.submit(seed=5, n=2)
    np.testing.assert_array_equal(t.result(timeout=15),
                                  replica_main.stub_rows(5, 2, STUB_SHAPE))
    rep.drain(timeout=10)


def test_server_drains_oversized_frame_and_keeps_serving(monkeypatch):
    """An over-limit INBOUND frame is not parent-gone: the server discards
    exactly the declared payload (stream stays framed), answers with a
    typed protocol_error event, and serves the next request — one bad
    frame must not os._exit a replica."""
    parent, child = socket.socketpair()
    try:
        srv = replica_main.ReplicaServer(child, replica=None,
                                         replica_id="lim")
        monkeypatch.setattr(remote, "MAX_FRAME_BYTES", 1024)
        parent.sendall(struct.pack(">I", 2048) + b"\x00" * 2048)
        remote.send_frame(parent, {"id": 2, "method": "ping", "params": {}})

        def server_turn():
            srv.handle(srv._recv_request())

        th = threading.Thread(target=server_turn, daemon=True)
        th.start()
        err_evt = remote.recv_frame(parent)
        assert err_evt["event"] == "protocol_error"
        assert "MAX_FRAME_BYTES" in err_evt["error"]["message"]
        pong = remote.recv_frame(parent)
        assert pong["id"] == 2 and pong["ok"]
        th.join(5)
    finally:
        parent.close()
        child.close()


# ------------------------------------------------------------ fleet failover


def test_router_failover_after_kill_is_bitwise_and_respawns(reaper):
    """The acceptance scenario at test scale: 2 subprocess replicas, r0
    SIGKILLed on its 2nd work frame mid-stream. Every ticket completes
    bitwise-identical to the deterministic stub rows (failover re-placed
    the dead replica's work), supervision spawns a replacement, and the
    fleet-wide compiles_after_warmup stays 0."""
    killed = {"DDIM_COLD_FAULTS": "replica.kill:kill:at=1,match=replica:r0|"}
    factory = remote.remote_factory({"backend": "stub",
                                     "stub": {"delay_s": 0.2}},
                                    env=killed, heartbeat_s=0.3,
                                    miss_budget=3)

    def tracking(rid):
        rep = factory(rid)
        reaper.append(rep)
        return rep

    router = Router(tracking, replicas=2, configs=(CFG,), buckets=(4, 8),
                    warm_kwargs=dict(persistent_cache=False),
                    drain_timeout_s=10, tick_s=0.02)
    try:
        tickets = [(seed, router.submit(seed=seed, n=2))
                   for seed in range(6)]
        for seed, t in tickets:
            np.testing.assert_array_equal(
                t.result(timeout=30),
                replica_main.stub_rows(seed, 2, STUB_SHAPE),
                err_msg=f"seed {seed} not bitwise after failover")
        assert _poll(lambda: router.health()["retired_replicas"] >= 1), \
            "the killed replica was never retired"
        assert _poll(lambda: router.health()["active_replicas"] == 2), \
            "no replacement spawned back to target"
        h = router.health()
        assert h["failovers"] >= 1
        assert h["compiles_after_warmup"] == 0
    finally:
        router.drain(timeout=15)
