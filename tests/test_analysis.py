"""graftcheck self-tests: one deliberately violating fixture per rule
(asserting the stable rule id, file, and — for source lint — line), the
baseline grammar, and the clean-tree run (zero non-baselined findings on
the repo as committed, which is what CI enforces).

Each jaxpr fixture is a tiny jitted function exhibiting exactly one hazard;
each AST fixture is a source snippet fed through ``lint_source`` so the
line numbers are knowable constants."""

import textwrap
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.analysis import ast_checks, cli, collective_checks, entries
from ddim_cold_tpu.analysis import jaxpr_checks, sharding_checks, thread_checks
from ddim_cold_tpu.analysis.findings import (
    RULES, Finding, load_baseline, rule_layer, write_baseline)

SITES = ("serve.assemble", "ckpt.save")  # a registry slice for lint fixtures


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- jaxpr rules


def test_j001_low_precision_accumulation():
    f = jax.jit(lambda a, b: a @ b)
    x = jax.ShapeDtypeStruct((8, 16), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((16, 8), jnp.bfloat16)
    closed = jax.make_jaxpr(f)(x, w)
    fs = jaxpr_checks.check_accumulation(closed, "fix", "fix.py")
    assert _rules_of(fs) == ["GRAFT-J001"]
    assert fs[0].path == "fix.py" and "dot_general" in fs[0].subject

    # the designed pattern — bf16 operands, f32 accumulate — must pass
    g = jax.jit(lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32))
    assert jaxpr_checks.check_accumulation(
        jax.make_jaxpr(g)(x, w), "ok", "ok.py") == []


def test_j002_weak_typed_output():
    f = jax.jit(lambda: jnp.sin(1.0))  # python float → weak f32 out
    fs = jaxpr_checks.check_weak_types(jax.eval_shape(f), "fix", "fix.py")
    assert _rules_of(fs) == ["GRAFT-J002"]

    g = jax.jit(lambda: jnp.sin(jnp.float32(1.0)))
    assert jaxpr_checks.check_weak_types(jax.eval_shape(g), "ok", "ok.py") == []


@pytest.mark.filterwarnings("ignore:Some donated buffers were not usable")
def test_j003_dropped_donation():
    @partial(jax.jit, donate_argnums=(0,))
    def f(x):
        return x.sum()  # () out can never alias the (8, 8) donation

    x = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    fs = jaxpr_checks.check_donation(
        f.lower(x).args_info, jax.eval_shape(f, x), "fix", "fix.py")
    assert _rules_of(fs) == ["GRAFT-J003"]

    @partial(jax.jit, donate_argnums=(0,))
    def g(x):
        return x * 2.0  # same aval out — donation lands

    assert jaxpr_checks.check_donation(
        g.lower(x).args_info, jax.eval_shape(g, x), "ok", "ok.py") == []


def test_j003_expected_donation_absent():
    f = jax.jit(lambda x: x * 2.0)
    x = jax.ShapeDtypeStruct((4,), jnp.float32)
    fs = jaxpr_checks.check_donation(
        f.lower(x).args_info, jax.eval_shape(f, x), "fix", "fix.py",
        expect_donation=True)
    assert [f_.subject for f_ in fs] == ["fix:<none-donated>"]


def test_j004_oversized_constant():
    big = jnp.asarray(np.ones((600, 600), np.float32))  # 1.44 MB closure
    f = jax.jit(lambda x: x + big)
    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((600, 600), jnp.float32))
    fs = jaxpr_checks.check_constants(closed, "fix", "fix.py")
    assert _rules_of(fs) == ["GRAFT-J004"]
    # raising the threshold clears it — the knob the CLI exposes
    assert jaxpr_checks.check_constants(closed, "fix", "fix.py",
                                        max_bytes=2 << 20) == []


def test_j005_host_callback_in_scan():
    def body(c, _):
        y = jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), c)
        return c + y, None

    f = jax.jit(lambda x: jax.lax.scan(body, x, None, length=3)[0])
    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((), jnp.float32))
    fs = jaxpr_checks.check_host_callbacks(closed, "fix", "fix.py")
    assert _rules_of(fs) == ["GRAFT-J005"]
    assert fs[0].subject == "fix:pure_callback"

    # the same callback OUTSIDE a loop body is not this rule's business
    g = jax.jit(lambda x: jax.pure_callback(
        lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), x))
    assert jaxpr_checks.check_host_callbacks(
        jax.make_jaxpr(g)(jax.ShapeDtypeStruct((), jnp.float32)),
        "ok", "ok.py") == []


def test_j007_while_primitive_flagged():
    # a while_loop anywhere in the program (nested under jit included) is a
    # data-dependent trip count — the exact thing the adaptive drift gate
    # must never introduce into a served sampler
    f = jax.jit(lambda x: jax.lax.while_loop(
        lambda v: v < 10.0, lambda v: v + 1.0, x))
    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((), jnp.float32))
    fs = jaxpr_checks.check_static_trip_count(closed, "fix", "fix.py")
    assert _rules_of(fs) == ["GRAFT-J007"]
    assert fs[0].subject == "fix:while"

    # a static-trip scan (the gate's actual home) is clean
    g = jax.jit(lambda x: jax.lax.scan(
        lambda c, _: (c + 1.0, None), x, None, length=4)[0])
    assert jaxpr_checks.check_static_trip_count(
        jax.make_jaxpr(g)(jax.ShapeDtypeStruct((), jnp.float32)),
        "ok", "ok.py") == []


# -------------------------------------------------- serve signature (J006)


def test_serve_sweep_matches_test_serve_geometry():
    import tests.test_serve as ts

    assert entries.TINY == ts.TINY
    assert entries.K == ts.K


def test_j006_serve_signatures_stable_and_distinct():
    sigs_a = entries.serve_signatures(entries.Context())
    sigs_b = entries.serve_signatures(entries.Context())
    assert sigs_a == sigs_b  # retrace from a fresh model world → same programs
    assert len(set(sigs_a.values())) == len(sigs_a)  # all pairs distinct
    # every warmed (config, bucket) pair of tests/test_serve.py is covered
    assert {"ddim_k500:b4", "ddim_k500:b8", "ddim_k500_ci2:b4",
            "cold_l4:b8", "ddim_k500_t999:b4",
            "ddim_k500_qxla:b4"} <= set(sigs_a)
    assert entries.run_serve_signature_check() == []


def test_j006_collision_detected(monkeypatch):
    from ddim_cold_tpu.serve.batching import SamplerConfig

    # two labels, identical (config, bucket) → identical trace → collision
    monkeypatch.setattr(entries, "serve_sweep", lambda: [
        ("a", SamplerConfig(k=entries.K), (4,)),
        ("b", SamplerConfig(k=entries.K), (4,)),
    ])
    fs = entries.run_serve_signature_check()
    assert _rules_of(fs) == ["GRAFT-J006"]
    assert any(f.subject.startswith("collision:") for f in fs)


# --------------------------------------------------------------- AST rules


def test_a001_nondeterminism_in_traced_fn():
    src = textwrap.dedent("""\
        import time, random
        import numpy as np
        import jax

        @jax.jit
        def f(x):
            return x + time.time()

        def body(c, _):
            return c + np.random.rand(), None

        def outer(x):
            return jax.lax.scan(body, x, None, length=2)

        def host_only_helper():
            return time.time()  # NOT traced — must not be flagged
    """)
    fs = ast_checks.lint_source(src, "fix.py", sites=SITES)
    assert _rules_of(fs) == ["GRAFT-A001"]
    assert {(f.line, f.subject) for f in fs} == {
        (7, "f:time.time"), (10, "body:numpy.random.rand")}


def test_a001_jit_assignment_and_partial_forms():
    src = textwrap.dedent("""\
        import time
        from functools import partial
        import jax

        def g(x):
            return x + time.time()

        g_fast = jax.jit(g, static_argnums=())
        h = partial(jax.jit, donate_argnums=(0,))(g)
    """)
    fs = ast_checks.lint_source(src, "fix.py", sites=SITES)
    assert [(f.rule, f.line) for f in fs] == [("GRAFT-A001", 6)]


def test_a002_broad_except():
    src = textwrap.dedent("""\
        def f():
            try:
                pass
            except Exception:
                pass
            try:
                pass
            except Exception:  # noqa: BLE001 — justified
                pass
            try:
                pass
            except ValueError:
                pass
    """)
    fs = ast_checks.lint_source(src, "fix.py", sites=SITES)
    assert [(f.rule, f.line) for f in fs] == [("GRAFT-A002", 4)]


def test_a003_fault_sites():
    src = textwrap.dedent("""\
        from ddim_cold_tpu.utils import faults

        def a():
            faults.fire("serve.bogus")

        def b(name):
            faults.fire(name)

        def c():
            faults.fire("ckpt.save", tag="swap")
            faults.fire("ckpt.save", tag="swap")
            faults.fire("serve.assemble", tag=f"bucket:{4}")
    """)
    fs = ast_checks.lint_source(src, "fix.py", sites=SITES)
    assert _rules_of(fs) == ["GRAFT-A003"]
    subjects = {(f.line, f.subject) for f in fs}
    assert (4, "fire:serve.bogus") in subjects        # unregistered
    assert (7, "fire:<dynamic>") in subjects          # non-literal site
    assert (11, "fire:ckpt.save:swap") in subjects    # duplicate (site, tag)
    assert len(fs) == 3  # the dynamic-tag fire at line 12 is exempt


def test_a004_device_calls_in_host_only_module():
    src = textwrap.dedent("""\
        import numpy as np
        import jax.numpy as jnp

        def plan(rows):
            pad = np.zeros(4)
            return jnp.zeros(4) + pad
    """)
    fs = ast_checks.lint_source(src, "fix.py", sites=SITES, host_only=True)
    assert [(f.rule, f.line) for f in fs] == [("GRAFT-A004", 6)]
    # the same file outside the host-only set is fine
    assert ast_checks.lint_source(src, "fix.py", sites=SITES) == []


# ---------------------------------------------------------- sharding rules


def _tiny_float_params():
    return sharding_checks._tiny_params()


def test_s001_trunk_leaf_fell_through(monkeypatch):
    from ddim_cold_tpu.parallel import sharding

    params = _tiny_float_params()
    # simulate the regression class S001 guards: a rename that empties the
    # kernel pattern tables, so every trunk GEMM falls to replicated
    monkeypatch.setattr(sharding, "_COL_KERNELS", ())
    monkeypatch.setattr(sharding, "_ROW_KERNELS", ())
    fs = sharding_checks.check_param_tree(
        params, sharding.param_partition_specs(params), "float")
    assert _rules_of(fs) == ["GRAFT-S001"]
    subjects = {f.subject for f in fs}
    assert "float:blocks_0/attn/qkv/kernel" in subjects
    assert len(fs) == 8  # 4 trunk kernels × depth 2


def test_s002_unusable_specs():
    from jax.sharding import PartitionSpec as P

    params = {"a": jax.ShapeDtypeStruct((4,), jnp.float32),
              "b": jax.ShapeDtypeStruct((4, 4), jnp.float32),
              "c": jax.ShapeDtypeStruct((4,), jnp.float32)}
    specs = {"a": P(None, "model"),       # rank overflow
             "b": P("warp", None),        # unknown mesh axis
             "c": "model"}                # not a PartitionSpec
    fs = sharding_checks.check_param_tree(params, specs, "t")
    assert _rules_of(fs) == ["GRAFT-S002"]
    assert {f.subject for f in fs} == {"t:a", "t:b", "t:c"}


def test_s002_structure_mismatch():
    from jax.sharding import PartitionSpec as P

    params = {"a": jax.ShapeDtypeStruct((4,), jnp.float32),
              "b": jax.ShapeDtypeStruct((4,), jnp.float32)}
    fs = sharding_checks.check_param_tree(params, {"a": P()}, "t")
    assert [(f.rule, f.subject) for f in fs] == [("GRAFT-S002", "t:b")]


# ---------------------------------------------------------- thread rules


def _tlint(src, lock_ranks=None):
    return thread_checks.lint_source(
        textwrap.dedent(src), "fix.py", lock_ranks=lock_ranks)


def test_t001_guarded_write_without_lock():
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: _lock

            def ok(self):
                with self._lock:
                    self._q.append(1)
                    self._q = []

            def bad(self):
                self._q.append(1)
    """)
    assert [(f.rule, f.line, f.subject) for f in fs] == [
        ("GRAFT-T001", 14, "W.bad:_q")]


def test_t001_requires_annotation_seeds_and_checks_callers():
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: _lock

            def _push(self, item):  # requires: _lock
                self._q.append(item)

            def good(self):
                with self._lock:
                    self._push(1)

            def bad(self):
                self._push(2)
    """)
    # _push's own body is clean (the annotation seeds its lockset); the
    # lock-free call site is the violation
    assert [(f.rule, f.line, f.subject) for f in fs] == [
        ("GRAFT-T001", 16, "W.bad:_push")]


def test_t002_rank_inversion_and_reentry():
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ok(self):
                with self._a:
                    with self._b:
                        pass

            def bad(self):
                with self._b:
                    with self._a:
                        pass

            def twice(self):
                with self._a:
                    with self._a:
                        pass
    """, lock_ranks={"_a": 0, "_b": 10})
    assert [(f.rule, f.line, f.subject) for f in fs] == [
        ("GRAFT-T002", 15, "W.bad:_b>_a"),
        ("GRAFT-T002", 20, "W.twice:_a>_a")]


def test_t002_cross_object_callee_rank():
    # `sink.inc(...)` is name-ranked at 30 (the obs surface); calling it
    # while holding an equal-ranked lock inverts the hierarchy
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._m = threading.Lock()

            def bad(self, sink):
                with self._m:
                    sink.inc("x")

            def ok(self, sink):
                sink.inc("x")
    """, lock_ranks={"_m": 30})
    assert [(f.rule, f.line, f.subject) for f in fs] == [
        ("GRAFT-T002", 9, "W.bad:_m>inc()")]


def test_t003_resolution_under_lock():
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self, t):
                with self._lock:
                    t._fail(RuntimeError("x"))

            def bad_cb(self, fn):
                with self._lock:
                    fn(self)

            def ok(self, t):
                t._fail(RuntimeError("x"))
    """)
    assert [(f.rule, f.line, f.subject) for f in fs] == [
        ("GRAFT-T003", 9, "W.bad:_fail"),
        ("GRAFT-T003", 13, "W.bad_cb:fn")]


def test_t004_blocking_wait_under_foreign_lock():
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition()
                self._ev = threading.Event()

            def bad(self):
                with self._lock:
                    self._ev.wait()

            def poll_ok(self, t):
                with self._lock:
                    t.exception(0)

            def cond_ok(self):
                with self._cond:
                    self._cond.wait()
    """)
    # the literal-0 poll and the Condition self-wait (which atomically
    # releases the condition) are the two legal forms
    assert [(f.rule, f.line, f.subject) for f in fs] == [
        ("GRAFT-T004", 11, "W.bad:wait")]


def test_t005_unguarded_lazy_init():
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._reg = None  # guarded-by: _lock

            def bad(self):
                if self._reg is None:
                    self._reg = {}
                return self._reg

            def ok(self):
                if self._reg is None:
                    with self._lock:
                        if self._reg is None:
                            self._reg = {}
                return self._reg
    """)
    # the unguarded write is ALSO a T001 — check-then-set without the lock
    # violates both; the double-checked `ok` form is clean for both
    assert [(f.rule, f.line, f.subject) for f in fs] == [
        ("GRAFT-T005", 9, "W.bad:_reg"),
        ("GRAFT-T001", 10, "W.bad:_reg")]


def test_thread_checks_nested_def_is_callback_context():
    # a nested def runs LATER on an arbitrary thread: writes inside it are
    # checked against an EMPTY lockset even when the def is created under
    # the lock
    fs = _tlint("""\
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: _lock

            def bad(self):
                with self._lock:
                    def later():
                        self._q.append(1)
                    return later
    """)
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-T001", "W.bad.later:_q")]


def test_thread_checks_clean_host_layer():
    """Every threaded host module passes the T-rules as committed — the
    slice of the clean-tree gate this layer owns."""
    assert thread_checks.lint_tree(cli.repo_root()) == []


# ------------------------------------------------------- collective rules


def _sp_mesh():
    from jax.sharding import Mesh

    if jax.device_count() < 2:
        pytest.skip("collective fixtures need >= 2 devices "
                    "(conftest forces 8 host devices)")
    return Mesh(np.array(jax.devices()[:2]), ("s",))


def _smap(fn, mesh):
    from jax.sharding import PartitionSpec as P

    from ddim_cold_tpu.parallel._compat import shard_map

    return shard_map(fn, mesh=mesh, in_specs=P("s"), out_specs=P("s"),
                     check_vma=False)


def test_c001_divergent_cond_inside_manual_region():
    mesh = _sp_mesh()

    def inner(x):
        def refresh(v):
            return jax.lax.psum(v, "s") + jax.lax.psum(v * 2.0, "s")

        def reuse(v):
            return jax.lax.psum(v, "s")

        # the predicate is PER-SHARD (x differs per shard) — shards can
        # take different branches and rendezvous out of order
        return jax.lax.cond(x[0] > 0, refresh, reuse, x)

    closed = jax.make_jaxpr(_smap(inner, mesh))(jnp.zeros((2,), jnp.float32))
    fs = collective_checks.check_jaxpr(closed, "fix")
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-C001", "fix:cond-divergent")]

    def uniform(x):  # identical branch sequences — provably same rendezvous
        return jax.lax.cond(x[0] > 0,
                            lambda v: jax.lax.psum(v, "s"),
                            lambda v: jax.lax.psum(v * 2.0, "s"), x)

    closed = jax.make_jaxpr(_smap(uniform, mesh))(
        jnp.zeros((2,), jnp.float32))
    assert collective_checks.check_jaxpr(closed, "ok") == []


def test_c001_divergent_cond_outside_manual_region_is_exempt():
    """The drift-gate shape: a cond OUTSIDE shard_map whose branches carry
    different collective counts is safe — its scalar predicate is
    replicated, so every device takes the same branch together (the
    in-tree refresh-vs-reuse cond over the sp attention)."""
    mesh = _sp_mesh()

    def sm(times):
        def inner(v):
            for _ in range(times):
                v = jax.lax.psum(v, "s")
            return v
        return _smap(inner, mesh)

    def outer(x):
        return jax.lax.cond(jnp.sum(x) > 0, sm(2), sm(1), x)

    closed = jax.make_jaxpr(outer)(jnp.zeros((2,), jnp.float32))
    assert collective_checks.check_jaxpr(closed, "ok") == []


def test_c001_collective_in_while_inside_manual_region():
    mesh = _sp_mesh()

    def inner(x):
        return jax.lax.while_loop(
            lambda v: jnp.sum(v) < 10.0,
            lambda v: v + jax.lax.psum(v, "s"), x)

    closed = jax.make_jaxpr(_smap(inner, mesh))(jnp.zeros((2,), jnp.float32))
    fs = collective_checks.check_jaxpr(closed, "fix")
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-C001", "fix:while:psum")]


def test_c002_collective_outside_any_mesh():
    closed = jax.make_jaxpr(lambda x: jax.lax.psum(x, "s"),
                            axis_env=[("s", 2)])(
        jnp.zeros((2,), jnp.float32))
    fs = collective_checks.check_jaxpr(closed, "fix")
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-C002", "fix:psum:s:no-mesh")]


class _FakePrim:
    def __init__(self, name):
        self.name = name


class _FakeEqn:
    def __init__(self, name, params):
        self.primitive = _FakePrim(name)
        self.params = params


class _FakeJaxpr:
    def __init__(self, eqns):
        self.eqns = eqns


class _FakeMesh:
    axis_names = ("data",)


def test_c002_axis_absent_from_mesh():
    """jax itself refuses to trace a collective over an unbound axis name,
    so the absent-axis branch is exercised on a duck-typed jaxpr (the
    walker only reads .eqns/.primitive.name/.params — the same shapes a
    version-skewed trace would present)."""
    inner = _FakeJaxpr([_FakeEqn("ppermute", {"axis_name": "seq"})])
    sm = _FakeEqn("shard_map", {"mesh": _FakeMesh(), "auto": frozenset(),
                                "jaxpr": inner})
    fs = collective_checks.check_jaxpr(_FakeJaxpr([sm]), "fix")
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-C002", "fix:ppermute:seq")]


def test_collective_signature_orders_per_axis():
    mesh = _sp_mesh()

    def inner(x):
        g = jax.lax.all_gather(x, "s")
        return jax.lax.psum(x, "s") + jnp.sum(g)

    closed = jax.make_jaxpr(_smap(inner, mesh))(jnp.zeros((2,), jnp.float32))
    sig = collective_checks.collective_signature(closed, "fix")
    assert sig == {"s": ("all_gather", "psum")}
    # a static-trip scan's body is walked once — the per-iteration order
    # stands in for all iterations and stays deadlock-free by repetition

    def scanned(x):
        return jax.lax.scan(
            lambda c, _: (jax.lax.psum(c, "s"), None), x, None, length=3)[0]

    closed = jax.make_jaxpr(_smap(scanned, mesh))(
        jnp.zeros((2,), jnp.float32))
    assert collective_checks.check_jaxpr(closed, "ok") == []
    assert collective_checks.collective_signature(closed, "ok") == {
        "s": ("psum",)}


def test_c001_passes_over_the_sp_serve_sweep():
    """The acceptance gate for the pipeline-parallel precondition: every sp
    sweep entry traces to a non-empty seq-axis collective signature (the
    pass really sees the all_to_alls) and none violates C001/C002. Reuses
    one cached sweep trace — the same path `graftcheck` runs."""
    if jax.device_count() < 2:
        pytest.skip("sp sweep entries need >= 2 devices")
    traces: dict = {}
    entries.serve_signatures(entries.Context(), traces=traces)
    sp_subjects = [s for s in traces
                   if traces[s][0].sp_mode != "none"]
    assert sp_subjects  # the sweep must actually carry sp entries
    for subject in sp_subjects:
        _config, closed = traces[subject]
        assert collective_checks.check_jaxpr(closed, subject) == []
        sig = collective_checks.collective_signature(closed, subject)
        assert "seq" in sig and sig["seq"], (subject, sig)


# ------------------------------------------------------ baseline + CLI


def test_baseline_roundtrip(tmp_path):
    path = str(tmp_path / "base")
    fs = [Finding("GRAFT-A002", "b.py", "g:except Exception", 9),
          Finding("GRAFT-A002", "a.py", "f:except Exception", 3),
          Finding("GRAFT-A002", "a.py", "f:except Exception", 3)]
    assert write_baseline(path, fs) == 2  # sorted, deduped
    keys = load_baseline(path)
    assert keys == {"GRAFT-A002 a.py :: f:except Exception",
                    "GRAFT-A002 b.py :: g:except Exception"}
    assert all(f.key in keys for f in fs)
    assert load_baseline(str(tmp_path / "missing")) == set()


def test_baseline_rejects_malformed(tmp_path):
    path = tmp_path / "base"
    path.write_text("NOT-A-RULE something :: else\n")
    with pytest.raises(ValueError):
        load_baseline(str(path))


def test_cli_fix_baseline_then_clean(tmp_path, monkeypatch):
    # findings → exit 1; --fix-baseline captures them; --baseline → exit 0
    fake = [Finding("GRAFT-A002", "x.py", "f:except Exception", 1, "msg")]
    monkeypatch.setattr(cli, "collect", lambda *a, **k: sorted(fake))
    base = str(tmp_path / "allow")
    assert cli.main(["--only", "ast"]) == 1
    assert cli.main(["--only", "ast", "--fix-baseline", base]) == 0
    assert cli.main(["--only", "ast", "--baseline", base]) == 0


def test_baseline_roundtrip_thread_and_collective_findings(tmp_path):
    path = str(tmp_path / "base")
    fs = [Finding("GRAFT-T001", "ddim_cold_tpu/serve/engine.py",
                  "Engine.drain:_pending", 1033),
          Finding("GRAFT-C001", "ddim_cold_tpu/serve/engine.py",
                  "ddim_k500_ci2_sp2u:b4:cond-divergent", 0)]
    assert write_baseline(path, fs) == 2
    keys = load_baseline(path)
    assert all(f.key in keys for f in fs)
    assert {rule_layer(k.split(" ", 1)[0]) for k in keys} == {
        "threads", "collective"}


def test_cli_fix_baseline_only_refreshes_selected_layers(tmp_path,
                                                         monkeypatch):
    """--fix-baseline --only regenerates JUST the selected layers' rule
    families, carrying the other layers' reviewed lines over verbatim —
    adopting the T/C rules must not churn the A/J/S entries."""
    base = str(tmp_path / "allow")
    ast_f = Finding("GRAFT-A002", "x.py", "f:except Exception", 1)
    t_old = Finding("GRAFT-T001", "y.py", "W.bad:_q", 5)
    t_new = Finding("GRAFT-T003", "y.py", "W.bad:_fail", 9)

    monkeypatch.setattr(cli, "collect", lambda *a, **k: [ast_f, t_old])
    assert cli.main(["--fix-baseline", base]) == 0  # full: both layers
    assert load_baseline(base) == {ast_f.key, t_old.key}

    # the threads layer alone now reports a DIFFERENT finding: a partial
    # refresh swaps the T entry and keeps the ast entry untouched
    monkeypatch.setattr(cli, "collect", lambda *a, **k: [t_new])
    assert cli.main(["--only", "T", "--fix-baseline", base]) == 0
    assert load_baseline(base) == {ast_f.key, t_new.key}

    # a FULL --fix-baseline stays authoritative for everything (no carry)
    monkeypatch.setattr(cli, "collect", lambda *a, **k: [ast_f])
    assert cli.main(["--fix-baseline", base]) == 0
    assert load_baseline(base) == {ast_f.key}


def test_cli_only_accepts_family_letters_and_names():
    assert cli.parse_only(["T,C"]) == ("threads", "collective")
    assert cli.parse_only(["P,M"]) == ("kernels", "memory")
    assert cli.parse_only(["ast", "j"]) == ("ast", "jaxpr")
    assert cli.parse_only(["threads,threads"]) == ("threads",)
    assert cli.parse_only(["R,X"]) == ("protocol", "config")
    with pytest.raises(Exception):
        cli.parse_only(["z"])


def test_rule_table_covers_all_emitted_rules():
    assert set(RULES) == {
        "GRAFT-J001", "GRAFT-J002", "GRAFT-J003", "GRAFT-J004", "GRAFT-J005",
        "GRAFT-J006", "GRAFT-J007", "GRAFT-A001", "GRAFT-A002", "GRAFT-A003",
        "GRAFT-A004", "GRAFT-A005", "GRAFT-S001", "GRAFT-S002",
        "GRAFT-T001", "GRAFT-T002", "GRAFT-T003", "GRAFT-T004", "GRAFT-T005",
        "GRAFT-C001", "GRAFT-C002",
        "GRAFT-P001", "GRAFT-P002", "GRAFT-P003",
        "GRAFT-M001", "GRAFT-M002",
        "GRAFT-R001", "GRAFT-R002", "GRAFT-R003", "GRAFT-R004",
        "GRAFT-R005",
        "GRAFT-X001", "GRAFT-X002", "GRAFT-X003"}
    assert {rule_layer(r) for r in RULES} == set(cli.LAYERS)


# ------------------------------------------------------------- clean tree


def test_clean_tree_ast_and_sharding():
    root = cli.repo_root()
    assert ast_checks.lint_tree(root) == []
    assert sharding_checks.run_sharding_checks() == []


def test_clean_tree_full_collect():
    """The acceptance gate: zero non-baselined findings on the whole repo —
    all nine layers, the same set CI's `graftcheck --baseline` run
    enforces (the collective layer rides the jaxpr layer's sweep traces
    here exactly as it does in the CLI)."""
    fs = cli.collect(cli.repo_root())
    assert [f.render() for f in fs] == []


def test_fleet_layer_is_covered_by_a003_and_a004():
    """The fleet layer stays inside the static net: router.py/fleet.py are
    host-only modules (A004 — routing must never touch a device array) and
    every router fault site is registered (A003 — a typo'd site string
    would silently never fire)."""
    from ddim_cold_tpu.utils import faults

    for mod in ("ddim_cold_tpu/serve/router.py",
                "ddim_cold_tpu/serve/fleet.py",
                "ddim_cold_tpu/serve/batching.py"):
        assert mod in ast_checks.HOST_ONLY_MODULES, mod
    for site in ("router.place", "router.failover", "replica.spawn"):
        assert site in faults.SITES, site


def test_workload_sites_and_sweep_registered():
    """The editing workloads stay inside the static net: the preview
    delivery stage is a registered fault site (A003) and every task — plus
    the preview-enabled scan variants — appears in the J006 serve sweep, so
    the zero-compiles contract is proven for them too."""
    from ddim_cold_tpu.analysis import entries
    from ddim_cold_tpu.utils import faults

    assert "serve.preview" in faults.SITES
    labels = [label for label, _, _ in entries.serve_sweep()]
    for label in ("inpaint_k500", "inpaint_k500_pv2", "inpaint_k500_qxla",
                  "superres_l3", "superres_l3_ci2", "superres_l3_pv1",
                  "draft_k500_t1200", "draft_k500_t1200_ci2",
                  "interp_k500_t400", "ddim_k500_pv2", "ddim_k500_ci2_pv2"):
        assert label in labels, label
