"""X-layer self-tests: the lattice quotient itself, one violating fixture
per rule (delete-a-sweep-entry for X001, an inconsistent build gate and a
frozen-config bypass for X002, an unswept warm set and an illegal bench
site for X003), the R/X partial --fix-baseline churn contract, and the
clean-tree run (the committed sweep fully covers the committed lattice)."""

import textwrap

from ddim_cold_tpu.analysis import config_checks as X
from ddim_cold_tpu.analysis import entries
from ddim_cold_tpu.analysis.findings import (
    RULES, Finding, load_baseline, rule_layer)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


def _sweep_without(*labels):
    return [row for row in entries.serve_sweep() if row[0] not in labels]


# ------------------------------------------------------- lattice quotient


def test_lattice_enumerates_and_classes_quotient():
    lattice = X.enumerate_lattice()
    assert len(lattice) > 50  # a real product space, not a toy list
    classes = [cls for cls, _ in lattice]
    assert len(classes) == len(set(classes))
    # constants are invisible to the quotient: two k values, one class
    a = X.config_class(X.try_config(k=10))
    b = X.config_class(X.try_config(k=500))
    assert a == b
    # student is param routing, not a program class of its own
    assert X.config_class(X.try_config(steps=2)) == \
        X.config_class(X.try_config(steps=2, student=True))
    # but family/cache/seq axes DO split classes
    assert X.config_class(X.try_config(cache_interval=2)) != a
    assert X.config_class(X.try_config(preview_every=2)) != a
    assert X.config_class(X.try_config(task="inpaint"))[0] == "inpaint"
    assert X.config_class(X.try_config(steps=4))[0] == "fewstep"


# ------------------------------------------------------------------ X001


def test_x001_clean_on_committed_sweep():
    assert X.check_sweep_completeness() == []


def test_x001_deleting_the_cold_seq_witness_fires_once():
    # superres_l3_pv1 is the ONLY uncached cold sequence witness: deleting
    # it must produce exactly one finding, for exactly that class
    fs = X.check_sweep_completeness(_sweep_without("superres_l3_pv1"))
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-X001"
    assert f.subject == "class:cold/seq"
    assert f.path == "ddim_cold_tpu/analysis/entries.py"


def test_x001_deleting_the_full_mode_witness_fires_once():
    # the D2 axis: ddim_k500_ci2_full is the only cache_mode="full" entry
    fs = X.check_sweep_completeness(_sweep_without("ddim_k500_ci2_full"))
    assert len(fs) == 1
    assert fs[0].rule == "GRAFT-X001"
    assert fs[0].subject == "cache-mode:full"


def test_x001_deleting_a_redundant_entry_is_silent():
    # ddim_k500_tok2 exists as a J006 distinctness probe (token_k=2 vs 3
    # — structurally distinct gathers), not as lattice coverage: tok3
    # already witnesses the token class, so deleting tok2 fires nothing
    fs = X.check_sweep_completeness(_sweep_without("ddim_k500_tok2"))
    assert fs == []


def test_x001_quant_classification_is_pinned():
    from ddim_cold_tpu.serve.batching import _QUANT_MODES

    assert set(X.COVERED_QUANT) | set(X.EXCLUDED_QUANT) == set(_QUANT_MODES)


# ------------------------------------------------------------------ X002


def test_x002_clean_on_committed_gates():
    assert X.check_validation_consistency() == []


def test_x002_inconsistent_build_gate_fires():
    # a build gate that rejects "full" while construction accepts it:
    # exactly one disagreement in the probe grid
    def spec_fn(interval, mode, threshold, tokens):
        if mode == "full":
            return False
        return X._default_spec_fn(interval, mode, threshold, tokens)

    fs = X.check_validation_consistency(spec_fn=spec_fn)
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-X002"
    assert f.subject == "cache:ci2/full/th=None/tok=0"
    assert "construction accepts what build rejects" in f.message


def test_x002_frozen_config_bypass_lint():
    fs = X.lint_config_source(textwrap.dedent("""\
        def tweak(cfg):
            object.__setattr__(cfg, "quant", "xla")
            object.__setattr__(cfg, "not_a_field", 1)
            object.__setattr__(other, "quant", "xla")
    """), "fix.py")
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-X002"
    assert f.subject == "bypass:quant"
    assert f.line == 2


def test_x002_student_boundary():
    # the distill chain's step counts serve; the stride-student hole stays
    assert X.try_config(steps=1, student=True) is not None
    assert X.try_config(steps=4, student=True) is not None
    assert X.try_config(steps=0, student=True) is None


# ------------------------------------------------------------------ X003


def test_x003_clean_on_committed_warm_sets():
    assert X.check_warmup_soundness() == []


def test_x003_unswept_edit_class_fires_once():
    # drop the one witness of the cold uncached SEQUENCE class: the edit
    # warm set at preview_every=2 warms exactly that program unswept
    fs = X.check_warmup_soundness(sweep=_sweep_without("superres_l3_pv1"))
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-X003"
    assert f.subject == "edit-unswept:superres:pv2"


def test_x003_illegal_bench_site_fires(tmp_path):
    (tmp_path / "bench.py").write_text(textwrap.dedent("""\
        from ddim_cold_tpu.serve.batching import SamplerConfig

        GOOD = SamplerConfig(k=10, cache_interval=2)
        BAD = SamplerConfig(cache_mode="bogus")
        DYN = SamplerConfig(k=some_sweep_variable)
    """))
    fs = X.check_warmup_soundness(root=str(tmp_path))
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-X003"
    assert f.subject == "bench.py:4"
    assert f.line == 4


def test_x003_bench_sites_substitute_sweep_variables():
    sites = X._bench_config_sites(textwrap.dedent("""\
        a = SamplerConfig(k=K, cache_interval=2)
        b = SamplerConfig(steps=n_steps)
        c = SamplerConfig(quant=mode_from_somewhere)
    """))
    # a and b substitute representatives for K/steps; c's dynamic kwarg
    # has no representative, so the site is skipped (not a false alarm)
    assert [line for line, _ in sites] == [1, 2]
    assert sites[0][1] == {"k": 10, "cache_interval": 2}


# ------------------------------------------------- layer wiring + baseline


def test_x_rules_registered_and_layered():
    for rule in ("GRAFT-X001", "GRAFT-X002", "GRAFT-X003"):
        assert rule in RULES
        assert rule_layer(rule) == "config"


def test_clean_tree_config_layer():
    assert X.run_config_checks() == []


def test_cli_only_rx_partial_fix_baseline_churn(tmp_path, monkeypatch):
    """--fix-baseline --only R,X refreshes ONLY the protocol/config rule
    families; reviewed lines from the other seven layers ride along
    verbatim (the adoption path for the two new layers)."""
    from ddim_cold_tpu.analysis import cli

    base = str(tmp_path / "allow")
    ast_f = Finding("GRAFT-A002", "x.py", "f:except Exception", 1)
    r_f = Finding("GRAFT-R003", "ddim_cold_tpu/serve/remote.py",
                  "RemoteReplica.submit", 0)
    x_old = Finding("GRAFT-X001", "ddim_cold_tpu/analysis/entries.py",
                    "cache-mode:full", 0)
    x_new = Finding("GRAFT-X001", "ddim_cold_tpu/analysis/entries.py",
                    "class:cold/seq", 0)

    monkeypatch.setattr(cli, "collect", lambda *a, **k: [ast_f, r_f, x_old])
    assert cli.main(["--fix-baseline", base]) == 0
    assert load_baseline(base) == {ast_f.key, r_f.key, x_old.key}

    # an R,X-only rerun reports different R/X findings: the partial
    # refresh swaps those families and keeps the ast line untouched
    monkeypatch.setattr(cli, "collect", lambda *a, **k: [x_new])
    assert cli.main(["--only", "R,X", "--fix-baseline", base]) == 0
    assert load_baseline(base) == {ast_f.key, x_new.key}


def test_cli_only_x_runs_config_layer(capsys):
    from ddim_cold_tpu.analysis import cli

    assert cli.main(["--only", "X"]) == 0
    assert "[layers: config]" in capsys.readouterr().out
