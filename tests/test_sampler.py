"""Sampler golden tests: scan loops vs a literal NumPy/Python oracle of the
reference update algebra, plus API-shape and range checks."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import sampling

T = 2000
TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2, num_heads=4, total_steps=T)


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x, jnp.array([0, 1], jnp.int32))["params"]
    return model, params


def oracle_ddim_loop(model, params, x_init, k, t_start=None):
    """Literal transcription of reference ViT.py:226-236 (python floats + clamp)."""
    x = np.asarray(x_init, dtype=np.float64)
    n = x.shape[0]
    x0 = None
    for t in range(T - 1 if t_start is None else t_start, 0, -k):
        pred = model.apply({"params": params}, jnp.asarray(x, jnp.float32),
                           jnp.full((n,), t, jnp.int32))
        x0 = np.clip(np.asarray(pred, dtype=np.float64), -1, 1)
        alpha_tk = 1 - math.sqrt((t + 1 - k) / T)
        alpha_t = 1 - math.sqrt((t + 1) / T) + 1e-5
        noise = (x - math.sqrt(alpha_t) * x0) / math.sqrt(1 - alpha_t)
        x = math.sqrt(alpha_tk) * (
            x / math.sqrt(alpha_t)
            + (math.sqrt((1 - alpha_tk) / alpha_tk) - math.sqrt((1 - alpha_t) / alpha_t)) * noise
        )
    return (x0 + 1) / 2


def test_ddim_matches_oracle(model_and_params):
    model, params = model_and_params
    x_init = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    ours = np.asarray(sampling.ddim_sample(model, params, x_init=x_init, k=400))
    want = oracle_ddim_loop(model, params, x_init, k=400)
    np.testing.assert_allclose(ours, want, rtol=1e-4, atol=1e-5)


def test_ddim_sample_shape_range(model_and_params):
    model, params = model_and_params
    for k in (100, 500):
        img = sampling.ddim_sample(model, params, jax.random.PRNGKey(2), k=k, n=3)
        assert img.shape == (3, 16, 16, 3)
        a = np.asarray(img)
        assert np.isfinite(a).all() and a.min() >= 0.0 and a.max() <= 1.0


def test_ddim_sequence_frames(model_and_params):
    model, params = model_and_params
    k = 500  # 4 steps: t = 1999, 1499, 999, 499
    seq = sampling.ddim_sample(model, params, jax.random.PRNGKey(3), k=k, n=2,
                               return_sequence=True)
    assert seq.shape == (5, 2, 16, 16, 3)  # init + one frame per step
    # last frame is the sample itself (same rng → same init)
    img = sampling.ddim_sample(model, params, jax.random.PRNGKey(3), k=k, n=2)
    np.testing.assert_allclose(np.asarray(seq[-1]), np.asarray(img), rtol=1e-5, atol=1e-6)


def test_sample_from_is_prefix_truncation(model_and_params):
    """sample_from(x, t_start, k) ≡ the oracle loop started at t_start."""
    model, params = model_and_params
    x_init = jax.random.normal(jax.random.PRNGKey(4), (1, 16, 16, 3))
    ours = np.asarray(sampling.sample_from(model, params, x_init, t_start=999, k=250))
    want = oracle_ddim_loop(model, params, x_init, k=250, t_start=999)
    np.testing.assert_allclose(ours, want, rtol=1e-4, atol=1e-5)


def test_forward_noise_alpha_semantics():
    """Encoding uses ᾱ = 1 − √(t/T) (no +1) and √ᾱ·x + √(1−ᾱ)·ε."""
    img = jnp.ones((1, 4, 4, 3))
    t_start = 1600
    out = sampling.forward_noise(jax.random.PRNGKey(0), img, t_start, T)
    alpha = 1 - math.sqrt(t_start / T)
    eps = jax.random.normal(jax.random.PRNGKey(0), img.shape, img.dtype)
    want = math.sqrt(alpha) * img + math.sqrt(1 - alpha) * eps
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-6)


def test_cold_sampler_constant_color_init_and_output(model_and_params):
    model, params = model_and_params
    seq = sampling.cold_sample(model, params, jax.random.PRNGKey(5), n=3,
                               return_sequence=True)
    assert seq.shape == (7, 3, 16, 16, 3)  # init + 6 levels
    init = np.asarray(seq[0])
    # init frame is a constant color per sample
    assert np.all(init == init[:, :1, :1, :])
    final = np.asarray(seq[-1])
    assert np.isfinite(final).all() and final.min() >= 0.0 and final.max() <= 1.0
    # non-sequence call agrees
    img = sampling.cold_sample(model, params, jax.random.PRNGKey(5), n=3)
    np.testing.assert_allclose(np.asarray(img), final, rtol=1e-5, atol=1e-6)


def test_cold_sampler_matches_oracle(model_and_params):
    """Oracle: x ← clamp(f(x,t)) for t=6..1 (ViT_draft2drawing.py:271-283)."""
    model, params = model_and_params
    color = jax.random.normal(jax.random.PRNGKey(5), (3, 1, 1, 3))
    x = jnp.broadcast_to(color, (3, 16, 16, 3))
    for t in range(6, 0, -1):
        pred = model.apply({"params": params}, x, jnp.full((3,), t, jnp.int32))
        x = jnp.clip(pred, -1, 1)
    want = (np.asarray(x) + 1) / 2
    got = np.asarray(sampling.cold_sample(model, params, jax.random.PRNGKey(5), n=3))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_ddim_sample_requires_rng_or_init(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="rng or x_init"):
        sampling.ddim_sample(model, params, k=100)


def test_slerp_endpoints_and_midpoint():
    """frac=0/1 return the endpoints; the midpoint of two orthogonal unit
    vectors is the normalized bisector (classic slerp identity)."""
    a = jnp.asarray([[1.0, 0.0]])
    b = jnp.asarray([[0.0, 1.0]])
    np.testing.assert_allclose(np.asarray(sampling.slerp(a, b, 0.0)), np.asarray(a), atol=1e-6)
    np.testing.assert_allclose(np.asarray(sampling.slerp(a, b, 1.0)), np.asarray(b), atol=1e-6)
    mid = np.asarray(sampling.slerp(a, b, 0.5))
    np.testing.assert_allclose(mid, [[math.sqrt(0.5), math.sqrt(0.5)]], rtol=1e-6)


def test_slerp_parallel_fallback():
    """Parallel endpoints degenerate to lerp instead of 0/0."""
    a = jnp.ones((1, 8))
    out = np.asarray(sampling.slerp(a, a * 1.0, 0.3))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, np.ones((1, 8)), rtol=1e-5)


def test_slerp_preserves_norm_on_sphere():
    """Interpolating unit vectors stays on the unit sphere for every frac."""
    rs = np.random.RandomState(0)
    a = rs.randn(4, 32)
    b = rs.randn(4, 32)
    a /= np.linalg.norm(a, axis=-1, keepdims=True)
    b /= np.linalg.norm(b, axis=-1, keepdims=True)
    for frac in (0.25, 0.5, 0.75):
        out = np.asarray(sampling.slerp(jnp.asarray(a), jnp.asarray(b), frac))
        np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, rtol=1e-5)


def test_slerp_interpolate_end_to_end(model_and_params):
    """C25: endpoints of the interpolation equal sample_from of each encoding."""
    model, params = model_and_params
    rng = jax.random.PRNGKey(7)
    img_a = jnp.clip(jax.random.normal(jax.random.PRNGKey(8), (16, 16, 3)), -1, 1)
    img_b = jnp.clip(jax.random.normal(jax.random.PRNGKey(9), (16, 16, 3)), -1, 1)
    frames = sampling.slerp_interpolate(model, params, rng, img_a, img_b,
                                        n_interp=3, t_start=1500, k=500)
    assert frames.shape == (3, 16, 16, 3)
    a = np.asarray(frames)
    assert np.isfinite(a).all() and a.min() >= 0.0 and a.max() <= 1.0
    # frac=0 endpoint ≡ decode of img_a's encoding (same rng key → same eps batch)
    noisy = sampling.forward_noise(rng, jnp.stack([img_a, img_b]), 1500, T)
    want = sampling.sample_from(model, params, noisy[:1], t_start=1500, k=500)
    np.testing.assert_allclose(a[0], np.asarray(want[0]), rtol=1e-4, atol=1e-5)


def test_slerp_interpolate_eta(model_and_params):
    """--eta now reaches the interpolate decode (ADVICE r3): eta>0 output is
    finite, in range, and differs from the deterministic decode."""
    model, params = model_and_params
    rng = jax.random.PRNGKey(7)
    img_a = jnp.clip(jax.random.normal(jax.random.PRNGKey(8), (16, 16, 3)), -1, 1)
    img_b = jnp.clip(jax.random.normal(jax.random.PRNGKey(9), (16, 16, 3)), -1, 1)
    det = sampling.slerp_interpolate(model, params, rng, img_a, img_b,
                                     n_interp=2, t_start=1500, k=500)
    sto = sampling.slerp_interpolate(model, params, rng, img_a, img_b,
                                     n_interp=2, t_start=1500, k=500, eta=1.0)
    s = np.asarray(sto)
    assert np.isfinite(s).all() and s.min() >= 0.0 and s.max() <= 1.0
    assert not np.allclose(s, np.asarray(det))


def test_slerp_unbatched_1d_vectors():
    """The 1-D (unbatched) path interpolates instead of crashing."""
    a = jnp.asarray([1.0, 0.0])
    b = jnp.asarray([0.0, 1.0])
    mid = np.asarray(sampling.slerp(a, b, 0.5))
    np.testing.assert_allclose(mid, [math.sqrt(0.5), math.sqrt(0.5)], rtol=1e-6)


def test_slerp_no_nan_under_debug_nans():
    """Parallel endpoints produce no NaN intermediates (jax_debug_nans-safe)."""
    jax.config.update("jax_debug_nans", True)
    try:
        out = sampling.slerp(jnp.ones((2, 8)), jnp.ones((2, 8)), 0.4)
        assert np.isfinite(np.asarray(out)).all()
    finally:
        jax.config.update("jax_debug_nans", False)


def test_mesh_sharded_sampling_matches_single_device(model_and_params):
    """ddim_sample/cold_sample with a data mesh: the SPMD scan over 8 shards
    must reproduce the single-device result (the reference sampler is
    single-GPU only; sharded sampling is the framework's multi-chip path)."""
    from ddim_cold_tpu.parallel.mesh import make_mesh

    model, params = model_and_params
    mesh = make_mesh({"data": 8})
    rng = jax.random.PRNGKey(7)
    single = np.asarray(sampling.ddim_sample(model, params, rng, k=500, n=8))
    sharded = sampling.ddim_sample(model, params, rng, k=500, n=8, mesh=mesh)
    assert len(sharded.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(sharded), single, rtol=2e-5, atol=2e-6)

    cold_single = np.asarray(sampling.cold_sample(model, params, rng, n=8, levels=4))
    cold_sharded = np.asarray(
        sampling.cold_sample(model, params, rng, n=8, levels=4, mesh=mesh))
    np.testing.assert_allclose(cold_sharded, cold_single, rtol=2e-5, atol=2e-6)


def test_eta_zero_coefficients_bit_identical_and_generalized_close():
    """eta=0 keeps the reference arithmetic untouched (bitwise — the parity
    path must not change); the eta-generalized expression agrees with it
    algebraically (allclose at a tiny eta)."""
    from ddim_cold_tpu.ops import schedule

    base = schedule.ddim_coefficients(2000, 20)
    again = schedule.ddim_coefficients(2000, 20, eta=0.0)
    np.testing.assert_array_equal(base.cx, again.cx)
    np.testing.assert_array_equal(base.cx0, again.cx0)
    assert not base.cz.any()
    gen = schedule.ddim_coefficients(2000, 20, eta=1e-12)
    np.testing.assert_allclose(gen.cx, base.cx, rtol=1e-5)
    np.testing.assert_allclose(gen.cx0, base.cx0, rtol=1e-5, atol=1e-7)


def test_eta_stochastic_sampling(model_and_params):
    """eta>0: finite [0,1] output, reproducible per rng, different from the
    deterministic path, and rng becomes required."""
    import pytest

    from ddim_cold_tpu.ops import sampling

    model, params = model_and_params
    rng = jax.random.PRNGKey(3)
    det = sampling.ddim_sample(model, params, rng, k=500, n=2)
    sto = sampling.ddim_sample(model, params, rng, k=500, n=2, eta=1.0)
    sto2 = sampling.ddim_sample(model, params, rng, k=500, n=2, eta=1.0)
    a = np.asarray(sto)
    assert np.isfinite(a).all() and a.min() >= 0.0 and a.max() <= 1.0
    np.testing.assert_array_equal(a, np.asarray(sto2))  # same key → same draw
    assert np.abs(a - np.asarray(det)).max() > 1e-4  # the noise did something
    with pytest.raises(ValueError, match="pass rng"):
        sampling.ddim_sample(model, params, x_init=np.asarray(det) * 2 - 1,
                             k=500, eta=0.5)


def test_last_only_scans_donate_buffers(model_and_params):
    """The last-only scan entry points donate x_init (and the cached ones the
    step-cache carry too): the lowered programs must carry input→output
    aliasing, or the sampler double-buffers x in HBM (the train step has
    donated since the seed; the samplers promised to in ISSUE 2)."""
    model, params = model_and_params
    x = jnp.zeros((2, 16, 16, 3))
    key = jax.random.PRNGKey(0)
    plain = sampling._ddim_scan_last.lower(
        model, params, x, key, k=500, t_start=None, eta=0.0).as_text()
    assert plain.count("tf.aliasing_output") == 1  # x_init → image
    from ddim_cold_tpu.ops import step_cache
    cache = step_cache.init_cache(2, model.num_patches + 1, model.embed_dim,
                                  model.dtype)
    cached = sampling._ddim_scan_cached.lower(
        model, params, x, key, cache, k=500, t_start=None, eta=0.0,
        cache_interval=2, cache_mode="delta", sequence=False).as_text()
    assert cached.count("tf.aliasing_output") == 3  # x + both cache halves
    cold = sampling._cold_scan.lower(
        model, params, x, levels=4, return_sequence=False).as_text()
    assert cold.count("tf.aliasing_output") == 1
    cold_cached = sampling._cold_scan_cached.lower(
        model, params, x, cache, levels=4, return_sequence=False,
        cache_interval=2, cache_mode="delta").as_text()
    assert cold_cached.count("tf.aliasing_output") == 3
    # the sequence scans must NOT donate — their frames output aliases no
    # input shape, so donation there would only emit jax's unused-donation
    # warning and delete a buffer for nothing
    seq = sampling._ddim_scan_sequence.lower(
        model, params, x, key, k=500, t_start=None, eta=0.0).as_text()
    assert "tf.aliasing_output" not in seq


def test_donation_consumes_direct_scan_input(model_and_params):
    """Calling the donated scan directly consumes its x_init buffer (the CPU
    backend honors donation, so is_deleted is a real check, not a no-op)."""
    model, params = model_and_params
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 16, 3), jnp.float32)
    sampling._ddim_scan_last(model, params, x, jax.random.PRNGKey(0),
                             k=500, t_start=None, eta=0.0)
    assert x.is_deleted()


def test_user_x_init_survives_ddim_sample(model_and_params):
    """The public API must NOT consume a caller's x_init (tests and the
    guided apps reuse their encodings): ddim_sample routes caller arrays
    through a private copy before the donated scan sees them."""
    model, params = model_and_params
    x_init = jax.random.normal(jax.random.PRNGKey(11), (2, 16, 16, 3))
    first = np.asarray(sampling.ddim_sample(model, params, x_init=x_init, k=500))
    assert not x_init.is_deleted()
    again = np.asarray(sampling.ddim_sample(model, params, x_init=x_init, k=500))
    np.testing.assert_array_equal(first, again)


def test_init_cache_halves_are_distinct_buffers():
    """init_cache must return two separate allocations: the cached scans
    donate the carry, and donating one buffer under two arguments is
    invalid (jax would reject or double-free)."""
    from ddim_cold_tpu.ops import step_cache

    a, b = step_cache.init_cache(2, 5, 8, jnp.float32)
    assert a is not b
    assert a.unsafe_buffer_pointer() != b.unsafe_buffer_pointer()
