"""Property-based hardening of the torch-free checkpoint bridge
(utils/torch_pickle.py) — VERDICT r4 item 9.

The 30 example-based tests in test_torch_pickle.py each pin one behavior;
these sweep the input space with seeded generators (hypothesis is not in the
image, so the strategies are hand-rolled and deterministic):

* random object trees round-trip save→load bit-exactly (structure, dtypes,
  shapes, scalar identity);
* the same random trees cross-check against REAL torch in both directions
  (torch is a test-only oracle, SURVEY.md §4);
* random single-byte corruptions and truncations of a valid archive must
  raise a clean, bounded error — never hang, crash the interpreter, allocate
  unbounded memory, or execute code (the strict find_class / materialization
  caps under fuzz, not just on the hand-written bombs).
"""

import io
import os
import pickle
import zipfile

import numpy as np
import pytest

from ddim_cold_tpu.utils import torch_pickle as tp

# dtypes the bridge supports (torch storage classes exist for each)
_DTYPES = [np.float32, np.float64, np.float16, np.int64, np.int32,
           np.int16, np.int8, np.uint8, np.bool_]


def _rand_array(r: np.random.RandomState):
    dt = _DTYPES[r.randint(len(_DTYPES))]
    ndim = r.randint(0, 4)
    shape = tuple(int(r.randint(0, 5)) for _ in range(ndim))  # 0-size legal
    if np.issubdtype(dt, np.floating):
        a = np.asarray(r.randn(*shape)).astype(dt)
    elif dt is np.bool_:
        a = np.asarray(r.rand(*shape) > 0.5)
    else:
        a = np.asarray(r.randint(
            -4 if np.issubdtype(dt, np.signedinteger) else 0,
            100, size=shape)).astype(dt)
    if a.ndim >= 2 and r.rand() < 0.3:
        a = np.asfortranarray(a)  # writer must re-contiguate
    return a


def _rand_scalar(r: np.random.RandomState):
    return [None, True, False, 0, -17, 3.5, float("inf"), "", "käse",
            b"\x00raw", 2**40][r.randint(11)]


def _rand_tree(r: np.random.RandomState, depth: int = 0):
    roll = r.rand()
    if depth >= 3 or roll < 0.35:
        return _rand_array(r) if r.rand() < 0.6 else _rand_scalar(r)
    n = r.randint(0, 4)
    if roll < 0.7:
        # keys: str / int / bool — all writer-validated key types
        keys = []
        for _ in range(n):
            k = [f"k{r.randint(100)}", int(r.randint(50)) + 1000,
                 ][r.randint(2)]
            keys.append(k)
        return {k: _rand_tree(r, depth + 1) for k in keys}
    if roll < 0.85:
        return [_rand_tree(r, depth + 1) for _ in range(n)]
    return tuple(_rand_tree(r, depth + 1) for _ in range(n))


def _assert_equal_tree(got, want, where="$"):
    if isinstance(want, np.ndarray):
        assert isinstance(got, np.ndarray), (where, type(got))
        assert got.dtype == want.dtype, (where, got.dtype, want.dtype)
        assert got.shape == want.shape, (where, got.shape, want.shape)
        np.testing.assert_array_equal(got, want, err_msg=where)
    elif isinstance(want, dict):
        assert isinstance(got, dict), (where, type(got))
        assert set(got) == set(want), (where, set(got), set(want))
        for k in want:
            _assert_equal_tree(got[k], want[k], f"{where}.{k!r}")
    elif isinstance(want, (list, tuple)):
        # the unpickler preserves list/tuple kinds
        assert type(got) is type(want), (where, type(got), type(want))
        assert len(got) == len(want), where
        for i, (g, w) in enumerate(zip(got, want)):
            _assert_equal_tree(g, w, f"{where}[{i}]")
    else:
        assert type(got) is type(want) and got == want or (
            isinstance(want, float) and isinstance(got, float)
            and got == want), (where, got, want)


def test_random_trees_roundtrip(tmp_path):
    """40 seeded random trees: save→load is the identity (arrays bit-exact,
    dtypes/shapes/container kinds preserved, scalars by value+type)."""
    for seed in range(40):
        r = np.random.RandomState(1000 + seed)
        tree = _rand_tree(r)
        path = str(tmp_path / f"t{seed}.pkl")
        tp.save(tree, path)
        _assert_equal_tree(tp.load(path), tree, where=f"seed{seed}:$")


def test_random_trees_cross_torch_oracle(tmp_path):
    """Both directions against the real torch serializer on a sample of the
    same generator's trees: torch reads ours, we read torch's."""
    torch = pytest.importorskip("torch")

    def to_torch(x):
        if isinstance(x, np.ndarray):
            # torch.from_numpy needs contiguous; ascontiguousarray is
            # at-least-1d, so restore 0-dim explicitly
            return torch.from_numpy(
                np.ascontiguousarray(x).copy().reshape(x.shape))
        if isinstance(x, dict):
            return {k: to_torch(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(to_torch(v) for v in x)
        return x

    def from_torch(x):
        if isinstance(x, torch.Tensor):
            return x.numpy()
        if isinstance(x, dict):
            return {k: from_torch(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(from_torch(v) for v in x)
        return x

    for seed in range(12):
        r = np.random.RandomState(2000 + seed)
        tree = _rand_tree(r)
        ours = str(tmp_path / f"ours{seed}.pkl")
        theirs = str(tmp_path / f"theirs{seed}.pkl")
        tp.save(tree, ours)
        got = from_torch(torch.load(ours, map_location="cpu",
                                    weights_only=False))
        _assert_equal_tree(got, tree, where=f"torch-reads-ours seed{seed}:$")
        torch.save(to_torch(tree), theirs)
        _assert_equal_tree(tp.load(theirs), tree,
                           where=f"we-read-torch seed{seed}:$")


#: every failure class the reader is allowed to surface on corrupt input —
#: anything outside this set (segfault, MemoryError from an unbounded
#: allocation, a hang, SystemExit) is a hardening bug
_CLEAN_ERRORS = (ValueError, KeyError, EOFError, OSError,
                 pickle.UnpicklingError, zipfile.BadZipFile,
                 IndexError, TypeError, AttributeError,
                 NotImplementedError, UnicodeDecodeError,
                 ModuleNotFoundError,
                 # zipfile raises bare RuntimeError when a flipped header
                 # bit claims the member is encrypted — bounded and loud
                 RuntimeError)


def _reference_archive(tmp_path) -> bytes:
    tree = {
        "params": {"w": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
                   "b": np.ones((7,), np.float16)},
        "steps": 123,
        "nested": [np.zeros((0, 2), np.int8), ("x", 2.5)],
    }
    path = str(tmp_path / "ref.pkl")
    tp.save(tree, path)
    with open(path, "rb") as f:
        return f.read()


def test_fuzz_bitflips_raise_cleanly(tmp_path):
    """300 seeded single-byte mutations of a valid archive: load() either
    succeeds (the flip hit dead bytes / tensor payload) or raises one of the
    bounded error classes. The mutated-payload success case must still obey
    the original shapes/dtypes — a flip can change VALUES, never widen an
    allocation past the header's claim."""
    blob = _reference_archive(tmp_path)
    r = np.random.RandomState(7)
    path = str(tmp_path / "fuzz.pkl")
    for i in range(300):
        mutated = bytearray(blob)
        pos = int(r.randint(len(blob)))
        mutated[pos] = (mutated[pos] + 1 + r.randint(255)) % 256
        with open(path, "wb") as f:
            f.write(bytes(mutated))
        try:
            got = tp.load(path)
        except _CLEAN_ERRORS:
            continue
        # survived: whatever parsed must be bounded by the original header
        leaves = []

        def walk(x):
            if isinstance(x, np.ndarray):
                leaves.append(x)
            elif isinstance(x, dict):
                for v in x.values():
                    walk(v)
            elif isinstance(x, (list, tuple)):
                for v in x:
                    walk(v)

        walk(got)
        assert sum(a.nbytes for a in leaves) <= 2 * len(blob), (
            f"mutation {i}@{pos} inflated allocations")


def test_fuzz_truncations_raise_cleanly(tmp_path):
    """Every truncation point on a coarse grid + the last 64 byte-boundaries:
    a cut-off download/copy must fail with a bounded error, never hang or
    misparse into silently-short tensors of the wrong shape."""
    blob = _reference_archive(tmp_path)
    path = str(tmp_path / "trunc.pkl")
    cuts = sorted(set(range(0, len(blob), 97))
                  | set(range(max(0, len(blob) - 64), len(blob))))
    for cut in cuts:
        with open(path, "wb") as f:
            f.write(blob[:cut])
        with pytest.raises(_CLEAN_ERRORS):
            got = tp.load(path)
            # zipfile tolerates some tail truncation (central directory
            # still intact): then the payload contract must hold exactly
            w = got["params"]["w"]
            assert w.shape == (2, 3, 4) and w.dtype == np.float32
            raise OSError("acceptable: archive readable up to cut")


def test_fuzz_garbage_headers_raise_cleanly(tmp_path):
    """Pure-garbage files (random bytes, wrong magic, empty, a zip with no
    data.pkl) fail loud with the documented errors."""
    path = str(tmp_path / "g.pkl")
    r = np.random.RandomState(11)
    for size in (0, 1, 4, 100, 4096):
        with open(path, "wb") as f:
            f.write(bytes(r.randint(0, 256, size=size, dtype=np.uint8)))
        with pytest.raises(_CLEAN_ERRORS):
            tp.load(path)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("unrelated.txt", "hi")
    with pytest.raises(ValueError, match="not a torch zip checkpoint"):
        tp.load(path)


def test_fuzz_adversarial_pickle_opcodes(tmp_path):
    """Hand-built archives whose data.pkl smuggles arbitrary globals
    (os.system, builtins.eval, numpy load-path gadgets) are refused by the
    strict find_class for EVERY payload position — seeded variants embed the
    gadget at different graph depths."""
    gadgets = [
        (b"cos\nsystem\n(S'true'\ntR.", "os.system call"),
        (b"cbuiltins\neval\n(S'1'\ntR.", "eval call"),
        (b"cbuiltins\ngetattr\n.", "getattr global"),
        (pickle.dumps({"k": pickle.PickleBuffer}, protocol=2)
         if hasattr(pickle, "PickleBuffer") else b"cpickle\nloads\n.",
         "stdlib global in dict"),
    ]
    for i, (payload, label) in enumerate(gadgets):
        path = str(tmp_path / f"adv{i}.pkl")
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("archive/data.pkl", payload)
            zf.writestr("archive/version", "3")
        with pytest.raises(_CLEAN_ERRORS):
            tp.load(path)


def test_fuzz_never_imports_new_modules(tmp_path):
    """The strict find_class must not even IMPORT a module outside the
    torch/collections allowlist — import side effects are code execution.
    An archive referencing a sentinel module is refused without the module
    landing in sys.modules."""
    import sys

    sentinel = "antigravity"  # stdlib, import has side effects, never loaded
    assert sentinel not in sys.modules
    payload = f"c{sentinel}\nfly\n.".encode()
    path = str(tmp_path / "imp.pkl")
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("archive/data.pkl", payload)
        zf.writestr("archive/version", "3")
    with pytest.raises(_CLEAN_ERRORS):
        tp.load(path)
    assert sentinel not in sys.modules, (
        "find_class imported an arbitrary module — import-time side "
        "effects are an execution primitive")
