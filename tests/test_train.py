"""Training-layer tests: config derivation rules, end-to-end CPU training,
checkpoint/resume, converter round-trips (SURVEY.md §4 integration plan)."""

import os

import numpy as np
import pytest
import yaml

from ddim_cold_tpu.config import ExperimentConfig, load_config


def _write_config(tmp_path, data_dir, **overrides):
    cfg = {
        "initializing": "none",
        "resume": "none",
        "AMP": False,
        "framework": "vit_test",
        "num_gpus": 1,
        "batch_size": 2,
        "epoch": [0, 2],
        "base_lr": 0.005,
        "dataStorage": [data_dir, data_dir],
        "image_size": [64, 64],
        "diff_step": 6,
        "patch_size": 8,
        "embed_dim": 32,
        "depth": 1,
        "head": 2,
    }
    cfg.update(overrides)
    path = os.path.join(tmp_path, "exp.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    return path


def test_config_derivation_rules(tmp_path, synthetic_image_dir):
    """AMP doubles batch; lr = base·batch·devices/512 (multi_gpu_trainer.py:191-196)."""
    path = _write_config(str(tmp_path), synthetic_image_dir, AMP=True,
                         batch_size=16, num_gpus=4, base_lr=0.005)
    cfg = load_config(path, "exp")
    assert cfg.effective_batch == 32
    assert cfg.lr == pytest.approx(0.005 * 32 * 4 / 512)
    assert cfg.run_name == "expvit_test"
    # diff_step read but table stays 2000 by default (quirk #4)
    assert cfg.diff_step == 6 and cfg.total_steps == 2000
    cfg2 = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                     honor_diff_step=True), "exp")
    assert cfg2.total_steps == 6


def test_config_rejects_unknown_keys(tmp_path, synthetic_image_dir):
    """A typo'd key must fail loud with a did-you-mean hint — the .get()-
    based loader would otherwise silently ignore it and the run would be
    silently misconfigured (e.g. `use_flahs: true` training dense)."""
    path = _write_config(str(tmp_path), synthetic_image_dir, use_flahs=True)
    with pytest.raises(ValueError, match="use_flahs.*did you mean 'use_flash'"):
        load_config(path, "exp")
    path = _write_config(str(tmp_path), synthetic_image_dir,
                         totally_novel_knob=1)
    with pytest.raises(ValueError, match="totally_novel_knob"):
        load_config(path, "exp")


def test_config_flash_blocks_plumbed(tmp_path, synthetic_image_dir):
    """`flash_blocks: [bq, bkv]` reaches the model (the --flash-block-sweep
    winner is pinnable in the YAML); malformed values fail loud."""
    from ddim_cold_tpu.train.trainer import build_model

    path = _write_config(str(tmp_path), synthetic_image_dir,
                         use_flash=True, flash_blocks=[512, 1024])
    cfg = load_config(path, "exp")
    assert cfg.flash_blocks == (512, 1024)
    assert build_model(cfg).flash_blocks == (512, 1024)
    bad = _write_config(str(tmp_path), synthetic_image_dir,
                        use_flash=True, flash_blocks=[512])
    with pytest.raises(ValueError, match="flash_blocks"):
        load_config(bad, "exp")
    # blocks without use_flash would silently attend dense — fail loud
    noflash = _write_config(str(tmp_path), synthetic_image_dir,
                            flash_blocks=[512, 1024])
    with pytest.raises(ValueError, match="use_flash is false"):
        load_config(noflash, "exp")


@pytest.fixture(scope="module")
def trained_run(tmp_path_factory, synthetic_image_dir):
    """Train 2 epochs on the 10-image folder (shared by several tests)."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path_factory.mktemp("run"))
    cfg = load_config(_write_config(base, synthetic_image_dir,
                                    snapshot_epochs=1), "exp")
    result = run(cfg, base, log_every=2)
    return base, cfg, result


@pytest.mark.isolated
def test_train_end_to_end(trained_run):
    base, cfg, result = trained_run
    assert result.steps == 2 * (10 // 2)  # 2 epochs × 5 batches
    assert np.isfinite(result.last_val_loss)
    assert result.best_loss < 5.0  # improved from the init sentinel
    run_dir = result.run_dir
    assert os.path.isdir(os.path.join(run_dir, "bestloss.ckpt"))
    assert os.path.isdir(os.path.join(run_dir, "lastepoch.ckpt"))
    assert os.path.isfile(os.path.join(run_dir, "bestloss.pkl"))  # legacy bridge
    log = open(os.path.join(run_dir, "train.log")).read()
    assert "TrainSet batchs:5" in log
    assert "steps:" in log and "time_cost:" in log  # reference line format
    assert "epoch:    0" in log and "epoch:    1" in log
    assert os.path.isfile(os.path.join(run_dir, "metrics.jsonl"))


@pytest.mark.isolated
def test_snapshot_epochs_writes_trend_checkpoints(trained_run):
    """snapshot_epochs=N saves bare params to snapshots/epoch_<E> — the
    per-checkpoint FID-trend source (scripts/fid_trend.py collect_points)."""
    import jax

    from ddim_cold_tpu.utils import checkpoint as ckpt

    _, cfg, result = trained_run
    snap = os.path.join(result.run_dir, "snapshots")
    assert sorted(os.listdir(snap)) == ["epoch_0", "epoch_1"]
    raw = ckpt.restore_checkpoint(os.path.join(snap, "epoch_0"))
    best = ckpt.restore_checkpoint(os.path.join(result.run_dir, "bestloss.ckpt"))
    assert jax.tree.structure(raw) == jax.tree.structure(best)  # bare params


@pytest.mark.isolated
def test_resume_continues(trained_run, synthetic_image_dir):
    from ddim_cold_tpu.train.trainer import run

    base, cfg, result = trained_run
    resume_cfg = load_config(
        _write_config(base, synthetic_image_dir, epoch=[0, 3],
                      resume=os.path.join(result.run_dir, "lastepoch.ckpt")),
        "exp")
    r2 = run(resume_cfg, base, log_every=2)
    # resumed at epoch 2 → one more epoch of 5 steps on top of the restored 10
    assert r2.steps == 15
    log = open(os.path.join(r2.run_dir, "train.log")).read()
    assert "resuming from epoch" in log
    assert "recovering best_loss" in log
    assert "epoch:    2" in log


def test_save_checkpoint_preserves_previous_on_failed_write(tmp_path, monkeypatch):
    """A crashed/failed re-save must leave the previous checkpoint intact —
    the old force=True-onto-destination path deleted it before writing."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    p = str(tmp_path / "last.ckpt")
    ckpt.save_checkpoint(p, {"a": np.arange(3)})

    import orbax.checkpoint as ocp

    monkeypatch.setattr(
        ocp.PyTreeCheckpointer, "save",
        lambda self, *a, **k: (_ for _ in ()).throw(RuntimeError("disk full")))
    with pytest.raises(RuntimeError, match="disk full"):
        ckpt.save_checkpoint(p, {"a": np.arange(4)})
    monkeypatch.undo()

    got = ckpt.restore_checkpoint(p, {"a": np.zeros(3, np.int64)})
    np.testing.assert_array_equal(np.asarray(got["a"]), np.arange(3))


def test_checkpoint_swap_crash_recovers_from_old(tmp_path):
    """Crash between the two swap renames leaves only <path>.old — the owner
    (recover_swap, called by the trainer's resume path and by save itself)
    must move it back, never delete it as a leftover. restore stays
    read-only (a concurrent reader must not race a writer's swap)."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    p = str(tmp_path / "last.ckpt")
    ckpt.save_checkpoint(p, {"a": np.arange(3)})
    os.rename(p, p + ".old")  # simulate the crash window

    ckpt.recover_swap(p)
    got = ckpt.restore_checkpoint(p, {"a": np.zeros(3, np.int64)})
    np.testing.assert_array_equal(np.asarray(got["a"]), np.arange(3))

    os.rename(p, p + ".old")
    ckpt.save_checkpoint(p, {"a": np.arange(4)})  # save-side heal + overwrite
    got = ckpt.restore_checkpoint(p, {"a": np.zeros(4, np.int64)})
    np.testing.assert_array_equal(np.asarray(got["a"]), np.arange(4))


def _sigterm_when(log_path, needle, timeout_s=120):
    """Background thread: SIGTERM this process once `needle` appears in the
    train log. The needle must be a line the trainer only writes AFTER the
    graceful handler is installed ("steps:"/"epoch:"; "TrainSet" is logged
    before it — a signal there would kill the interpreter)."""
    import os as _os
    import signal
    import threading
    import time

    def watch():
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            try:
                if needle in open(log_path).read():
                    _os.kill(_os.getpid(), signal.SIGTERM)
                    return
            except OSError:
                pass
            time.sleep(0.25)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    return t


@pytest.mark.isolated
def test_sigterm_checkpoints_and_exits(tmp_path, synthetic_image_dir):
    """SIGTERM mid-training → the loop finishes the step, evaluates, saves
    both checkpoints, and run() returns normally (a hard kill would lose the
    epoch AND can wedge a remote TPU's session claim)."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path)
    cfg = load_config(_write_config(base, synthetic_image_dir, epoch=[0, 200]),
                      "exp")
    log_path = os.path.join(base, "Saved_Models", cfg.run_name, "train.log")
    t = _sigterm_when(log_path, "steps:")
    result = run(cfg, base, log_every=1)  # returns instead of dying
    t.join()
    assert result.steps < 200 * 5  # stopped early
    assert np.isfinite(result.last_val_loss)
    log = open(log_path).read()
    assert "stop signal at step" in log
    assert os.path.isdir(os.path.join(result.run_dir, "lastepoch.ckpt"))


@pytest.mark.isolated
def test_sigterm_with_short_epochs_stops_at_epoch_end(tmp_path,
                                                      synthetic_image_dir):
    """A stop signal must take effect at the next EPOCH boundary even when
    epochs are shorter than log_every — observed on a 16-step/epoch run with
    log_every=100, where the in-epoch check (steps % log_every) never fired
    and the signal was ignored for ~6 epochs."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path)
    cfg = load_config(_write_config(base, synthetic_image_dir, epoch=[0, 50]),
                      "exp")
    log_path = os.path.join(base, "Saved_Models", cfg.run_name, "train.log")
    # signal lands during epoch 1 (after epoch 0's eval line, handler live);
    # log_every=1000 >> the 5 steps/epoch: only the epoch-end check can stop
    t = _sigterm_when(log_path, "epoch:")
    result = run(cfg, base, log_every=1000)
    t.join()
    # delivery-lag-immune invariant (the signal thread can lag epochs when
    # the single core hiccups, so a raw step bound flakes): once the trainer
    # LOGS the stop, it must train zero further epochs — the stop-line epoch
    # is the run's last. The regression this guards ran all 50 epochs.
    import re as _re

    log_text = open(log_path).read()
    stop = _re.search(r"stop signal at epoch\s+(\d+) end", log_text)
    assert stop, "no epoch-end stop line"
    last_epoch = int(_re.findall(r"epoch:\s*(\d+)\s+loss", log_text)[-1])
    assert last_epoch == int(stop.group(1)), "trained past the stop epoch"
    assert result.steps < 50 * 5, "stop signal ignored entirely"
    assert os.path.isdir(os.path.join(result.run_dir, "lastepoch.ckpt"))


def test_loss_decreases_over_training(synthetic_image_dir):
    """Overfit one fixed batch through the real train_step: loss must drop."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.data import ColdDownSampleDataset, ShardedLoader
    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.ops.losses import smooth_l1
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    ds = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64])
    batch = next(iter(ShardedLoader(ds, 5, shuffle=False, drop_last=False,
                                    num_threads=1)))
    batch = tuple(jnp.asarray(b) for b in batch)
    model = DiffusionViT(img_size=(64, 64), patch_size=8, embed_dim=32, depth=1,
                         num_heads=2)
    state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                               total_steps=200, sample_batch=batch)

    def eval_loss(params):
        pred = model.apply({"params": params}, batch[0], batch[2])
        return float(smooth_l1(pred, batch[1]))

    before = eval_loss(state.params)
    train_step = make_train_step(model)
    rng = jax.random.PRNGKey(1)
    loss_rec = jnp.float32(5.0)
    for _ in range(100):
        state, _, loss_rec = train_step(state, batch, rng, loss_rec)
    after = eval_loss(state.params)
    assert after < before * 0.7, (before, after)


def test_steps_per_dispatch_matches_sequential():
    """spd=4 over a stacked batch ≡ 4 sequential single-step calls passing
    the same rng: the scan body folds per-step keys off state.step, which
    advances inside the scan, so the math is step-identical."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32,
                         depth=1, num_heads=2)
    r = np.random.RandomState(0)
    batches = [
        (jnp.asarray(r.randn(2, 16, 16, 3), jnp.float32),
         jnp.asarray(r.randn(2, 16, 16, 3), jnp.float32),
         jnp.asarray(r.randint(1, 7, size=(2,)), jnp.int32))
        for _ in range(4)
    ]
    mk_state = lambda: create_train_state(  # noqa: E731
        model, jax.random.PRNGKey(0), lr=1e-3, total_steps=100,
        sample_batch=batches[0])
    rng = jax.random.PRNGKey(1)

    seq_state, seq_rec = mk_state(), jnp.float32(5.0)
    one_step = make_train_step(model)
    seq_losses = []
    for b in batches:
        seq_state, loss, seq_rec = one_step(seq_state, b, rng, seq_rec)
        seq_losses.append(float(loss))

    multi_state, multi_rec = mk_state(), jnp.float32(5.0)
    multi_step = make_train_step(model, steps_per_dispatch=4)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
    multi_state, mean_loss, multi_rec = multi_step(
        multi_state, stacked, rng, multi_rec)

    assert float(mean_loss) == pytest.approx(np.mean(seq_losses), rel=1e-5)
    assert float(multi_rec) == pytest.approx(float(seq_rec), rel=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        multi_state.params, seq_state.params)
    assert int(multi_state.step) == int(seq_state.step) == 4


@pytest.mark.isolated
def test_steps_per_dispatch_trainer_run(tmp_path, synthetic_image_dir):
    """The trainer wires config.steps_per_dispatch end to end: grouped
    loader, grouped sharding, boundary-crossing step logs, finite losses."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path)
    cfg = load_config(_write_config(base, synthetic_image_dir, epoch=[0, 1],
                                    steps_per_dispatch=2), "exp")
    assert cfg.steps_per_dispatch == 2
    result = run(cfg, base, log_every=2)
    assert np.isfinite(result.best_loss)
    log = os.path.join(base, "Saved_Models", cfg.run_name, "train.log")
    text = open(log).read()
    # 10-image folder @ batch 2 → 5 batches → 2 dispatches (tail dropped)
    # → 4 steps; log_every=2 boundaries at steps 2 and 4
    assert "steps:        2 " in text and "steps:        4 " in text


def test_steps_per_dispatch_composes_with_grad_accum_and_ema():
    """spd=2 × grad_accum=2 × ema_decay: the scanned dispatch must equal two
    sequential accumulated steps, EMA shadow included (nested lax.scans plus
    the optimizer-tail EMA update all advance correctly inside the outer
    scan)."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32,
                         depth=1, num_heads=2)
    r = np.random.RandomState(1)
    batches = [
        (jnp.asarray(r.randn(4, 16, 16, 3), jnp.float32),
         jnp.asarray(r.randn(4, 16, 16, 3), jnp.float32),
         jnp.asarray(r.randint(1, 7, size=(4,)), jnp.int32))
        for _ in range(2)
    ]
    mk = lambda: create_train_state(  # noqa: E731
        model, jax.random.PRNGKey(0), lr=1e-3, total_steps=100,
        sample_batch=batches[0], ema_decay=0.9)
    rng = jax.random.PRNGKey(2)

    seq_state = mk()
    one = make_train_step(model, grad_accum=2, ema_decay=0.9)
    rec = jnp.float32(5.0)
    for b in batches:
        seq_state, _, rec = one(seq_state, b, rng, rec)

    multi_state = mk()
    multi = make_train_step(model, grad_accum=2, ema_decay=0.9,
                            steps_per_dispatch=2)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
    multi_state, _, mrec = multi(multi_state, stacked, rng, jnp.float32(5.0))

    assert float(mrec) == pytest.approx(float(rec), rel=1e-5)
    for tree_a, tree_b in ((multi_state.params, seq_state.params),
                           (multi_state.ema_params, seq_state.ema_params)):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
            tree_a, tree_b)
    assert int(multi_state.step) == int(seq_state.step) == 2


def test_steps_per_dispatch_validation(tmp_path, synthetic_image_dir):
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                  steps_per_dispatch=0), "exp")
    from ddim_cold_tpu.train.step import make_train_step

    from ddim_cold_tpu.models import DiffusionViT

    with pytest.raises(ValueError, match="steps_per_dispatch"):
        make_train_step(DiffusionViT(img_size=(16, 16), patch_size=8,
                                     embed_dim=32, depth=1, num_heads=2),
                        steps_per_dispatch=0)


def test_checkpoint_converter_roundtrip():
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
                         num_heads=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)),
                        jnp.zeros((1,), jnp.int32))["params"]
    sd = ckpt.torch_state_dict_from_flax(params, patch_size=8)
    # torch-side key surface matches the reference state_dict naming
    assert "blocks.0.attn.qkv.weight" in sd
    assert "patch_embed.proj.weight" in sd and sd["patch_embed.proj.weight"].shape == (32, 3, 8, 8)
    back = ckpt.flax_from_torch_state_dict(sd, patch_size=8)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
                 params, back)


def test_checkpoint_converter_sincos_roundtrip():
    """use_sincos_pos models have no pos_embed param; the converter must
    tolerate its absence in both directions (regression: KeyError on export)."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32, depth=1,
                         num_heads=2, use_sincos_pos=True)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)),
                        jnp.zeros((1,), jnp.int32))["params"]
    assert "pos_embed" not in params
    sd = ckpt.torch_state_dict_from_flax(params, patch_size=8)
    assert "pos_embed" not in sd
    back = ckpt.flax_from_torch_state_dict(sd, patch_size=8)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
                 params, back)


def test_torch_pkl_file_roundtrip(tmp_path):
    torch = pytest.importorskip("torch")
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32, depth=1,
                         num_heads=2)
    x = jnp.asarray(np.random.RandomState(0).randn(1, 16, 16, 3), jnp.float32)
    t = jnp.array([5], jnp.int32)
    params = model.init(jax.random.PRNGKey(1), x, t)["params"]
    pkl = str(tmp_path / "w.pkl")
    ckpt.save_torch_pkl(params, pkl, patch_size=8)
    # a torch user can load it...
    sd = torch.load(pkl, weights_only=False)
    assert all(hasattr(v, "numpy") for v in sd.values())
    # ...and we can load it back with identical model behavior
    params2 = ckpt.load_torch_pkl(pkl, patch_size=8)
    out1 = model.apply({"params": params}, x, t)
    out2 = model.apply({"params": params2}, x, t)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_smooth_l1_matches_torch():
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    from ddim_cold_tpu.ops.losses import smooth_l1

    rng = np.random.RandomState(0)
    a = rng.randn(4, 8, 8, 3).astype(np.float32) * 2
    b = rng.randn(4, 8, 8, 3).astype(np.float32)
    want = torch.nn.functional.smooth_l1_loss(torch.from_numpy(a), torch.from_numpy(b)).item()
    got = float(smooth_l1(jnp.asarray(a), jnp.asarray(b)))
    assert got == pytest.approx(want, rel=1e-6)


@pytest.mark.isolated
def test_profile_steps_writes_trace(tmp_path, synthetic_image_dir):
    """profile_steps traces the first N steps into <run_dir>/trace and the
    run completes normally (reference had only wall-clock prints)."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="prof", framework="trace", batch_size=2, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=1, head=2,
        profile_steps=2,
    )
    result = run(cfg, str(tmp_path), max_steps=3)
    assert np.isfinite(result.best_loss)
    trace_dir = os.path.join(result.run_dir, "trace")
    assert os.path.isdir(trace_dir)
    assert any(f for _, _, fs in os.walk(trace_dir) for f in fs), "empty trace"


@pytest.mark.isolated
def test_steps_per_dispatch_rejects_indivisible_max_steps(tmp_path,
                                                          synthetic_image_dir):
    """max_steps not a multiple of steps_per_dispatch fails loud (ADVICE r4):
    the loop advances in whole spd-dispatches, so a non-divisible bound would
    silently run up to spd-1 optimizer steps past max_steps — and the cosine
    schedule/checkpoint counters would include them."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="spd_guard", framework="t", batch_size=2, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=1, head=2,
        steps_per_dispatch=2,
    )
    with pytest.raises(ValueError, match="not reachable in whole dispatches"):
        run(cfg, str(tmp_path), max_steps=3)
    # divisible bound: exact — the run stops at precisely max_steps
    result = run(cfg, str(tmp_path), max_steps=4)
    assert np.isfinite(result.best_loss)
    assert result.steps == 4


def test_ema_step_math():
    """ema_decay>0: the shadow follows ema ← d·ema + (1−d)·p exactly, seeded
    from the init params; off (0): ema_params stays None and the step is the
    plain parity path."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=16,
                         depth=1, num_heads=2, total_steps=8)
    rng = np.random.RandomState(0)
    batch = (jnp.asarray(rng.randn(2, 16, 16, 3), jnp.float32),
             jnp.asarray(rng.randn(2, 16, 16, 3), jnp.float32),
             jnp.asarray([1, 2], jnp.int32))
    d = 0.5
    state = create_train_state(model, jax.random.PRNGKey(0), 1e-2, 10, batch,
                               ema_decay=d)
    p0 = jax.tree.map(np.asarray, state.params)
    step = make_train_step(model, ema_decay=d)
    state, _, _ = step(state, batch, jax.random.PRNGKey(1), jnp.float32(5.0))
    p1 = jax.tree.map(np.asarray, state.params)
    want = jax.tree.map(lambda e, p: d * e + (1 - d) * p, p0, p1)
    got = jax.tree.map(np.asarray, state.ema_params)
    for w, g in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(w, g, rtol=1e-6)

    off = create_train_state(model, jax.random.PRNGKey(0), 1e-2, 10, batch)
    assert off.ema_params is None
    off2, _, _ = make_train_step(model)(off, batch, jax.random.PRNGKey(1),
                                        jnp.float32(5.0))
    assert off2.ema_params is None


@pytest.mark.isolated
def test_ema_trainer_checkpoints_and_resume(tmp_path, synthetic_image_dir):
    """ema_decay in the yaml: bestloss_ema.ckpt appears, lastepoch carries
    the shadow, resume restores it, and resuming an ema-less checkpoint
    re-seeds instead of crashing."""
    import jax

    from ddim_cold_tpu.train.trainer import run
    from ddim_cold_tpu.utils import checkpoint as ckpt

    base = str(tmp_path)
    cfg = load_config(_write_config(base, synthetic_image_dir,
                                    ema_decay=0.9, snapshot_epochs=1), "exp")
    result = run(cfg, base, log_every=2)
    run_dir = result.run_dir
    # EMA snapshots land beside the raw ones; the FID trend's strict
    # epoch_(\d+) match must keep ignoring them
    snaps = sorted(os.listdir(os.path.join(run_dir, "snapshots")))
    assert snaps == ["epoch_0", "epoch_0_ema", "epoch_1", "epoch_1_ema"]
    assert os.path.isdir(os.path.join(run_dir, "bestloss_ema.ckpt"))
    assert os.path.isfile(os.path.join(run_dir, "bestloss_ema.pkl"))
    best = ckpt.restore_checkpoint(os.path.join(run_dir, "bestloss.ckpt"))
    ema = ckpt.restore_checkpoint(os.path.join(run_dir, "bestloss_ema.ckpt"))
    assert jax.tree.structure(ema) == jax.tree.structure(best)
    # the shadow trails the live params — identical trees would mean the
    # decay never applied (update magnitudes make exact equality impossible)
    diffs = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
             for a, b in zip(jax.tree.leaves(ema), jax.tree.leaves(best))]
    assert max(diffs) > 0

    resume_cfg = load_config(
        _write_config(base, synthetic_image_dir, epoch=[0, 3], ema_decay=0.9,
                      resume=os.path.join(run_dir, "lastepoch.ckpt")), "exp")
    r2 = run(resume_cfg, base, log_every=2)
    assert r2.steps == 15
    assert "re-seeding" not in open(os.path.join(r2.run_dir, "train.log")).read()


@pytest.mark.isolated
def test_ema_resume_from_pre_ema_checkpoint(tmp_path, synthetic_image_dir):
    """Turning ema_decay on mid-run (resume from a checkpoint written without
    it) re-seeds the shadow from the restored params with a log note. Own
    run dir: the shared trained_run fixture's checkpoint is advanced by
    test_resume_continues, which would leave this resume zero epochs."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path)
    r1 = run(load_config(_write_config(base, synthetic_image_dir,
                                       epoch=[0, 1]), "exp"), base, log_every=2)
    resume_cfg = load_config(
        _write_config(base, synthetic_image_dir, epoch=[0, 2], ema_decay=0.9,
                      resume=os.path.join(r1.run_dir, "lastepoch.ckpt")),
        "exp")
    r2 = run(resume_cfg, base, log_every=2)
    assert r2.steps == 10
    log = open(os.path.join(r2.run_dir, "train.log")).read()
    assert "no ema_params" in log and "re-seeding" in log
    # the shadow is carried forward: every lastepoch written after the
    # re-seed includes it
    from ddim_cold_tpu.utils import checkpoint as ckpt2

    last = ckpt2.restore_checkpoint(os.path.join(r2.run_dir, "lastepoch.ckpt"))
    assert "ema_params" in last


@pytest.mark.isolated
def test_ema_off_resume_from_ema_checkpoint(tmp_path, synthetic_image_dir):
    """The reverse toggle: a checkpoint written WITH ema_params resumes
    cleanly under ema_decay=0 (the shadow is dropped with a log note) —
    orbax is strict about the extra on-disk key, so this needs the flipped
    retry."""
    from ddim_cold_tpu.train.trainer import run
    from ddim_cold_tpu.utils import checkpoint as ckpt2

    base = str(tmp_path)
    cfg = load_config(_write_config(base, synthetic_image_dir,
                                    ema_decay=0.9), "exp")
    result = run(cfg, base, log_every=2)
    resume_cfg = load_config(
        _write_config(base, synthetic_image_dir, epoch=[0, 3],
                      resume=os.path.join(result.run_dir, "lastepoch.ckpt")),
        "exp")
    r2 = run(resume_cfg, base, log_every=2)
    assert r2.steps == 15
    log = open(os.path.join(r2.run_dir, "train.log")).read()
    assert "dropping the shadow" in log
    last = ckpt2.restore_checkpoint(os.path.join(r2.run_dir, "lastepoch.ckpt"))
    assert "ema_params" not in last


@pytest.mark.isolated
def test_warm_start_shape_mismatch_fails_loudly(tmp_path, synthetic_image_dir):
    """A stale `initializing` pkl from a different model config must raise a
    clear error naming the mismatched leaves — not surface later as an opaque
    jit shape error (fatal for unattended runs; observed with a leftover
    rehearsal pkl under the real run's warm-start name)."""
    import jax

    from ddim_cold_tpu.train.trainer import run
    from ddim_cold_tpu.utils import checkpoint as ckpt2

    pytest.importorskip("torch")
    base = str(tmp_path)
    # write a WRONG-config pkl under the warm-start name (embed 16 vs 32)
    from ddim_cold_tpu.models import DiffusionViT

    wrong = DiffusionViT(img_size=(64, 64), patch_size=8, embed_dim=16,
                         depth=1, num_heads=2)
    params = wrong.init(jax.random.PRNGKey(0),
                        np.zeros((1, 64, 64, 3), np.float32),
                        np.zeros((1,), np.int32))["params"]
    os.makedirs(os.path.join(base, "Saved_Models"), exist_ok=True)
    ckpt2.save_torch_pkl(params, os.path.join(base, "Saved_Models", "warm.pkl"), 8)
    cfg = load_config(_write_config(base, synthetic_image_dir,
                                    initializing="warm.pkl"), "exp")
    with pytest.raises(ValueError, match="does not match this model config"):
        run(cfg, base, log_every=2)
    # same guard on the checkpoint-DIRECTORY branch (orbax restore returns
    # the on-disk shapes when they differ from the template — measured)
    ckpt2.save_checkpoint(os.path.join(base, "Saved_Models", "warm.ckpt"), params)
    cfg = load_config(_write_config(base, synthetic_image_dir,
                                    initializing="warm.ckpt"), "exp")
    with pytest.raises(ValueError, match="does not match this model config"):
        run(cfg, base, log_every=2)


def test_ema_decay_range_validated(tmp_path, synthetic_image_dir):
    """Out-of-range ema_decay (a 9.99-for-0.999 typo diverges the shadow to
    NaN; 1.0 freezes it at init) fails loudly at config load."""
    for bad in (9.99, 1.0, -0.1):
        path = _write_config(str(tmp_path), synthetic_image_dir, ema_decay=bad)
        with pytest.raises(ValueError, match="ema_decay"):
            load_config(path, "exp")


@pytest.mark.isolated
def test_resume_shape_mismatch_fails_loudly(tmp_path, synthetic_image_dir):
    """`resume:` pointing at a different-config run's lastepoch.ckpt raises
    the clear mismatch error (same guard as warm-start), not an opaque jit
    shape error mid-run."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path)
    small = load_config(_write_config(base, synthetic_image_dir,
                                      embed_dim=16, epoch=[0, 1]), "exp")
    r1 = run(small, base, log_every=2)
    big = load_config(
        _write_config(base, synthetic_image_dir, embed_dim=32, epoch=[0, 2],
                      resume=os.path.join(r1.run_dir, "lastepoch.ckpt")),
        "exp")
    with pytest.raises(ValueError, match="does not match this model config"):
        run(big, base, log_every=2)


def test_grad_accum_matches_unaccumulated_step():
    """grad_accum=4 with dropout off is the same math as one full-batch step
    (smooth-L1 is a mean; mean of equal-slice grads == full-batch grad), and
    composes with the EMA shadow."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=16,
                         depth=1, num_heads=2, total_steps=8, drop_rate=0.0,
                         attn_drop_rate=0.0, drop_path_rate=0.0)
    rng = np.random.RandomState(0)
    batch = (jnp.asarray(rng.randn(8, 16, 16, 3), jnp.float32),
             jnp.asarray(rng.randn(8, 16, 16, 3), jnp.float32),
             jnp.asarray(rng.randint(1, 7, size=(8,)), jnp.int32))

    def one(accum):
        st = create_train_state(model, jax.random.PRNGKey(0), 1e-2, 10, batch,
                                ema_decay=0.5)
        step = make_train_step(model, ema_decay=0.5, grad_accum=accum)
        st, loss, _ = step(st, batch, jax.random.PRNGKey(1), jnp.float32(5.0))
        return st, float(loss)

    s1, l1 = one(1)
    s4, l4 = one(4)
    # tolerances: mean-of-slice-means vs full mean differ only in float
    # summation order (measured max |Δ| ≈ 1.4e-7 on these shapes)
    assert l1 == pytest.approx(l4, rel=1e-5)
    for tree1, tree4 in ((s1.params, s4.params),
                         (s1.ema_params, s4.ema_params)):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
            tree1, tree4)


@pytest.mark.isolated
def test_grad_accum_config_validation(tmp_path, synthetic_image_dir):
    """grad_accum < 1 fails at config load; grad_accum with a pipe mesh is
    rejected (the pipeline has its own microbatching)."""
    with pytest.raises(ValueError, match="grad_accum"):
        load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                  grad_accum=0), "exp")
    from ddim_cold_tpu.train.trainer import run

    cfg = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                    grad_accum=2, batch_size=8,
                                    mesh={"data": 2, "pipe": 2}), "exp")
    with pytest.raises(ValueError, match="grad_accum composes"):
        run(cfg, str(tmp_path), log_every=2)


@pytest.mark.isolated
def test_grad_accum_trainer_end_to_end(tmp_path, synthetic_image_dir):
    """A short run with grad_accum=2 trains, logs, and checkpoints normally."""
    from ddim_cold_tpu.train.trainer import run

    cfg = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                    grad_accum=2, epoch=[0, 1]), "exp")
    result = run(cfg, str(tmp_path), log_every=2)
    assert result.steps == 5 and np.isfinite(result.last_val_loss)
    assert os.path.isdir(os.path.join(result.run_dir, "lastepoch.ckpt"))


def test_make_train_step_validates_ema_inputs():
    """Direct API callers can't bypass the config-layer guards: bad ema_decay
    raises at construction; ema_decay>0 against a shadow-less state raises at
    trace time instead of silently training without EMA."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=16,
                         depth=1, num_heads=2, total_steps=8)
    with pytest.raises(ValueError, match="ema_decay"):
        make_train_step(model, ema_decay=1.0)
    rng = np.random.RandomState(0)
    batch = (jnp.asarray(rng.randn(2, 16, 16, 3), jnp.float32),
             jnp.asarray(rng.randn(2, 16, 16, 3), jnp.float32),
             jnp.asarray([1, 2], jnp.int32))
    st = create_train_state(model, jax.random.PRNGKey(0), 1e-2, 10, batch)
    with pytest.raises(ValueError, match="no ema_params"):
        make_train_step(model, ema_decay=0.9)(
            st, batch, jax.random.PRNGKey(1), jnp.float32(5.0))
