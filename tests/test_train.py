"""Training-layer tests: config derivation rules, end-to-end CPU training,
checkpoint/resume, converter round-trips (SURVEY.md §4 integration plan)."""

import os

import numpy as np
import pytest
import yaml

from ddim_cold_tpu.config import ExperimentConfig, load_config


def _write_config(tmp_path, data_dir, **overrides):
    cfg = {
        "initializing": "none",
        "resume": "none",
        "AMP": False,
        "framework": "vit_test",
        "num_gpus": 1,
        "batch_size": 2,
        "epoch": [0, 2],
        "base_lr": 0.005,
        "dataStorage": [data_dir, data_dir],
        "image_size": [64, 64],
        "diff_step": 6,
        "patch_size": 8,
        "embed_dim": 32,
        "depth": 1,
        "head": 2,
    }
    cfg.update(overrides)
    path = os.path.join(tmp_path, "exp.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    return path


def test_config_derivation_rules(tmp_path, synthetic_image_dir):
    """AMP doubles batch; lr = base·batch·devices/512 (multi_gpu_trainer.py:191-196)."""
    path = _write_config(str(tmp_path), synthetic_image_dir, AMP=True,
                         batch_size=16, num_gpus=4, base_lr=0.005)
    cfg = load_config(path, "exp")
    assert cfg.effective_batch == 32
    assert cfg.lr == pytest.approx(0.005 * 32 * 4 / 512)
    assert cfg.run_name == "expvit_test"
    # diff_step read but table stays 2000 by default (quirk #4)
    assert cfg.diff_step == 6 and cfg.total_steps == 2000
    cfg2 = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                     honor_diff_step=True), "exp")
    assert cfg2.total_steps == 6


@pytest.fixture(scope="module")
def trained_run(tmp_path_factory, synthetic_image_dir):
    """Train 2 epochs on the 10-image folder (shared by several tests)."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path_factory.mktemp("run"))
    cfg = load_config(_write_config(base, synthetic_image_dir,
                                    snapshot_epochs=1), "exp")
    result = run(cfg, base, log_every=2)
    return base, cfg, result


def test_train_end_to_end(trained_run):
    base, cfg, result = trained_run
    assert result.steps == 2 * (10 // 2)  # 2 epochs × 5 batches
    assert np.isfinite(result.last_val_loss)
    assert result.best_loss < 5.0  # improved from the init sentinel
    run_dir = result.run_dir
    assert os.path.isdir(os.path.join(run_dir, "bestloss.ckpt"))
    assert os.path.isdir(os.path.join(run_dir, "lastepoch.ckpt"))
    assert os.path.isfile(os.path.join(run_dir, "bestloss.pkl"))  # legacy bridge
    log = open(os.path.join(run_dir, "train.log")).read()
    assert "TrainSet batchs:5" in log
    assert "steps:" in log and "time_cost:" in log  # reference line format
    assert "epoch:    0" in log and "epoch:    1" in log
    assert os.path.isfile(os.path.join(run_dir, "metrics.jsonl"))


def test_snapshot_epochs_writes_trend_checkpoints(trained_run):
    """snapshot_epochs=N saves bare params to snapshots/epoch_<E> — the
    per-checkpoint FID-trend source (scripts/fid_trend.py collect_points)."""
    import jax

    from ddim_cold_tpu.utils import checkpoint as ckpt

    _, cfg, result = trained_run
    snap = os.path.join(result.run_dir, "snapshots")
    assert sorted(os.listdir(snap)) == ["epoch_0", "epoch_1"]
    raw = ckpt.restore_checkpoint(os.path.join(snap, "epoch_0"))
    best = ckpt.restore_checkpoint(os.path.join(result.run_dir, "bestloss.ckpt"))
    assert jax.tree.structure(raw) == jax.tree.structure(best)  # bare params


def test_resume_continues(trained_run, synthetic_image_dir):
    from ddim_cold_tpu.train.trainer import run

    base, cfg, result = trained_run
    resume_cfg = load_config(
        _write_config(base, synthetic_image_dir, epoch=[0, 3],
                      resume=os.path.join(result.run_dir, "lastepoch.ckpt")),
        "exp")
    r2 = run(resume_cfg, base, log_every=2)
    # resumed at epoch 2 → one more epoch of 5 steps on top of the restored 10
    assert r2.steps == 15
    log = open(os.path.join(r2.run_dir, "train.log")).read()
    assert "resuming from epoch" in log
    assert "recovering best_loss" in log
    assert "epoch:    2" in log


def test_save_checkpoint_preserves_previous_on_failed_write(tmp_path, monkeypatch):
    """A crashed/failed re-save must leave the previous checkpoint intact —
    the old force=True-onto-destination path deleted it before writing."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    p = str(tmp_path / "last.ckpt")
    ckpt.save_checkpoint(p, {"a": np.arange(3)})

    import orbax.checkpoint as ocp

    monkeypatch.setattr(
        ocp.PyTreeCheckpointer, "save",
        lambda self, *a, **k: (_ for _ in ()).throw(RuntimeError("disk full")))
    with pytest.raises(RuntimeError, match="disk full"):
        ckpt.save_checkpoint(p, {"a": np.arange(4)})
    monkeypatch.undo()

    got = ckpt.restore_checkpoint(p, {"a": np.zeros(3, np.int64)})
    np.testing.assert_array_equal(np.asarray(got["a"]), np.arange(3))


def test_checkpoint_swap_crash_recovers_from_old(tmp_path):
    """Crash between the two swap renames leaves only <path>.old — the owner
    (recover_swap, called by the trainer's resume path and by save itself)
    must move it back, never delete it as a leftover. restore stays
    read-only (a concurrent reader must not race a writer's swap)."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    p = str(tmp_path / "last.ckpt")
    ckpt.save_checkpoint(p, {"a": np.arange(3)})
    os.rename(p, p + ".old")  # simulate the crash window

    ckpt.recover_swap(p)
    got = ckpt.restore_checkpoint(p, {"a": np.zeros(3, np.int64)})
    np.testing.assert_array_equal(np.asarray(got["a"]), np.arange(3))

    os.rename(p, p + ".old")
    ckpt.save_checkpoint(p, {"a": np.arange(4)})  # save-side heal + overwrite
    got = ckpt.restore_checkpoint(p, {"a": np.zeros(4, np.int64)})
    np.testing.assert_array_equal(np.asarray(got["a"]), np.arange(4))


def _sigterm_when(log_path, needle, timeout_s=120):
    """Background thread: SIGTERM this process once `needle` appears in the
    train log. The needle must be a line the trainer only writes AFTER the
    graceful handler is installed ("steps:"/"epoch:"; "TrainSet" is logged
    before it — a signal there would kill the interpreter)."""
    import os as _os
    import signal
    import threading
    import time

    def watch():
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            try:
                if needle in open(log_path).read():
                    _os.kill(_os.getpid(), signal.SIGTERM)
                    return
            except OSError:
                pass
            time.sleep(0.25)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    return t


def test_sigterm_checkpoints_and_exits(tmp_path, synthetic_image_dir):
    """SIGTERM mid-training → the loop finishes the step, evaluates, saves
    both checkpoints, and run() returns normally (a hard kill would lose the
    epoch AND can wedge a remote TPU's session claim)."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path)
    cfg = load_config(_write_config(base, synthetic_image_dir, epoch=[0, 200]),
                      "exp")
    log_path = os.path.join(base, "Saved_Models", cfg.run_name, "train.log")
    t = _sigterm_when(log_path, "steps:")
    result = run(cfg, base, log_every=1)  # returns instead of dying
    t.join()
    assert result.steps < 200 * 5  # stopped early
    assert np.isfinite(result.last_val_loss)
    log = open(log_path).read()
    assert "stop signal at step" in log
    assert os.path.isdir(os.path.join(result.run_dir, "lastepoch.ckpt"))


def test_sigterm_with_short_epochs_stops_at_epoch_end(tmp_path,
                                                      synthetic_image_dir):
    """A stop signal must take effect at the next EPOCH boundary even when
    epochs are shorter than log_every — observed on a 16-step/epoch run with
    log_every=100, where the in-epoch check (steps % log_every) never fired
    and the signal was ignored for ~6 epochs."""
    from ddim_cold_tpu.train.trainer import run

    base = str(tmp_path)
    cfg = load_config(_write_config(base, synthetic_image_dir, epoch=[0, 50]),
                      "exp")
    log_path = os.path.join(base, "Saved_Models", cfg.run_name, "train.log")
    # signal lands during epoch 1 (after epoch 0's eval line, handler live);
    # log_every=1000 >> the 5 steps/epoch: only the epoch-end check can stop
    t = _sigterm_when(log_path, "epoch:")
    result = run(cfg, base, log_every=1000)
    t.join()
    assert result.steps <= 3 * 5, "stop signal ignored past the next epoch end"
    assert "stop signal at epoch" in open(log_path).read()
    assert os.path.isdir(os.path.join(result.run_dir, "lastepoch.ckpt"))


def test_loss_decreases_over_training(synthetic_image_dir):
    """Overfit one fixed batch through the real train_step: loss must drop."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.data import ColdDownSampleDataset, ShardedLoader
    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.ops.losses import smooth_l1
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    ds = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64])
    batch = next(iter(ShardedLoader(ds, 5, shuffle=False, drop_last=False,
                                    num_threads=1)))
    batch = tuple(jnp.asarray(b) for b in batch)
    model = DiffusionViT(img_size=(64, 64), patch_size=8, embed_dim=32, depth=1,
                         num_heads=2)
    state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                               total_steps=200, sample_batch=batch)

    def eval_loss(params):
        pred = model.apply({"params": params}, batch[0], batch[2])
        return float(smooth_l1(pred, batch[1]))

    before = eval_loss(state.params)
    train_step = make_train_step(model)
    rng = jax.random.PRNGKey(1)
    loss_rec = jnp.float32(5.0)
    for _ in range(100):
        state, _, loss_rec = train_step(state, batch, rng, loss_rec)
    after = eval_loss(state.params)
    assert after < before * 0.7, (before, after)


def test_checkpoint_converter_roundtrip():
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
                         num_heads=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)),
                        jnp.zeros((1,), jnp.int32))["params"]
    sd = ckpt.torch_state_dict_from_flax(params, patch_size=8)
    # torch-side key surface matches the reference state_dict naming
    assert "blocks.0.attn.qkv.weight" in sd
    assert "patch_embed.proj.weight" in sd and sd["patch_embed.proj.weight"].shape == (32, 3, 8, 8)
    back = ckpt.flax_from_torch_state_dict(sd, patch_size=8)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
                 params, back)


def test_checkpoint_converter_sincos_roundtrip():
    """use_sincos_pos models have no pos_embed param; the converter must
    tolerate its absence in both directions (regression: KeyError on export)."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32, depth=1,
                         num_heads=2, use_sincos_pos=True)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)),
                        jnp.zeros((1,), jnp.int32))["params"]
    assert "pos_embed" not in params
    sd = ckpt.torch_state_dict_from_flax(params, patch_size=8)
    assert "pos_embed" not in sd
    back = ckpt.flax_from_torch_state_dict(sd, patch_size=8)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
                 params, back)


def test_torch_pkl_file_roundtrip(tmp_path):
    torch = pytest.importorskip("torch")
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils import checkpoint as ckpt

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32, depth=1,
                         num_heads=2)
    x = jnp.asarray(np.random.RandomState(0).randn(1, 16, 16, 3), jnp.float32)
    t = jnp.array([5], jnp.int32)
    params = model.init(jax.random.PRNGKey(1), x, t)["params"]
    pkl = str(tmp_path / "w.pkl")
    ckpt.save_torch_pkl(params, pkl, patch_size=8)
    # a torch user can load it...
    sd = torch.load(pkl, weights_only=False)
    assert all(hasattr(v, "numpy") for v in sd.values())
    # ...and we can load it back with identical model behavior
    params2 = ckpt.load_torch_pkl(pkl, patch_size=8)
    out1 = model.apply({"params": params}, x, t)
    out2 = model.apply({"params": params2}, x, t)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_smooth_l1_matches_torch():
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    from ddim_cold_tpu.ops.losses import smooth_l1

    rng = np.random.RandomState(0)
    a = rng.randn(4, 8, 8, 3).astype(np.float32) * 2
    b = rng.randn(4, 8, 8, 3).astype(np.float32)
    want = torch.nn.functional.smooth_l1_loss(torch.from_numpy(a), torch.from_numpy(b)).item()
    got = float(smooth_l1(jnp.asarray(a), jnp.asarray(b)))
    assert got == pytest.approx(want, rel=1e-6)


def test_profile_steps_writes_trace(tmp_path, synthetic_image_dir):
    """profile_steps traces the first N steps into <run_dir>/trace and the
    run completes normally (reference had only wall-clock prints)."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="prof", framework="trace", batch_size=2, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(16, 16), patch_size=8, embed_dim=32, depth=1, head=2,
        profile_steps=2,
    )
    result = run(cfg, str(tmp_path), max_steps=3)
    assert np.isfinite(result.best_loss)
    trace_dir = os.path.join(result.run_dir, "trace")
    assert os.path.isdir(trace_dir)
    assert any(f for _, _, fs in os.walk(trace_dir) for f in fs), "empty trace"
