"""Fused sampler-trunk kernel tests (ops/flash_attention.fused_trunk_attention
+ ops/quant.mlp_pallas + ops/tuning.py + the vit/serve wiring).

The contract ladder, strictest first:
* the fused program is BITWISE the unfused ``QuantDense → flash → QuantDense``
  + ``Dense → gelu → Dense`` composition at f32 — through the serving engine,
  at two buckets, composed with the step cache, and (for the fused Mlp, the
  part that survives the sp gate) under sp_degree=2;
* ``fused=True`` + ``quant='xla'`` is refused at config construction AND at
  model call — 'xla' explicitly opts out of Pallas;
* every committed TUNED_BLOCKS entry is legal under exactly the rules
  graftcheck's kernels layer proves (P001 tile units, P002 double-buffered
  VMEM, P003 padding waste), and the enumerator's mirrored constants are
  pinned equal to analysis/kernel_checks.py's so they cannot drift;
* the w8a8 mode rides the paired-FID ``quantized_sampler_guard``;
* analysis/entries.py certifies every fused variant (P/M-rule coverage).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu import serve
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import quant, sampling, tiling, tuning
from ddim_cold_tpu.utils import flops as flops_util

# flash + explicit blocks: both the fused and unfused clones inherit the SAME
# kv-chunk boundaries, which is what makes the f32 oracle bitwise (dense
# einsum attention would differ from the online softmax in round-off)
TINY = dict(img_size=(32, 32), patch_size=8, embed_dim=64, depth=2,
            num_heads=4, total_steps=2000, use_flash=True,
            flash_blocks=(32, 32))
K = 500  # 4 reverse steps (tests/test_serve.py's budget)


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def warmed_fused(model_and_params):
    """One engine + warmed unfused/fused w8a16 programs at two buckets —
    the AOT compiles are the expensive part, shared across the tests."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(2, 4))
    cfg_u = serve.SamplerConfig(k=K, quant="pallas")
    cfg_f = serve.SamplerConfig(k=K, quant="pallas", fused=True)
    report = serve.warmup(eng, [cfg_u, cfg_f], persistent_cache=False)
    assert report["new_compiles"] == 4  # one program per (config, bucket)
    return eng, cfg_u, cfg_f


# ------------------------------------------------------------ engine parity

def _drain(eng, cfg, seeds_and_ns):
    tickets = [eng.submit(seed=s, n=n, config=cfg) for s, n in seeds_and_ns]
    eng.run()
    return [np.asarray(t.result(timeout=30)) for t in tickets]


def test_engine_fused_bitwise_two_buckets(warmed_fused):
    """Acceptance: the fused program serves BITWISE-identical images to the
    unfused w8a16 program at both warmed buckets, with zero compiles after
    warmup — same param tree, same rng, different compiled program."""
    eng, cfg_u, cfg_f = warmed_fused
    compiles = eng.stats["compiles"]
    reqs = [(201, 4), (202, 2)]
    got_u = _drain(eng, cfg_u, reqs)
    got_f = _drain(eng, cfg_f, reqs)
    assert eng.stats["compiles"] == compiles
    for a, b in zip(got_u, got_f):
        np.testing.assert_array_equal(a, b)
        assert np.isfinite(a).all()


def test_engine_fused_cached_composition(model_and_params):
    """fused × step-cache composes bitwise: the cache is a trunk-structure
    hook (block-delta capture), independent of how each block computes."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(2,))
    cfg_u = serve.SamplerConfig(k=K, quant="pallas", cache_interval=2,
                                cache_mode="full")
    cfg_f = serve.SamplerConfig(k=K, quant="pallas", cache_interval=2,
                                cache_mode="full", fused=True)
    serve.warmup(eng, [cfg_u, cfg_f], persistent_cache=False)
    compiles = eng.stats["compiles"]
    (a,) = _drain(eng, cfg_u, [(211, 2)])
    (b,) = _drain(eng, cfg_f, [(211, 2)])
    assert eng.stats["compiles"] == compiles
    np.testing.assert_array_equal(a, b)


@pytest.mark.skipif(jax.device_count() % 2 != 0,
                    reason="sp_degree=2 needs an even device count")
def test_engine_fused_sp2_composition(model_and_params):
    """fused × sp_degree=2: the fused ATTENTION is gated off under sp (the
    kernel owns the full sequence axis), so the sp×fused program is the sp
    attention + the fused w8a16 Mlp — still bitwise vs the sp unfused
    program (the Mlp is per-token; sharding doesn't reorder its reduction)."""
    model, params = model_and_params
    # the bucket must tile the sp data axis (devices / sp_degree)
    eng = serve.Engine(model, params, buckets=(4,))
    cfg_u = serve.SamplerConfig(k=K, quant="pallas", sp_mode="ulysses",
                                sp_degree=2)
    cfg_f = serve.SamplerConfig(k=K, quant="pallas", sp_mode="ulysses",
                                sp_degree=2, fused=True)
    serve.warmup(eng, [cfg_u, cfg_f], persistent_cache=False)
    compiles = eng.stats["compiles"]
    (a,) = _drain(eng, cfg_u, [(221, 4)])
    (b,) = _drain(eng, cfg_f, [(221, 4)])
    assert eng.stats["compiles"] == compiles
    np.testing.assert_array_equal(a, b)


def test_fused_param_tree_shared(model_and_params):
    """The fused clone declares the SAME param tree as the unfused one —
    fused=True switches the compiled program, never the checkpoint."""
    model, params = model_and_params
    fused = model.clone(quant="pallas", fused=True)
    unfused = model.clone(quant="pallas")
    qp = quant.quantize_params(params)
    x = jnp.zeros((1, 32, 32, 3))
    t = jnp.array([0], jnp.int32)
    tf = jax.eval_shape(lambda: fused.init(jax.random.PRNGKey(0), x, t))
    tu = jax.eval_shape(lambda: unfused.init(jax.random.PRNGKey(0), x, t))
    assert jax.tree_util.tree_structure(tf) == jax.tree_util.tree_structure(tu)
    # and the quantized tree drives the fused model directly
    out = fused.apply({"params": qp}, x, t, deterministic=True)
    assert np.isfinite(np.asarray(out)).all()


def test_fused_xla_refused(model_and_params):
    """quant='xla' explicitly opts out of Pallas; fused=True contradicts it
    — refused at config construction AND at model call, naming the fix."""
    model, _ = model_and_params
    with pytest.raises(ValueError, match="fused"):
        serve.SamplerConfig(k=K, quant="xla", fused=True)
    bad = model.clone(quant="xla", fused=True)
    with pytest.raises(ValueError, match="quant='pallas'"):
        bad.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                 jnp.array([0], jnp.int32))


# -------------------------------------------------- tuned-block table rules

def test_tuning_constants_pinned_to_kernel_checks():
    """The enumerator's mirrored constants must equal the verifier's — a
    drift would let tuning.py commit blocks graftcheck then rejects."""
    from ddim_cold_tpu.analysis import kernel_checks as kc

    assert tuning.DEVICE_KIND == kc.DEVICE_KIND
    assert tuning.WASTE_THRESHOLD == kc.WASTE_THRESHOLD
    assert tuning.PIPELINE_BUFFERS == kc.PIPELINE_BUFFERS
    # the tiling units tuning enumerates with ARE the P001 MIN_TILE rows
    for itemsize, (sub, lane) in kc.MIN_TILE.items():
        dt = {4: jnp.float32, 2: jnp.bfloat16, 1: jnp.int8}[itemsize]
        assert tiling.sublane_unit(dt) == sub
        assert tiling.LANE == lane


_DT = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "int8": jnp.int8}


def test_tuned_blocks_all_legal():
    """Every committed TUNED_BLOCKS entry obeys the P-rules it was
    enumerated under: sequence blocks are MIN_TILE sublane multiples (P001),
    padding waste stays under the ceiling (P003), and the kernel's
    double-buffered VMEM footprint fits the device (P002)."""
    from ddim_cold_tpu.analysis import kernel_checks as kc

    budget = flops_util.vmem_bytes(tuning.DEVICE_KIND)
    assert budget is not None
    for (kind, dt_name, geom), blocks in tuning.TUNED_BLOCKS.items():
        dt = _DT[dt_name]
        unit = kc.MIN_TILE[jnp.dtype(dt).itemsize][0]
        m = re.fullmatch(r"attn_n(\d+)_c(\d+)_h(\d+)", geom)
        if m:
            n, c, h = map(int, m.groups())
            bq, bkv = blocks
            for b in (bq, bkv):
                assert b % unit == 0, (geom, dt_name, blocks)
                assert tiling.round_up(n, b) / n <= tuning.WASTE_THRESHOLD
            cdt = jnp.float32 if dt == jnp.int8 else dt
            assert tuning.attn_vmem_bytes(
                bq, bkv, c, h, dt, compute_dtype=cdt) <= budget, (geom, dt_name)
            continue
        m = re.fullmatch(r"(mlpf?)_c(\d+)_h(\d+)", geom)
        if m:
            q = m.group(1) == "mlp"
            c, h = int(m.group(2)), int(m.group(3))
            (bm,) = blocks
            assert bm % unit == 0, (geom, dt_name, blocks)
            assert tuning.mlp_vmem_bytes(bm, c, h, c, dt,
                                         quant=q) <= budget, (geom, dt_name)
            continue
        m = re.fullmatch(r"dequant_m(\d+)_k(\d+)_n(\d+)", geom)
        assert m, f"unrecognized geometry tag {geom}"
        mm, k, n = map(int, m.groups())
        bm, bn, bk = blocks
        assert bm % unit == 0
        assert bn % tiling.LANE == 0
        # dual-dtype K axis: activation LANE dim AND int8-weight sublane dim
        assert bk % tiling.LANE == 0 and bk % kc.MIN_TILE[1][0] == 0
        assert tiling.round_up(mm, bm) / mm <= tuning.WASTE_THRESHOLD
        assert tuning.dequant_vmem_bytes(bm, bn, bk, dt) <= budget


def test_tuned_lookup_and_fallbacks():
    """lookup() prefix-matches the device kind; un-tuned geometries fall
    back to NS_FLASH_BLOCKS / the kernel default — never None."""
    from ddim_cold_tpu.ops.flash_attention import NS_FLASH_BLOCKS

    got = tuning.attn_blocks(2501, 256, 4, jnp.float32,
                             device_kind="TPU v5 lite core 1")
    assert got == (1328, 1288)  # prefix match on the committed entry
    assert tuning.attn_blocks(2501, 256, 4, jnp.float32,
                              device_kind="cpu") == NS_FLASH_BLOCKS
    assert tuning.mlp_block_m(256, 256, jnp.bfloat16,
                              device_kind="TPU v5 lite") == 4016
    assert tuning.mlp_block_m(256, 256, jnp.bfloat16, quant=False,
                              device_kind="TPU v5 lite") == 3952
    assert tuning.mlp_block_m(99, 99, jnp.float32,
                              device_kind="TPU v5 lite") == 256  # default


def test_static_picks_reproduce_committed_table():
    """`python -m ddim_cold_tpu.ops.tuning` provenance: the static model
    re-derives the committed 200px/p4 entries exactly."""
    for dt_name, (bq, bkv) in (("float32", (1328, 1288)),
                               ("bfloat16", (1552, 2512)),
                               ("int8", (1536, 2528))):
        dt = _DT[dt_name]
        cdt = jnp.float32 if dt == jnp.int8 else dt
        assert tuning.pick_attn(2501, 256, 4, dt,
                                compute_dtype=cdt) == (bq, bkv), dt_name
    assert tuning.pick_mlp(16 * 2501, 256, 256, 256, jnp.bfloat16) == 4016
    assert tuning.pick_mlp(16 * 2501, 256, 256, 256, jnp.bfloat16,
                           quant=False) == 3952


# ------------------------------------------------------------- w8a8 quality

def test_w8a8_sampler_guard_smoke(model_and_params):
    """The w8a8 mode (int8 activations, per-tensor dynamic scale) ships
    behind the SAME paired-FID guard as w8a16 — the guard runs end to end
    over the fused w8a8 sampler and its drift stays bounded (w8a8 is NOT
    bitwise vs float: activation requantization is a real approximation)."""
    from ddim_cold_tpu.eval import fid

    model, params = model_and_params
    rep = fid.quantized_sampler_guard(model, params,
                                      rng=jax.random.PRNGKey(13),
                                      n_samples=2, sample_batch=2, k=K,
                                      quant="w8a8")
    assert rep["quant_rev"] == quant.QUANT_REV
    assert np.isfinite(rep["fid_exact_vs_quant"])
    assert rep["max_abs_pixel_delta"] < 0.25  # 4-step drift of an ~1% eps gap


def test_w8a8_direct_sampler_close_to_float(model_and_params):
    """Direct (engine-free) fused w8a8 sampling stays near the float
    sampler and is deterministic."""
    model, params = model_and_params
    qp = quant.quantize_params(params)
    w8a8 = model.clone(quant="w8a8", fused=True)
    rng = jax.random.PRNGKey(31)
    exact = np.asarray(sampling.ddim_sample(model, params, rng, k=K, n=2))
    got = np.asarray(sampling.ddim_sample(w8a8, qp, rng, k=K, n=2))
    assert np.isfinite(got).all()
    assert np.abs(got - exact).max() < 0.25
    again = np.asarray(sampling.ddim_sample(w8a8, qp, rng, k=K, n=2))
    np.testing.assert_array_equal(got, again)


# ------------------------------------------------------------ P/M coverage

def test_kernel_entries_cover_fused_variants():
    """analysis/entries.py certifies every fused program and kernel variant
    the sampler can dispatch — the graftcheck P/M layers run over these."""
    from ddim_cold_tpu.analysis import entries as entries_mod

    names = {e.name for e in entries_mod.kernel_entries()}
    for want in ("ns200_w8a16_fused", "ns200_w8a8_fused",
                 "fused200_attn_f32", "fused200_attn_bf16",
                 "fused200_attn_w8a8", "mlp200_float_bf16",
                 "mlp200_w8a16_bf16", "mlp200_w8a8"):
        assert want in names, f"missing kernel entry {want}"
