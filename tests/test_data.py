"""Data layer tests: resize oracles vs torch interpolate, dataset contracts,
degradation parity (host vs device), sharded-loader semantics."""

import numpy as np
import pytest

from ddim_cold_tpu.data import ColdDownSampleDataset, DiffusionDataset, ShardedLoader
from ddim_cold_tpu.data import resize


# ---------- resize oracles ----------

@pytest.mark.parametrize("inout", [(64, 8), (64, 64), (96, 64), (13, 7), (8, 64)])
def test_resize_nearest_matches_torch(inout, rng):
    torch = pytest.importorskip("torch")
    size_in, size_out = inout
    img = rng.rand(size_in, size_in, 3).astype(np.float32)
    want = (
        torch.nn.functional.interpolate(
            torch.from_numpy(img.transpose(2, 0, 1))[None], size=(size_out, size_out),
            mode="nearest",
        )[0].numpy().transpose(1, 2, 0)
    )
    got = resize.resize_nearest(img, (size_out, size_out))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("inout", [(96, 64), (80, 64), (64, 200), (50, 64)])
def test_resize_bilinear_matches_torch(inout, rng):
    torch = pytest.importorskip("torch")
    size_in, size_out = inout
    img = rng.rand(size_in, size_in, 3).astype(np.float32)
    want = (
        torch.nn.functional.interpolate(
            torch.from_numpy(img.transpose(2, 0, 1))[None], size=(size_out, size_out),
            mode="bilinear", align_corners=False, antialias=False,
        )[0].numpy().transpose(1, 2, 0)
    )
    got = resize.resize_bilinear(img, (size_out, size_out))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_cold_degrade_golden(rng):
    """D(x,s) = nearest down to floor(size/s) then nearest up (the operator the
    trainer's targets are built from)."""
    torch = pytest.importorskip("torch")
    img = rng.rand(64, 64, 3).astype(np.float32)
    for t in range(1, 7):
        s = 2**t
        target = int(np.floor(64 / s))
        tt = torch.from_numpy(img.transpose(2, 0, 1))[None]
        small = torch.nn.functional.interpolate(tt, size=(target, target), mode="nearest")
        big = torch.nn.functional.interpolate(small, size=(64, 64), mode="nearest")
        want = big[0].numpy().transpose(1, 2, 0)
        got = resize.cold_degrade(img, s, 64)
        np.testing.assert_array_equal(got, want)


def test_device_degrade_matches_host(rng):
    import jax.numpy as jnp

    from ddim_cold_tpu.ops.degrade import cold_degrade as device_degrade

    imgs = rng.rand(7, 64, 64, 3).astype(np.float32)
    ts = np.array([0, 1, 2, 3, 4, 5, 6], dtype=np.int32)
    got = np.asarray(device_degrade(jnp.asarray(imgs), jnp.asarray(ts), size=64))
    for i, t in enumerate(ts):
        want = resize.cold_degrade(imgs[i], 2 ** int(t), 64)
        np.testing.assert_array_equal(got[i], want)


# ---------- datasets ----------

def test_cold_dataset_contract(synthetic_image_dir):
    ds = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64])
    assert len(ds) == 10  # quirk #1 fixed: __len__ exists
    assert ds.max_step == 6
    noisy, target, t = ds[0]
    assert noisy.shape == (64, 64, 3) and target.shape == (64, 64, 3)
    assert 1 <= t <= 6
    assert noisy.dtype == np.float32
    assert noisy.min() >= -1.0 and noisy.max() <= 1.0
    # explicit t: chain mode gives (D(t), D(t-1)) of the same clean image
    n6, t5, _ = ds.__getitem__(0, t=6)
    img_direct = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64],
                                       target_mode="direct").__getitem__(0, t=6)
    x0 = img_direct[1]
    np.testing.assert_array_equal(n6, resize.cold_degrade(x0, 64, 64))
    np.testing.assert_array_equal(t5, resize.cold_degrade(x0, 32, 64))


def test_cold_dataset_direct_mode(synthetic_image_dir):
    ds = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64], target_mode="direct")
    noisy, target, t = ds.__getitem__(3, t=2)
    # direct mode target is the clean image itself
    np.testing.assert_array_equal(target, ds.__getitem__(3, t=5)[1])
    np.testing.assert_array_equal(noisy, resize.cold_degrade(target, 4, 64))


def test_cold_dataset_rejects_nonsquare(synthetic_image_dir):
    with pytest.raises(ValueError, match="square"):
        ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 32])


def test_diffusion_dataset_contract(synthetic_image_dir):
    ds = DiffusionDataset(synthetic_image_dir, imgSize=[32, 32], max_step=2000)
    noisy, img, t = ds[4]
    assert noisy.shape == (32, 32, 3) and img.shape == (32, 32, 3)
    assert 0 <= t < 2000
    # index honored (quirk #2 fixed): different files differ
    a = ds.__getitem__(0, t=100)[1]
    b = ds.__getitem__(1, t=100)[1]
    assert not np.array_equal(a, b)
    # forward noising at t: noisy = sqrt(a)*img + sqrt(1-a)*eps with finite stats
    assert np.isfinite(noisy).all()


def test_dataset_determinism(synthetic_image_dir):
    ds = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64], seed=7)
    a = ds[2]
    b = ds[2]
    np.testing.assert_array_equal(a[0], b[0])
    assert a[2] == b[2]
    ds.set_epoch(1)  # new epoch → new t draw (almost surely different pair)
    c = ds[2]
    assert (a[2] != c[2]) or not np.array_equal(a[0], c[0]) or True  # t may collide; just smoke
    ds2 = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64], seed=7)
    d = ds2[2]
    np.testing.assert_array_equal(a[0], d[0])  # same seed/epoch/index → identical


# ---------- sharded loader ----------

class _IntDataset:
    """Items are (index-array, index-array, index) so batches reveal ordering."""

    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        a = np.full((2, 2, 3), i, dtype=np.float32)
        return a, a, i

    def __len__(self):
        return self.n


def _collect_indices(loader):
    out = []
    for _, _, t in loader:
        out.extend(int(v) for v in t)
    return out


def test_loader_shards_partition_train():
    n, world = 103, 4
    shards = []
    for r in range(world):
        ld = ShardedLoader(_IntDataset(n), batch_size=5, shuffle=True, seed=42,
                           drop_last=True, shard_index=r, shard_count=world,
                           num_threads=1)
        ld.set_epoch(0)
        shards.append(_collect_indices(ld))
    # equal sizes, disjoint, subset of range(n); drop_last trims to floor(103/4)*4=100
    sizes = {len(s) for s in shards}
    assert sizes == {25}
    flat = [i for s in shards for i in s]
    assert len(set(flat)) == 100
    assert set(flat) <= set(range(n))


def test_loader_epoch_reshuffle_deterministic():
    ld = ShardedLoader(_IntDataset(50), batch_size=5, shuffle=True, seed=42,
                       drop_last=True, num_threads=1)
    ld.set_epoch(0)
    e0 = _collect_indices(ld)
    ld.set_epoch(0)
    assert _collect_indices(ld) == e0  # deterministic per epoch
    ld.set_epoch(1)
    e1 = _collect_indices(ld)
    assert e0 != e1 and set(e0) == set(e1)  # reshuffled, same coverage


def test_loader_eval_padding():
    n, world = 10, 4  # ceil(10/4)*4 = 12 → 2 wrap-around pads
    shards = []
    for r in range(world):
        ld = ShardedLoader(_IntDataset(n), batch_size=2, shuffle=False,
                           drop_last=False, shard_index=r, shard_count=world,
                           num_threads=1)
        shards.append(_collect_indices(ld))
    assert all(len(s) == 3 for s in shards)
    flat = [i for s in shards for i in s]
    assert set(flat) == set(range(n))  # every item seen at least once


def test_loader_pad_final_batch():
    """Eval batches must all be full size (sharded leading dim needs even
    divisibility over the 'data' mesh axis)."""
    ld = ShardedLoader(_IntDataset(10), batch_size=4, shuffle=False,
                       drop_last=False, pad_final_batch=True, num_threads=1)
    batches = list(ld)
    assert len(batches) == 3
    assert all(b[0].shape[0] == 4 for b in batches)
    # padding wraps from the start of the shard's index order
    assert batches[-1][2].tolist() == [8, 9, 0, 1]


def test_loader_dataset_smaller_than_shards():
    """Tiled padding: every shard gets a batch even with 3 items / 8 shards."""
    counts = []
    for r in range(8):
        ld = ShardedLoader(_IntDataset(3), batch_size=1, shuffle=False,
                           drop_last=False, shard_index=r, shard_count=8,
                           num_threads=1)
        counts.append(sum(1 for _ in ld))
    assert counts == [1] * 8  # equal batch counts → no multi-host deadlock


def test_loader_abandoned_iterator_stops_decoding():
    """Breaking out of iteration must not keep decoding the whole epoch."""
    import time

    decoded = []

    class SlowDs:
        def __getitem__(self, i):
            decoded.append(i)
            return np.zeros((2, 2, 3), np.float32), np.zeros((2, 2, 3), np.float32), i

        def __len__(self):
            return 10_000

    ld = ShardedLoader(SlowDs(), batch_size=10, shuffle=False, drop_last=True,
                       num_threads=4, prefetch=1)
    it = iter(ld)
    next(it)
    it.close()  # abandon
    time.sleep(0.3)
    n = len(decoded)
    time.sleep(0.3)
    # decoding stopped (allow the in-flight batch to finish)
    assert len(decoded) - n <= ld.batch_size
    assert len(decoded) < 200


def test_loader_threaded_matches_sync(synthetic_image_dir):
    ds = ColdDownSampleDataset(synthetic_image_dir, imgSize=[64, 64])
    a = list(ShardedLoader(ds, batch_size=4, shuffle=True, seed=1, num_threads=1))
    b = list(ShardedLoader(ds, batch_size=4, shuffle=True, seed=1, num_threads=4))
    assert len(a) == len(b) == 2
    for (x1, y1, t1), (x2, y2, t2) in zip(a, b):
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(t1, t2)


def test_cache_matches_uncached(synthetic_image_dir):
    """Decoded-image cache changes nothing observable: per-item and batch
    outputs are identical with cache on/off, for both dataset families."""
    from ddim_cold_tpu.data import ColdDownSampleDataset, DiffusionDataset

    for cls, kw in ((ColdDownSampleDataset, {}),
                    (ColdDownSampleDataset, {"target_mode": "direct"}),
                    (DiffusionDataset, {"max_step": 100})):
        cold = cls(synthetic_image_dir, imgSize=[32, 32], cache_images=False, **kw)
        hot = cls(synthetic_image_dir, imgSize=[32, 32], cache_images=True, **kw)
        for i in range(4):
            a, b = cold[i], hot[i]
            np.testing.assert_array_equal(a[0], b[0])
            np.testing.assert_array_equal(a[1], b[1])
            assert a[2] == b[2]
        # second pass hits the now-warm cache
        for i in range(4):
            a, b = cold[i], hot[i]
            np.testing.assert_array_equal(a[1], b[1])
        ga = cold.get_batch(np.arange(6), num_threads=2)
        gb = hot.get_batch(np.arange(6), num_threads=2)
        if ga is not None and gb is not None:
            for x, y in zip(ga, gb):
                np.testing.assert_array_equal(x, y)


def test_group_batches_stacks_and_drops_tail():
    """group_batches(n) stacks n batches on a new leading axis and drops a
    short tail (drop_last semantics) — the host half of steps_per_dispatch."""
    from ddim_cold_tpu.data.loader import group_batches

    batches = [(np.full((2, 4), i, np.uint8), np.full((2,), i, np.int32))
               for i in range(5)]
    groups = list(group_batches(iter(batches), 2))
    assert len(groups) == 2  # batch 4 is the dropped tail
    assert groups[0][0].shape == (2, 2, 4) and groups[0][1].shape == (2, 2)
    np.testing.assert_array_equal(groups[1][1], [[2, 2], [3, 3]])
    # n=1 passes batches through untouched
    assert list(group_batches(iter(batches), 1))[3][1][0] == 3


def test_cache_auto_threshold(synthetic_image_dir):
    from ddim_cold_tpu.data import ColdDownSampleDataset
    from ddim_cold_tpu.data import datasets as dsmod

    small = ColdDownSampleDataset(synthetic_image_dir, imgSize=[32, 32])
    assert small.cache_images  # 10 × 32×32×3×4 ≪ budget
    old = dsmod.CACHE_BUDGET_BYTES
    try:
        dsmod.CACHE_BUDGET_BYTES = 10
        big = ColdDownSampleDataset(synthetic_image_dir, imgSize=[32, 32])
        assert not big.cache_images
    finally:
        dsmod.CACHE_BUDGET_BYTES = old
