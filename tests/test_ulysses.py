"""Ulysses (all-to-all) sequence parallelism: equivalence with dense
attention, composition with dp, flash-local-attention variant, model-level
parity, and the heads-divisibility guard. Mirrors the ring-attention test
strategy (tests/test_ring_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops.flash_attention import _dense_attention_f32
from ddim_cold_tpu.parallel.mesh import make_mesh
from ddim_cold_tpu.parallel.ulysses import ulysses_self_attention


def _qkv(seed, B, N, H, D):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, N, H, D), jnp.float32) for k in ks)


@pytest.mark.parametrize("N", [64, 65, 257])
def test_ulysses_matches_dense(N):
    """Pure-sp mesh {seq: 8}, including non-divisible sequence lengths
    (padding sliced off after the gather-side all-to-all)."""
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(0, 2, N, 8, 16)
    scale = 16**-0.5
    out = ulysses_self_attention(q, k, v, mesh, scale=scale)
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_ulysses_composes_with_dp():
    """{data: 2, seq: 4}: batch stays dp-sharded, heads reshard over seq."""
    mesh = make_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(1, 4, 33, 4, 8)
    scale = 8**-0.5
    out = ulysses_self_attention(q, k, v, mesh, batch_axis="data", scale=scale)
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("impl", [True, "xla"])
def test_ulysses_flash_local_attention(impl):
    """use_flash=True runs the Pallas kernel per shard inside the shard_map;
    use_flash='xla' runs the pure-XLA blockwise path there instead."""
    mesh = make_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(2, 1, 40, 4, 8)
    scale = 8**-0.5
    out = ulysses_self_attention(q, k, v, mesh, scale=scale, use_flash=impl)
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(3, 1, 16, 4, 8)  # 4 heads over 8 shards
    with pytest.raises(ValueError, match="divisible"):
        ulysses_self_attention(q, k, v, mesh)


@pytest.mark.parametrize("impl", [False, "xla"])
def test_ulysses_gradient_matches_dense(impl):
    """Reverse-mode through the two all-to-alls + local attention ≡ dense
    autodiff (the all-to-all transposes to the inverse all-to-all); with
    impl='xla' the local attention is the blockwise online-softmax scan,
    whose saved-carry backward is exercised under the resharding too."""
    mesh = make_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(7, 2, 33, 4, 8)
    scale = 8**-0.5

    def loss_ul(q, k, v):
        return jnp.sum(ulysses_self_attention(
            q, k, v, mesh, batch_axis="data", scale=scale,
            use_flash=impl) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention_f32(q, k, v, scale)[1] ** 2)

    g_ours = jax.jit(jax.grad(loss_ul, argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for name, ours, want in zip("qkv", g_ours, g_want):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_ulysses_gradient_composed_tp_matches_dense():
    """Gradients through the tp-composed ulysses (heads split over 'model'
    AND 'seq') match dense autodiff."""
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    q, k, v = _qkv(8, 2, 33, 4, 8)
    scale = 8**-0.5

    def loss_ul(q, k, v):
        return jnp.sum(ulysses_self_attention(
            q, k, v, mesh, batch_axis="data", head_axis="model",
            scale=scale) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention_f32(q, k, v, scale)[1] ** 2)

    g_ours = jax.jit(jax.grad(loss_ul, argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for name, ours, want in zip("qkv", g_ours, g_want):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_model_sp_mode_ulysses_matches_dense_model():
    """DiffusionViT(sp_mode='ulysses') ≡ the plain dense model in eval mode
    (same params — sp adds none)."""
    mesh = make_mesh({"data": 2, "seq": 4})
    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2,
               num_heads=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 3))
    t = jnp.array([3, 500], jnp.int32)
    base = DiffusionViT(**cfg)
    params = jax.jit(base.init)(jax.random.PRNGKey(1), x, t)["params"]
    sp = DiffusionViT(seq_mesh=mesh, seq_axis="seq", batch_axis="data",
                      sp_mode="ulysses", attn_drop_rate=0.0, **cfg)
    out_base = jax.jit(base.apply)({"params": params}, x, t)
    out_sp = jax.jit(sp.apply)({"params": params}, x, t)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_base),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_composes_with_tp():
    """{model: 2, seq: 2} (VERDICT r4 weak #6 — previously refused): the
    all-to-all splits each tp group's LOCAL H/tp heads over 'seq', so every
    (tp, sp) pair attends the full sequence for H/(tp·sp) heads."""
    mesh = make_mesh({"model": 2, "seq": 2, "data": 2})
    q, k, v = _qkv(4, 2, 33, 4, 8)  # H/tp = 2, divisible by sp = 2
    scale = 8**-0.5
    out = ulysses_self_attention(q, k, v, mesh, batch_axis="data",
                                 head_axis="model", scale=scale)
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_ulysses_rejects_indivisible_local_heads():
    """tp composition shifts the divisibility constraint to LOCAL heads:
    6 heads / tp 2 = 3 local, not divisible by sp 4."""
    mesh = make_mesh({"model": 2, "seq": 4})
    q, k, v = _qkv(5, 1, 16, 6, 8)
    with pytest.raises(ValueError, match="local heads"):
        ulysses_self_attention(q, k, v, mesh, head_axis="model")


def test_model_sp_mode_ulysses_composes_with_tp():
    """DiffusionViT(sp_mode='ulysses', head_axis='model') ≡ the plain dense
    model in eval mode — the model-level form of the tp×sp composition."""
    mesh = make_mesh({"model": 2, "seq": 2, "data": 2})
    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2,
               num_heads=4)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 16, 3))
    t = jnp.array([3, 500], jnp.int32)
    base = DiffusionViT(**cfg)
    params = jax.jit(base.init)(jax.random.PRNGKey(1), x, t)["params"]
    sp = DiffusionViT(seq_mesh=mesh, seq_axis="seq", batch_axis="data",
                      head_axis="model", sp_mode="ulysses",
                      attn_drop_rate=0.0, **cfg)
    out_base = jax.jit(base.apply)({"params": params}, x, t)
    out_sp = jax.jit(sp.apply)({"params": params}, x, t)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_base),
                               rtol=2e-4, atol=2e-5)

def test_divisibility_error_is_typed_and_actionable():
    """The head-divisibility guard raises SeqParallelConfigError (still a
    ValueError for old callers) and the message names the serving knobs —
    the error a misconfigured SamplerConfig surfaces at warmup must say
    which field to change, not just which reshape failed."""
    from ddim_cold_tpu.parallel import SeqParallelConfigError

    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(8, 1, 16, 4, 8)  # 4 heads over 8 shards
    with pytest.raises(SeqParallelConfigError) as ei:
        ulysses_self_attention(q, k, v, mesh)
    assert isinstance(ei.value, ValueError)
    msg = str(ei.value)
    assert "sp_mode='ring'" in msg and "sp_degree" in msg


def test_sp_clone_resolves_ulysses_with_ring_fallback():
    """models.sp_clone is THE resolver every caller routes through (engine,
    analysis sweep, direct use): 'ulysses' survives when the tp-local head
    count divides the seq axis and falls back to 'ring' otherwise, so
    serving and static analysis can never resolve differently."""
    from ddim_cold_tpu.models import sp_clone

    cfg = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
               num_heads=4)
    base = DiffusionViT(**cfg)
    ok = sp_clone(base, make_mesh({"data": 4, "seq": 2}), sp_mode="ulysses")
    assert ok.sp_mode == "ulysses" and ok.seq_mesh is not None
    assert ok.seq_axis == "seq" and ok.batch_axis == "data"
    fb = sp_clone(base, make_mesh({"data": 1, "seq": 8}), sp_mode="ulysses")
    assert fb.sp_mode == "ring"  # 4 % 8 — the ring has no head constraint
    tp = sp_clone(base, make_mesh({"model": 2, "seq": 4}),
                  sp_mode="ulysses", head_axis="model")
    assert tp.sp_mode == "ring"  # LOCAL heads 4//2 = 2, and 2 % 4 != 0
