"""Attribution + trend-gate tests (ISSUE 13): the checked-in synthetic
trace fixture pinned to its generator, scope-tree reconstruction and the
busy/idle split over crafted timelines, the flops join (both roofline
branches, unknown-device degradation), the ≥90% coverage floor, fusion
ranking, driver-wrapper unwrapping (parsed / tail / truncated-tail /
garbage), the regression gate over the committed series and over injected
tmp series, the thinning + delta-annotation helpers fid_trend rides, the
run_meta provenance stamp, and the GRAFT-A004 host-only contract for both
new modules."""

import gzip
import json
import os
import re

import pytest

from ddim_cold_tpu.analysis import ast_checks
from ddim_cold_tpu.obs import attrib, trend
from ddim_cold_tpu.utils import flops as flops_util
from ddim_cold_tpu.utils.record import run_metadata

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "attrib_trace.json")


# ---------------------------------------------------------------------------
# fixture + loading
# ---------------------------------------------------------------------------

def test_fixture_pinned_to_generator():
    """The checked-in trace IS synthetic_demo_trace() — fixture drift (edit
    one without the other) is a hard failure, so --demo, the CPU bench
    fallback, and these tests always attribute the same timeline."""
    with open(FIXTURE) as f:
        on_disk = json.load(f)
    assert on_disk == attrib.synthetic_demo_trace()


def test_load_trace_dict_passthrough_and_validation():
    t = attrib.synthetic_demo_trace()
    assert attrib.load_trace(t) is t
    with pytest.raises(attrib.AttribError):
        attrib.load_trace({"no_events": []})


def test_load_trace_file_and_gz(tmp_path):
    t = attrib.synthetic_demo_trace()
    plain = tmp_path / "t.trace.json"
    plain.write_text(json.dumps(t))
    assert attrib.load_trace(str(plain)) == t
    gz = tmp_path / "t.trace.json.gz"
    with gzip.open(gz, "wt") as f:
        json.dump(t, f)
    assert attrib.load_trace(str(gz)) == t
    bad = tmp_path / "bad.json"
    bad.write_text("not json at all {")
    with pytest.raises(attrib.AttribError):
        attrib.load_trace(str(bad))


def test_load_trace_profiler_dir_layout(tmp_path):
    """The jax.profiler on-disk shape: plugins/profile/<run>/<host>.trace
    .json.gz, newest run wins, per-host dumps merge."""
    old = tmp_path / "plugins" / "profile" / "2026_01_01"
    new = tmp_path / "plugins" / "profile" / "2026_02_02"
    for d in (old, new):
        d.mkdir(parents=True)
    with gzip.open(old / "h.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": [{"ph": "M", "name": "stale"}]}, f)
    t = attrib.synthetic_demo_trace()
    half = len(t["traceEvents"]) // 2
    with gzip.open(new / "a.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": t["traceEvents"][:half]}, f)
    with gzip.open(new / "b.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": t["traceEvents"][half:]}, f)
    merged = attrib.load_trace(str(tmp_path))
    assert len(merged["traceEvents"]) == len(t["traceEvents"])
    assert not any(e.get("name") == "stale" for e in merged["traceEvents"])
    with pytest.raises(attrib.AttribError):
        attrib.load_trace(str(tmp_path / "plugins"))  # no dumps below here


# ---------------------------------------------------------------------------
# scope matching + interval arithmetic
# ---------------------------------------------------------------------------

def test_scope_chain_orders_by_text_position():
    ev = {"name": "fusion.3", "args": {"long_name":
          "jit(f)/sampler/model/flash_attention/fwd/flash_fwd"}}
    assert attrib.scope_chain(ev) == ("sampler/model", "flash_attention/fwd")
    # bare op: the scope path is the event name itself
    assert attrib.scope_chain(
        {"name": "jit(f)/sampler/cached_step/select_n"}) == (
        "sampler/cached_step",)
    assert attrib.scope_chain({"name": "copy.1"}) == ()


def test_merged_busy_overlap_union():
    # [0,100] ∪ [50,150] ∪ [200,250] → 200µs busy over two merged spans
    busy, merged = attrib._merged_busy([(0, 100), (50, 150), (200, 250)])
    assert busy == pytest.approx(200e-6)
    assert merged == [[0, 150], [200, 250]]
    assert attrib._merged_busy([]) == (0.0, [])


def _crafted(events):
    meta = [{"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}}]
    return {"traceEvents": meta + events}


def test_busy_idle_split_arithmetic():
    t = _crafted([
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "jit(f)/sampler/model/dot"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 50, "dur": 100,
         "name": "jit(f)/sampler/model/dot2"},  # overlaps: no double count
        {"ph": "X", "pid": 1, "tid": 1, "ts": 200, "dur": 50,
         "name": "copy.1"},  # busy but unattributed
    ])
    rep = attrib.attribute(t)
    assert rep["device_lanes"] == 1
    assert rep["window_s"] == pytest.approx(250e-6)
    assert rep["device_busy_s"] == pytest.approx(200e-6)
    assert rep["idle_s"] == pytest.approx(50e-6)
    assert rep["busy_fraction"] == pytest.approx(0.8)
    assert rep["coverage"] == pytest.approx(150e-6 / 200e-6)
    node = rep["scopes"]["sampler/model"]
    assert node["events"] == 2
    assert node["self_s"] == pytest.approx(200e-6)  # per-event durations sum


def test_lane_selection_ignores_hosts_and_module_lanes():
    """The demo trace carries a /host:CPU shadow lane with identical
    timings; a second device lane with no scope names (the XLA Modules
    plane) must lose to the op lane rather than double busy time."""
    t = attrib.synthetic_demo_trace()
    t["traceEvents"].append({"ph": "M", "pid": 1, "tid": 7,
                             "name": "thread_name",
                             "args": {"name": "XLA Modules"}})
    t["traceEvents"] += [{"ph": "X", "pid": 1, "tid": 7, "ts": 1000,
                          "dur": 4000, "name": "jit(ddim_sample)"}]
    rep = attrib.attribute(t)
    assert rep["device_lanes"] == 1
    base = attrib.attribute(attrib.synthetic_demo_trace())
    assert rep["device_busy_s"] == base["device_busy_s"]


def test_scope_tree_reconstruction():
    rep = attrib.demo_report()
    assert rep["tree"] == {"sampler/model":
                           ["dequant_matmul/pallas", "flash_attention/fwd"]}
    model = rep["scopes"]["sampler/model"]
    # inclusive total covers the nested flash + dequant events too
    assert model["total_s"] > model["self_s"]
    flash = rep["scopes"]["flash_attention/fwd"]
    assert flash["total_s"] == pytest.approx(flash["self_s"])


# ---------------------------------------------------------------------------
# flops join + coverage + fusion
# ---------------------------------------------------------------------------

def test_flops_join_both_roofline_branches():
    rep = attrib.demo_report()
    ridge = flops_util.ridge_flops_per_byte(attrib.DEMO_DEVICE_KIND)
    assert rep["ridge_flops_per_byte"] == pytest.approx(ridge, abs=0.1)
    flash = rep["scopes"]["flash_attention/fwd"]
    assert flash["flops_per_byte"] >= ridge
    assert flash["roofline"] == "compute-bound"
    model = rep["scopes"]["sampler/model"]
    assert model["flops_per_byte"] < ridge
    assert model["roofline"] == "hbm-bound"
    # demo MFU lands in the measured sampler range (PERF.md ~0.03–0.09)
    assert 0.03 <= model["mfu"] <= 0.09
    assert model["achieved_tflops"] == pytest.approx(
        model["flops"] / model["total_s"] / 1e12, rel=1e-3)
    # zero-flop comms scope: defined, not a divide-by-zero
    a2a = rep["scopes"]["sp/all_to_all_gather"]
    assert a2a["mfu"] == 0.0 and a2a["roofline"] == "hbm-bound"


def test_unknown_device_degrades_to_time_only():
    rep = attrib.attribute(attrib.synthetic_demo_trace(),
                           scope_costs=attrib.demo_scope_costs())
    assert rep["peak_bf16_tflops"] is None
    assert rep["ridge_flops_per_byte"] is None
    model = rep["scopes"]["sampler/model"]
    assert model["mfu"] is None and model["roofline"] is None
    assert model["achieved_tflops"] is not None  # flops need no peak


def test_coverage_meets_floor_and_drops_without_scopes():
    rep = attrib.demo_report()
    assert rep["coverage"] >= attrib.COVERAGE_FLOOR
    stripped = attrib.synthetic_demo_trace()
    for ev in stripped["traceEvents"]:
        ev.pop("args", None) if ev.get("ph") == "X" else None
    bare = attrib.attribute(stripped)
    assert (bare["coverage"] or 0.0) < attrib.COVERAGE_FLOOR
    assert bare["device_busy_s"] == rep["device_busy_s"]  # busy is scope-free


def test_fusion_candidates_ranked_and_gap_gated():
    rep = attrib.demo_report()
    cands = rep["fusion_candidates"]
    assert cands, "demo timeline has 5µs launch gaps — candidates expected"
    gaps = [c["total_gap_us"] for c in cands]
    assert gaps == sorted(gaps, reverse=True)
    top = cands[0]
    assert top["count"] == attrib._DEMO_STEPS
    assert top["mean_gap_us"] == pytest.approx(attrib._DEMO_GAP_US)
    # combined busy counts BOTH ops of the pair
    assert top["combined_busy_us"] > top["total_gap_us"]
    # a ceiling under the demo's launch gap empties the list
    assert attrib.demo_report(gap_us=1.0)["fusion_candidates"] == []


def test_ranked_scopes_slowest_first():
    rep = attrib.demo_report()
    ranked = attrib.ranked_scopes(rep)
    selfs = [node["self_s"] for _, node in ranked]
    assert selfs == sorted(selfs, reverse=True)
    assert ranked[0][0] == "sampler/model"


def test_registered_scopes_pinned_to_tree_call_sites():
    """Every registry entry is a literal profiling.scope(...) call in the
    tree — renaming a planted scope without updating the registry (or vice
    versa) breaks attribution silently otherwise."""
    pat = re.compile(r'profiling\.scope\("([^"]+)"\)')
    planted = set()
    for sub in ("ops", "parallel"):
        root = os.path.join(REPO, "ddim_cold_tpu", sub)
        for dirpath, _, names in os.walk(root):
            for n in names:
                if n.endswith(".py"):
                    with open(os.path.join(dirpath, n)) as f:
                        planted |= set(pat.findall(f.read()))
    assert set(attrib.REGISTERED_SCOPES) == planted


def test_vit_scope_costs_shape():
    costs = flops_util.vit_scope_costs(flash=True, quant=True)
    assert {"sampler/model", "flash_attention/fwd",
            "dequant_matmul/pallas"} <= set(costs)
    for c in costs.values():
        assert c["flops"] >= 0 and c["bytes"] > 0
    # nested scopes cost no more than the inclusive model forward
    assert costs["flash_attention/fwd"]["flops"] <= \
        costs["sampler/model"]["flops"]
    assert flops_util.vit_scope_costs().keys() == {"sampler/model"}


# ---------------------------------------------------------------------------
# trend: wrapper unwrapping + series loading
# ---------------------------------------------------------------------------

def test_unwrap_wrapper_variants():
    rec = {"value": 1.0, "chip": "TPU v5 lite"}
    assert trend.unwrap({"cmd": "x", "rc": 0, "tail": "noise",
                         "parsed": rec}) == (rec, None)
    tail = "log line\n" + json.dumps(rec) + "\n"
    got, note = trend.unwrap({"cmd": "x", "rc": 0, "tail": tail})
    assert got == rec and note is None
    got, note = trend.unwrap({"cmd": "x", "rc": 0,
                              "tail": 'truncated..."mfu": 0.05}'})
    assert got is None and "truncated" in note
    assert trend.unwrap(rec) == (rec, None)  # non-wrapper passthrough


def test_load_record_error_paths(tmp_path):
    garbage = tmp_path / "BENCH_r01.json"
    garbage.write_text("definitely { not json")
    with pytest.raises(trend.TrendError):
        trend.load_record(str(garbage))
    with pytest.raises(trend.TrendError):
        trend.load_record(str(tmp_path / "absent.json"))
    jsonl = tmp_path / "BENCH_r02.json"
    jsonl.write_text('junk\n{"value": 1}\n{"value": 2}\n')
    assert trend.load_record(str(jsonl)) == ({"value": 2}, None)


def _bench(tmp_path, rnd, value, ts=None, chip="TPU v5 lite", wrap=True):
    rec = {"value": value, "mfu": round(value / 80000, 4), "chip": chip}
    if ts is not None:
        rec["run_meta"] = {"timestamp": ts}
    obj = {"cmd": "bench", "rc": 0, "tail": json.dumps(rec) + "\n",
           "parsed": rec} if wrap else rec
    p = tmp_path / f"BENCH_r{rnd:02d}.json"
    p.write_text(json.dumps(obj))
    return str(p)


def test_series_orders_by_run_meta_timestamp(tmp_path):
    # filenames say r01 < r02, stamps say the opposite — stamps win
    _bench(tmp_path, 1, 4000, ts=200.0)
    _bench(tmp_path, 2, 3000, ts=100.0)
    pts = trend.load_series(str(tmp_path / "BENCH_r*.json"))
    assert [pt.record["value"] for pt in pts] == [3000, 4000]
    # an unstamped point anywhere → the whole series falls back to rounds
    _bench(tmp_path, 3, 5000)
    pts = trend.load_series(str(tmp_path / "BENCH_r*.json"))
    assert [pt.round for pt in pts] == [1, 2, 3]


def test_truncated_wrapper_is_skipped_point_not_crash(tmp_path):
    _bench(tmp_path, 1, 4000)
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"cmd": "bench", "rc": 124, "tail": '"value": 3980}'}))
    pts = trend.load_series(str(tmp_path / "BENCH_r*.json"))
    assert pts[1].record is None and "truncated" in pts[1].note
    res = trend.check(pts, "value", "higher")
    assert res["points"] == 1  # the skipped point never enters the series
    assert res["status"] == "first_run"


# ---------------------------------------------------------------------------
# trend: noise bands + the gate
# ---------------------------------------------------------------------------

def test_noise_band_maths():
    assert trend.noise_band([]) == trend.REL_FLOOR
    assert trend.noise_band([100.0]) == trend.REL_FLOOR
    # deltas 0.2 and ~0.1667 → median 0.1833, band = 3× that
    band = trend.noise_band([100.0, 120.0, 100.0])
    assert band == pytest.approx(3.0 * 0.5 * (0.2 + 20 / 120), rel=1e-6)
    # tight series floors out
    assert trend.noise_band([100.0, 101.0, 100.5]) == trend.REL_FLOOR


def _pts(tmp_path):
    return trend.load_series(str(tmp_path / "BENCH_r*.json"))


def test_gate_first_run_missing_and_in_band(tmp_path):
    _bench(tmp_path, 1, 4000)
    assert trend.check(_pts(tmp_path), "value")["status"] == "first_run"
    _bench(tmp_path, 2, 3900)  # −2.5%: inside the 10% floor
    res = trend.check(_pts(tmp_path), "value")
    assert res["status"] == "ok"
    assert res["delta_rel"] == pytest.approx(-0.025)
    missing = trend.check(_pts(tmp_path), "submetrics.absent.value")
    assert missing["status"] == "missing"
    # higher-is-better: a +40% jump is not a regression
    _bench(tmp_path, 3, 5600)
    assert trend.check(_pts(tmp_path), "value")["status"] == "ok"


def test_gate_flags_injected_regression(tmp_path):
    _bench(tmp_path, 1, 4000)
    _bench(tmp_path, 2, 4100)
    _bench(tmp_path, 3, 2000)  # −51% vs median 4050: beyond any band
    res = trend.check(_pts(tmp_path), "value")
    assert res["status"] == "regression"
    report = trend.gate(str(tmp_path))
    assert report["exit_code"] == 1
    assert report["statuses"]["regression"] >= 1
    assert trend.main(["--root", str(tmp_path)]) == 1


def test_gate_ignores_cpu_fallback_records(tmp_path):
    _bench(tmp_path, 1, 4000)
    _bench(tmp_path, 2, 100, chip="cpu (fallback)")  # r02-style outage
    res = trend.check(_pts(tmp_path), "value")
    assert res["status"] == "first_run"  # CPU point filtered, one remains


def test_multichip_checks_rc_and_ok(tmp_path):
    p = tmp_path / "MULTICHIP_r01.json"
    p.write_text(json.dumps({"n_devices": 4, "rc": 0, "ok": True,
                             "tail": ""}))
    report = trend.gate(str(tmp_path))
    assert report["exit_code"] == 0
    p.write_text(json.dumps({"n_devices": 4, "rc": 1, "ok": False,
                             "tail": ""}))
    report = trend.gate(str(tmp_path))
    assert report["exit_code"] == 1


def test_gate_green_on_committed_series():
    """The acceptance bar: the repo's own BENCH_r01..r05 / MULTICHIP series
    passes — r05's truncated tail is a skipped point, not a failure."""
    report = trend.gate(REPO)
    assert report["exit_code"] == 0
    assert report["bench_points"] >= 5
    assert report["multichip_points"] >= 1
    assert "regression" not in report["statuses"]
    assert trend.main(["--root", REPO]) == 0


# ---------------------------------------------------------------------------
# series shaping + provenance
# ---------------------------------------------------------------------------

def test_thin_keeps_first_and_last():
    seq = list(range(25))
    out = trend.thin(seq, 10)
    assert len(out) == 10 and out[0] == 0 and out[-1] == 24
    assert out == sorted(out)
    assert trend.thin(seq, 100) == seq
    assert trend.thin(seq, 1) == [0]
    assert trend.thin([], 5) == []


def test_annotate_deltas_lower_is_better():
    rows = [{"ckpt": "random", "fid": 400.0},
            {"ckpt": "epoch_1", "fid": 120.0},
            {"ckpt": "best", "fid": 118.0},
            {"ckpt": "drift", "fid": 250.0}]
    out = trend.annotate_deltas(rows, "fid", lower_is_better=True)
    assert "delta_rel" not in out[0]  # first point has no predecessor
    assert out[1]["in_band"]  # improvement is always in band
    assert out[2]["in_band"]
    assert not out[3]["in_band"]  # +112% FID: out of band, flagged
    assert rows[1].keys() == {"ckpt", "fid"}  # input rows untouched


def test_run_metadata_stamp(monkeypatch):
    monkeypatch.setenv("DDIM_COLD_RUN_TS", "1754400000")
    monkeypatch.setenv("DDIM_COLD_ROUND", "6")
    meta = run_metadata(chip="TPU v5 lite")
    assert meta["timestamp"] == 1754400000.0
    assert meta["round"] == 6
    assert meta["device_kind"] == "TPU v5 lite"
    assert meta["jax"]  # installed in every supported environment
    monkeypatch.delenv("DDIM_COLD_RUN_TS")
    monkeypatch.delenv("DDIM_COLD_ROUND")
    monkeypatch.delenv("SOURCE_DATE_EPOCH", raising=False)
    meta = run_metadata()
    assert meta["timestamp"] is None  # never the wall clock
    assert meta["round"] is None


# ---------------------------------------------------------------------------
# host-only contract (GRAFT-A004) + emit-site lint (GRAFT-A005)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rel", ("ddim_cold_tpu/obs/attrib.py",
                                 "ddim_cold_tpu/obs/trend.py"))
def test_new_modules_registered_host_only_and_clean(rel):
    assert rel in ast_checks.HOST_ONLY_MODULES
    with open(os.path.join(REPO, rel)) as f:
        src = f.read()
    findings = ast_checks.lint_source(src, rel, host_only=True)
    assert [f for f in findings if f.rule == "GRAFT-A004"] == []


def test_attrib_metrics_registered():
    from ddim_cold_tpu.obs import metrics
    names = {m[0] for m in metrics.METRICS}
    assert {"attrib.traces", "attrib.coverage_pct", "attrib.device_busy_s",
            "trend.points", "trend.checks"} <= names
