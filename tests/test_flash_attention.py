"""Flash-attention kernel parity: the Pallas fused path must match the dense
einsum path (the reference semantics, ViT.py:110-114) on both odd and aligned
sequence lengths, under grad, and when slotted into the full model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops.flash_attention import _dense_attention_f32, flash_attention


def _rand_qkv(rng, B, N, H, D):
    ks = jax.random.split(jax.random.PRNGKey(rng), 3)
    return tuple(jax.random.normal(k, (B, N, H, D), jnp.float32) for k in ks)


@pytest.mark.parametrize("N", [8, 257, 320])
def test_flash_matches_dense(N):
    """257 = the OxfordFlower-64 sequence (odd, needs padding); 320 aligned."""
    q, k, v = _rand_qkv(0, 2, N, 4, 16)
    scale = 16**-0.5
    ours = np.asarray(flash_attention(q, k, v, scale))
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(ours, np.asarray(want), rtol=2e-5, atol=2e-6)


def test_flash_block_q_smaller_than_seq():
    """Multiple query blocks per head (block_q < N) tile correctly."""
    q, k, v = _rand_qkv(1, 1, 100, 2, 8)
    ours = np.asarray(flash_attention(q, k, v, 8**-0.5, 32))
    _, want = _dense_attention_f32(q, k, v, 8**-0.5)
    np.testing.assert_allclose(ours, np.asarray(want), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("N,bq,bkv", [(300, 64, 128), (257, 32, 64)])
def test_flash_blocked_kv_matches_dense(N, bq, bkv):
    """K/V streamed in chunks (n_kv > 1): the online-softmax accumulation
    across kv blocks must match the dense softmax, including the masked
    padded tail of the last chunk."""
    q, k, v = _rand_qkv(4, 2, N, 2, 16)
    scale = 16**-0.5
    ours = np.asarray(flash_attention(q, k, v, scale, bq, bkv))
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(ours, np.asarray(want), rtol=2e-5, atol=2e-6)


def test_flash_long_sequence_bounded_vmem():
    """N well past the in-repo maximum (2501): the kernel's VMEM need is set
    by (block_q, block_kv), not N — this shape would not fit a single-pass
    K/V-resident kernel's VMEM on real hardware."""
    q, k, v = _rand_qkv(5, 1, 4096, 1, 8)
    scale = 8**-0.5
    ours = np.asarray(flash_attention(q, k, v, scale, 512, 512))
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(ours, np.asarray(want), rtol=2e-5, atol=2e-6)


def test_flash_bf16_inputs():
    q, k, v = _rand_qkv(2, 1, 64, 2, 8)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, 8**-0.5)
    assert out.dtype == jnp.bfloat16
    _, want = _dense_attention_f32(qb, kb, vb, 8**-0.5)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(want, np.float32), rtol=2e-2, atol=2e-2
    )


def test_flash_gradient_matches_dense():
    """Custom VJP (recompute backward) ≡ autodiff through the einsum path."""
    q, k, v = _rand_qkv(3, 1, 33, 2, 8)
    scale = 8**-0.5

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, scale) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention_f32(q, k, v, scale)[1] ** 2)

    g_ours = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for ours, want in zip(g_ours, g_want):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("N,bq,bkv", [(300, 64, 128), (130, 32, 64)])
def test_flash_gradient_blocked_matches_dense(N, bq, bkv):
    """The Pallas backward (dq kernel + transposed dk/dv kernel) over
    multiple q AND kv chunks, including the masked padded tails, must match
    autodiff through the dense einsum."""
    q, k, v = _rand_qkv(6, 1, N, 2, 16)
    scale = 16**-0.5

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, scale, bq, bkv) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention_f32(q, k, v, scale)[1] ** 2)

    g_ours = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, ours, want in zip("qkv", g_ours, g_want):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want),
                                   rtol=1e-4, atol=1e-5, err_msg=f"d{name}")


def test_flash_gradient_north_star_shape_matches_dense():
    """The Pallas BACKWARD at the exact north-star shape — N=2501 tokens
    (200px, patch 4, +1 time token), H=4, D=64, production default blocks —
    against autodiff through the dense einsum (VERDICT r4 item 9: forward
    was exercised at this length, the 200px training stage runs the
    backward, and Mosaic has rejected this kernel family on hardware once;
    interpret mode proves the math, the tile-rule guard below covers the
    lowering constraints)."""
    q, k, v = _rand_qkv(13, 1, 2501, 4, 64)
    scale = 64**-0.5

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, scale) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention_f32(q, k, v, scale)[1] ** 2)

    g_ours = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, ours, want in zip("qkv", g_ours, g_want):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")


def test_flash_bf16_gradient_north_star_shape_matches_dense():
    """The Pallas BACKWARD on bf16 inputs at the north-star shape (N=2501,
    H=4, D=64, tuned NS_FLASH_BLOCKS) — against autodiff through the dense
    f32 oracle on the same bf16 inputs. The 200px training stage runs this
    exact backward in bf16, and the bf16-gemm-v2 kernel routes its backward
    GEMMs through the input dtype — a path the f32 gradient tests above
    never touch (ADVICE r5 item 1: the bf16 backward GEMM path had zero
    numerics coverage). Tolerances follow the bf16 forward tests (~2e-2):
    the comparison isolates kernel-vs-einsum error on identical bf16
    operands, not bf16-vs-f32 rounding."""
    from bench import NS_FLASH_BLOCKS

    q32, k32, v32 = _rand_qkv(19, 1, 2501, 4, 64)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))
    scale = 64**-0.5

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, scale, *NS_FLASH_BLOCKS)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_dense(q, k, v):
        out = _dense_attention_f32(q, k, v, scale)[1]
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_ours = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, ours, want in zip("qkv", g_ours, g_want):
        assert ours.dtype == jnp.bfloat16, f"d{name} dtype {ours.dtype}"
        np.testing.assert_allclose(np.asarray(ours, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=2e-2, atol=2e-2, err_msg=f"d{name}")


def test_flash_bf16_north_star_headline_config_matches_dense():
    """The EXACT path bench_v2 measures on chip: bf16 inputs, N=2501, H=4,
    D=64, the tuned NS_FLASH_BLOCKS single-chunk config — against the dense
    f32 oracle on the same bf16 inputs. The bf16-gemm-v2 kernel runs its
    GEMMs in bf16 here (input dtype), so this pins the numerics of the
    production sampler configuration, not just the f32 test shapes."""
    from bench import NS_FLASH_BLOCKS

    q32, k32, v32 = _rand_qkv(17, 1, 2501, 4, 64)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))
    scale = 64**-0.5
    out = flash_attention(q, k, v, scale, *NS_FLASH_BLOCKS)
    assert out.dtype == jnp.bfloat16
    want = _dense_attention_f32(q, k, v, scale)[1]
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_model_use_flash_parity():
    """DiffusionViT(use_flash=True) ≡ the einsum model in eval mode — same
    params tree (flash adds no parameters), same outputs."""
    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2, num_heads=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 3))
    t = jnp.array([3, 500], jnp.int32)
    base = DiffusionViT(**cfg)
    params = base.init(jax.random.PRNGKey(1), x, t)["params"]
    flash = DiffusionViT(use_flash=True, **cfg)
    out_base = base.apply({"params": params}, x, t)
    out_flash = flash.apply({"params": params}, x, t)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_base),
                               rtol=2e-4, atol=2e-5)


def test_model_flash_blocks_tuning_matches_default():
    """flash_blocks threads model → Attention → kernel and changes only the
    schedule, never the numbers — including a block_kv far past N (clamped
    inside the kernel to the padded sequence: fully VMEM-resident K/V)."""
    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2, num_heads=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 3))
    t = jnp.array([3, 500], jnp.int32)
    base = DiffusionViT(use_flash=True, **cfg)
    params = base.init(jax.random.PRNGKey(1), x, t)["params"]
    want = np.asarray(base.apply({"params": params}, x, t))
    for blocks in ((8, 8), (16, 4096)):
        tuned = DiffusionViT(use_flash=True, flash_blocks=blocks, **cfg)
        got = np.asarray(tuned.apply({"params": params}, x, t))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_model_remat_flash_gradients_match():
    """remat (jax.checkpoint per block) composed with the flash custom-VJP:
    the memory-tight 200px training combination. Gradients must equal the
    non-remat flash model's — recompute may not perturb the custom backward."""
    import jax.numpy as jnp

    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2,
               num_heads=4, drop_rate=0.0, attn_drop_rate=0.0,
               drop_path_rate=0.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 3))
    t = jnp.array([3, 500], jnp.int32)
    base = DiffusionViT(use_flash=True, **cfg)
    params = base.init(jax.random.PRNGKey(1), x, t)["params"]
    rem = DiffusionViT(use_flash=True, remat=True, **cfg)

    def loss(model, p):
        return jnp.sum(model.apply({"params": p}, x, t) ** 2)

    g_base = jax.grad(lambda p: loss(base, p))(params)
    g_rem = jax.grad(lambda p: loss(rem, p))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6),
        g_base, g_rem)


def test_model_attention_probe_still_works_with_flash():
    """return_attention_layer forces the weights-producing path even when
    use_flash is on (the kernel never materializes attention weights)."""
    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2, num_heads=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 16, 3))
    t = jnp.zeros((1,), jnp.int32)
    model = DiffusionViT(use_flash=True, **cfg)
    params = model.init(jax.random.PRNGKey(1), x, t)["params"]
    attn = model.apply({"params": params}, x, t, return_attention_layer=1)
    assert attn.shape == (1, 4, 17, 17)
    s = np.asarray(jnp.sum(attn, axis=-1))
    np.testing.assert_allclose(s, np.ones_like(s), rtol=1e-5)


def _tile_rule_spy(monkeypatch, fa):
    """Install a pallas_call spy asserting every BlockSpec satisfies the TPU
    tile rule — dtype-aware sublane unit (f32 8, bf16 16, int8 32) × lane
    128, each dim exempt when the block spans the whole array dim — against
    the real call arguments; returns the call-name list for count
    assertions. CPU interpret mode does not enforce the rule, so this spy is
    what stands between a green CI and a Mosaic rejection on chip."""
    from jax.experimental import pallas as pl

    from ddim_cold_tpu.ops import tiling

    def check(block, arr, dtype, ctx):
        assert len(block) == len(arr.shape), (ctx, block, arr.shape)
        if len(block) < 2:
            return
        (bs, bl), (asub, alane) = block[-2:], arr.shape[-2:]
        unit = tiling.sublane_unit(dtype)
        assert bs % unit == 0 or bs == asub, (ctx, block, arr.shape, unit)
        assert bl % 128 == 0 or bl == alane, (ctx, block, arr.shape)

    real = pl.pallas_call
    calls = []

    def spy(kernel, **kw):
        inner = real(kernel, **kw)

        def wrapper(*ops):
            name = getattr(kernel, "func", kernel).__name__
            calls.append(name)
            in_specs = kw["in_specs"]
            for i, (spec, op) in enumerate(zip(in_specs, ops)):
                check(spec.block_shape, op, op.dtype, f"{name} in[{i}]")
            outs = kw["out_shape"]
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            specs = kw["out_specs"]
            specs = specs if isinstance(specs, (list, tuple)) else [specs]
            for i, (spec, o) in enumerate(zip(specs, outs)):
                check(spec.block_shape, o, o.dtype, f"{name} out[{i}]")
            return inner(*ops)

        return wrapper

    monkeypatch.setattr(fa.pl, "pallas_call", spy)
    return calls


def test_block_sweep_configs_satisfy_tpu_tile_rule(monkeypatch):
    """The bench's --flash-block-sweep configs at the exact 200px shape
    (N=2501) must pass the same tile rule — a sweep entry that Mosaic
    rejects on chip would burn its slot in the one hardware window."""
    from ddim_cold_tpu.ops import flash_attention as fa

    from bench import FLASH_BLOCK_SWEEP

    calls = _tile_rule_spy(monkeypatch, fa)
    q, k, v = _rand_qkv(11, 1, 2501, 1, 64)  # 1 head: forward-only sweep
    for bq, bkv in FLASH_BLOCK_SWEEP:
        out = flash_attention(q, k, v, 64**-0.5, bq, bkv)
        assert np.isfinite(np.asarray(out)).all(), (bq, bkv)
    assert calls.count("_fwd_kernel") == len(FLASH_BLOCK_SWEEP), calls
    assert len(calls) == len(FLASH_BLOCK_SWEEP), calls


def test_block_specs_satisfy_tpu_tile_rule(monkeypatch):
    """Every BlockSpec the kernels build must satisfy Mosaic's TPU tiling
    rule: the last two dims of a block are divisible by (8, 128) or equal
    the array's. CPU interpret mode never enforces this, which let a
    (1, bq) lse row block ship and fail to compile on real hardware at the
    200px config (N=2501, BH=64) — this guard reproduces the check the TPU
    lowering applies, against the real pallas_call arguments."""
    from ddim_cold_tpu.ops import flash_attention as fa

    calls = _tile_rule_spy(monkeypatch, fa)
    # 65 = vit_tiny, 257 = oxford_flower_64, 2501 = the 200px north-star
    # shape that failed on hardware (keep it last: largest)
    for N, H, D in ((65, 12, 32), (257, 4, 64), (2501, 4, 64)):
        q, k, v = _rand_qkv(7, 1, N, H, D)
        scale = D**-0.5
        out = flash_attention(q, k, v, scale)
        assert np.isfinite(np.asarray(out)).all()
        g = jax.grad(lambda q: flash_attention(q, k, v, scale).sum())(q)
        assert np.isfinite(np.asarray(g)).all()
    # per shape: primal fwd + vjp fwd + dq + dkv
    assert calls.count("_fwd_kernel") == 6 and len(calls) == 12, calls


def test_odd_requested_blocks_legalized_at_200px(monkeypatch):
    """Regression for the 200px tile-legality bug: a hand-tuned block size
    that doesn't divide the dtype's tile unit (say 300, or N itself at
    N=2501) used to flow straight into the BlockSpecs via ``min(block, N)``
    — silently fine under CPU interpret, a Mosaic reject on chip. Every
    request must now be legalized (ops/tiling.legal_block), forward and
    backward, f32 and bf16, at both 200px token counts (p4 N=2501,
    p8 N=626)."""
    from ddim_cold_tpu.ops import flash_attention as fa

    calls = _tile_rule_spy(monkeypatch, fa)
    cases = [(2501, jnp.float32, 300, 500), (2501, jnp.float32, 2501, 2501),
             (626, jnp.bfloat16, 100, 104), (626, jnp.bfloat16, 8, 632)]
    for N, dtype, bq, bkv in cases:
        q, k, v = (x.astype(dtype) for x in _rand_qkv(13, 1, N, 1, 64))
        scale = 64**-0.5
        out = fa.flash_attention(q, k, v, scale, bq, bkv)
        assert np.isfinite(np.asarray(out, np.float32)).all(), (N, bq, bkv)
        g = jax.grad(lambda q: fa.flash_attention(
            q, k, v, scale, bq, bkv).astype(jnp.float32).sum())(q)
        assert np.isfinite(np.asarray(g, np.float32)).all(), (N, bq, bkv)
    assert calls.count("_fwd_kernel") == 2 * len(cases), calls


def test_legal_block_policy():
    """The pad-or-clamp helper itself (pure host arithmetic)."""
    from ddim_cold_tpu.ops import tiling

    assert tiling.legal_block(256, 2504, jnp.float32) == 256
    assert tiling.legal_block(300, 2504, jnp.float32) == 304   # round up
    assert tiling.legal_block(300, 2504, jnp.bfloat16) == 304  # 304 % 16 == 0
    assert tiling.legal_block(100, 2504, jnp.bfloat16) == 112
    assert tiling.legal_block(4096, 626, jnp.bfloat16) == 640  # clamp to dim⁺
    assert tiling.legal_block(8, 2504, jnp.bfloat16) == 16     # sub-unit
    assert tiling.legal_block(100, 384, jnp.float32, lane=True) == 128
    # K of the dequant matmul: lane for the activation AND int8 sublane
    assert tiling.legal_block(100, 384, jnp.bfloat16, lane=True,
                              min_unit=32) == 128
    assert tiling.sublane_unit(jnp.float32) == 8
    assert tiling.sublane_unit(jnp.bfloat16) == 16
    assert tiling.sublane_unit(jnp.int8) == 32
    with pytest.raises(ValueError):
        tiling.legal_block(0, 64, jnp.float32)
    with pytest.raises(ValueError):
        tiling.sublane_unit(jnp.float64)


def _sub_jaxprs(val):
    """Jaxpr-valued payloads inside an eqn param (Jaxpr, ClosedJaxpr, lists)."""
    if hasattr(val, "eqns"):
        return [val]
    if hasattr(val, "jaxpr"):
        return [val.jaxpr]
    if isinstance(val, (list, tuple)):
        return [j for item in val for j in _sub_jaxprs(item)]
    return []


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                yield from _iter_eqns(sub)


def _kernel_dot_eqns(jaxpr):
    """dot_general eqns INSIDE pallas_call kernel bodies (the MXU GEMMs)."""
    dots = []
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        inner = eqn.params["jaxpr"]
        inner = getattr(inner, "jaxpr", inner)
        dots += [e for e in _iter_eqns(inner)
                 if e.primitive.name == "dot_general"]
    return dots


@pytest.mark.parametrize("dtype", ["bfloat16", "float32"])
def test_kernel_gemms_run_in_input_dtype_with_f32_accumulation(dtype):
    """CPU guard for the bf16-gemm-v2 contract, on TRACED dtypes: every GEMM
    inside the Pallas kernels (forward AND both backward kernels) must take
    its operands in the model's input dtype — an explicit f32 upcast would
    silently cost ~4× MXU throughput on v5e and double VMEM traffic, which no
    numerics test can see — while accumulating in f32 via
    preferred_element_type (which every parity test above DOES depend on).
    Asserting on the jaxpr pins both halves of the contract on CPU, where the
    perf regression itself is unmeasurable. Keyed to KERNEL_REV so a future
    kernel revision must revisit this contract explicitly rather than
    inheriting a stale guard."""
    from ddim_cold_tpu.ops import flash_attention as fa

    assert fa.KERNEL_REV == "fused-trunk-v3", (
        "kernel revision changed — re-derive the GEMM dtype contract here")

    dt = jnp.dtype(dtype)
    q, k, v = (x.astype(dt) for x in _rand_qkv(23, 1, 64, 2, 8))
    scale = 8**-0.5

    fwd = jax.make_jaxpr(lambda q, k, v: flash_attention(q, k, v, scale))(q, k, v)
    fwd_dots = _kernel_dot_eqns(fwd.jaxpr)
    assert len(fwd_dots) == 2, fwd_dots  # q·kᵀ logits + p·v

    bwd = jax.make_jaxpr(jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, scale).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    # fwd rerun (2) + dq kernel (logits, dp, ds·k) + dkv kernel
    # (logits, pᵀ·do, dp, dsᵀ·q) = 9; ≥ 7 tolerates residual-sharing tweaks
    bwd_dots = _kernel_dot_eqns(bwd.jaxpr)
    assert len(bwd_dots) >= 7, bwd_dots

    for eqn in fwd_dots + bwd_dots:
        pref = eqn.params.get("preferred_element_type")
        assert pref is not None and jnp.dtype(pref) == jnp.float32, eqn
        for invar in eqn.invars:
            assert invar.aval.dtype == dt, (
                f"kernel GEMM operand traced as {invar.aval.dtype}, "
                f"expected input dtype {dt}: {eqn}")


@pytest.mark.parametrize("dtype", ["bfloat16", "float32"])
def test_fused_kernel_gemms_run_in_input_dtype(dtype):
    """fused-trunk-v3 extension of the GEMM dtype guard: every dot inside
    the fused trunk-attention megakernel (qkv dequant producer, logits,
    p·v, proj consumer) and the fused Mlp kernel (x·w1, gelu·w2) takes its
    operands in the ACTIVATION dtype with f32 accumulation — the int8
    weights are upcast to the activation dtype, never to f32."""
    from ddim_cold_tpu.ops import quant
    from ddim_cold_tpu.ops.flash_attention import fused_trunk_attention

    dt = jnp.dtype(dtype)
    C, H, N = 64, 2, 40
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((1, N, C)), dt)
    wq = jnp.asarray(rng.integers(-127, 128, (C, 3 * C)), jnp.int8)
    wp = jnp.asarray(rng.integers(-127, 128, (C, C)), jnp.int8)
    sq = jnp.ones((3 * C,), jnp.float32)
    bq = jnp.zeros((3 * C,), jnp.float32)
    sp = jnp.ones((C,), jnp.float32)
    bp = jnp.zeros((C,), jnp.float32)

    fwd = jax.make_jaxpr(lambda xx: fused_trunk_attention(
        xx, wq, sq, bq, wp, sp, bp, num_heads=H, scale=(C // H) ** -0.5,
        block_q=48, block_kv=48))(x)
    dots = _kernel_dot_eqns(fwd.jaxpr)
    # q projection + kv-chunk projection + proj consumer, plus the unrolled
    # per-head logits and p·v dots
    assert len(dots) == 3 + 2 * H, dots
    for eqn in dots:
        pref = eqn.params.get("preferred_element_type")
        assert pref is not None and jnp.dtype(pref) == jnp.float32, eqn
        for invar in eqn.invars:
            assert invar.aval.dtype == dt, (
                f"fused kernel GEMM operand traced as {invar.aval.dtype}, "
                f"expected input dtype {dt}: {eqn}")

    x2 = jnp.asarray(rng.standard_normal((N, C)), dt)
    w1 = jnp.asarray(rng.integers(-127, 128, (C, C)), jnp.int8)
    mlp = jax.make_jaxpr(lambda xx: quant.mlp_pallas(
        xx, w1, bp, w1, bp, scale1=sp, scale2=sp, mode="pallas",
        block_m=48))(x2)
    mdots = _kernel_dot_eqns(mlp.jaxpr)
    assert len(mdots) == 2, mdots  # x·w1, gelu(h)·w2
    for eqn in mdots:
        pref = eqn.params.get("preferred_element_type")
        assert pref is not None and jnp.dtype(pref) == jnp.float32, eqn
        for invar in eqn.invars:
            assert invar.aval.dtype == dt, eqn


def test_fused_kernel_w8a8_gemms_hit_int8_path():
    """w8a8: the two weight-side GEMMs in each fused kernel run int8×int8
    with int32 accumulation (requantized activations); the attention's
    logits/p·v dots stay in the f32 compute dtype."""
    from ddim_cold_tpu.ops import quant
    from ddim_cold_tpu.ops.flash_attention import fused_trunk_attention

    C, H, N = 64, 2, 40
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((1, N, C)), jnp.float32)
    wq = jnp.asarray(rng.integers(-127, 128, (C, 3 * C)), jnp.int8)
    wp = jnp.asarray(rng.integers(-127, 128, (C, C)), jnp.int8)
    sq = jnp.ones((3 * C,), jnp.float32)
    bq = jnp.zeros((3 * C,), jnp.float32)
    sp = jnp.ones((C,), jnp.float32)
    bp = jnp.zeros((C,), jnp.float32)

    fwd = jax.make_jaxpr(lambda xx: fused_trunk_attention(
        xx, wq, sq, bq, wp, sp, bp, num_heads=H, scale=(C // H) ** -0.5,
        block_q=48, block_kv=48, mode="w8a8"))(x)
    dots = _kernel_dot_eqns(fwd.jaxpr)
    assert len(dots) == 3 + 2 * H, dots
    int8_dots = [e for e in dots
                 if all(v.aval.dtype == jnp.int8 for v in e.invars)]
    assert len(int8_dots) == 3, dots  # q + kv producers, proj consumer
    for eqn in int8_dots:
        assert jnp.dtype(eqn.params["preferred_element_type"]) == jnp.int32

    x2 = jnp.asarray(rng.standard_normal((N, C)), jnp.float32)
    w1 = jnp.asarray(rng.integers(-127, 128, (C, C)), jnp.int8)
    mlp = jax.make_jaxpr(lambda xx: quant.mlp_pallas(
        xx, w1, bp, w1, bp, scale1=sp, scale2=sp, mode="w8a8",
        block_m=48))(x2)
    mdots = _kernel_dot_eqns(mlp.jaxpr)
    assert len(mdots) == 2, mdots
    for eqn in mdots:
        assert all(v.aval.dtype == jnp.int8 for v in eqn.invars), eqn
        assert jnp.dtype(eqn.params["preferred_element_type"]) == jnp.int32


from ddim_cold_tpu.ops.flash_attention import blockwise_attention_xla  # noqa: E402


@pytest.mark.parametrize("N,bkv", [(8, 512), (257, 64), (300, 128)])
def test_blockwise_xla_matches_dense(N, bkv):
    """The pure-XLA blockwise path (the Mosaic-free safety net) must match
    dense softmax attention, including odd N with a masked padded tail."""
    q, k, v = _rand_qkv(8, 2, N, 4, 16)
    scale = 16**-0.5
    ours = np.asarray(blockwise_attention_xla(q, k, v, scale, bkv))
    _, want = _dense_attention_f32(q, k, v, scale)
    np.testing.assert_allclose(ours, np.asarray(want), rtol=2e-5, atol=2e-6)


def test_model_use_flash_xla_parity():
    """DiffusionViT(use_flash='xla') ≡ the einsum model in eval mode, and the
    YAML surface parses the string (false/true/'xla')."""
    import jax.numpy as jnp

    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2, num_heads=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 3))
    t = jnp.array([3, 500], jnp.int32)
    base = DiffusionViT(**cfg)
    params = base.init(jax.random.PRNGKey(1), x, t)["params"]
    xla = DiffusionViT(use_flash="xla", **cfg)
    np.testing.assert_allclose(
        np.asarray(xla.apply({"params": params}, x, t)),
        np.asarray(base.apply({"params": params}, x, t)),
        rtol=2e-4, atol=2e-5)

    from ddim_cold_tpu.config import _check_use_flash

    assert _check_use_flash("xla") == "xla"
    assert _check_use_flash(True) is True
    assert _check_use_flash("pallas") is True
    assert _check_use_flash(False) is False
    with pytest.raises(ValueError, match="use_flash"):
        _check_use_flash("fast")
