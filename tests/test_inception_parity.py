"""InceptionV3 converter validated against a REAL torch forward pass.

The bench host has no network and no torchvision, so the pretrained
``inception_v3`` weights cannot be fetched; what CAN be validated offline is
everything the converter and the Flax architecture are responsible for: conv
padding/stride conventions, BatchNorm eval semantics (eps=1e-3, running
stats), the count_include_pad avg-pool, branch concatenation order, and the
(O,I,kh,kw) → (kh,kw,I,O) layout transform. This file builds a torch replica
of torchvision's inception_v3 feature path — module names and structure
verbatim from the torchvision source so its ``state_dict()`` keys are
byte-identical to the real checkpoint's — randomizes its weights AND running
stats, exports the state_dict through ``flax_from_torch_inception``, and
asserts feature parity torch-vs-Flax. With this green, loading the actual
pretrained ``.pth`` is pure data movement.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from ddim_cold_tpu.eval.inception import (  # noqa: E402
    InceptionV3Features, flax_from_torch_inception,
)


# --- torch replica of torchvision.models.inception (feature path only) -----

class TBasicConv2d(nn.Module):
    def __init__(self, cin, cout, **kw):
        super().__init__()
        self.conv = nn.Conv2d(cin, cout, bias=False, **kw)
        self.bn = nn.BatchNorm2d(cout, eps=0.001)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


def _avg(x):
    return F.avg_pool2d(x, kernel_size=3, stride=1, padding=1)


class TInceptionA(nn.Module):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.branch1x1 = TBasicConv2d(cin, 64, kernel_size=1)
        self.branch5x5_1 = TBasicConv2d(cin, 48, kernel_size=1)
        self.branch5x5_2 = TBasicConv2d(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = TBasicConv2d(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = TBasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = TBasicConv2d(96, 96, kernel_size=3, padding=1)
        self.branch_pool = TBasicConv2d(cin, pool_features, kernel_size=1)

    def forward(self, x):
        b3 = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        return torch.cat([
            self.branch1x1(x), self.branch5x5_2(self.branch5x5_1(x)), b3,
            self.branch_pool(_avg(x))], 1)


class TInceptionB(nn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3 = TBasicConv2d(cin, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = TBasicConv2d(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = TBasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = TBasicConv2d(96, 96, kernel_size=3, stride=2)

    def forward(self, x):
        bd = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        return torch.cat([self.branch3x3(x), bd,
                          F.max_pool2d(x, kernel_size=3, stride=2)], 1)


class TInceptionC(nn.Module):
    def __init__(self, cin, c7):
        super().__init__()
        self.branch1x1 = TBasicConv2d(cin, 192, kernel_size=1)
        self.branch7x7_1 = TBasicConv2d(cin, c7, kernel_size=1)
        self.branch7x7_2 = TBasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7_3 = TBasicConv2d(c7, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = TBasicConv2d(cin, c7, kernel_size=1)
        self.branch7x7dbl_2 = TBasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = TBasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = TBasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = TBasicConv2d(c7, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch_pool = TBasicConv2d(cin, 192, kernel_size=1)

    def forward(self, x):
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        bd = self.branch7x7dbl_5(self.branch7x7dbl_4(self.branch7x7dbl_3(
            self.branch7x7dbl_2(self.branch7x7dbl_1(x)))))
        return torch.cat([self.branch1x1(x), b7, bd,
                          self.branch_pool(_avg(x))], 1)


class TInceptionD(nn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3_1 = TBasicConv2d(cin, 192, kernel_size=1)
        self.branch3x3_2 = TBasicConv2d(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = TBasicConv2d(cin, 192, kernel_size=1)
        self.branch7x7x3_2 = TBasicConv2d(192, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7x3_3 = TBasicConv2d(192, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7x3_4 = TBasicConv2d(192, 192, kernel_size=3, stride=2)

    def forward(self, x):
        b3 = self.branch3x3_2(self.branch3x3_1(x))
        b7 = self.branch7x7x3_4(self.branch7x7x3_3(
            self.branch7x7x3_2(self.branch7x7x3_1(x))))
        return torch.cat([b3, b7, F.max_pool2d(x, kernel_size=3, stride=2)], 1)


class TInceptionE(nn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch1x1 = TBasicConv2d(cin, 320, kernel_size=1)
        self.branch3x3_1 = TBasicConv2d(cin, 384, kernel_size=1)
        self.branch3x3_2a = TBasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3_2b = TBasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = TBasicConv2d(cin, 448, kernel_size=1)
        self.branch3x3dbl_2 = TBasicConv2d(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = TBasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = TBasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch_pool = TBasicConv2d(cin, 192, kernel_size=1)

    def forward(self, x):
        b3 = self.branch3x3_1(x)
        b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], 1)
        bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        bd = torch.cat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)], 1)
        return torch.cat([self.branch1x1(x), b3, bd,
                          self.branch_pool(_avg(x))], 1)


class TorchInceptionFeatures(nn.Module):
    """torchvision inception_v3 through pool3 (aux head / fc omitted)."""

    def __init__(self):
        super().__init__()
        self.Conv2d_1a_3x3 = TBasicConv2d(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = TBasicConv2d(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = TBasicConv2d(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = TBasicConv2d(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = TBasicConv2d(80, 192, kernel_size=3)
        self.Mixed_5b = TInceptionA(192, 32)
        self.Mixed_5c = TInceptionA(256, 64)
        self.Mixed_5d = TInceptionA(288, 64)
        self.Mixed_6a = TInceptionB(288)
        self.Mixed_6b = TInceptionC(768, 128)
        self.Mixed_6c = TInceptionC(768, 160)
        self.Mixed_6d = TInceptionC(768, 160)
        self.Mixed_6e = TInceptionC(768, 192)
        self.Mixed_7a = TInceptionD(768)
        self.Mixed_7b = TInceptionE(1280)
        self.Mixed_7c = TInceptionE(2048)

    def forward(self, x, taps=None):
        out = {}
        x = self.Conv2d_1a_3x3(x); out["1a"] = x
        x = self.Conv2d_2a_3x3(x)
        x = self.Conv2d_2b_3x3(x); out["2b"] = x
        x = F.max_pool2d(x, kernel_size=3, stride=2)
        x = self.Conv2d_3b_1x1(x)
        x = self.Conv2d_4a_3x3(x); out["4a"] = x
        x = F.max_pool2d(x, kernel_size=3, stride=2)
        x = self.Mixed_5b(x)
        x = self.Mixed_5c(x)
        x = self.Mixed_5d(x); out["5d"] = x
        x = self.Mixed_6a(x)
        x = self.Mixed_6b(x)
        x = self.Mixed_6c(x)
        x = self.Mixed_6d(x)
        x = self.Mixed_6e(x); out["6e"] = x
        x = self.Mixed_7a(x)
        x = self.Mixed_7b(x)
        x = self.Mixed_7c(x); out["7c"] = x
        out["pool"] = x.mean(dim=(2, 3))
        return out


def _randomized(seed=0):
    """Replica with randomized weights AND non-trivial BN running stats (so
    eval-mode normalization is actually exercised, not identity)."""
    torch.manual_seed(seed)
    m = TorchInceptionFeatures()
    with torch.no_grad():
        for mod in m.modules():
            if isinstance(mod, nn.BatchNorm2d):
                mod.running_mean.normal_(0.0, 0.2)
                mod.running_var.uniform_(0.5, 1.5)
                mod.weight.normal_(1.0, 0.1)
                mod.bias.normal_(0.0, 0.1)
    m.eval()
    return m


def test_state_dict_keys_match_torchvision_schema():
    """The replica exists to stand in for the real checkpoint — its keys must
    follow the torchvision naming the converter is written against."""
    sd = TorchInceptionFeatures().state_dict()
    assert "Conv2d_1a_3x3.conv.weight" in sd
    assert "Mixed_5b.branch5x5_2.bn.running_var" in sd
    assert "Mixed_7c.branch3x3dbl_3b.conv.weight" in sd
    # every key converts without error (unknown keys raise)
    variables = flax_from_torch_inception(sd)
    assert "Mixed_7c" in variables["params"]
    assert "Mixed_7c" in variables["batch_stats"]


def test_feature_parity_torch_vs_flax():
    """Layer-wise activation parity: converted weights through the Flax model
    must reproduce the torch replica at every tap, not just the output —
    localizes any padding/pool/BN convention drift to a stage."""
    m = _randomized()
    variables = flax_from_torch_inception(m.state_dict())

    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (2, 299, 299, 3)).astype(np.float32)
    with torch.no_grad():
        taps = m(torch.from_numpy(x.transpose(0, 3, 1, 2)))

    import jax.numpy as jnp

    model = InceptionV3Features()
    feats = np.asarray(model.apply(variables, jnp.asarray(x)))

    want = taps["pool"].numpy()
    # f32 conv stacks accumulate; rtol dominated by the 94-conv depth
    np.testing.assert_allclose(feats, want, rtol=2e-3, atol=2e-4)
    # cosine similarity as the structural check (scale-free)
    num = (feats * want).sum(-1)
    den = np.linalg.norm(feats, axis=-1) * np.linalg.norm(want, axis=-1)
    assert (num / den > 0.9999).all()


def test_load_torch_inception_pth_end_to_end(tmp_path):
    """The documented canonical-weights path: `torch.save` a full
    torchvision-schema state_dict to disk, load via `load_torch_inception`
    (the --inception-pth code path), and get a verified, working extractor.
    A truncated file must fail the structural verification loudly, naming
    the missing path — not crash deep inside the first FID batch."""
    import jax.numpy as jnp

    from ddim_cold_tpu.eval.inception import (
        FEATURE_DIM, load_torch_inception,
    )

    m = _randomized(3)
    pth = str(tmp_path / "inception_v3.pth")
    torch.save(m.state_dict(), pth)
    model, variables = load_torch_inception(pth)
    x = jnp.zeros((1, 299, 299, 3))
    feats = model.apply(variables, x)
    assert feats.shape == (1, FEATURE_DIM)
    assert bool(jnp.isfinite(feats).all())

    sd = m.state_dict()
    dropped = next(k for k in sd if k.startswith("Mixed_7c"))
    sd = {k: v for k, v in sd.items() if not k.startswith("Mixed_7c")}
    bad = str(tmp_path / "truncated.pth")
    torch.save(sd, bad)
    with pytest.raises(ValueError, match="Mixed_7c"):
        load_torch_inception(bad)
    assert dropped  # (sanity: the truncation removed something real)


def test_stem_tap_parity():
    """First-conv tap in isolation: catches layout-transform errors directly
    at the input boundary (stride-2 VALID conv + BN eval)."""
    m = _randomized(1)
    variables = flax_from_torch_inception(m.state_dict())
    rng = np.random.RandomState(1)
    x = rng.uniform(-1, 1, (1, 75, 75, 3)).astype(np.float32)
    with torch.no_grad():
        want = m.Conv2d_1a_3x3(
            torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy().transpose(0, 2, 3, 1)

    import jax.numpy as jnp

    from ddim_cold_tpu.eval.inception import BasicConv2d

    sub = BasicConv2d(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")
    out = sub.apply(
        {"params": variables["params"]["Conv2d_1a_3x3"],
         "batch_stats": variables["batch_stats"]["Conv2d_1a_3x3"]},
        jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)
