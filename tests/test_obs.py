"""Observability subsystem tests (ISSUE 11): span propagation through a
2-replica chaos run (hedged attempts share one trace; retired replicas close
their lifetime spans), the tracing-disabled zero-overhead/bitwise contract,
device step telemetry against the adaptive gate's schedule, the metrics
registry as the single source behind the legacy ``stats`` surfaces, the
GRAFT-A005 emit-site lint, and the health/timeout diagnostics satellites."""

import json

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu import serve
from ddim_cold_tpu.analysis import ast_checks
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.obs import device as obs_device
from ddim_cold_tpu.obs import metrics, spans
from ddim_cold_tpu.ops import sampling, schedule
from ddim_cold_tpu.serve.router import Router
from ddim_cold_tpu.utils import faults, profiling
from ddim_cold_tpu.utils.faults import FaultSpec

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
            num_heads=4, total_steps=2000)
K = 500  # 4 reverse steps — same geometry as test_serve.py / test_fleet.py
CFG = serve.SamplerConfig(k=K)


@pytest.fixture(autouse=True)
def clean_tracing():
    """Tracing is process-global: every test starts disabled with an empty
    recorder and must leave it that way."""
    spans.disable()
    spans.clear()
    yield
    assert not spans.enabled(), "test leaked an enabled tracing state"
    spans.disable()
    spans.clear()


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


def _router(model_and_params, **kwargs):
    model, params = model_and_params
    factory = serve.local_factory(model, params, buckets=(4, 8))
    kwargs.setdefault("configs", [CFG])
    kwargs.setdefault("warm_kwargs", dict(persistent_cache=False))
    kwargs.setdefault("drain_timeout_s", 10.0)
    return Router(factory, **kwargs)


def _direct(model, params, seed, n):
    return np.asarray(sampling.ddim_sample(
        model, params, jax.random.PRNGKey(seed), k=K, n=n))


def _by_name(all_spans, name):
    return [s for s in all_spans if s.name == name]


# ------------------------------------------------- trace propagation (fleet)


def test_chaos_run_spans_share_trace_and_close(model_and_params, tmp_path):
    """The tentpole acceptance run: a hedged request's attempts all carry
    ONE trace_id, every span of the completed request closes, a retired
    replica's lifetime span closes, and both exports round-trip — with zero
    compiles after warmup."""
    model, params = model_and_params
    with spans.tracing():
        router = _router(model_and_params, replicas=2, quarantine_limit=2,
                         max_hedges=2)
        # phase A — deterministic hedge: one assembly kill on r0 (the idle
        # fleet's first placement) re-places the request on r1
        spec = FaultSpec("serve.assemble", "transient", rate=1.0,
                         match="replica:r0|", max_fires=1)
        with faults.inject(spec) as plan:
            t = router.submit(seed=151, n=3, config=CFG)
            got = t.result(timeout=60)
        np.testing.assert_array_equal(got, _direct(model, params, 151, 3))
        assert len(plan.realized) == 1 and router.stats["hedges"] == 1

        roots = _by_name(spans.spans(), "router.request")
        assert len(roots) == 1
        root = roots[0]
        trace = root.trace_id
        attempts = _by_name(spans.spans(), "router.attempt")
        assert len(attempts) == 2  # original + hedge
        assert {a.trace_id for a in attempts} == {trace}
        assert {a.parent_id for a in attempts} == {root.span_id}
        # both attempts hit distinct replicas and both ended with an outcome
        assert {a.attrs["replica"] for a in attempts} == {"r0", "r1"}
        assert all(a.ended and "outcome" in a.attrs for a in attempts)
        # the engine leg parents under its attempt, stages under the engine
        engine_spans = [s for s in _by_name(spans.spans(), "engine.request")
                        if s.trace_id == trace]
        assert engine_spans and all(s.ended for s in engine_spans)
        att_ids = {a.span_id for a in attempts}
        assert all(s.parent_id in att_ids for s in engine_spans)
        done = [s for s in engine_spans if "latency_s" in s.attrs]
        assert len(done) == 1  # exactly one attempt delivered
        stage_names = {s.name for s in spans.spans()
                       if s.trace_id == trace
                       and s.parent_id in {e.span_id for e in engine_spans}}
        assert {"plan", "assemble", "dispatch", "fetch"} <= stage_names
        assert root.ended and root.attrs["hedges"] == 1

        # phase B — permanent dispatch kill on r0: quarantine, retire,
        # replace; the retired replica's lifetime span must close
        kill = FaultSpec("serve.dispatch", "permanent", rate=1.0,
                         match="replica:r0|")
        with faults.inject(kill):
            for seed in (152, 153):  # quarantine_limit=2 needs two victims
                t2 = router.submit(seed=seed, n=1, config=CFG)
                assert t2.exception(timeout=60) is not None
            deadline = time.time() + 30
            while time.time() < deadline:
                h = router.health()
                if h["retired_replicas"] >= 1 and h["active_replicas"] == 2:
                    break
                time.sleep(0.05)
        lifetimes = _by_name(spans.spans(), "replica.lifetime")
        r0 = [s for s in lifetimes if s.attrs.get("replica") == "r0"]
        assert len(r0) == 1 and r0[0].ended and r0[0].attrs["retired"]
        # the failed requests' traces closed with the error recorded
        failed_roots = [s for s in _by_name(spans.spans(), "router.request")
                        if "error" in s.attrs]
        assert len(failed_roots) == 2 and all(s.ended for s in failed_roots)

        h = router.drain(timeout=10)
        assert h["compiles_after_warmup"] == 0
        # drain closes the survivors' lifetime spans too (retired=False)
        assert all(s.ended
                   for s in _by_name(spans.spans(), "replica.lifetime"))

        # exports round-trip: chrome JSON loads, jsonl parses line-per-span
        chrome_path = tmp_path / "trace.json"
        doc = spans.export_chrome(str(chrome_path))
        loaded = json.loads(chrome_path.read_text())
        assert loaded == json.loads(json.dumps(doc))
        assert loaded["traceEvents"]
        for ev in loaded["traceEvents"]:
            assert ev["ph"] == "X" and ev["ts"] >= 0 and ev["dur"] >= 0
        jsonl_path = tmp_path / "trace.jsonl"
        rows = spans.export_jsonl(str(jsonl_path))
        lines = [json.loads(ln) for ln in
                 jsonl_path.read_text().splitlines()]
        assert lines == json.loads(json.dumps(rows))
        assert len(lines) == len(spans.spans())
    spans.clear()


def test_tracing_disabled_records_nothing_and_is_bitwise(model_and_params):
    """Disabled tracing is the default and must be absolutely inert: no
    spans recorded, NULL handles everywhere, and outputs bitwise-identical
    to a traced run of the same seeds (tracing never perturbs numerics)."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    serve.warmup(eng, [CFG], persistent_cache=False)
    compiles = eng.stats["compiles"]

    n_spans = len(spans.spans())
    t = eng.submit(seed=171, n=2, config=CFG)
    eng.run()
    plain = t.result(timeout=60)
    assert len(spans.spans()) == n_spans  # not one span recorded
    assert t.span is None and t.telemetry is None

    with spans.tracing():
        t2 = eng.submit(seed=171, n=2, config=CFG)
        eng.run()
        traced = t2.result(timeout=60)
        assert t2.span is not None and t2.span.ended
    assert len(spans.spans()) > n_spans
    np.testing.assert_array_equal(plain, traced)
    np.testing.assert_array_equal(plain, _direct(model, params, 171, 2))
    assert eng.stats["compiles"] == compiles  # both runs: zero new programs
    spans.clear()


def test_begin_returns_null_when_disabled():
    s = spans.begin("anything", rid=1)
    assert s is spans.NULL and not s
    s.set(a=1).child("x").end()  # all no-ops
    spans.record(s, "stage", 0.0, 1.0)
    assert spans.spans() == []


# --------------------------------------------------------- device telemetry


def test_telemetry_static_mode_matches_schedule(model_and_params):
    model, params = model_and_params
    out, tel = sampling.ddim_sample(
        model, params, jax.random.PRNGKey(5), k=K, n=2, cache_interval=2,
        telemetry=True)
    branch = np.asarray(tel.branch)
    want = obs_device.static_schedule(4, 2, "delta")
    np.testing.assert_array_equal(branch, want)
    np.testing.assert_array_equal(np.asarray(tel.drift), np.zeros(4))
    # telemetry never changes the images
    plain = sampling.ddim_sample(
        model, params, jax.random.PRNGKey(5), k=K, n=2, cache_interval=2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(plain))


def test_telemetry_adaptive_gate_limits(model_and_params):
    """τ=0 promotes every step to refresh (the ``>=`` gate); τ=∞ collapses
    to the static adaptive schedule; the summary's promoted count is the
    difference against the static plan."""
    model, params = model_and_params

    def run(tau):
        _, tel = sampling.ddim_sample(
            model, params, jax.random.PRNGKey(6), k=K, n=2, cache_interval=2,
            cache_mode="adaptive", cache_threshold=tau, telemetry=True)
        return np.asarray(tel.branch), np.asarray(tel.drift)

    always, drift0 = run(0.0)
    np.testing.assert_array_equal(
        always, np.full(4, schedule.CACHE_REFRESH, np.int32))
    never, drift_inf = run(1e30)
    np.testing.assert_array_equal(
        never, obs_device.static_schedule(4, 2, "adaptive"))
    # the gate computed real drifts on reuse steps in both runs
    assert np.all(np.isfinite(drift0)) and np.all(drift_inf >= 0.0)

    summary = obs_device.summarize(
        obs_device.StepTelemetry(branch=always, drift=drift0),
        cache_interval=2, cache_mode="adaptive", cache_threshold=0.0)
    assert summary["steps"] == 4
    assert summary["refreshes"] == 4 and summary["reuses"] == 0
    assert summary["promoted_refreshes"] == (
        4 - summary["planned_refreshes"]) > 0
    assert summary["refresh_ratio"] == 1.0
    assert len(summary["branch"]) == len(summary["drift"]) == 4


def test_telemetry_validation(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="telemetry"):
        sampling.ddim_sample(model, params, jax.random.PRNGKey(0), k=K, n=2,
                             telemetry=True)  # uncached
    with pytest.raises(ValueError, match="last-only"):
        sampling.ddim_sample(model, params, jax.random.PRNGKey(0), k=K, n=2,
                             cache_interval=2, telemetry=True,
                             return_sequence=True)
    with pytest.raises(ValueError, match="telemetry"):
        serve.SamplerConfig(k=K, telemetry=True)  # uncached config
    with pytest.raises(ValueError, match="telemetry"):
        serve.SamplerConfig(k=K, cache_interval=2, preview_every=2,
                            telemetry=True)


def test_served_telemetry_attaches_to_ticket(model_and_params):
    """The engine fetches the step aux with the batch, decodes it once and
    attaches it to every ticket before delivery — with zero serve-time
    compiles (the telemetry program is its own warmed executable)."""
    model, params = model_and_params
    cfg = serve.SamplerConfig(k=K, cache_interval=2, cache_mode="adaptive",
                              cache_threshold=0.05, telemetry=True)
    eng = serve.Engine(model, params, buckets=(4,))
    serve.warmup(eng, [cfg], persistent_cache=False)
    compiles = eng.stats["compiles"]
    t = eng.submit(seed=181, n=2, config=cfg)
    eng.run()
    assert t.result(timeout=60).shape == (2, 16, 16, 3)
    tel = t.telemetry
    assert tel is not None and tel["steps"] == 4
    assert tel["cache_mode"] == "adaptive" and tel["cache_threshold"] == 0.05
    assert tel["refreshes"] + tel["reuses"] == 4
    assert tel["refreshes"] >= tel["planned_refreshes"]
    assert eng.stats["compiles"] == compiles
    assert eng.metrics.value("engine.cache_refresh_steps") == tel["refreshes"]
    assert eng.metrics.value("engine.cache_reuse_steps") == tel["reuses"]


# --------------------------------------------------------- metrics registry


def test_engine_stats_is_a_registry_view(model_and_params):
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    serve.warmup(eng, [CFG], persistent_cache=False)
    for seed in (191, 192):
        eng.submit(seed=seed, n=2, config=CFG)
    eng.run()
    s = eng.stats
    m = eng.metrics
    assert s["compiles"] == m.value("engine.compiles") > 0
    assert s["dispatches"] == m.value("engine.dispatches") > 0
    assert s["rows"] == m.value("engine.rows") == 4
    assert s["latencies_s"] == m.samples("engine.latency_s")
    assert len(s["latencies_s"]) == 2
    # unquantized path: gauge never set, stats renders it as legacy None
    assert s["param_bytes"] is m.raw("engine.param_bytes") is None
    snap = m.snapshot()
    assert snap["engine.rows"] == 4
    # the registry-level snapshot carries this engine's scope verbatim
    assert metrics.snapshot()[m.sid] == snap
    with pytest.raises(ValueError, match="unregistered"):
        m.inc("engine.not_a_metric")
    with pytest.raises(ValueError, match="gauge"):
        m.inc("engine.param_bytes")  # kind mismatch: gauge emitted as counter


def test_router_stats_is_a_registry_view(model_and_params):
    router = _router(model_and_params, replicas=1)
    t = router.submit(seed=195, n=1, config=CFG)
    t.result(timeout=60)
    s = router.stats
    m = router.metrics
    assert s["submitted"] == m.value("router.submitted") == 1
    assert s["completed"] == m.value("router.completed") == 1
    assert s["placements"] == m.value("router.placements") >= 1
    assert s["replicas_spawned"] == m.value("router.replicas_spawned") == 1
    assert s["rejected_by_tenant"] == m.by_key("router.rejected_by_tenant")
    h = router.drain(timeout=10)
    assert h["compiles_after_warmup"] == 0
    # fleet lifecycle transitions landed keyed by state (new→ready→…→closed)
    fleet_keys = {}
    for sid, series in metrics.snapshot().items():
        if sid.startswith("fleet#"):
            for key, n in series.get(
                    "fleet.replica_transitions/by_key", {}).items():
                fleet_keys[key] = fleet_keys.get(key, 0) + n
    assert fleet_keys.get("new", 0) >= 1 and fleet_keys.get("closed", 0) >= 1


def test_faults_injected_metric():
    before = sum(
        series.get("faults.injected/by_key", {}).get("data.next", 0)
        for sid, series in metrics.snapshot().items()
        if sid.startswith("faults#"))
    with faults.inject(FaultSpec("data.next", "latency", rate=1.0,
                                 latency_s=0.0)):
        faults.fire("data.next", tag="t")
    after = sum(
        series.get("faults.injected/by_key", {}).get("data.next", 0)
        for sid, series in metrics.snapshot().items()
        if sid.startswith("faults#"))
    assert after == before + 1


# ------------------------------------------------------------- A005 lint


NAMES = ("engine.compiles", "engine.failed_batches")


def _lint(src, **kw):
    kw.setdefault("metric_names", NAMES)
    return ast_checks.lint_source(src, "f.py", **kw)


def test_a005_dynamic_name_flagged():
    fs = _lint("m.inc(name)\n")
    assert [f.rule for f in fs] == ["GRAFT-A005"]
    assert fs[0].subject == "metric:<dynamic>"


def test_a005_unregistered_name_flagged():
    fs = _lint('m.inc("engine.nope")\n')
    assert [f.subject for f in fs] == ["metric:engine.nope"]


def test_a005_duplicate_site_flagged_and_keys_disambiguate():
    dup = 'm.inc("engine.compiles")\nother.inc("engine.compiles")\n'
    fs = _lint(dup)
    assert len(fs) == 1 and "duplicate" in fs[0].message
    keyed = ('m.inc("engine.failed_batches", key="dispatch")\n'
             'm.inc("engine.failed_batches", key="plan")\n')
    assert _lint(keyed) == []
    # a dynamic key subdivides ONE site — never part of the uniqueness map
    dyn = 'm.inc("engine.compiles", key=state)\n' * 2
    assert _lint(dyn) == []
    # gauge/observe emits share the uniqueness map with inc
    mixed = ('m.gauge("engine.compiles", 1)\n'
             'm.observe("engine.compiles", 2)\n')
    fs = _lint(mixed)
    assert len(fs) == 1 and "duplicate" in fs[0].message


def test_a005_live_tree_is_clean_and_covered():
    """The real tree lints clean against the live registry — and actually
    contains emit sites (the rule is exercised, not vacuous)."""
    from ddim_cold_tpu.analysis import cli

    root = cli.repo_root()
    assert ast_checks.lint_tree(root) == []
    import os

    n_emits = 0
    for rel in ("ddim_cold_tpu/serve/engine.py",
                "ddim_cold_tpu/serve/router.py",
                "ddim_cold_tpu/serve/fleet.py",
                "ddim_cold_tpu/utils/faults.py"):
        with open(os.path.join(root, rel)) as f:
            import ast as ast_mod

            n_emits += len(ast_checks._metric_calls(ast_mod.parse(f.read())))
    assert n_emits >= 20


# ----------------------------------------------- satellites: profiling etc.


def test_latency_summary_has_p99_and_count():
    s = profiling.latency_summary([0.01 * i for i in range(1, 101)])
    assert s["count"] == s["n"] == 100
    assert s["p50_s"] <= s["p95_s"] <= s["p99_s"] <= s["max_s"]
    assert s["p99_s"] == pytest.approx(np.percentile(
        [0.01 * i for i in range(1, 101)], 99))
    empty = profiling.latency_summary([])
    assert empty["count"] == 0 and empty["p99_s"] == 0.0


def test_health_last_stage_and_timeout_message(model_and_params):
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    serve.warmup(eng, [CFG], persistent_cache=False)
    t = eng.submit(seed=201, n=1, config=CFG)
    eng.run()
    t.result(timeout=60)
    h = eng.health()
    assert isinstance(h["last_stage"], str) and h["last_stage"]
    assert h["stalled_for_s"] >= 0.0
    # a timed-out waiter sees the stage diagnostics in its message
    t2 = eng.submit(seed=202, n=1, config=CFG)  # never run
    with pytest.raises(TimeoutError, match="last seen at stage"):
        t2.result(timeout=0.01)
    eng.drain(timeout=5)


def test_span_trace_dir_is_span_keyed(tmp_path):
    with spans.tracing():
        sp = spans.begin("bench.obs")
        ctx = profiling.span_trace(str(tmp_path), sp)
        with ctx:
            jnp.zeros((2, 2)).block_until_ready()
        sub = tmp_path / f"trace_{sp.ctx.trace_id}_{sp.ctx.span_id}"
        assert sub.exists()
        sp.end()
    spans.clear()
