"""Fleet router tests (serve/router.py + serve/fleet.py): placement
bitwise-vs-direct, hedged re-placement, quarantine terminality, the
chaos-contract acceptance test (ISSUE 6: permanent dispatch kill on one
replica + ≥20% transients elsewhere → survivors bitwise, failures typed and
replica-named, killed replica drained AND replaced, zero compiles after
warmup across every replica including the replacement), tenant QoS
fair-share admission, and stub-backed supervision/lifecycle units.

The in-process replicas serve from worker threads, so WHICH replica a
request lands on is timing-dependent — assertions here are placement-
agnostic (bitwise for survivors, typed-and-named for failures, fleet-level
counters) rather than schedule-exact."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu import serve
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import sampling
from ddim_cold_tpu.serve import fleet
from ddim_cold_tpu.serve.router import Router
from ddim_cold_tpu.utils import faults
from ddim_cold_tpu.utils.faults import FaultSpec

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
            num_heads=4, total_steps=2000)
K = 500  # 4 reverse steps, same geometry as test_serve.py

pytestmark = pytest.mark.usefixtures("no_leaked_faults")


@pytest.fixture()
def no_leaked_faults():
    assert not faults.active(), "a previous test leaked an armed fault scope"
    yield
    assert not faults.active(), "this test leaked an armed fault scope"


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


CFG = serve.SamplerConfig(k=K)


def _router(model_and_params, **kwargs):
    model, params = model_and_params
    factory = serve.local_factory(model, params, buckets=(4, 8))
    kwargs.setdefault("configs", [CFG])
    kwargs.setdefault("warm_kwargs", dict(persistent_cache=False))
    kwargs.setdefault("drain_timeout_s", 10.0)
    return Router(factory, **kwargs)


def _direct(model, params, seed, n):
    return np.asarray(sampling.ddim_sample(
        model, params, jax.random.PRNGKey(seed), k=K, n=n))


# ------------------------------------------------------------ clean routing


def test_router_bitwise_and_zero_compiles(model_and_params):
    """The inherited engine contract at fleet scope: mixed-size requests
    spread over two replicas all come back bitwise equal to direct
    sampling, with zero program builds after warmup anywhere."""
    model, params = model_and_params
    router = _router(model_and_params, replicas=2)
    sizes = [(41, 5), (42, 4), (43, 3), (44, 1)]
    tickets = {s: router.submit(seed=s, n=n, config=CFG) for s, n in sizes}
    for s, n in sizes:
        got = tickets[s].result(timeout=60)
        assert got.shape == (n, 16, 16, 3)
        np.testing.assert_array_equal(got, _direct(model, params, s, n))
    h = router.drain(timeout=10)
    assert h["compiles_after_warmup"] == 0
    assert h["completed"] == len(sizes) and h["failed"] == 0
    assert h["active_replicas"] == 2 and h["retired_replicas"] == 0
    # every placement named a real replica and warmup compiled per replica
    for rid, rh in h["replicas"].items():
        assert rh["replica"] == rid
        assert rh["compiles_after_warmup"] == 0


def test_router_guided_request_bitwise(model_and_params):
    """x_init requests (the sample_from path) route like fresh ones — the
    router passes the host array through untouched."""
    model, params = model_and_params
    router = _router(model_and_params, replicas=2,
                     configs=[serve.SamplerConfig(k=K, t_start=1000)])
    x0 = np.asarray(jax.random.normal(jax.random.PRNGKey(7), (3, 16, 16, 3)))
    t = router.submit(x_init=x0, config=serve.SamplerConfig(k=K, t_start=1000))
    got = t.result(timeout=60)
    want = np.asarray(sampling.sample_from(
        model, params, jnp.asarray(x0, jnp.float32), t_start=1000, k=K))
    np.testing.assert_array_equal(got, want)
    assert router.drain(timeout=10)["compiles_after_warmup"] == 0


def test_router_validation():
    with pytest.raises(ValueError, match="replicas"):
        Router(lambda rid: None, replicas=0, auto_start=False)
    with pytest.raises(ValueError, match="max_pending"):
        Router(lambda rid: None, replicas=1, max_pending=0, auto_start=False)


# ------------------------------------------------------- hedging and chaos


def test_hedged_replacement_is_bitwise(model_and_params):
    """A retryable failure (assembly-stage transient — the engine does NOT
    retry assembly internally) hedges the request once to another replica;
    the hedge re-issues the same rng, so the result is bitwise."""
    model, params = model_and_params
    router = _router(model_and_params, replicas=2)
    # the first placement of an idle fleet is deterministic (least loaded,
    # id tiebreak → r0); kill exactly one assembly there
    spec = FaultSpec("serve.assemble", "transient", rate=1.0,
                     match="replica:r0|", max_fires=1)
    with faults.inject(spec) as plan:
        t = router.submit(seed=51, n=3, config=CFG)
        got = t.result(timeout=60)
    np.testing.assert_array_equal(got, _direct(model, params, 51, 3))
    assert len(plan.realized) == 1
    assert router.stats["hedges"] == 1
    h = router.drain(timeout=10)
    assert h["compiles_after_warmup"] == 0  # hedge reused warmed programs


def test_quarantined_request_is_never_hedged(model_and_params):
    """RequestQuarantinedError is terminal: bisection proved the request
    itself is the poison, so the router fails it through — with the
    replica-naming message — instead of poisoning the next replica."""
    router = _router(model_and_params, replicas=2)
    spec = FaultSpec("serve.dispatch", "permanent", rate=1.0,
                     match="replica:r0|")
    with faults.inject(spec):
        t = router.submit(seed=52, n=2, config=CFG)
        exc = t.exception(timeout=60)
        assert isinstance(exc, serve.RequestQuarantinedError)
        assert "replica 'r0'" in str(exc)
        assert router.stats["hedges"] == 0
        # let supervision retire the poisoned replica inside the fault
        # scope (its engine keeps the armed spec realistic); the request
        # counter guard (quarantine_limit=2) needs a second victim
        t2 = router.submit(seed=53, n=1, config=CFG)
        t2.exception(timeout=60)
        deadline = time.time() + 20
        while time.time() < deadline:
            h = router.health()
            if h["retired_replicas"] >= 1 and h["active_replicas"] >= 2:
                break
            time.sleep(0.05)
    h = router.drain(timeout=10)
    assert h["retired_replicas"] >= 1
    assert h["replicas_spawned"] >= 3  # 2 initial + the replacement


def test_fleet_chaos_contract(model_and_params):
    """ISSUE 6 acceptance: seeded schedule kills r0's dispatch outright
    (permanent) and injects 20–25% transients at assembly and placement.
    Every surviving ticket is bitwise-equal to direct sampling, every
    failed ticket carries a typed cause naming its replica, r0 is drained
    and replaced, and compiles-after-warmup is 0 across ALL replicas —
    replacement included."""
    model, params = model_and_params
    router = _router(model_and_params, replicas=2, quarantine_limit=2,
                     max_hedges=2)
    schedule = (
        FaultSpec("serve.dispatch", "permanent", rate=1.0,
                  match="replica:r0|"),
        FaultSpec("serve.assemble", "transient", rate=0.25, seed=11),
        # scoped to r1 so place-transients never steer requests away from
        # r0 — the kill must actually be hit for the lifecycle to run
        FaultSpec("router.place", "transient", rate=0.2, seed=12,
                  match="replica:r1|"),
    )
    sizes = [(61, 3), (62, 2), (63, 4), (64, 1), (65, 2), (66, 3), (67, 1)]
    with faults.inject(*schedule) as plan:
        tickets = {s: router.submit(seed=s, n=n, config=CFG)
                   for s, n in sizes}
        outcomes = {s: tickets[s].exception(timeout=120) for s, _ in sizes}
        # wait for supervision to finish the lifecycle: r0 retired and the
        # fleet back at target size
        deadline = time.time() + 30
        while time.time() < deadline:
            h = router.health()
            if h["retired_replicas"] >= 1 and h["active_replicas"] == 2:
                break
            time.sleep(0.05)
    assert len(plan.realized) >= 3 and "serve.dispatch" in plan.by_site()
    survivors = failures = 0
    for s, n in sizes:
        exc = outcomes[s]
        if exc is None:
            survivors += 1
            np.testing.assert_array_equal(tickets[s].result(0),
                                          _direct(model, params, s, n))
        else:
            failures += 1
            # typed, and the message names the replica it died on
            assert isinstance(exc, serve.ServeError)
            assert "replica 'r" in str(exc)
    assert survivors >= 1  # the fleet kept serving through the kill
    h = router.drain(timeout=10)
    # the killed replica was drained (closed) and the fleet healed
    retired = [rh for rh in h["replicas"].values()
               if rh.get("state") == fleet.CLOSED and rh["replica"] == "r0"]
    assert h["retired_replicas"] >= 1 and retired, \
        f"r0 was not retired: {h['replicas'].keys()}"
    assert h["replicas_spawned"] >= 3
    assert h["active_replicas"] == 2
    # the headline: zero compiles after warmup, replacement included
    assert h["compiles_after_warmup"] == 0
    for rid, rh in h["replicas"].items():
        assert rh.get("compiles_after_warmup", 0) == 0, rid


def test_router_place_permanent_fault_fails_typed(model_and_params):
    """A permanent fault in the placement path itself (router.place) fails
    the request with a typed error naming the target replica."""
    router = _router(model_and_params, replicas=1)
    with faults.inject(FaultSpec("router.place", "permanent", rate=1.0)):
        t = router.submit(seed=54, n=1, config=CFG)
        exc = t.exception(timeout=30)
    assert isinstance(exc, serve.RequestFailedError)
    assert isinstance(exc.__cause__, faults.PermanentFault)
    assert "replica 'r0'" in str(exc)
    router.drain(timeout=5)


def test_replica_spawn_fault_is_fatal_at_cold_start(model_and_params):
    """replica.spawn chaos at construction surfaces immediately — a fleet
    that cannot build its initial replicas must not pretend to exist."""
    with faults.inject(FaultSpec("replica.spawn", "permanent", rate=1.0)):
        with pytest.raises(faults.PermanentFault):
            _router(model_and_params, replicas=1)


# -------------------------------------------------------------- tenant QoS


def test_qos_flooding_tenant_only_exhausts_its_share(model_and_params):
    """ISSUE 6 QoS acceptance at 4:1 weights over max_pending=10: the
    flooder caps at 8 (its excess gets QueueFullError), the light tenant
    keeps its 2 and completes within its deadline. auto_start=False makes
    admission deterministic: nothing resolves until start()."""
    router = _router(model_and_params, replicas=2,
                     tenants={"heavy": 4, "light": 1}, max_pending=10,
                     auto_start=False)
    heavy, rejected = [], 0
    for i in range(14):
        try:
            heavy.append(router.submit(seed=100 + i, n=1, config=CFG,
                                       tenant="heavy"))
        except serve.QueueFullError as exc:
            rejected += 1
            assert "'heavy'" in str(exc) and "fair share" in str(exc)
    assert len(heavy) == 8 and rejected == 6  # 10 * 4 // 5
    light = [router.submit(seed=200 + i, n=1, config=CFG, tenant="light",
                           priority=1, deadline_s=60.0) for i in range(2)]
    router.start()
    for t in light:
        assert t.result(timeout=60).shape == (1, 16, 16, 3)
        assert t.latency_s < 60.0  # completed within its deadline
    for t in heavy:
        assert t.result(timeout=60) is not None
    h = router.drain(timeout=10)
    assert h["rejected_by_tenant"] == {"heavy": 6}
    assert h["completed"] == 10
    assert h["compiles_after_warmup"] == 0


def test_qos_share_frees_up_as_tickets_resolve(model_and_params):
    """The cap is on admitted-UNRESOLVED requests: once the flood drains,
    the same tenant can submit again (backpressure, not a ban)."""
    router = _router(model_and_params, replicas=1,
                     tenants={"a": 1, "b": 1}, max_pending=4)
    first = [router.submit(seed=300 + i, n=1, config=CFG, tenant="a")
             for i in range(2)]
    for t in first:
        t.result(timeout=60)
    # share released — two more admit cleanly
    again = [router.submit(seed=310 + i, n=1, config=CFG, tenant="a")
             for i in range(2)]
    for t in again:
        t.result(timeout=60)
    assert router.drain(timeout=10)["rejected"] == 0


# -------------------------------------------------- shutdown and stub units


def test_router_drain_rejects_and_fails_queued(model_and_params):
    """After drain: new submissions raise EngineClosedError and anything
    still queued failed with it (typed, never stranded)."""
    router = _router(model_and_params, replicas=1, auto_start=False)
    t = router.submit(seed=70, n=1, config=CFG)
    h = router.drain(timeout=0.2)  # control loop never ran: t still queued
    assert h["closed"]
    assert isinstance(t.exception(timeout=5), serve.EngineClosedError)
    with pytest.raises(serve.EngineClosedError):
        router.submit(seed=71, n=1, config=CFG)


class StubReplica(fleet.ReplicaHandle):
    """Health-programmable replica for supervision units (no jax, no
    engine — exactly the ReplicaHandle surface the router sees)."""

    def __init__(self, rid):
        self.replica_id = rid
        self.state = fleet.NEW
        self.drained = False
        self.h = {"stalled": False, "closed": False, "quarantined": 0,
                  "queue_depth": 0, "open_tickets": 0,
                  "last_progress_s": 0.0, "compiles_after_warmup": 0}

    def warm(self, configs, buckets=None, **kwargs):
        self.state = fleet.READY
        return {"new_compiles": 0}

    def start(self):
        pass

    def health(self):
        return dict(self.h, state=self.state, replica=self.replica_id)

    def drain(self, timeout=None):
        self.drained = True
        self.state = fleet.CLOSED
        return self.health()

    def close(self):
        self.state = fleet.CLOSED


def test_supervision_retires_and_replaces_stalled_replica():
    """A replica whose snapshot turns stalled is drained and replaced —
    pure control-plane logic, provable without an engine."""
    reps = {}

    def factory(rid):
        reps[rid] = StubReplica(rid)
        return reps[rid]

    router = Router(factory, replicas=2, configs=(), tick_s=0.01)
    reps["r0"].h["stalled"] = True
    deadline = time.time() + 10
    while time.time() < deadline:
        h = router.health()
        if h["retired_replicas"] == 1 and h["active_replicas"] == 2:
            break
        time.sleep(0.02)
    assert reps["r0"].drained and reps["r0"].state == fleet.CLOSED
    assert "r2" in reps  # the replacement
    h = router.drain(timeout=2)
    assert h["replicas_spawned"] == 3 and h["replicas_retired"] == 1


def test_supervision_counts_spawn_failures_and_retries():
    """A failing factory leaves a deficit and a counter — the fleet keeps
    retrying on its tick instead of crashing the control loop."""
    calls = {"n": 0}

    def factory(rid):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("no capacity")
        return StubReplica(rid)

    router = Router(factory, replicas=2, configs=(), tick_s=0.01)
    # retire r0 → replacement spawn fails → deficit persists, counter grows
    router._replicas["r0"].h["quarantined"] = 99
    deadline = time.time() + 10
    while time.time() < deadline:
        if router.stats["spawn_failures"] >= 2:
            break
        time.sleep(0.02)
    assert router.stats["spawn_failures"] >= 2
    assert router.health()["active_replicas"] == 1
    router.drain(timeout=2)


def test_wedge_detection_from_snapshot():
    """wedge_after_s retires a replica whose last_progress_s age exceeds
    the budget while it holds open tickets — the snapshot-only stall
    detection the engine's health() satellite exists for."""
    reps = {}

    def factory(rid):
        reps[rid] = StubReplica(rid)
        return reps[rid]

    router = Router(factory, replicas=1, configs=(), tick_s=0.01,
                    wedge_after_s=0.5)
    reps["r0"].h.update(open_tickets=3, last_progress_s=9.0)
    deadline = time.time() + 10
    while time.time() < deadline:
        if router.stats["replicas_retired"] >= 1:
            break
        time.sleep(0.02)
    assert reps["r0"].drained
    router.drain(timeout=2)


def test_sp_ticket_failover_reuses_warmed_programs(model_and_params):
    """Sequence-parallel placement contract (the sp tentpole at fleet
    scope): every replica warms the SAME config set, sp included, so an sp
    ticket hedged off a faulted replica lands on a peer whose (data, seq)
    program is already compiled — allclose to direct (the mesh tolerance)
    with zero compiles after warmup anywhere."""
    model, params = model_and_params
    sp_cfg = serve.SamplerConfig(k=K, sp_mode="ulysses", sp_degree=2)
    router = _router(model_and_params, replicas=2, configs=[CFG, sp_cfg])
    spec = FaultSpec("serve.assemble", "transient", rate=1.0,
                     match="replica:r0|", max_fires=1)
    with faults.inject(spec) as plan:
        t = router.submit(seed=91, n=4, config=sp_cfg)
        got = t.result(timeout=60)
    np.testing.assert_allclose(
        got, _direct(model, params, 91, 4), rtol=2e-5, atol=2e-5)
    assert len(plan.realized) == 1
    assert router.stats["hedges"] == 1
    h = router.drain(timeout=10)
    assert h["compiles_after_warmup"] == 0
