"""Distributed tests on the 8-virtual-device CPU mesh (SURVEY.md §4):
dp-sharded training must match single-device training; tensor-parallel
sharding must preserve model outputs; the loader shard × mesh shard
composition must reconstruct the global batch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.parallel import (
    make_mesh,
    param_partition_specs,
    shard_batch,
    shard_params,
    shard_train_state,
)
from ddim_cold_tpu.train.step import create_train_state, make_train_step

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


def _fake_batch(n=8):
    rng = np.random.RandomState(0)
    return (
        rng.randn(n, 16, 16, 3).astype(np.float32),
        rng.randn(n, 16, 16, 3).astype(np.float32),
        rng.randint(0, 2000, size=(n,)).astype(np.int32),
    )


def _tiny_state(rng_seed=0, ema_decay=0.0):
    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
                         num_heads=4, drop_rate=0.0, attn_drop_rate=0.0,
                         drop_path_rate=0.0)
    batch = tuple(jnp.asarray(b) for b in _fake_batch())
    state = create_train_state(model, jax.random.PRNGKey(rng_seed), lr=1e-3,
                               total_steps=100, sample_batch=batch,
                               ema_decay=ema_decay)
    return model, state, batch


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    mesh2 = make_mesh({"data": 4, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError, match="does not match"):
        make_mesh({"data": 3, "model": 2})


def test_dp_training_matches_single_device():
    """Same init, same batch: 8-way dp loss/params == single-device (psum-mean
    equivalence — the SPMD analogue of DDP allreduce correctness)."""
    model, state0, batch = _tiny_state()
    train_step = make_train_step(model)
    rng = jax.random.PRNGKey(42)

    # single device: replicate nothing, run as-is
    s1, _, ema1 = train_step(state0, batch, rng, jnp.float32(5.0))

    # dp over 8 devices
    model2, state2, _ = _tiny_state()
    mesh = make_mesh({"data": 8, "model": 1})
    state2 = shard_params(state2, mesh)
    sharded = shard_batch(batch, mesh)
    s2, _, ema2 = train_step(state2, sharded, rng, jnp.float32(5.0))

    np.testing.assert_allclose(float(ema1), float(ema2), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
        s1.params, s2.params)


def test_steps_per_dispatch_grouped_sharding_matches():
    """spd>1 under a dp mesh: the grouped batch shards dim 1 on 'data'
    (scan axis unsharded) and the scanned dispatch matches sequential
    single-device steps on the same batches."""
    model, state0, batch = _tiny_state()
    rng = jax.random.PRNGKey(42)
    one_step = make_train_step(model)
    s1 = state0
    rec1 = jnp.float32(5.0)
    for _ in range(2):  # same batch twice: rng folds differ via state.step
        s1, _, rec1 = one_step(s1, batch, rng, rec1)

    model2, state2, _ = _tiny_state()
    mesh = make_mesh({"data": 8, "model": 1})
    state2 = shard_params(state2, mesh)
    stacked = jax.tree.map(lambda x: jnp.stack([x, x]), batch)
    grouped = shard_batch(stacked, mesh, grouped=True)
    # leading (scan) axis replicated, batch dim sharded over 'data'
    assert grouped[0].sharding.spec == jax.sharding.PartitionSpec(None, "data")
    multi = make_train_step(model2, steps_per_dispatch=2)
    s2, _, rec2 = multi(state2, grouped, rng, jnp.float32(5.0))

    np.testing.assert_allclose(float(rec1), float(rec2), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
        s1.params, s2.params)


def test_tp_forward_matches_replicated():
    """Megatron-style tensor sharding is output-invariant."""
    model, state, batch = _tiny_state()
    x, _, t = batch
    want = np.asarray(model.apply({"params": state.params}, x, t))

    mesh = make_mesh({"data": 2, "model": 4})  # heads=4 → 4-way head sharding
    specs = param_partition_specs(state.params)
    params_tp = shard_params(state.params, mesh, specs)
    x_sh = shard_batch(x, mesh)
    got = np.asarray(jax.jit(model.apply)({"params": params_tp}, x_sh, t))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_tp_dp_train_step_matches():
    """Full train step under dp×tp mesh reproduces the single-device step."""
    model, state0, batch = _tiny_state()
    train_step = make_train_step(model)
    rng = jax.random.PRNGKey(7)
    s1, _, _ = train_step(state0, batch, rng, jnp.float32(5.0))

    _, state2, _ = _tiny_state()
    mesh = make_mesh({"data": 2, "model": 4})
    specs = param_partition_specs(state2.params)
    state2 = shard_train_state(state2, mesh, specs)
    # adam moments must be co-sharded with their params, not replicated
    mu = state2.opt_state[1][0].mu
    assert mu["blocks_0"]["attn"]["qkv"]["kernel"].sharding.spec == specs[
        "blocks_0"]["attn"]["qkv"]["kernel"]
    s2, _, _ = train_step(state2, shard_batch(batch, mesh), rng, jnp.float32(5.0))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5),
        s1.params, s2.params)


def test_param_partition_specs_rules():
    from jax.sharding import PartitionSpec as P

    model, state, _ = _tiny_state()
    specs = param_partition_specs(state.params)
    b0 = specs["blocks_0"]
    assert b0["attn"]["qkv"]["kernel"] == P(None, "model")
    assert b0["attn"]["qkv"]["bias"] == P("model")
    assert b0["attn"]["proj"]["kernel"] == P("model", None)
    assert b0["attn"]["proj"]["bias"] == P()
    assert b0["mlp"]["fc1"]["kernel"] == P(None, "model")
    assert b0["mlp"]["fc2"]["kernel"] == P("model", None)
    assert specs["pos_embed"] == P()
    assert specs["patch_embed"]["proj"]["kernel"] == P()


@pytest.mark.isolated
def test_trainer_multidevice_eval_ragged_tail(tmp_path, synthetic_image_dir):
    """End-to-end trainer on a data=4 mesh where the eval set does NOT divide
    the global batch — the padded eval path must not crash (regression:
    ragged tail vs sharded leading dim)."""
    import yaml

    from ddim_cold_tpu.config import load_config
    from ddim_cold_tpu.train.trainer import run

    cfg_d = {
        "AMP": False, "framework": "vt", "num_gpus": 4, "batch_size": 1,
        "epoch": [0, 1], "base_lr": 0.005,
        "dataStorage": [synthetic_image_dir, synthetic_image_dir],
        "image_size": [64, 64], "diff_step": 6, "patch_size": 8,
        "embed_dim": 32, "depth": 1, "head": 2,
    }
    path = str(tmp_path / "m.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg_d, f)
    cfg = load_config(path, "m")
    # global batch 4; 10 eval images → batches of 4,4,2 → padded to 4,4,4
    result = run(cfg, str(tmp_path), log_every=2)
    assert np.isfinite(result.last_val_loss)


def test_loader_mesh_composition(synthetic_image_dir):
    """2 loader shards × 4-device data mesh: every global batch element lands
    exactly once (the DistributedSampler → sharding-annotation translation)."""
    from ddim_cold_tpu.data import ShardedLoader

    class IntDs:
        def __getitem__(self, i):
            return (np.full((4, 4, 3), i, np.float32),) * 2 + (i,)

        def __len__(self):
            return 32

    world = 2
    per_host = []
    for r in range(world):
        ld = ShardedLoader(IntDs(), batch_size=8, shuffle=True, seed=42,
                           drop_last=True, shard_index=r, shard_count=world,
                           num_threads=1)
        ld.set_epoch(0)
        per_host.append([b[2] for b in ld])
    # hosts see disjoint halves, and per-step global batches are disjoint
    for step in range(2):
        merged = np.concatenate([per_host[0][step], per_host[1][step]])
        assert len(set(merged.tolist())) == 16


def test_ema_shadow_cosharded_under_tp_mesh():
    """ema_params mirrors the params' tensor shardings through
    shard_train_state, and a tp×dp step updates the shadow to the same values
    as an unsharded step (elementwise decay: no resharding inserted)."""
    model, s1, batch = _tiny_state(ema_decay=0.9)
    step = make_train_step(model, ema_decay=0.9)
    rng = jax.random.PRNGKey(7)
    s1, _, _ = step(s1, batch, rng, jnp.float32(5.0))

    _, s2, _ = _tiny_state(ema_decay=0.9)
    mesh = make_mesh({"data": 2, "model": 4})
    specs = param_partition_specs(s2.params)
    s2 = shard_train_state(s2, mesh, specs)
    qkv = s2.ema_params["blocks_0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == specs["blocks_0"]["attn"]["qkv"]["kernel"]
    s2, _, _ = step(s2, shard_batch(batch, mesh), rng, jnp.float32(5.0))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5),
        s1.ema_params, s2.ema_params)


def test_grad_accum_under_dp_mesh_matches():
    """grad_accum under a data-sharded mesh reproduces the single-device
    accumulated step — the interleaved slice layout keeps every micro-slice
    resident across the 'data' axis (a contiguous split would reshard or
    idle devices each scan iteration)."""
    model, s1, batch = _tiny_state()
    step = make_train_step(model, grad_accum=2)
    rng = jax.random.PRNGKey(7)
    s1, _, _ = step(s1, batch, rng, jnp.float32(5.0))

    _, s2, _ = _tiny_state()
    mesh = make_mesh({"data": 8})
    s2 = shard_train_state(s2, mesh, None)
    s2, _, _ = step(s2, shard_batch(batch, mesh), rng, jnp.float32(5.0))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5),
        s1.params, s2.params)
