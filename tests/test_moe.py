"""Switch-MoE (models/moe.py) — routing oracle, aux loss, ep sharding, and
trainer integration. The reference has no MoE (its MLP is dense,
reference ViT.py:74-90); this is the 'expert' axis of the parallelism
story, beyond-parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.models.moe import SwitchMlp


def _mlp_params_and_out(key, B=2, N=16, D=8, E=4, cf=1.25):
    m = SwitchMlp(num_experts=E, hidden_features=D, out_features=D,
                  capacity_factor=cf, drop=0.0)
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, N, D))
    # params only: init's variables also hold a "losses" entry, and passing
    # it back in would make apply APPEND a second sown value
    variables = {"params": m.init(key, x)["params"]}
    y, aux = m.apply(variables, x, mutable=["losses"])
    return m, variables, x, y, aux


def test_switch_mlp_routing_matches_numpy_oracle():
    """Top-1 routing with capacity: per batch row, the first C tokens
    arriving at each expert get gate·expert(x); overflow tokens get 0."""
    key = jax.random.PRNGKey(0)
    B, N, D, E = 2, 16, 8, 4
    cf = 0.5  # tight capacity → overflow actually happens
    m, variables, x, y, _ = _mlp_params_and_out(key, B, N, D, E, cf)
    p = variables["params"]

    import math

    C = max(1, math.ceil(N * cf / E))
    xn = np.asarray(x, np.float32)
    wr = np.asarray(p["router"])
    logits = xn @ wr
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros((B, N, D), np.float32)
    for b in range(B):
        counts = np.zeros(E, int)
        for n in range(N):
            e = int(np.argmax(probs[b, n]))
            gate = probs[b, n, e]
            if counts[e] < C:
                counts[e] += 1
                h = xn[b, n] @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e])
                h = 0.5 * h * (1.0 + np.vectorize(math.erf)(h / math.sqrt(2)))
                want[b, n] = (h @ np.asarray(p["w2"][e])
                              + np.asarray(p["b2"][e])) * gate
    np.testing.assert_allclose(np.asarray(y), want, rtol=2e-4, atol=2e-5)


def test_switch_mlp_aux_loss_sown_and_bounded():
    """The Switch load-balance loss E·Σ f_e·P_e is sown; it is ≥ 1 with
    equality only at perfect balance, and absent when not mutable."""
    key = jax.random.PRNGKey(1)
    m, variables, x, y, aux = _mlp_params_and_out(key)
    leaves = jax.tree.leaves(aux["losses"])
    assert len(leaves) == 1
    val = float(leaves[0])
    assert np.isfinite(val) and val >= 0.99  # ≥1 up to float error
    # immutable apply: sow is a silent no-op, same output
    y2 = m.apply(variables, x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y2))


def test_vit_with_experts_trains_and_routes_grads():
    """DiffusionViT(num_experts=4): forward is finite; the train step with
    the aux loss sends gradients through the router."""
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    model = DiffusionViT(img_size=(16, 16), patch_size=4, embed_dim=16,
                         depth=2, num_heads=2, total_steps=8, num_experts=4,
                         drop_rate=0.0, attn_drop_rate=0.0, drop_path_rate=0.0)
    rng = np.random.RandomState(0)
    batch = (jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32),
             jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32),
             jnp.asarray(rng.randint(1, 7, size=(4,)), jnp.int32))
    state = create_train_state(model, jax.random.PRNGKey(0), 1e-2, 10, batch)
    assert "moe" in state.params["blocks_0"]  # expert bank in place of mlp
    router_before = np.asarray(  # snapshot BEFORE the donating step
        state.params["blocks_0"]["moe"]["router"]).copy()
    step = make_train_step(model, moe_aux_weight=0.01)
    s2, loss, _ = step(state, batch, jax.random.PRNGKey(1), jnp.float32(5.0))
    assert np.isfinite(float(loss))
    # router moved → aux gradient flowed through the routing path
    delta = np.abs(np.asarray(s2.params["blocks_0"]["moe"]["router"])
                   - router_before)
    assert delta.max() > 0


@pytest.mark.parametrize("dispatch", ["einsum", "index"])
def test_expert_sharded_step_matches_single_device(dispatch):
    """dp×ep mesh: expert banks shard over 'expert', the step reproduces the
    unsharded result — for BOTH routing implementations (the einsums are
    layout-independent under GSPMD; the index path's gathers must be too)."""
    from ddim_cold_tpu.parallel import make_mesh, shard_batch, shard_train_state
    from ddim_cold_tpu.parallel.sharding import param_partition_specs
    from ddim_cold_tpu.train.step import create_train_state, make_train_step
    from jax.sharding import PartitionSpec as P

    def build():
        model = DiffusionViT(img_size=(16, 16), patch_size=4, embed_dim=16,
                             depth=1, num_heads=2, total_steps=8,
                             num_experts=4, drop_rate=0.0,
                             moe_dispatch=dispatch,
                             attn_drop_rate=0.0, drop_path_rate=0.0)
        rng = np.random.RandomState(0)
        batch = (jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32),
                 jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32),
                 jnp.asarray(rng.randint(1, 7, size=(4,)), jnp.int32))
        state = create_train_state(model, jax.random.PRNGKey(0), 1e-2, 10,
                                   batch)
        return model, state, batch

    model, s1, batch = build()
    step = make_train_step(model, moe_aux_weight=0.01)
    rng = jax.random.PRNGKey(7)
    s1, _, _ = step(s1, batch, rng, jnp.float32(5.0))

    _, s2, _ = build()
    mesh = make_mesh({"data": 2, "expert": 4})
    specs = param_partition_specs(s2.params, axes=("expert",))
    assert specs["blocks_0"]["moe"]["w1"] == P("expert", None, None)
    assert specs["blocks_0"]["moe"]["router"] == P()
    s2 = shard_train_state(s2, mesh, specs)
    s2, _, _ = step(s2, shard_batch(batch, mesh), rng, jnp.float32(5.0))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5),
        s1.params, s2.params)


def test_expert_parallel_composes_with_sequence_parallel():
    """ep×sp on one {data, seq, expert} mesh: ring attention over 'seq'
    (manual shard_map) with expert banks sharded over 'expert' (GSPMD) —
    the step must reproduce the unsharded single-device result."""
    from ddim_cold_tpu.parallel import make_mesh, shard_batch, shard_train_state
    from ddim_cold_tpu.parallel.sharding import param_partition_specs
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    def build(mesh=None):
        kw = dict(img_size=(16, 16), patch_size=4, embed_dim=16,
                  depth=1, num_heads=2, total_steps=8, num_experts=2,
                  drop_rate=0.0, attn_drop_rate=0.0, drop_path_rate=0.0)
        if mesh is not None:
            kw.update(seq_mesh=mesh, seq_axis="seq", batch_axis="data")
        model = DiffusionViT(**kw)
        rng = np.random.RandomState(0)
        batch = (jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32),
                 jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32),
                 jnp.asarray(rng.randint(1, 7, size=(4,)), jnp.int32))
        state = create_train_state(model, jax.random.PRNGKey(0), 1e-2, 10,
                                   batch)
        return model, state, batch

    model, s1, batch = build()
    rng = jax.random.PRNGKey(7)
    s1, _, _ = make_train_step(model, moe_aux_weight=0.01)(
        s1, batch, rng, jnp.float32(5.0))

    mesh = make_mesh({"data": 2, "seq": 2, "expert": 2})
    model2, s2, _ = build(mesh)
    specs = param_partition_specs(s2.params, axes=("expert",))
    s2 = shard_train_state(s2, mesh, specs)
    s2, _, _ = make_train_step(model2, moe_aux_weight=0.01)(
        s2, shard_batch(batch, mesh), rng, jnp.float32(5.0))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5),
        s1.params, s2.params)


@pytest.mark.isolated
def test_moe_trainer_end_to_end(tmp_path, synthetic_image_dir):
    """yaml num_experts=2 trains, evaluates (sow no-op on the immutable
    eval path), and checkpoints — in BOTH block layouts (scan_blocks
    composition was previously rejected; the scan now stacks the sown aux
    losses on the layer axis)."""
    from ddim_cold_tpu.config import load_config
    from ddim_cold_tpu.train.trainer import run
    from tests.test_train import _write_config

    cfg = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                    num_experts=2, epoch=[0, 1]), "exp")
    result = run(cfg, str(tmp_path), log_every=2)
    assert result.steps == 5 and np.isfinite(result.last_val_loss)

    scanned = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                        num_experts=2, scan_blocks=True,
                                        epoch=[0, 1]), "exp")
    result = run(scanned, str(tmp_path / "scan"), log_every=2)
    assert result.steps == 5 and np.isfinite(result.last_val_loss)


def test_moe_expert_sharding_in_scan_layout():
    """Stacked scan_blocks MoE params are (depth, E, ...): the 'expert' spec
    must land on dim 1, not the leading layer axis (sharding dim 0 splits
    layers over the expert mesh — a crash whenever depth % E != 0, silently
    wrong layout otherwise). End-to-end: shard a depth-3, E-2 model on a
    {data, expert} mesh and take one finite step."""
    from ddim_cold_tpu.parallel.mesh import make_mesh, shard_batch, shard_train_state
    from ddim_cold_tpu.parallel.sharding import param_partition_specs
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    cfg = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=3,
               num_heads=2, num_experts=2, scan_blocks=True)
    model = DiffusionViT(**cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16, 3))
    t = jnp.array([3, 500, 9, 77], jnp.int32)
    params = model.init(jax.random.PRNGKey(1), x, t)["params"]

    specs = param_partition_specs(params, axes=("expert",))
    # depth=3 is NOT divisible by E=2 — a dim-0 'expert' spec cannot even shard
    for spec in jax.tree.leaves(specs["blocks"]["moe"],
                                is_leaf=lambda s: not isinstance(s, dict)):
        if "expert" in tuple(spec):
            assert tuple(spec)[0] is None and tuple(spec)[1] == "expert", spec

    mesh = make_mesh({"data": 4, "expert": 2})
    batch = (x, x, t)
    state = create_train_state(model, jax.random.PRNGKey(2), lr=1e-3,
                               total_steps=10, sample_batch=batch)
    state = shard_train_state(state, mesh, specs)
    step = make_train_step(model, moe_aux_weight=0.01)
    state, loss, _ = step(state, shard_batch(batch, mesh),
                          jax.random.PRNGKey(3), jnp.float32(5.0))
    assert np.isfinite(float(loss)), loss


def test_moe_aux_loss_layout_parity():
    """The Switch aux loss is identical (same params, same inputs) whether
    the trunk is unrolled or nn.scan-stacked — the scan keeps the sown
    'losses' collection on the layer axis, and the step normalizes by total
    element count so both layouts weight it the same."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    cfg = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
               num_heads=2, num_experts=2, drop_rate=0.0, attn_drop_rate=0.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16, 3))
    t = jnp.array([3, 500, 9, 77], jnp.int32)
    loop = DiffusionViT(**cfg)
    scan = DiffusionViT(scan_blocks=True, **cfg)
    params = loop.init(jax.random.PRNGKey(1), x, t)["params"]
    stacked = ckpt.stack_block_params(params)

    def total_aux(model, p):
        out, aux_vars = model.apply({"params": p}, x, t, mutable=["losses"])
        sown = jax.tree.leaves(aux_vars.get("losses", {}))
        n = sum(s.size for s in sown)
        return out, sum(jnp.sum(s) for s in sown) / n

    out_a, aux_a = total_aux(loop, params)
    out_b, aux_b = total_aux(scan, stacked)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_a),
                               rtol=1e-5, atol=1e-6)
    assert float(aux_a) > 0.0
    np.testing.assert_allclose(float(aux_b), float(aux_a), rtol=1e-6)


@pytest.mark.isolated
def test_expert_mesh_axis_validated(tmp_path, synthetic_image_dir):
    """An 'expert' mesh axis without (divisible) num_experts fails fast."""
    from ddim_cold_tpu.config import load_config
    from ddim_cold_tpu.train.trainer import run
    from tests.test_train import _write_config

    cfg = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                    mesh={"data": 2, "expert": 2}), "exp")
    with pytest.raises(ValueError, match="expert"):
        run(cfg, str(tmp_path), log_every=2)


@pytest.mark.isolated
def test_moe_bridge_refusal_and_warm_start_fallback(tmp_path,
                                                    synthetic_image_dir):
    """MoE params have no reference torch layout: the pkl bridge refuses
    them with a clear error, and a warm-starting MoE run falls back to an
    orbax init persist instead of crashing at startup."""
    from ddim_cold_tpu.config import load_config
    from ddim_cold_tpu.train.trainer import run
    from ddim_cold_tpu.utils import checkpoint as ckpt
    from tests.test_train import _write_config

    model = DiffusionViT(img_size=(16, 16), patch_size=4, embed_dim=16,
                         depth=1, num_heads=2, num_experts=2)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 16, 16, 3), np.float32),
                        np.zeros((1,), np.int32))["params"]
    with pytest.raises(ValueError, match="no reference torch layout"):
        ckpt.torch_state_dict_from_flax(params, patch_size=4)

    cfg = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                    num_experts=2, epoch=[0, 1],
                                    initializing="warm.pkl"), "exp")
    result = run(cfg, str(tmp_path), log_every=2)
    assert result.steps == 5
    import os as _os

    init = _os.path.join(str(tmp_path), "Saved_Models", "warm.pkl")
    assert _os.path.isdir(init)  # orbax fallback, not a pkl file
    log = open(_os.path.join(result.run_dir, "train.log")).read()
    assert "init pkl export unavailable" in log


def test_num_experts_validated(tmp_path, synthetic_image_dir):
    from ddim_cold_tpu.config import load_config
    from tests.test_train import _write_config

    with pytest.raises(ValueError, match="num_experts"):
        load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                  num_experts=0), "exp")


def test_switch_mlp_out_features_respected():
    """out_features != input width projects to the declared width (the field
    must not be dead code)."""
    m = SwitchMlp(num_experts=2, hidden_features=8, out_features=6, drop=0.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 4))
    variables = {"params": m.init(jax.random.PRNGKey(1), x)["params"]}
    y = m.apply(variables, x)
    assert y.shape == (1, 8, 6)


def test_moe_config_knobs_validated(tmp_path, synthetic_image_dir):
    from ddim_cold_tpu.config import load_config
    from tests.test_train import _write_config

    with pytest.raises(ValueError, match="moe_capacity_factor"):
        load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                  moe_capacity_factor=0.0), "exp")
    with pytest.raises(ValueError, match="moe_aux_weight"):
        load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                  moe_aux_weight=-0.1), "exp")


def test_index_dispatch_matches_einsum():
    """The sort/gather dispatch is numerically interchangeable with the
    one-hot einsum dispatch — same params, same inputs, same outputs, same
    aux loss — including under tight capacity where overflow happens (the
    stable sort must drop exactly the cumsum-priority overflow set)."""
    key = jax.random.PRNGKey(3)
    for cf in (1.25, 0.5):  # roomy and overflowing
        B, N, D, E = 2, 16, 8, 4
        m_e = SwitchMlp(num_experts=E, hidden_features=D, out_features=D,
                        capacity_factor=cf, drop=0.0)
        m_i = SwitchMlp(num_experts=E, hidden_features=D, out_features=D,
                        capacity_factor=cf, drop=0.0, dispatch="index")
        x = jax.random.normal(jax.random.fold_in(key, 1), (B, N, D))
        variables = {"params": m_e.init(key, x)["params"]}
        y_e, aux_e = m_e.apply(variables, x, mutable=["losses"])
        y_i, aux_i = m_i.apply(variables, x, mutable=["losses"])
        np.testing.assert_allclose(np.asarray(y_i), np.asarray(y_e),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(aux_i)[0]),
            np.asarray(jax.tree.leaves(aux_e)[0]), rtol=1e-6)


def test_index_dispatch_gradients_match_einsum():
    """Both dispatch modes differentiate to the same parameter gradients —
    the gather/scatter-free combine must not detach any path."""
    key = jax.random.PRNGKey(4)
    B, N, D, E = 2, 12, 8, 4
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, N, D))
    m_e = SwitchMlp(num_experts=E, hidden_features=D, out_features=D,
                    capacity_factor=0.75, drop=0.0)
    m_i = SwitchMlp(num_experts=E, hidden_features=D, out_features=D,
                    capacity_factor=0.75, drop=0.0, dispatch="index")
    params = m_e.init(key, x)["params"]

    def loss(mod, p):
        return jnp.sum(mod.apply({"params": p}, x) ** 2)

    g_e = jax.grad(lambda p: loss(m_e, p))(params)
    g_i = jax.grad(lambda p: loss(m_i, p))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        g_e, g_i)


def test_index_dispatch_in_model_and_config(tmp_path, synthetic_image_dir):
    """moe_dispatch threads YAML → config → model → SwitchMlp, validates its
    values, and the index model trains a step."""
    from ddim_cold_tpu.config import load_config
    from ddim_cold_tpu.train.step import create_train_state, make_train_step
    from tests.test_train import _write_config

    with pytest.raises(ValueError, match="moe_dispatch"):
        load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                  moe_dispatch="sparse"), "exp")
    cfg = load_config(_write_config(str(tmp_path), synthetic_image_dir,
                                    num_experts=2, moe_dispatch="index"),
                      "exp")
    assert cfg.model_kwargs()["moe_dispatch"] == "index"

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32,
                         depth=1, num_heads=2, num_experts=2,
                         moe_dispatch="index")
    r = np.random.RandomState(0)
    batch = (jnp.asarray(r.randn(2, 16, 16, 3), jnp.float32),
             jnp.asarray(r.randn(2, 16, 16, 3), jnp.float32),
             jnp.asarray(r.randint(1, 7, size=(2,)), jnp.int32))
    state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                               total_steps=10, sample_batch=batch)
    step = make_train_step(model, moe_aux_weight=0.01)
    state, loss, _ = step(state, batch, jax.random.PRNGKey(1),
                          jnp.float32(5.0))
    assert np.isfinite(float(loss))


def test_index_dispatch_long_sequence_parity():
    """N=2501 (the 200px/p4 token count): the index path matches the einsum
    path at the scale it exists for. B=1 keeps the einsum reference's
    (B, N, E, C) dispatch tensor affordable (~31 MB) — at training batch
    sizes only the index path is viable, which is the point."""
    key = jax.random.PRNGKey(5)
    N, D, E = 2501, 32, 4
    m_e = SwitchMlp(num_experts=E, hidden_features=D, out_features=D,
                    capacity_factor=1.25, drop=0.0)
    m_i = SwitchMlp(num_experts=E, hidden_features=D, out_features=D,
                    capacity_factor=1.25, drop=0.0, dispatch="index")
    x = jax.random.normal(jax.random.fold_in(key, 1), (1, N, D))
    variables = {"params": m_e.init(key, x)["params"]}
    y_e = m_e.apply(variables, x)
    y_i = m_i.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y_i), np.asarray(y_e),
                               rtol=2e-5, atol=2e-6)
