"""StallWatchdog (utils/watchdog.py) — the wedged-tunnel guard extracted
from bench.py after r05's fid_trend hang (results/tunnel_diag_r05.txt).

os._exit semantics force subprocess tests: the abort path must kill a
process whose main thread never re-enters the interpreter.
"""

import subprocess
import sys
import time

import pytest

PRELUDE = """
import sys, time
sys.path.insert(0, {repo!r})
from ddim_cold_tpu.utils.watchdog import StallWatchdog
"""


def run_script(body, repo, timeout=30):
    code = PRELUDE.format(repo=repo) + body
    t0 = time.time()
    proc = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                          capture_output=True, text=True)
    return proc, time.time() - t0


@pytest.fixture()
def repo():
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_stall_aborts_with_partial_artifact(tmp_path, repo):
    marker = tmp_path / "partial.txt"
    body = f"""
def on_abort(label, silent):
    open({str(marker)!r}, "w").write(f"{{label}}|{{silent:.1f}}")
wd = StallWatchdog(0.4, on_abort=on_abort, name="t").start()
wd.mark("the-silent-op")
time.sleep(30)
"""
    proc, dt = run_script(body, repo)
    assert proc.returncode == 3
    assert dt < 10, f"abort took {dt:.1f}s for a 0.4s budget"
    assert marker.read_text().startswith("the-silent-op|")
    assert "STALL" in proc.stderr


def test_marks_keep_it_alive_and_done_disarms(repo):
    body = """
wd = StallWatchdog(0.6, name="t").start()
for i in range(8):
    wd.mark(f"step {i}")
    time.sleep(0.25)  # each window < 0.6s: never stalls
wd.done()
time.sleep(1.0)  # disarmed: silence after done() must not abort
print("finished")
"""
    proc, _ = run_script(body, repo)
    assert proc.returncode == 0
    assert "finished" in proc.stdout


def test_budget_stretches_one_window(repo):
    body = """
wd = StallWatchdog(0.3, name="t").start()
wd.mark("long first compile", budget_s=5.0)
time.sleep(1.2)  # > stall_s, < budget: must survive
wd.mark("fast op")           # budget does NOT carry to the next window
wd.done()
print("survived")
"""
    proc, _ = run_script(body, repo)
    assert proc.returncode == 0
    assert "survived" in proc.stdout


def test_disabled_when_nonpositive(repo):
    body = """
wd = StallWatchdog(0.0, name="t").start()  # CPU runs: no tunnel to wedge
time.sleep(0.5)
print("no thread, no abort")
"""
    proc, _ = run_script(body, repo)
    assert proc.returncode == 0


def test_soft_mode_calls_abort_without_exit(repo):
    """exit_code=None (the serving engine's mode): on stall the watchdog
    fires on_abort ONCE, stops itself, and the process lives on — waiters
    get failed by the hook instead of the host dying. In-process test: no
    os._exit to dodge."""
    from ddim_cold_tpu.utils.watchdog import StallWatchdog

    calls = []
    wd = StallWatchdog(0.2, exit_code=None,
                       on_abort=lambda label, silent: calls.append(label),
                       name="soft").start()
    wd.mark("wedged-op")
    deadline = time.time() + 10
    while not calls and time.time() < deadline:
        time.sleep(0.05)
    assert calls == ["wedged-op"]
    time.sleep(0.3)  # watchdog stopped itself: no second abort, no exit
    assert calls == ["wedged-op"]
    assert wd._state["done"]  # the thread retired after the one abort
