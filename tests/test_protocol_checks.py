"""R-layer self-tests: one deliberately violating fixture per protocol
rule (asserting the stable rule id, subject, and line), the PR-19
regression shapes (rid-after-send, unbounded hello) replayed as sources,
the import-time parity checks under monkeypatched tables, and the
clean-tree run (the committed protocol modules carry zero findings)."""

import textwrap

import pytest

from ddim_cold_tpu.analysis import protocol_checks as P
from ddim_cold_tpu.analysis.findings import RULES, rule_layer

WIRE = frozenset({"ServeError", "RequestFailedError", "TimeoutError",
                  "ConnectionError", "ValueError", "RuntimeError"})


def _lint(source, rel="fix.py"):
    return P.lint_source(textwrap.dedent(source), rel, wire_names=WIRE)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------------ R001


def test_r001_table_missing_wire_method():
    fs = _lint("""\
        CLIENT_METHODS = ("ping",)
        CLIENT_EVENT_ARMS = ()

        class C:
            def warm(self):
                return self._call("ping"), self._call("warm")
    """)
    assert _rules_of(fs) == ["GRAFT-R001"]
    assert len(fs) == 1
    assert fs[0].subject == "CLIENT_METHODS:warm"
    assert fs[0].line == 1  # points at the stale table, not the site


def test_r001_table_entry_without_site():
    fs = _lint("""\
        SERVER_METHODS = ("ping", "drain")
        SERVER_EVENTS = ()

        class S:
            def handle(self, method, msg):
                self.faults.fire("replica.kill", tag="t|")
                self.faults.fire("replica.hang", tag="t|")
                if method == "ping":
                    return {}
    """)
    assert len(fs) == 1
    assert fs[0].rule == "GRAFT-R001"
    assert fs[0].subject == "SERVER_METHODS:drain"


def test_r001_wire_literals_without_any_table():
    fs = _lint("""\
        class C:
            def ping(self):
                return self._call("ping")
    """)
    assert len(fs) == 1
    assert fs[0].subject == "missing-table:CLIENT_METHODS"


def test_r001_import_half_table_parity(monkeypatch):
    from ddim_cold_tpu.serve import remote

    monkeypatch.setattr(
        remote, "CLIENT_EVENT_ARMS",
        tuple(e for e in remote.CLIENT_EVENT_ARMS
              if e != "protocol_error"))
    fs = P._table_parity()
    assert len(fs) == 1
    assert fs[0].rule == "GRAFT-R001"
    assert fs[0].subject == "undispatched-event:protocol_error"


def test_r001_health_pin_flags_unprovided_key(monkeypatch):
    # a key no backend provides AND no consumer reads: one finding per
    # provider pair plus the consumer-freshness finding
    monkeypatch.setattr(P, "REQUIRED_HEALTH_KEYS",
                        P.REQUIRED_HEALTH_KEYS + ("bogus_key",))
    fs = P._check_health_parity(_repo_root())
    assert _rules_of(fs) == ["GRAFT-R001"]
    subjects = {f.subject for f in fs}
    assert "health-key:Engine:bogus_key" in subjects
    assert "health-key:StubEngine:bogus_key" in subjects
    assert "health-key:bogus_key" in subjects  # nobody reads it either


def _repo_root():
    from ddim_cold_tpu.analysis.cli import repo_root

    return repo_root()


# ------------------------------------------------------------------ R002


def test_r002_unregistered_raise_in_protocol_module():
    fs = _lint("""\
        class C:
            def process(self, method):
                if method is None:
                    raise BogusError("not on the wire")
    """)
    assert len(fs) == 1
    assert fs[0].rule == "GRAFT-R002"
    assert fs[0].subject == "C.process:BogusError"
    assert fs[0].line == 4


def test_r002_registered_raises_and_reraises_pass():
    fs = _lint("""\
        class C:
            def process(self, exc):
                try:
                    raise ValueError("typed")
                except ValueError:
                    raise
                raise exc
    """)
    assert fs == []


def test_r002_wire_roundtrip_clean():
    assert P._check_wire_roundtrip() == []


# ------------------------------------------------------------------ R003


PR19_RACE = """\
    CLIENT_METHODS = ("submit",)
    CLIENT_EVENT_ARMS = ()

    class Replica:
        def submit(self, params, ticket):
            rid = self._next_rid()
            resp = self._call("submit", params)
            self._tickets[rid] = ticket
            return ticket
"""


def test_r003_rid_registered_after_send_the_pr19_race():
    fs = _lint(PR19_RACE)
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-R003"
    assert f.subject == "Replica.submit"
    assert f.line == 8  # the late registration statement


def test_r003_submit_without_any_registration():
    fs = _lint("""\
        CLIENT_METHODS = ("submit",)
        CLIENT_EVENT_ARMS = ()

        class Replica:
            def submit(self, params):
                return self._call("submit", params)
    """)
    assert len(fs) == 1
    assert fs[0].rule == "GRAFT-R003"
    assert fs[0].subject == "Replica.submit"


def test_r003_register_before_send_passes():
    fs = _lint("""\
        CLIENT_METHODS = ("submit",)
        CLIENT_EVENT_ARMS = ()

        class Replica:
            def submit(self, params, ticket):
                rid = self._next_rid()
                self._tickets[rid] = ticket
                resp = self._call("submit", params)
                return ticket
    """)
    assert fs == []


# ------------------------------------------------------------------ R004


def test_r004_unchecked_length_prefix():
    fs = _lint("""\
        import struct

        def recv_frame(sock):
            (length,) = struct.unpack(">I", recv_exact(sock, 4))
            return recv_exact(sock, length)
    """)
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-R004"
    assert f.subject == "recv_frame:unchecked-length"
    assert f.line == 4  # the first read fed by the unchecked prefix


def test_r004_unbounded_hello_the_pr19_shape():
    fs = _lint("""\
        def remote_factory(conn):
            conn.settimeout(None)
            hello = recv_frame(conn)
            return hello
    """)
    assert len(fs) == 1
    f = fs[0]
    assert f.rule == "GRAFT-R004"
    assert f.subject == "remote_factory:unbounded-read"
    assert f.line == 2


def test_r004_uncapped_recv_chunk():
    fs = _lint("""\
        def drain(sock, n):
            return sock.recv(n)
    """)
    assert len(fs) == 1
    assert fs[0].subject == "drain:uncapped-recv"


def test_r004_unchecked_sendall():
    fs = _lint("""\
        def send_frame(sock, payload):
            sock.sendall(payload)
    """)
    assert len(fs) == 1
    assert fs[0].subject == "send_frame:unchecked-send"


def test_r004_disciplined_wire_functions_pass():
    fs = _lint("""\
        import struct

        MAX_FRAME_BYTES = 1 << 30

        def recv_frame(sock):
            (length,) = struct.unpack(">I", recv_exact(sock, 4))
            if length > MAX_FRAME_BYTES:
                raise ValueError("frame too large")
            return recv_exact(sock, length)

        def recv_exact(sock, n):
            return sock.recv(min(n, 1 << 20))

        def send_frame(sock, payload):
            if len(payload) > MAX_FRAME_BYTES:
                raise ValueError("frame too large")
            sock.sendall(payload)

        def remote_factory(conn, deadline):
            conn.settimeout(deadline)
            hello = recv_frame(conn)
            conn.settimeout(None)
            return hello
    """)
    assert fs == []


# ------------------------------------------------------------------ R005


def test_r005_send_path_missing_one_chaos_site():
    fs = _lint("""\
        class Replica:
            def _send(self, frame):
                self.faults.fire("rpc.drop", tag="t|")
                self._write(frame)
    """)
    # exactly one finding: rpc.drop fires but rpc.latency never does
    assert len(fs) == 1
    assert fs[0].rule == "GRAFT-R005"
    assert fs[0].subject == "rpc.latency"


def test_r005_handle_without_kill_hang_sites():
    fs = _lint("""\
        SERVER_METHODS = ("ping",)
        SERVER_EVENTS = ()

        class S:
            def handle(self, method, msg):
                if method == "ping":
                    return {}
    """)
    r5 = [f for f in fs if f.rule == "GRAFT-R005"]
    assert {f.subject for f in r5} == {"replica.kill", "replica.hang"}


def test_r005_site_registration_clean():
    assert P._check_site_registration() == []


# ------------------------------------------------- layer wiring + clean


def test_r_rules_registered_and_layered():
    for rule in ("GRAFT-R001", "GRAFT-R002", "GRAFT-R003", "GRAFT-R004",
                 "GRAFT-R005"):
        assert rule in RULES
        assert rule_layer(rule) == "protocol"


def test_clean_tree_protocol_layer():
    """The committed wire is fully disciplined: zero R findings, same as
    CI's `graftcheck --only R` run."""
    assert P.run_protocol_checks() == []


def test_cli_only_r_runs_protocol_layer(capsys):
    from ddim_cold_tpu.analysis import cli

    assert cli.main(["--only", "R"]) == 0
    assert "[layers: protocol]" in capsys.readouterr().out
