"""Entry-point smoke tests: the three reference CLIs (`python ViT.py`,
`python ViT_draft2drawing.py`, `python multi_gpu_trainer.py <Exp>`) run
end-to-end with a tiny injected config and produce their artifacts."""

import importlib.util
import os
import sys

import numpy as np
import pytest
from click.testing import CliRunner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2, num_heads=4)


def _load(name):
    spec = importlib.util.spec_from_file_location(name, os.path.join(REPO, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def tiny_config(monkeypatch, tmp_path):
    from ddim_cold_tpu.models import MODEL_CONFIGS

    monkeypatch.setitem(MODEL_CONFIGS, "test_tiny", TINY)
    return "test_tiny"


def test_vit_cli_smoke(tiny_config, monkeypatch, tmp_path):
    vit = _load("ViT")
    monkeypatch.setattr(vit, "HERE", str(tmp_path))
    res = CliRunner().invoke(
        vit.main,
        ["--config", tiny_config, "--init-random", "--sample_n", "4", "--acc_k", "500"],
    )
    assert res.exit_code == 0, res.output
    saved = tmp_path / "Saved_Models"
    assert (saved / "denoise_sequence.png").is_file()
    assert (saved / "samples.png").is_file()


def test_draft2drawing_cli_smoke(tiny_config, monkeypatch, tmp_path, synthetic_image_dir):
    d2d = _load("ViT_draft2drawing")
    monkeypatch.setattr(d2d, "HERE", str(tmp_path))
    draft = os.path.join(synthetic_image_dir, "0.jpg")
    res = CliRunner().invoke(
        d2d.main,
        ["--config", tiny_config, "--init-random", "--cold-n", "2",
         "--draft", draft, "--interpolate", draft,
         os.path.join(synthetic_image_dir, "1.jpg")],
    )
    assert res.exit_code == 0, res.output
    saved = tmp_path / "Saved_Models"
    for artifact in ("cold_sequence.png", "cold_samples.png",
                     "draft2img.png", "interpolation.png"):
        assert (saved / artifact).is_file(), artifact


def test_draft2drawing_img2tensor_range(synthetic_image_dir):
    d2d = _load("ViT_draft2drawing")
    x = np.asarray(d2d.img2tensor(os.path.join(synthetic_image_dir, "0.jpg"), (16, 16)))
    assert x.shape == (1, 16, 16, 3)
    assert x.min() >= -1.0 and x.max() <= 1.0
