"""Entry-point smoke tests: the three reference CLIs (`python ViT.py`,
`python ViT_draft2drawing.py`, `python multi_gpu_trainer.py <Exp>`) run
end-to-end with a tiny injected config and produce their artifacts."""

import importlib.util
import os
import sys

import numpy as np
import pytest
from click.testing import CliRunner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2, num_heads=4)


def _load(name):
    spec = importlib.util.spec_from_file_location(name, os.path.join(REPO, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def tiny_config(monkeypatch, tmp_path):
    from ddim_cold_tpu.models import MODEL_CONFIGS

    monkeypatch.setitem(MODEL_CONFIGS, "test_tiny", TINY)
    return "test_tiny"


def test_vit_cli_smoke(tiny_config, monkeypatch, tmp_path):
    vit = _load("ViT")
    monkeypatch.setattr(vit, "HERE", str(tmp_path))
    res = CliRunner().invoke(
        vit.main,
        ["--config", tiny_config, "--init-random", "--sample_n", "4", "--acc_k", "500"],
    )
    assert res.exit_code == 0, res.output
    saved = tmp_path / "Saved_Models"
    assert (saved / "denoise_sequence.png").is_file()
    assert (saved / "samples.png").is_file()


def test_draft2drawing_cli_smoke(tiny_config, monkeypatch, tmp_path, synthetic_image_dir):
    d2d = _load("ViT_draft2drawing")
    monkeypatch.setattr(d2d, "HERE", str(tmp_path))
    draft = os.path.join(synthetic_image_dir, "0.jpg")
    res = CliRunner().invoke(
        d2d.main,
        ["--config", tiny_config, "--init-random", "--cold-n", "2",
         "--draft", draft, "--interpolate", draft,
         os.path.join(synthetic_image_dir, "1.jpg")],
    )
    assert res.exit_code == 0, res.output
    saved = tmp_path / "Saved_Models"
    for artifact in ("cold_sequence.png", "cold_samples.png",
                     "draft2img.png", "interpolation.png"):
        assert (saved / artifact).is_file(), artifact


@pytest.mark.isolated
def test_trainer_launcher_smoke(monkeypatch, tmp_path, synthetic_image_dir):
    """`python multi_gpu_trainer.py <Exp>`: yaml → run dir → train.log +
    dual checkpoints (reference multi_gpu_trainer.py:167-219 surface)."""
    import yaml

    cfg = dict(
        initializing="none", resume="none", AMP=False, framework="smoke",
        num_gpus=1, batch_size=2, epoch=[0, 1], base_lr=0.005,
        dataStorage=[synthetic_image_dir, synthetic_image_dir],
        image_size=[16, 16], diff_step=4, patch_size=8, embed_dim=32,
        depth=2, head=4,
    )
    with open(tmp_path / "exp.yaml", "w") as f:
        yaml.safe_dump(cfg, f)
    monkeypatch.chdir(tmp_path)

    trainer = _load("multi_gpu_trainer")
    assert trainer.main(["multi_gpu_trainer.py", "exp"], base_dir=str(tmp_path)) == 0
    run_dir = tmp_path / "Saved_Models" / "expsmoke"
    assert (run_dir / "train.log").is_file()
    assert (run_dir / "exp.yaml").is_file()
    assert (run_dir / "lastepoch.ckpt").is_dir()
    log = (run_dir / "train.log").read_text()
    assert "TrainSet batchs:" in log and "epoch:" in log


def test_shipped_experiment_yaml_parses():
    """The in-repo 20220822.yaml matches the reference schema and derivations
    (batch doubling under AMP, lr rule — multi_gpu_trainer.py:191-196)."""
    from ddim_cold_tpu.config import load_config

    cfg = load_config(os.path.join(REPO, "20220822.yaml"), "20220822")
    assert cfg.effective_batch == 32  # AMP doubles 16
    assert abs(cfg.lr - 0.005 * 32 * 1 / 512) < 1e-12
    assert cfg.run_name == "20220822vit_tiny_diffusion"
    assert cfg.model_kwargs()["embed_dim"] == 384
    assert cfg.total_steps == 2000  # diff_step recorded but not forwarded (quirk #4)


def test_diffusion_loader_shim(tmp_path, synthetic_image_dir):
    """Reference import surface + the C26 visual check script
    (diffusion_loader.py:141-154)."""
    dl = _load("diffusion_loader")
    ds = dl.ColdDownSampleDataset_au(synthetic_image_dir, imgSize=(16, 16))
    noisy, target, t = ds[0]
    assert ds.target_mode == "direct"
    assert noisy.shape == (16, 16, 3) and target.shape == (16, 16, 3)
    assert 1 <= t <= ds.max_step
    out = str(tmp_path / "pairs.png")
    assert dl.main(["diffusion_loader.py", synthetic_image_dir, out]) == 0
    assert os.path.getsize(out) > 0


def test_draft2drawing_img2tensor_range(synthetic_image_dir):
    d2d = _load("ViT_draft2drawing")
    x = np.asarray(d2d.img2tensor(os.path.join(synthetic_image_dir, "0.jpg"), (16, 16)))
    assert x.shape == (1, 16, 16, 3)
    assert x.min() >= -1.0 and x.max() <= 1.0


@pytest.mark.isolated
def test_publish_run_levels_follow_run_config(monkeypatch, tmp_path,
                                              synthetic_image_dir):
    """scripts/publish_run.py on a finished run dir: artifacts appear and the
    cold-sample grids use the run's OWN level count (t ∈ [1, log2(H)]) — a
    200px run must publish 7-level sequences, not the 64px default of 6
    (the rule compute_fid/fid_trend already apply)."""
    import importlib.util as ilu

    import yaml

    cfg = dict(
        initializing="none", resume="none", AMP=False, framework="smoke",
        num_gpus=1, batch_size=2, epoch=[0, 1], base_lr=0.005,
        dataStorage=[synthetic_image_dir, synthetic_image_dir],
        image_size=[16, 16], diff_step=4, patch_size=8, embed_dim=32,
        depth=2, head=4,
    )
    with open(tmp_path / "exp.yaml", "w") as f:
        yaml.safe_dump(cfg, f)
    monkeypatch.chdir(tmp_path)
    trainer = _load("multi_gpu_trainer")
    assert trainer.main(["multi_gpu_trainer.py", "exp"], base_dir=str(tmp_path)) == 0
    run_dir = tmp_path / "Saved_Models" / "expsmoke"

    spec = ilu.spec_from_file_location(
        "publish_run", os.path.join(REPO, "scripts", "publish_run.py"))
    pub = ilu.module_from_spec(spec)
    spec.loader.exec_module(pub)
    monkeypatch.setattr(pub, "REPO", str(tmp_path))

    seen_levels = []
    from ddim_cold_tpu.ops import sampling

    real_cold = sampling.cold_sample

    def spy(model, params, rng, **kw):
        seen_levels.append(kw.get("levels", 6))
        return real_cold(model, params, rng, **kw)

    monkeypatch.setattr(sampling, "cold_sample", spy)
    pub.main([str(run_dir), "--cpu"])

    out = tmp_path / "results" / "expsmoke"
    for artifact in ("val_curve.png", "samples.png", "cold_sequence.png",
                     "summary.json", "train.log"):
        assert (out / artifact).is_file(), artifact
    # 16px run → log2(16) = 4 levels, for the grid and the sequence alike
    assert seen_levels == [4, 4], seen_levels
