"""Serving engine tests: bucket planning edge cases, engine-vs-direct
BITWISE equality (the ISSUE-2 contract: same request rng, padding rows
discarded), and the zero-compiles-after-warmup guard.

Bitwise works because every sampler row is computed independently of its
batchmates; the engine draws each request's init at the request's own n
(the draw the direct call makes) and only ever slices it. The mesh path is
allclose, not bitwise — a sharded reduction orders differently (same
tolerance as the sampler's own mesh tests)."""

import os
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu import serve
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import sampling
from ddim_cold_tpu.serve.batching import Request, cover_rows, plan_batches, select_bucket
from ddim_cold_tpu.utils import faults

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
            num_heads=4, total_steps=2000)
K = 500  # 4 reverse steps — cheap enough to AOT-compile several programs


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def warmed(model_and_params):
    """One engine + warmed plain-DDIM programs at two buckets, shared by the
    bitwise/packing/stats tests (AOT compiles are the expensive part)."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4, 8))
    cfg = serve.SamplerConfig(k=K)
    report = serve.warmup(eng, [cfg], persistent_cache=False)
    assert report["new_compiles"] == 2  # one program per bucket
    return eng, cfg


def _direct(model, params, seed, n, **kw):
    return np.asarray(sampling.ddim_sample(
        model, params, jax.random.PRNGKey(seed), k=K, n=n, **kw))


# --------------------------------------------------------------- planning


def test_select_bucket():
    assert select_bucket(1, (8, 32, 128)) == 8
    assert select_bucket(8, (8, 32, 128)) == 8
    assert select_bucket(9, (8, 32, 128)) == 32
    assert select_bucket(129, (8, 32, 128)) is None


def test_cover_rows():
    assert cover_rows(5, (4, 8)) == [8]            # 1 batch beats [4, 4]
    assert cover_rows(5, (4, 32, 128)) == [4, 4]   # pad 3 beats [32]'s 27
    assert cover_rows(11, (4, 8)) == [8, 4]
    assert cover_rows(8, (8,)) == [8]
    assert cover_rows(260, (8, 32, 128)) == [128, 128, 8]
    assert cover_rows(1, (8, 32)) == [8]
    with pytest.raises(ValueError):
        cover_rows(3, ())
    with pytest.raises(ValueError):
        cover_rows(3, (0, 4))


def test_plan_batches_empty_queue():
    assert plan_batches([], (8, 32)) == []


def test_plan_batches_packing_offsets_and_split():
    """A request above the largest bucket splits; offsets tile each batch
    contiguously and only the last batch of a group carries padding."""
    cfg = serve.SamplerConfig(k=K)
    reqs = [Request(config=cfg, n=11), Request(config=cfg, n=3)]
    plans = plan_batches(reqs, (4, 8))  # 14 rows → [8, 8] (pad 2)
    assert [p.bucket for p in plans] == [8, 8]
    assert [p.rows for p in plans] == [8, 6]
    assert plans[0].padded_rows == 0 and plans[1].padded_rows == 2
    # request 0's rows 0..8 ride batch 0; rows 8..11 open batch 1, then
    # request 1's rows 0..3 follow at offset 3
    assert plans[0].entries == ((reqs[0], 0, 8, 0),)
    assert plans[1].entries == ((reqs[0], 8, 11, 0), (reqs[1], 0, 3, 3))
    # every batch is tiled contiguously from offset 0
    for plan in plans:
        offset = 0
        for _, lo, hi, off in plan.entries:
            assert off == offset
            offset += hi - lo
        assert offset == plan.rows


def test_plan_batches_mixed_configs_never_share():
    a = serve.SamplerConfig(k=K)
    b = serve.SamplerConfig(k=K, cache_interval=2)
    c = serve.SamplerConfig(sampler="cold")
    reqs = [Request(config=a, n=2), Request(config=b, n=2),
            Request(config=a, n=2), Request(config=c, n=2)]
    plans = plan_batches(reqs, (4, 8))
    assert len(plans) == 3  # a-group coalesced; b and c alone
    for plan in plans:
        assert {e[0].config for e in plan.entries} == {plan.config}
    a_plan = next(p for p in plans if p.config == a)
    assert a_plan.rows == 4 and a_plan.bucket == 4  # coalesced, zero pad


# ----------------------------------------------------------------- engine


def test_engine_bitwise_at_two_buckets(model_and_params, warmed):
    """The acceptance contract, at both compiled buckets in one drain: mixed
    request sizes coalesce into a bucket-8 and a bucket-4 batch, and every
    request comes back bitwise equal to its direct ddim_sample."""
    model, params = model_and_params
    eng, cfg = warmed
    compiles = eng.stats["compiles"]
    tickets = {seed: eng.submit(seed=seed, n=n, config=cfg)
               for seed, n in [(21, 5), (22, 4), (23, 3)]}  # 12 rows → [8, 4]
    report = eng.run()
    assert report["batches"] == 2 and report["rows"] == 12
    assert report["padded_rows"] == 0
    assert eng.stats["compiles"] == compiles  # warmed: zero new programs
    for seed, n in [(21, 5), (22, 4), (23, 3)]:
        got = tickets[seed].result(timeout=5)
        assert got.shape == (n, 16, 16, 3)
        np.testing.assert_array_equal(got, _direct(model, params, seed, n))


def test_engine_bitwise_padded_single_request(model_and_params, warmed):
    """A lone n=3 request pads to bucket 4; padding rows are discarded and
    the real rows keep their bits."""
    model, params = model_and_params
    eng, cfg = warmed
    t = eng.submit(seed=31, n=3, config=cfg)
    report = eng.run()
    assert report["batches"] == 1 and report["padded_rows"] == 1
    np.testing.assert_array_equal(t.result(timeout=5),
                                  _direct(model, params, 31, 3))


def test_engine_bitwise_split_request(model_and_params, warmed):
    """n=11 exceeds the largest bucket (8): the request splits across two
    batches and reassembles bitwise."""
    model, params = model_and_params
    eng, cfg = warmed
    t = eng.submit(seed=41, n=11, config=cfg)
    report = eng.run()
    assert report["batches"] == 2  # [8, 4]
    np.testing.assert_array_equal(t.result(timeout=5),
                                  _direct(model, params, 41, 11))


def test_engine_bitwise_cached_and_cold(model_and_params):
    """Cached-sampler and cold-sampler configs serve bitwise too (their
    scans return the recycled cache; rows must be untouched by that)."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    cached = serve.SamplerConfig(k=K, cache_interval=2)
    cold = serve.SamplerConfig(sampler="cold", levels=4)
    serve.warmup(eng, [cached, cold], persistent_cache=False)
    compiles = eng.stats["compiles"]
    tc = eng.submit(seed=51, n=3, config=cached)
    tk = eng.submit(seed=52, n=2, config=cold)
    # second cached request: exercises cache-buffer recycling across batches
    tc2 = eng.submit(seed=53, n=2, config=cached)
    eng.run()
    assert eng.stats["compiles"] == compiles
    np.testing.assert_array_equal(
        tc.result(timeout=5),
        _direct(model, params, 51, 3, cache_interval=2))
    np.testing.assert_array_equal(
        tc2.result(timeout=5),
        _direct(model, params, 53, 2, cache_interval=2))
    np.testing.assert_array_equal(
        tk.result(timeout=5),
        np.asarray(sampling.cold_sample(model, params, jax.random.PRNGKey(52),
                                        n=2, levels=4)))


def test_engine_guided_requests_bitwise(model_and_params, warmed):
    """Guided serving (x_init + t_start — the sample_from path): the host
    array uploads through the prefetch thread and returns bitwise equal to
    the direct call."""
    model, params = model_and_params
    eng, _ = warmed
    cfg = serve.SamplerConfig(k=K, t_start=999)
    enc = np.asarray(jax.random.normal(jax.random.PRNGKey(61), (2, 16, 16, 3)))
    t = eng.submit(x_init=enc, config=cfg)  # new config: compiles lazily
    eng.run()
    want = np.asarray(sampling.sample_from(model, params, jnp.asarray(enc),
                                           t_start=999, k=K))
    np.testing.assert_array_equal(t.result(timeout=5), want)


def test_zero_compiles_after_warmup_mixed_sizes(model_and_params, warmed):
    """The compile-count guard: after warmup, a stream of requests at many
    distinct sizes — across several drains — triggers ZERO program builds
    (dispatch only ever calls the warmup's AOT executables, which cannot
    retrace). Complement: an unwarmed engine does compile, so the counter
    is live, not trivially zero."""
    model, params = model_and_params
    eng, cfg = warmed
    compiles = eng.stats["compiles"]
    for batch_sizes in ([1, 2], [3, 5, 7], [11], [4, 8, 6]):
        tickets = [eng.submit(seed=70 + n, n=n, config=cfg)
                   for n in batch_sizes]
        eng.run()
        for t in tickets:
            assert t.done
    assert eng.stats["compiles"] == compiles

    fresh = serve.Engine(model, params, buckets=(4,))
    t = fresh.submit(seed=1, n=2, config=cfg)
    fresh.run()
    assert fresh.stats["compiles"] > 0  # lazy compile happened and was counted
    assert t.done


def test_engine_stats_and_latency(model_and_params, warmed):
    eng, cfg = warmed
    n_before = len(eng.stats["latencies_s"])
    t = eng.submit(seed=81, n=2, config=cfg)
    assert eng.queue_depth() == 1
    report = eng.run()
    assert eng.queue_depth() == 0
    assert t.latency_s is not None and t.latency_s > 0
    assert len(eng.stats["latencies_s"]) == n_before + 1
    lat = report["latency"]
    assert lat["n"] == 1 and lat["p95_s"] >= lat["p50_s"] > 0
    assert report["img_per_sec"] > 0
    assert eng.stats["max_queue_depth"] >= 1


def test_engine_validation_and_ticket_timeout(model_and_params):
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    with pytest.raises(ValueError, match="seed= or rng="):
        eng.submit(n=2)
    with pytest.raises(ValueError, match="not both"):
        eng.submit(seed=0, n=2, config=serve.SamplerConfig(), k=10)
    with pytest.raises(ValueError, match="DDIM path"):
        eng.submit(x_init=np.zeros((1, 16, 16, 3)), sampler="cold")
    with pytest.raises(ValueError, match="n must be"):
        eng.submit(seed=0, n=0)
    with pytest.raises(ValueError, match="sampler must be"):
        serve.SamplerConfig(sampler="euler")
    with pytest.raises(ValueError, match="cache_mode"):
        serve.SamplerConfig(cache_mode="none")
    with pytest.raises(ValueError, match="buckets"):
        serve.Engine(model, params, buckets=())
    ticket = eng.submit(seed=0, n=2)
    # never ran — must not hang forever, and the timeout carries the engine
    # health snapshot (an ops page beats "did Engine.run() run?")
    with pytest.raises(TimeoutError, match="queue_depth"):
        ticket.result(timeout=0.01)
    with pytest.raises(TimeoutError, match="engine health"):
        ticket.exception(timeout=0.01)
    # a BARE ticket (no engine attached) keeps the did-run hint
    with pytest.raises(TimeoutError, match="no engine attached"):
        serve.Ticket(1).result(timeout=0.01)


def test_engine_mesh_sharded(model_and_params):
    """Sharded serving: buckets must divide the data axis, and the sharded
    drain reproduces the single-device result within the sampler's own
    SPMD tolerance (bitwise is a per-backend contract, not cross-mesh)."""
    from ddim_cold_tpu.parallel.mesh import make_mesh

    model, params = model_and_params
    mesh = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="divide"):
        serve.Engine(model, params, mesh=mesh, buckets=(4, 8))
    eng = serve.Engine(model, params, mesh=mesh, buckets=(8,))
    cfg = serve.SamplerConfig(k=K)
    serve.warmup(eng, [cfg], persistent_cache=False)
    compiles = eng.stats["compiles"]
    t = eng.submit(seed=91, n=8, config=cfg)
    eng.run()
    assert eng.stats["compiles"] == compiles
    np.testing.assert_allclose(t.result(timeout=5),
                               _direct(model, params, 91, 8),
                               rtol=2e-5, atol=2e-6)


def test_check_compile_cache_script():
    """The scripts/ CI check passes (or capability-skips) on the running
    jax — rc 0 either way; rc 1 means the persistent cache wiring broke."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "check_compile_cache.py")],
        capture_output=True, text=True, timeout=300, cwd=root,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert ("PASS" in proc.stdout) or ("SKIP" in proc.stdout), proc.stdout


# ------------------------------------------------------------------ chaos
#
# Failure isolation under deterministic fault injection (utils/faults.py).
# The liveness contract every case pins: NO ticket ever blocks forever —
# each resolves to its rows or to a typed exception — and the engine keeps
# serving after the chaos scope closes, with ZERO new compiles (recovery
# re-packs at the warmed buckets).


def _all_resolved(tickets, timeout=30):
    """Every ticket resolves (rows or error) within timeout — the no-hung-
    ticket guarantee. Returns the failures."""
    errs = []
    for t in tickets:
        exc = t.exception(timeout=timeout)  # raises TimeoutError on a hang
        if exc is not None:
            errs.append(exc)
    return errs


def test_chaos_transient_dispatch_kill(model_and_params, warmed):
    """Kill a seeded ≥20% of dispatches with the retryable fault class: the
    backoff-retry path absorbs every hit, ALL tickets complete, and every
    one is bitwise-equal to the direct sampler."""
    model, params = model_and_params
    eng, cfg = warmed
    compiles = eng.stats["compiles"]
    retries0 = eng.stats["retries"]
    reqs = [(s, n) for s, n in zip(range(200, 210), [3, 5, 2, 8, 1, 4, 6, 2, 7, 3])]
    spec = faults.FaultSpec("serve.dispatch", "transient", rate=0.35, seed=11)
    with faults.inject(spec) as plan:
        tickets = {s: eng.submit(seed=s, n=n, config=cfg) for s, n in reqs}
        report = eng.run()
        injected = len(plan.realized)
    dispatch_calls = report["batches"] + injected  # every fire = one attempt
    assert injected >= 0.2 * dispatch_calls, (injected, dispatch_calls)
    assert _all_resolved(list(tickets.values())) == []
    assert eng.stats["retries"] - retries0 == injected
    for s, n in reqs:
        np.testing.assert_array_equal(tickets[s].result(timeout=5),
                                      _direct(model, params, s, n))
    assert eng.stats["compiles"] == compiles  # recovery never compiles


def test_chaos_every_serve_site(model_and_params, warmed):
    """Faults at EVERY serve.* pipeline site at once (assemble raises,
    dispatch raises transient, fetch raises): each batch fails only itself,
    non-quarantined survivors are bitwise, nothing hangs, and the engine
    serves a clean follow-up drain."""
    model, params = model_and_params
    eng, cfg = warmed
    compiles = eng.stats["compiles"]
    reqs = [(s, n) for s, n in zip(range(300, 312),
                                   [2, 3, 1, 4, 2, 5, 3, 2, 1, 6, 2, 3])]
    with faults.inject(
            faults.FaultSpec("serve.assemble", "permanent", rate=0.25, seed=2),
            faults.FaultSpec("serve.dispatch", "transient", rate=0.3, seed=3),
            faults.FaultSpec("serve.fetch", "permanent", rate=0.25, seed=4),
    ) as plan:
        tickets = {s: eng.submit(seed=s, n=n, config=cfg) for s, n in reqs}
        eng.run()
        assert len(plan.realized) > 0
        assert set(plan.by_site()) <= {"serve.assemble", "serve.dispatch",
                                       "serve.fetch"}
    errs = _all_resolved(list(tickets.values()))
    for e in errs:  # typed failures only, each carrying the injected cause
        assert isinstance(e, serve.RequestFailedError)
        assert isinstance(e.__cause__, faults.FaultError)
    for s, n in reqs:  # survivors keep their bits
        if not tickets[s].failed:
            np.testing.assert_array_equal(tickets[s].result(timeout=5),
                                          _direct(model, params, s, n))
    assert eng.stats["compiles"] == compiles
    # chaos scope closed: the engine serves clean
    t = eng.submit(seed=399, n=3, config=cfg)
    eng.run()
    np.testing.assert_array_equal(t.result(timeout=5),
                                  _direct(model, params, 399, 3))
    assert eng.stats["compiles"] == compiles


def test_chaos_bisection_quarantines_poisoned_request(model_and_params,
                                                      warmed):
    """A request that deterministically fails ANY batch containing it is
    bisected out: only IT fails (RequestQuarantinedError, injected fault as
    cause), every innocent batchmate completes bitwise, and recovery stays
    on the warmed programs."""
    model, params = model_and_params
    eng, cfg = warmed
    compiles = eng.stats["compiles"]
    quarantined0 = eng.stats["quarantined"]
    tickets = {}
    poison_rid = eng._next_rid + 2  # the third of the five submits below
    with faults.inject(faults.FaultSpec("serve.dispatch", "permanent",
                                        match=f"req:{poison_rid}|")):
        for i, (s, n) in enumerate(zip(range(410, 415), [2, 1, 2, 1, 2])):
            tickets[s] = eng.submit(seed=s, n=n, config=cfg)
        eng.run()
    errs = _all_resolved(list(tickets.values()))
    assert len(errs) == 1 and isinstance(errs[0], serve.RequestQuarantinedError)
    assert isinstance(errs[0].__cause__, faults.PermanentFault)
    assert eng.stats["quarantined"] - quarantined0 == 1
    assert poison_rid in eng.quarantined
    for s, n in zip(range(410, 415), [2, 1, 2, 1, 2]):
        if not tickets[s].failed:
            np.testing.assert_array_equal(tickets[s].result(timeout=5),
                                          _direct(model, params, s, n))
    assert sum(1 for s in range(410, 415) if tickets[s].failed) == 1
    assert eng.stats["compiles"] == compiles  # bisection repacks, no compile


def test_chaos_fetch_corrupt_is_detectable(model_and_params, warmed):
    """A corrupt injection at the fetch site lands exactly one NaN in the
    delivered buffer (detectability: a checksum/validation layer upstream
    would catch it) and records which element in the plan."""
    model, params = model_and_params
    eng, cfg = warmed
    with faults.inject(faults.FaultSpec("serve.fetch", "corrupt", seed=5,
                                        max_fires=1)) as plan:
        t = eng.submit(seed=420, n=4, config=cfg)
        eng.run()
        out = t.result(timeout=5)
    assert int(np.isnan(out).sum()) <= 1  # ≤: the flip may land in padding
    assert plan.realized[0]["detail"]["index"] >= 0
    clean = _direct(model, params, 420, 4)
    mism = out != clean
    assert mism.sum() <= 1  # exactly the flipped element differs


def test_deadline_enforced_at_plan_and_dispatch(model_and_params, warmed):
    """deadline_s=0 expires in the queue (plan-time gate); a live deadline
    that lapses during a slow assembly expires at the dispatch gate and the
    all-expired batch skips the device entirely."""
    model, params = model_and_params
    eng, cfg = warmed
    t0 = eng.submit(seed=430, n=2, config=cfg, deadline_s=0.0)
    time.sleep(0.01)
    eng.run()
    assert isinstance(t0.exception(timeout=5), serve.DeadlineExceeded)
    skipped0 = eng.stats["skipped_batches"]
    t1 = eng.submit(seed=431, n=4, config=cfg, deadline_s=0.1)
    with faults.inject(faults.FaultSpec("serve.assemble", "latency",
                                        latency_s=0.3, max_fires=1)):
        eng.run()
    assert isinstance(t1.exception(timeout=5), serve.DeadlineExceeded)
    assert eng.stats["skipped_batches"] == skipped0 + 1
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit(seed=0, n=1, config=cfg, deadline_s=-1)


def test_bounded_queue_rejects_on_overload(model_and_params):
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,), max_queue=2)
    cfg = serve.SamplerConfig(k=K)
    a = eng.submit(seed=0, n=1, config=cfg)
    b = eng.submit(seed=1, n=1, config=cfg)
    with pytest.raises(serve.QueueFullError, match="max_queue=2"):
        eng.submit(seed=2, n=1, config=cfg)
    assert eng.stats["rejected"] == 1
    assert eng.health()["queue_depth"] == 2
    # drain (without ever running): queued tickets fail deterministically
    health = eng.drain(timeout=1)
    assert health["closed"] and health["queue_depth"] == 0
    for t in (a, b):
        assert isinstance(t.exception(timeout=5), serve.EngineClosedError)
    with pytest.raises(serve.EngineClosedError):
        eng.submit(seed=3, n=1, config=cfg)
    with pytest.raises(ValueError, match="max_queue"):
        serve.Engine(model, params, buckets=(4,), max_queue=0)


def test_stall_watchdog_fails_tickets_not_process(model_and_params):
    """A wedged dispatch (injected 1.2s silence against a 0.3s stall budget)
    trips the SOFT watchdog: in-flight tickets fail with EngineStalledError,
    run() returns (stalled flagged) — the process survives, nothing hangs."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,), stall_s=0.3)
    cfg = serve.SamplerConfig(k=K)
    serve.warmup(eng, [cfg], persistent_cache=False)
    t = eng.submit(seed=440, n=4, config=cfg)
    with faults.inject(faults.FaultSpec("serve.dispatch", "latency",
                                        latency_s=1.2, max_fires=1)):
        report = eng.run()
    assert report["stalled"]
    assert isinstance(t.exception(timeout=5), serve.EngineStalledError)
    assert eng.stats["stalls"] == 1
    assert eng.health()["stalled"]
    # the engine recovers on the next drain (fresh watchdog per run)
    t2 = eng.submit(seed=441, n=2, config=cfg)
    report2 = eng.run()
    assert not report2["stalled"]
    np.testing.assert_array_equal(t2.result(timeout=5),
                                  _direct(model, params, 441, 2))


def test_warmup_tolerate_errors(model_and_params):
    """Degraded startup: a failing compile is recorded, the rest of the
    programs warm, and strict mode still raises."""
    model, params = model_and_params
    cfg = serve.SamplerConfig(k=K)
    eng = serve.Engine(model, params, buckets=(4, 8))
    with faults.inject(faults.FaultSpec("serve.compile", "permanent",
                                        max_fires=1)):
        with pytest.raises(faults.PermanentFault):
            serve.warmup(eng, [cfg], persistent_cache=False)
        report = serve.warmup(eng, [cfg], persistent_cache=False,
                              tolerate_errors=True)
    assert len(report["errors"]) == 0  # max_fires spent on the strict call
    eng2 = serve.Engine(model, params, buckets=(4, 8))
    with faults.inject(faults.FaultSpec("serve.compile", "permanent",
                                        max_fires=1)):
        report = serve.warmup(eng2, [cfg], persistent_cache=False,
                              tolerate_errors=True)
    assert len(report["errors"]) == 1
    assert report["new_compiles"] == 1  # the other program warmed anyway


def test_disarmed_serving_is_bitwise_and_compile_free(model_and_params,
                                                      warmed):
    """The zero-overhead-disarmed contract: after any amount of chaos, a
    disarmed drain is byte-identical to the direct sampler and triggers no
    compiles — the fault hooks cost a flag check on the fast path."""
    model, params = model_and_params
    eng, cfg = warmed
    assert not faults.active()
    compiles = eng.stats["compiles"]
    t = eng.submit(seed=450, n=6, config=cfg)
    eng.run()
    np.testing.assert_array_equal(t.result(timeout=5),
                                  _direct(model, params, 450, 6))
    assert eng.stats["compiles"] == compiles


# ----------------------------------------------------- fleet satellites
#
# Engine-level pieces the replica router (serve/router.py) builds on: the
# drain(timeout) idle-report fix, the health() snapshot fields supervision
# reads, and replica-id threading through failure messages and fault tags.


def test_drain_timeout_skips_sweep_when_not_idle(model_and_params):
    """drain(timeout) against a mid-flight run reports idle=False and does
    NOT sweep the queue — the old code dropped the wait's return and failed
    queued requests while their batches were still on the device. Liveness
    still holds: the run itself fails what it finds queued after close."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    cfg = serve.SamplerConfig(k=K)
    serve.warmup(eng, [cfg], persistent_cache=False)
    a = eng.submit(seed=460, n=2, config=cfg)
    with faults.inject(faults.FaultSpec("serve.dispatch", "latency",
                                        latency_s=0.5, max_fires=1)):
        worker = threading.Thread(target=eng.run, daemon=True)
        worker.start()
        deadline = time.time() + 5
        while (eng.queue_depth() > 0 or not eng.health()["running"]) \
                and time.time() < deadline:
            time.sleep(0.005)  # wait until the run owns request a
        b = eng.submit(seed=461, n=1, config=cfg)  # queued behind the run
        report = eng.drain(timeout=0.05)
        assert report["idle"] is False
        assert not a.done and not b.done  # sweep skipped, nothing raced
        worker.join(timeout=10)
    # the run flushed a (bitwise) and failed b typed on seeing closed
    np.testing.assert_array_equal(a.result(timeout=5),
                                  _direct(model, params, 460, 2))
    assert isinstance(b.exception(timeout=5), serve.EngineClosedError)
    assert eng.drain(timeout=5)["idle"] is True  # settled now


def test_health_has_supervision_fields(model_and_params):
    """health() carries what fleet supervision needs without touching the
    engine: replica identity, max_queue (admission headroom), uptime_s, and
    last_progress_s (wedge detection from a snapshot alone)."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,), max_queue=5,
                       replica_id="rX")
    h = eng.health()
    assert h["replica"] == "rX" and h["max_queue"] == 5
    assert h["uptime_s"] >= 0 and h["last_progress_s"] >= 0
    time.sleep(0.05)
    cfg = serve.SamplerConfig(k=K)
    t = eng.submit(seed=470, n=1, config=cfg)
    eng.run()
    assert t.result(timeout=30) is not None
    h2 = eng.health()
    assert h2["uptime_s"] > h["uptime_s"]
    # the run just made progress: its age is far below the engine's
    assert h2["last_progress_s"] < h2["uptime_s"]
    assert h2["last_progress_s"] < 0.05 + h2["uptime_s"] - h["uptime_s"]


def test_replica_id_in_failure_messages_and_fault_tags(model_and_params):
    """A replica-scoped engine names itself in every failure message (so a
    fleet-level error is attributable) and prefixes its fault tags with
    replica:<id>| (so chaos schedules can target one replica)."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,), replica_id="r9")
    cfg = serve.SamplerConfig(k=K)
    serve.warmup(eng, [cfg], persistent_cache=False)
    with faults.inject(faults.FaultSpec("serve.dispatch", "permanent",
                                        match="replica:r9|")) as plan:
        t = eng.submit(seed=480, n=1, config=cfg)
        eng.run()
        exc = t.exception(timeout=5)
    assert isinstance(exc, serve.RequestQuarantinedError)
    assert "replica 'r9'" in str(exc)
    assert plan.realized and all(
        r["tag"].startswith("replica:r9|") for r in plan.realized)
    # drain-path message carries the id too
    t2 = eng.submit(seed=481, n=1, config=cfg)
    eng.drain(timeout=1)
    assert "replica 'r9'" in str(t2.exception(timeout=5))


# ------------------------------------------------------ sequence parallelism


SP2 = serve.SamplerConfig(k=K, sp_mode="ulysses", sp_degree=2)


def test_sp_config_validation():
    """The sp fields are validated at CONSTRUCTION (satellite of the sp
    tentpole): mode domain, degree floor, the none⟺degree-1 identity in
    both directions, and the sp × batch-coupled-adaptive rejection — each
    error names the knob to change and is the typed
    parallel.SeqParallelConfigError (a ValueError, so untyped callers
    still catch it)."""
    from ddim_cold_tpu.parallel import SeqParallelConfigError
    with pytest.raises(SeqParallelConfigError, match="sp_mode"):
        serve.SamplerConfig(k=K, sp_mode="megatron")
    with pytest.raises(SeqParallelConfigError, match="sp_degree"):
        serve.SamplerConfig(k=K, sp_degree=0)
    with pytest.raises(SeqParallelConfigError, match="sp_mode='ulysses'"):
        serve.SamplerConfig(k=K, sp_degree=2)  # a degree needs a strategy
    with pytest.raises(SeqParallelConfigError, match="sp_degree >= 2"):
        serve.SamplerConfig(k=K, sp_mode="ulysses")  # a strategy, a degree
    with pytest.raises(SeqParallelConfigError, match="adaptive"):
        serve.SamplerConfig(k=K, sp_mode="ring", sp_degree=2,
                            cache_interval=2, cache_mode="adaptive",
                            cache_threshold=0.05)


def test_sp_degenerate_degree1_is_default_config():
    """sp_degree=1 IS the existing program: the config carries no sp state
    (sp_mode='none' is the only legal degree-1 spelling), so it hashes and
    compares equal to the pre-sp default — bitwise-vs-existing is identity
    at the registry key, not a float comparison."""
    assert serve.SamplerConfig(k=K, sp_mode="none", sp_degree=1) == \
        serve.SamplerConfig(k=K)
    assert hash(serve.SamplerConfig(k=K, sp_mode="none", sp_degree=1)) == \
        hash(serve.SamplerConfig(k=K))


@pytest.mark.skipif(jax.device_count() % 2 != 0,
                    reason="sp_degree=2 needs an even device count")
def test_sp_serving_allclose_both_buckets(model_and_params):
    """sp_degree=2 serves at BOTH warmed buckets with zero compiles after
    warmup; rows are allclose to direct sampling — the mesh tolerance (a
    sharded reduction orders differently), not the bitwise contract."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4, 8))
    wu = serve.warmup(eng, [SP2], persistent_cache=False)
    assert wu["new_compiles"] == 2  # one sp program per bucket
    compiles = eng.stats["compiles"]
    tickets = {seed: eng.submit(seed=seed, n=n, config=SP2)
               for seed, n in [(61, 8), (62, 4)]}
    report = eng.run()
    assert report["batches"] == 2
    assert eng.stats["compiles"] == compiles  # zero compiles after warmup
    for seed, n in [(61, 8), (62, 4)]:
        got = tickets[seed].result(timeout=5)
        assert got.shape == (n, 16, 16, 3)
        np.testing.assert_allclose(got, _direct(model, params, seed, n),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.skipif(jax.device_count() % 8 != 0,
                    reason="sp_degree=8 needs a multiple of 8 devices")
def test_sp_ring_fallback_serves(model_and_params):
    """sp_degree=8 with 4 heads cannot run Ulysses (4 % 8 != 0): the engine
    resolves the model through models.sp_clone — the ONE resolver shared
    with the analysis sweep — and serves the config as ring, transparently
    to the caller, at the same float tolerance."""
    model, params = model_and_params
    cfg = serve.SamplerConfig(k=K, sp_mode="ulysses", sp_degree=8)
    eng = serve.Engine(model, params, buckets=(4,))
    serve.warmup(eng, [cfg], persistent_cache=False)
    assert eng._model_for(cfg).sp_mode == "ring"
    compiles = eng.stats["compiles"]
    t = eng.submit(seed=71, n=4, config=cfg)
    eng.run()
    assert eng.stats["compiles"] == compiles
    np.testing.assert_allclose(t.result(timeout=5),
                               _direct(model, params, 71, 4),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.skipif(jax.device_count() != 8,
                    reason="pins the 8-device data-axis arithmetic")
def test_sp_bucket_must_divide_data_axis(model_and_params):
    """bucket 2 cannot tile sp_degree=2's data axis (8 devices → data=4):
    the engine refuses at ensure_program with an actionable error instead
    of letting a mis-tiled batch reach placement."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(2, 4))
    with pytest.raises(ValueError, match="data axis"):
        eng.ensure_program(SP2, 2)


@pytest.mark.skipif(jax.device_count() % 2 != 0,
                    reason="sp_degree=2 needs an even device count")
def test_sp_cached_config_prewarms_spare_pool(model_and_params):
    """A cached sp config warms its program AND a spare step-cache carry
    keyed by (bucket, (kind, sp_mode, sp_degree)) — a carry placed on one
    mesh can never be donated to a program compiled for another — and the
    drain itself is allclose with zero compiles."""
    model, params = model_and_params
    cfg = serve.SamplerConfig(k=K, cache_interval=2, cache_mode="full",
                              sp_mode="ulysses", sp_degree=2)
    eng = serve.Engine(model, params, buckets=(4,))
    serve.warmup(eng, [cfg], persistent_cache=False)
    assert (4, ("pair", "ulysses", 2)) in eng._spare_caches
    compiles = eng.stats["compiles"]
    t = eng.submit(seed=81, n=4, config=cfg)
    eng.run()
    assert eng.stats["compiles"] == compiles
    np.testing.assert_allclose(
        t.result(timeout=5),
        _direct(model, params, 81, 4, cache_interval=2, cache_mode="full"),
        rtol=2e-5, atol=2e-5)
