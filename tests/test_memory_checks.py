"""GRAFT-M self-tests: the liveness walk on small known programs (with and
without donation, nested bodies), the over-budget and padded-token
fixtures, and the clean run over the 200px sampler entries + serve sweep.

The walk's arithmetic is checked against hand-counted byte schedules —
the fixtures use (1024,) f32 arrays so every aval is exactly 4 KiB and
the expected peaks are knowable constants."""

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.analysis import entries, memory_checks
from ddim_cold_tpu.analysis.findings import load_baseline, write_baseline

KB4 = 1024 * 4  # bytes of one (1024,) f32
X = jax.ShapeDtypeStruct((1024,), jnp.float32)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------- liveness walk


def test_peak_counts_chain_liveness():
    # x -> y -> z: x retained (not donated) so the peak holds all three
    def f(x):
        y = x + 1.0
        return y * 2.0

    closed = jax.make_jaxpr(f)(X)
    assert memory_checks._jaxpr_peak(closed.jaxpr) == 3 * KB4
    # donating x lets it die after eqn 0: never three live at once
    assert memory_checks._jaxpr_peak(closed.jaxpr, donated=(True,)) == 2 * KB4


def test_peak_live_bytes_unwraps_pjit_donation():
    def f(x):
        y = x + 1.0
        return y * 2.0

    plain = jax.make_jaxpr(jax.jit(f))(X)
    donated = jax.make_jaxpr(jax.jit(f, donate_argnums=0))(X)
    assert memory_checks.peak_live_bytes(plain) == 3 * KB4
    assert memory_checks.peak_live_bytes(donated) == 2 * KB4


def test_peak_counts_fanout_operands():
    # non-donated x is caller-retained: at the last eqn x, a, b and the
    # output d are all live; donating x frees it after its last use (the
    # mul), dropping the peak by one block
    def f(x):
        a = x + 1.0
        b = x * 2.0
        return a + b

    closed = jax.make_jaxpr(f)(X)
    assert memory_checks._jaxpr_peak(closed.jaxpr) == 4 * KB4
    assert memory_checks._jaxpr_peak(closed.jaxpr, donated=(True,)) == 3 * KB4


def test_nested_scan_body_adds_interior_peak_once():
    # the scan body materializes temporaries above its carry; one
    # iteration's interior stands in for all (XLA reuses body buffers)
    def f(x):
        def body(c, _):
            t = c + 1.0
            return t * 2.0, ()

        out, _ = jax.lax.scan(body, x, None, length=8)
        return out

    closed = jax.make_jaxpr(f)(X)
    peak = memory_checks.peak_live_bytes(closed)
    assert 2 * KB4 <= peak <= 4 * KB4, peak


def test_consts_are_resident():
    big = np.ones((1024,), np.float32)

    def f(x):
        return x + jnp.asarray(big)

    closed = jax.make_jaxpr(f)(X)
    assert memory_checks.peak_live_bytes(closed) >= 2 * KB4


# --------------------------------------------------------------- M001


def test_m001_over_budget_program():
    def f(x):
        return (x + 1.0) * 2.0

    closed = jax.make_jaxpr(jax.jit(f))(X)
    fs = memory_checks.check_peak_hbm(closed, "fix", "fix.py",
                                      budget_bytes=2 * KB4)
    assert [(f_.rule, f_.subject) for f_ in fs] == [
        ("GRAFT-M001", "fix:peak")]
    assert "shrink the bucket" in fs[0].message
    assert memory_checks.check_peak_hbm(closed, "fix", "fix.py",
                                        budget_bytes=4 * KB4) == []


# --------------------------------------------------------------- M002


def test_m002_padded_token_axis_at_200px():
    # a pad-to-4096 class bug at N=2501: 64% padding, over the 30% line
    def f(x):
        return x * 2.0

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4096, 8), jnp.float32))
    fs = memory_checks.check_padding(closed, "fix", "fix.py", tokens=2501)
    assert [(f_.rule, f_.subject) for f_ in fs] == [
        ("GRAFT-M002", "fix:pad")]
    assert "64%" in fs[0].message
    # the in-tree streamed-kv worst case (3072/2501 = 1.228) passes
    c2 = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((3072, 8), jnp.float32))
    assert memory_checks.check_padding(c2, "fix", "fix.py", tokens=2501) == []


def test_m002_abstains_below_min_tokens():
    # at the TINY sweep's 5 tokens the [tokens, 2·tokens) window catches
    # batch/pixel dims — the check must abstain, not guess
    def f(x):
        return x * 2.0

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((8, 16, 16, 3),
                                                    jnp.float32))
    assert memory_checks.check_padding(closed, "fix", "fix.py", tokens=5) == []
    assert 5 < memory_checks.MIN_PAD_TOKENS <= entries.NS_TOKENS


# ------------------------------------------------- baseline + clean tree


def test_m_finding_keys_round_trip(tmp_path):
    def f(x):
        return x + 1.0

    closed = jax.make_jaxpr(jax.jit(f))(X)
    fs = memory_checks.check_program(closed, "fix", "fix.py", tokens=2501,
                                     budget_bytes=KB4)
    assert _rules_of(fs) == ["GRAFT-M001"]
    base = tmp_path / "baseline.txt"
    write_baseline(str(base), fs)
    assert load_baseline(str(base)) == {f_.key for f_ in fs}


def test_clean_in_tree_memory(kernel_traces):
    """The acceptance gate: every 200px sampler program's donation-aware
    peak fits the v5e HBM budget and carries no over-threshold padding,
    and the peaks are sane (params + a 200px batch land well under a GiB
    at TINY depths, nonzero because params are resident)."""
    fs = memory_checks.run_memory_checks(serve_traces={},
                                         kernel_traces=kernel_traces)
    assert [f.render() for f in fs] == []
    peaks = {name: memory_checks.peak_live_bytes(c)
             for name, (e, c) in kernel_traces.items()
             if (e.meta or {}).get("memory")}
    assert set(peaks) == {"ns200_f32", "ns200_bf16", "ns200_w8a16",
                          "ns200_w8a16_fused", "ns200_w8a8_fused",
                          "ns200_fewstep4_bf16"}
    for name, peak in peaks.items():
        assert 10 * 2**20 < peak < 2**31, (name, peak)
    # quantized weights must not peak above the f32 build
    assert peaks["ns200_w8a16"] < peaks["ns200_f32"]
    # fusing deletes intermediates; it must not grow the liveness peak
    assert peaks["ns200_w8a16_fused"] <= peaks["ns200_w8a16"] * 1.05
    # the few-step scan holds one sampler state, not k of them — its peak
    # stays in family with the stride sampler at the same dtype
    assert peaks["ns200_fewstep4_bf16"] <= peaks["ns200_bf16"] * 1.05


def test_budget_report_rollups(kernel_traces):
    """bench's memory_budget section consumes exactly this shape, and
    obs/trend.py bands the two rollup keys — pin them."""
    report = memory_checks.budget_report(kernel_traces=kernel_traces)
    assert report["findings"] == []
    assert 0 < report["peak_hbm_gb"] <= report["hbm_budget_gib"]
    assert 0 < report["max_kernel_vmem_mb"] <= report["vmem_budget_mib"]
    assert set(report["programs"]) == {"ns200_f32", "ns200_bf16",
                                       "ns200_w8a16", "ns200_w8a16_fused",
                                       "ns200_w8a8_fused",
                                       "ns200_fewstep4_bf16"}
    assert len(report["kernels"]) >= 10
