"""DiffusionViT unit tests: shapes, unpatchify round-trip, init statistics,
time-embedding semantics, torch-oracle forward parity (torch cpu is available
in this image as a test-only dependency)."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.models.init import trunc_normal

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2, num_heads=4)


def make_model(**kw):
    cfg = dict(TINY)
    cfg.update(kw)
    return DiffusionViT(**cfg)


@pytest.fixture(scope="module")
def tiny_model_and_params():
    model = make_model()
    x = jnp.zeros((2, 16, 16, 3))
    t = jnp.array([0, 5], dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, t)["params"]
    return model, params


def test_forward_shape_and_finite(tiny_model_and_params):
    model, params = tiny_model_and_params
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 16, 3))
    t = jnp.array([10, 100, 1999], dtype=jnp.int32)
    out = model.apply({"params": params}, x, t)
    assert out.shape == (3, 16, 16, 3)
    assert out.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(out)))


def test_param_tree_names(tiny_model_and_params):
    """Names must stay converter-compatible with torch state_dict keys."""
    _, params = tiny_model_and_params
    assert set(params.keys()) == {
        "patch_embed", "cls_token", "pos_embed", "time_embed",
        "blocks_0", "blocks_1", "norm", "head",
    }
    blk = params["blocks_0"]
    assert set(blk.keys()) == {"norm1", "attn", "norm2", "mlp"}
    assert set(blk["attn"].keys()) == {"qkv", "proj"}
    assert set(blk["mlp"].keys()) == {"fc1", "fc2"}
    # shapes
    assert params["pos_embed"].shape == (1, 2 * 2 + 1, 32)
    assert params["time_embed"]["embedding"].shape == (2000, 32)
    assert params["head"]["kernel"].shape == (32, 3 * 64)
    assert blk["attn"]["qkv"]["kernel"].shape == (32, 96)
    assert blk["attn"]["qkv"]["bias"].shape == (96,)  # qkv_bias=True default
    # mlp_ratio=1.0 default: hidden == dim
    assert blk["mlp"]["fc1"]["kernel"].shape == (32, 32)


def test_unpatchify_roundtrip():
    """Patch-extract then unpatchify must be the identity pixel mapping."""
    model = make_model()
    B, H, W, C, p = 2, 16, 16, 3, 8
    img = np.random.RandomState(0).randn(B, H, W, C).astype(np.float32)
    # patch extraction identical to PatchEmbed's reshape path
    x = img.reshape(B, H // p, p, W // p, p, C).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(B, (H // p) * (W // p), p * p * C)
    out = np.asarray(model.unpatchify(jnp.asarray(x)))
    np.testing.assert_array_equal(out, img)


def test_unpatchify_matches_torch_permute():
    """Oracle: the reference's view/permute(0,5,1,3,2,4)/view (ViT.py:214-217)."""
    torch = pytest.importorskip("torch")
    B, H, W, C, p = 2, 16, 16, 3, 8
    feat = np.random.RandomState(1).randn(B, (H // p) * (W // p), p * p * C).astype(np.float32)
    tt = torch.from_numpy(feat)
    ref = tt.view(-1, H // p, W // p, p, p, C).permute(0, 5, 1, 3, 2, 4).contiguous()
    ref = ref.view(-1, C, H, W).numpy()  # NCHW
    ours = np.asarray(make_model().unpatchify(jnp.asarray(feat)))  # NHWC
    np.testing.assert_array_equal(ours.transpose(0, 3, 1, 2), ref)


def test_trunc_normal_moments():
    init = trunc_normal(std=0.02)
    x = np.asarray(init(jax.random.PRNGKey(0), (200_000,)))
    assert abs(x.mean()) < 1e-3
    assert abs(x.std() - 0.02) < 1e-3
    assert x.min() >= -2 and x.max() <= 2
    # tight absolute bounds actually truncate
    tight = np.asarray(trunc_normal(std=1.0, a=-0.5, b=0.5)(jax.random.PRNGKey(1), (10_000,)))
    assert tight.min() >= -0.5 and tight.max() <= 0.5


def test_time_embedding_changes_output(tiny_model_and_params):
    model, params = tiny_model_and_params
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 16, 3))
    o1 = model.apply({"params": params}, x, jnp.array([3], jnp.int32))
    o2 = model.apply({"params": params}, x, jnp.array([1500], jnp.int32))
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


def test_dropout_deterministic_vs_training(tiny_model_and_params):
    model, params = tiny_model_and_params
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16, 3))
    t = jnp.array([7, 7], jnp.int32)
    a = model.apply({"params": params}, x, t, deterministic=True)
    b = model.apply({"params": params}, x, t, deterministic=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = model.apply(
        {"params": params}, x, t, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(4)},
    )
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_attention_probe(tiny_model_and_params):
    model, params = tiny_model_and_params
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 16, 16, 3))
    t = jnp.array([0, 0], jnp.int32)
    attn = model.apply({"params": params}, x, t, return_attention_layer=-1)
    N = model.num_patches + 1
    assert attn.shape == (2, 4, N, N)
    np.testing.assert_allclose(np.asarray(attn).sum(-1), 1.0, rtol=1e-5)


def test_forward_parity_with_torch_oracle():
    """Port flax params into a torch transcription of the reference model and
    compare eval-mode forwards. Catches layout/ordering/scale drift."""
    torch = pytest.importorskip("torch")
    import torch.nn as tnn

    E, p, img, heads, depth = 32, 8, 16, 4, 2
    model = make_model()
    x = jnp.asarray(np.random.RandomState(0).randn(2, img, img, 3).astype(np.float32))
    t = jnp.array([3, 77], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, t)["params"]
    ours = np.asarray(model.apply({"params": params}, x, t))

    g = lambda *ks: np.asarray(params[ks[0]][ks[1]][ks[2]] if len(ks) == 3 else params[ks[0]][ks[1]])

    class TBlock(tnn.Module):
        def __init__(self):
            super().__init__()
            self.norm1 = tnn.LayerNorm(E, eps=1e-5)
            self.qkv = tnn.Linear(E, 3 * E)
            self.proj = tnn.Linear(E, E)
            self.norm2 = tnn.LayerNorm(E, eps=1e-5)
            self.fc1 = tnn.Linear(E, E)
            self.fc2 = tnn.Linear(E, E)

        def forward(self, x):
            B, N, C = x.shape
            h = self.norm1(x)
            qkv = self.qkv(h).reshape(B, N, 3, heads, C // heads).permute(2, 0, 3, 1, 4)
            q, k, v = qkv[0], qkv[1], qkv[2]
            attn = (q @ k.transpose(-2, -1)) * (C // heads) ** -0.5
            attn = attn.softmax(dim=-1)
            h = (attn @ v).transpose(1, 2).reshape(B, N, C)
            x = x + self.proj(h)
            x = x + self.fc2(torch.nn.functional.gelu(self.fc1(self.norm2(x))))
            return x

    with torch.no_grad():
        blocks = [TBlock() for _ in range(depth)]
        patch = tnn.Conv2d(3, E, kernel_size=p, stride=p)
        norm = tnn.LayerNorm(E, eps=1e-5)
        head = tnn.Linear(E, 3 * p * p)
        # load flax params (flax Dense kernel is (in, out) = torch weight.T)
        patch.weight.copy_(torch.from_numpy(
            g("patch_embed", "proj", "kernel").reshape(p, p, 3, E).transpose(3, 2, 0, 1)))
        patch.bias.copy_(torch.from_numpy(g("patch_embed", "proj", "bias")))
        norm.weight.copy_(torch.from_numpy(g("norm", "scale")))
        norm.bias.copy_(torch.from_numpy(g("norm", "bias")))
        head.weight.copy_(torch.from_numpy(g("head", "kernel").T))
        head.bias.copy_(torch.from_numpy(g("head", "bias")))
        for i, tb in enumerate(blocks):
            bp = params[f"blocks_{i}"]
            tb.norm1.weight.copy_(torch.from_numpy(np.asarray(bp["norm1"]["scale"])))
            tb.norm1.bias.copy_(torch.from_numpy(np.asarray(bp["norm1"]["bias"])))
            tb.norm2.weight.copy_(torch.from_numpy(np.asarray(bp["norm2"]["scale"])))
            tb.norm2.bias.copy_(torch.from_numpy(np.asarray(bp["norm2"]["bias"])))
            tb.qkv.weight.copy_(torch.from_numpy(np.asarray(bp["attn"]["qkv"]["kernel"]).T))
            tb.qkv.bias.copy_(torch.from_numpy(np.asarray(bp["attn"]["qkv"]["bias"])))
            tb.proj.weight.copy_(torch.from_numpy(np.asarray(bp["attn"]["proj"]["kernel"]).T))
            tb.proj.bias.copy_(torch.from_numpy(np.asarray(bp["attn"]["proj"]["bias"])))
            tb.fc1.weight.copy_(torch.from_numpy(np.asarray(bp["mlp"]["fc1"]["kernel"]).T))
            tb.fc1.bias.copy_(torch.from_numpy(np.asarray(bp["mlp"]["fc1"]["bias"])))
            tb.fc2.weight.copy_(torch.from_numpy(np.asarray(bp["mlp"]["fc2"]["kernel"]).T))
            tb.fc2.bias.copy_(torch.from_numpy(np.asarray(bp["mlp"]["fc2"]["bias"])))

        xt = torch.from_numpy(np.asarray(x).transpose(0, 3, 1, 2))  # NCHW
        tok = patch(xt).flatten(2).transpose(1, 2)
        cls = torch.from_numpy(np.asarray(params["cls_token"]))
        tok = torch.cat([cls.expand(2, -1, -1), tok], dim=1)
        te = torch.from_numpy(np.asarray(params["time_embed"]["embedding"]))[
            torch.tensor([3, 77])
        ].unsqueeze(1)
        pe = torch.from_numpy(np.asarray(params["pos_embed"]))
        tok = tok + pe + te
        for tb in blocks:
            tok = tb(tok)
        tok = head(norm(tok))
        img_t = tok[:, 1:, :].view(-1, img // p, img // p, p, p, 3)
        ref = img_t.permute(0, 5, 1, 3, 2, 4).contiguous().view(-1, 3, img, img).numpy()

    np.testing.assert_allclose(ours.transpose(0, 3, 1, 2), ref, rtol=2e-4, atol=2e-5)


def test_remat_matches_plain(tiny_model_and_params):
    """remat=True must be a pure memory/compute trade: identical params,
    outputs, and gradients (eval and training mode)."""
    model, params = tiny_model_and_params
    rmodel = make_model(remat=True)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 16, 3))
    t = jnp.array([3, 1500], dtype=jnp.int32)

    rparams = jax.jit(rmodel.init)(jax.random.PRNGKey(0), x, t)["params"]
    assert jax.tree.structure(params) == jax.tree.structure(rparams)

    out = jax.jit(model.apply)({"params": params}, x, t)
    rout = jax.jit(rmodel.apply)({"params": params}, x, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rout), atol=1e-6)

    def loss(m, p):
        drng = jax.random.PRNGKey(7)
        y = m.apply({"params": p}, x, t, deterministic=False, rngs={"dropout": drng})
        return jnp.mean(y**2)

    g = jax.jit(jax.grad(lambda p: loss(model, p)))(params)
    rg = jax.jit(jax.grad(lambda p: loss(rmodel, p)))(params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(rg)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # probe path still works under remat
    attn = rmodel.apply({"params": params}, x, t, return_attention_layer=0)
    assert attn.shape[0] == 2


def test_scan_blocks_matches_unrolled(tiny_model_and_params):
    """scan_blocks=True is a layout change only: unrolled params stacked into
    the scanned layout produce identical eval outputs, and the converter
    round-trips both layouts."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    model, params = tiny_model_and_params
    smodel = make_model(scan_blocks=True)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16, 3))
    t = jnp.array([7, 1200], dtype=jnp.int32)

    stacked = ckpt.stack_block_params(params)
    sparams = smodel.init(jax.random.PRNGKey(0), x, t)["params"]
    assert jax.tree.structure(jax.tree.map(lambda a: a.shape, stacked)) \
        == jax.tree.structure(jax.tree.map(lambda a: a.shape, sparams))

    a = np.asarray(model.apply({"params": params}, x, t))
    b = np.asarray(smodel.apply({"params": stacked}, x, t))
    np.testing.assert_allclose(a, b, atol=1e-6)

    # unstack inverts stack exactly
    back = ckpt.unstack_block_params(stacked)
    jax.tree.map(lambda u, v: np.testing.assert_array_equal(np.asarray(u), np.asarray(v)),
                 params, back)

    # torch export is layout-independent
    sd_a = ckpt.torch_state_dict_from_flax(params, patch_size=8)
    sd_b = ckpt.torch_state_dict_from_flax(stacked, patch_size=8)
    assert sd_a.keys() == sd_b.keys()
    for k in sd_a:
        np.testing.assert_array_equal(sd_a[k], sd_b[k])

    # training mode runs finite with split per-layer dropout rngs
    y = smodel.apply({"params": stacked}, x, t, deterministic=False,
                     rngs={"dropout": jax.random.PRNGKey(9)})
    assert bool(jnp.isfinite(y).all())

    with pytest.raises(ValueError, match="probe"):
        smodel.apply({"params": stacked}, x, t, return_attention_layer=0)
