"""Torch-bridge validation against the ACTUAL reference implementation.

The converter round-trip tests in test_train.py use self-generated state
dicts; these tests close the loop by importing the reference's torch model
(`/root/reference/ViT.py:158-218`) itself, saving a real ``state_dict()``
pickle, loading it through ``load_torch_pkl``, and asserting forward parity —
and the reverse direction: a ``save_torch_pkl`` export must ``load_state_dict
(strict=True)`` into the reference class and produce the same outputs.
"""

import importlib.util
import os
import sys

import numpy as np
import pytest

REF_VIT = "/root/reference/ViT.py"

pytestmark = pytest.mark.skipif(
    not os.path.isfile(REF_VIT), reason="reference snapshot not present")

CFG = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2,
           num_heads=4, total_steps=50)


@pytest.fixture(scope="module")
def ref_module():
    torch = pytest.importorskip("torch")  # conversion-time-only dep
    spec = importlib.util.spec_from_file_location("_reference_vit", REF_VIT)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_reference_vit"] = mod
    spec.loader.exec_module(mod)  # top level: imports + sys.path lines only
    return mod


@pytest.fixture(scope="module")
def ref_model(ref_module):
    import torch

    torch.manual_seed(0)
    m = ref_module.DiffusionVisionTransformer(
        img_size=list(CFG["img_size"]), patch_size=CFG["patch_size"],
        embed_dim=CFG["embed_dim"], depth=CFG["depth"],
        num_heads=CFG["num_heads"], total_steps=CFG["total_steps"])
    m.eval()
    return m


def _ref_forward(ref_model, x_nhwc: np.ndarray, t: np.ndarray) -> np.ndarray:
    import torch

    with torch.no_grad():
        out = ref_model(torch.from_numpy(x_nhwc.transpose(0, 3, 1, 2)),
                        torch.from_numpy(t).long())
    return out.numpy().transpose(0, 2, 3, 1)  # NCHW → NHWC


def _our_forward(params, x_nhwc: np.ndarray, t: np.ndarray) -> np.ndarray:
    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT

    model = DiffusionViT(**CFG)
    return np.asarray(model.apply(
        {"params": params}, jnp.asarray(x_nhwc), jnp.asarray(t)))


def _test_batch(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(2, 16, 16, 3).astype(np.float32)
    t = rng.randint(0, CFG["total_steps"], size=(2,)).astype(np.int32)
    return x, t


def test_load_reference_state_dict_forward_parity(ref_model, tmp_path):
    """reference torch.save(state_dict) → load_torch_pkl → same outputs."""
    import torch

    from ddim_cold_tpu.utils.checkpoint import load_torch_pkl

    pkl = tmp_path / "ref_bestloss.pkl"
    torch.save(ref_model.state_dict(), pkl)  # the bestloss.pkl format
    params = load_torch_pkl(str(pkl), CFG["patch_size"])
    x, t = _test_batch()
    np.testing.assert_allclose(
        _our_forward(params, x, t), _ref_forward(ref_model, x, t),
        rtol=2e-4, atol=2e-5)


def test_load_reference_lastepoch_dict(ref_model, tmp_path):
    """the lastepoch.pkl format: nested dict, DDP 'module.' prefixes
    (multi_gpu_trainer.py:155-163)."""
    import torch

    from ddim_cold_tpu.utils.checkpoint import load_torch_pkl

    pkl = tmp_path / "ref_lastepoch.pkl"
    torch.save({
        "epoch": 3, "steps": 2048, "loss_rec": 0.1, "metric": 0.07,
        "state_dict": {"module." + k: v for k, v in ref_model.state_dict().items()},
    }, pkl)
    params = load_torch_pkl(str(pkl), CFG["patch_size"])
    x, t = _test_batch(1)
    np.testing.assert_allclose(
        _our_forward(params, x, t), _ref_forward(ref_model, x, t),
        rtol=2e-4, atol=2e-5)


def test_export_loads_into_reference_strict(ref_module, ref_model, tmp_path):
    """save_torch_pkl output must be key/shape-exact for the reference class
    (strict=True) and forward-equal — a reference user can consume our
    checkpoints directly."""
    import jax
    import jax.numpy as jnp
    import torch

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils.checkpoint import save_torch_pkl

    model = DiffusionViT(**CFG)
    x, t = _test_batch(2)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x),
                        jnp.asarray(t))["params"]
    pkl = tmp_path / "ours.pkl"
    save_torch_pkl(params, str(pkl), CFG["patch_size"])

    consumer = ref_module.DiffusionVisionTransformer(
        img_size=list(CFG["img_size"]), patch_size=CFG["patch_size"],
        embed_dim=CFG["embed_dim"], depth=CFG["depth"],
        num_heads=CFG["num_heads"], total_steps=CFG["total_steps"])
    missing = consumer.load_state_dict(
        torch.load(pkl, map_location="cpu", weights_only=False), strict=True)
    assert not missing.missing_keys and not missing.unexpected_keys
    consumer.eval()
    np.testing.assert_allclose(
        _our_forward(params, x, t), _ref_forward(consumer, x, t),
        rtol=2e-4, atol=2e-5)
