"""Schedule unit tests: ᾱ values and DDIM-update algebra vs a transcribed oracle."""

import math

import numpy as np
import pytest

from ddim_cold_tpu.ops import schedule


def oracle_ddim_step(x, x0, t, k, T):
    """Literal transcription of the reference update (ViT.py:231-234)."""
    alpha_tk = 1 - math.sqrt((t + 1 - k) / T)  # no eps
    alpha_t = 1 - math.sqrt((t + 1) / T) + 1e-5
    noise = (x - math.sqrt(alpha_t) * x0) / math.sqrt(1 - alpha_t)
    return math.sqrt(alpha_tk) * (
        x / math.sqrt(alpha_t)
        + (math.sqrt((1 - alpha_tk) / alpha_tk) - math.sqrt((1 - alpha_t) / alpha_t)) * noise
    )


def test_alpha_bar_values():
    T = 2000
    # spot values from the closed form
    assert schedule.alpha_bar(1999, T) == pytest.approx(1 - math.sqrt(2000 / 2000))
    assert schedule.alpha_bar(0, T) == pytest.approx(1 - math.sqrt(1 / 2000))
    # eps lands on the current-step variant only
    assert schedule.alpha_bar(99, T, eps=schedule.ALPHA_EPS) == pytest.approx(
        1 - math.sqrt(100 / 2000) + 1e-5
    )


def test_time_sequence_matches_range():
    for k in (1, 10, 20, 50, 100):
        assert schedule.ddim_time_sequence(2000, k).tolist() == list(range(1999, 0, -k))
    # guided-sampling restart (draft2drawing t_start)
    assert schedule.ddim_time_sequence(2000, 10, t_start=1599).tolist() == list(
        range(1599, 0, -10)
    )


@pytest.mark.parametrize("k", [1, 10, 20, 50, 100])
def test_ddim_coefficients_match_oracle(k, rng):
    T = 2000
    coeffs = schedule.ddim_coefficients(T, k)
    x = rng.randn(4).astype(np.float64)
    x0 = np.clip(rng.randn(4), -1, 1).astype(np.float64)
    for i, t in enumerate(coeffs.t_seq):
        want = oracle_ddim_step(x, x0, int(t), k, T)
        got = coeffs.cx[i] * x + coeffs.cx0[i] * x0
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_ddim_coefficients_clamp_negative_radicand():
    # k=7: final t=4, t+1-k=-2 — the reference's math.sqrt would raise; we clamp.
    coeffs = schedule.ddim_coefficients(2000, 7)
    assert np.all(np.isfinite(coeffs.cx))
    assert np.all(np.isfinite(coeffs.cx0))


def test_forward_noise_alpha_no_plus_one():
    # draft2drawing forward-noising uses t/T, not (t+1)/T (ViT_draft2drawing.py:395)
    assert schedule.forward_noise_alpha(1600, 2000) == pytest.approx(1 - math.sqrt(0.8))


def test_cold_time_sequence():
    assert schedule.cold_time_sequence(6).tolist() == [6, 5, 4, 3, 2, 1]
