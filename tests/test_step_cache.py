"""Step-cache subsystem tests (ops/step_cache.py + the vit.py hooks).

The contract under test, in order of strictness:
* interval=1 routes around the cache machinery entirely — BITWISE equal to
  the plain sampler (the dispatch in sampling.ddim_sample/cold_sample);
* a refresh forward (capture_split) computes the exact plain forward while
  emitting the half-trunk deltas (bitwise on the image output);
* a reuse forward never executes the skipped blocks — proven functionally:
  its output is invariant to arbitrary perturbation of their params;
* the refresh→reuse round trip reproduces the plain forward to float
  round-off (a + (b − a) ≠ b bitwise, so this one is allclose, not equal);
* the schedule is static: one XLA compile per (k, interval, mode);
* SPMD cached sampling over a data mesh matches single-device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import sampling, schedule, step_cache

T = 2000
# depth 4: distinct front (0,1) / rear (2,3) halves, so a delta-mode reuse
# still runs real blocks and param-invariance has something to bite on
TINY4 = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=4,
             num_heads=4, total_steps=T)


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY4)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


# ---------------------------------------------------------------- schedule

def test_branch_sequence_delta_phase_split():
    seq = schedule.cache_branch_sequence(10, 2, "delta")
    assert seq.dtype == np.int32
    # refreshes at every interval-th step, reuse between; early half reuses
    # the REAR delta (branch 1), late half the FRONT (branch 2)
    assert list(seq) == [0, 1, 0, 1, 0, 2, 0, 2, 0, 2]


def test_branch_sequence_full_mode_and_intervals():
    assert list(schedule.cache_branch_sequence(6, 2, "full")) == [0, 1] * 3
    assert list(schedule.cache_branch_sequence(7, 3, "full")) == [
        0, 1, 1, 0, 1, 1, 0]
    # interval <= 1: every step refreshes (the exact sampler)
    assert list(schedule.cache_branch_sequence(4, 1)) == [0] * 4
    assert list(schedule.cache_branch_sequence(4, 0)) == [0] * 4
    with pytest.raises(ValueError):
        schedule.cache_branch_sequence(4, 2, "bogus")


def test_cache_spec_validation():
    spec = step_cache.cache_spec(4, 10, 2, "delta")
    assert spec.split == 2 and spec.n_steps == 10 and spec.interval == 2
    hash(spec)  # must stay hashable — it rides jit static args
    with pytest.raises(ValueError):
        step_cache.cache_spec(1, 10, 2)  # no half to skip
    with pytest.raises(ValueError):
        step_cache.cache_spec(4, 10, 2, split=0)
    with pytest.raises(ValueError):
        step_cache.cache_spec(4, 10, 2, split=4)


def test_flops_saved_fraction():
    # interval=2, 10 steps: 5 reuse steps skipping half the trunk → 25%
    assert step_cache.flops_saved_fraction(
        step_cache.cache_spec(4, 10, 2, "delta")) == pytest.approx(0.25)
    # full mode skips the whole trunk on reuse steps → 50%
    assert step_cache.flops_saved_fraction(
        step_cache.cache_spec(4, 10, 2, "full")) == pytest.approx(0.5)
    assert step_cache.flops_saved_fraction(
        step_cache.cache_spec(4, 10, 1)) == 0.0
    assert not step_cache.enabled(1) and step_cache.enabled(2)


# ---------------------------------------------------------- model-level hooks

def test_capture_split_forward_is_bitwise_plain(model_and_params):
    """A refresh step must cost nothing in exactness: same blocks, same
    order, deltas read off the already-computed token stream."""
    model, params = model_and_params
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    t = jnp.array([100, 100], jnp.int32)
    plain = model.apply({"params": params}, x, t)
    out, (d_front, d_rear) = model.apply({"params": params}, x, t,
                                         capture_split=2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(plain))
    assert d_front.shape == d_rear.shape == (2, model.num_patches + 1,
                                             model.embed_dim)


def test_skip_with_true_delta_matches_plain(model_and_params):
    """Refresh → reuse round trip: skipping a half and adding its captured
    delta reproduces the plain forward to float round-off."""
    model, params = model_and_params
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 16, 3))
    t = jnp.array([50, 50], jnp.int32)
    plain = np.asarray(model.apply({"params": params}, x, t))
    _, (d_front, d_rear) = model.apply({"params": params}, x, t,
                                       capture_split=2)
    for skip, delta in (((0, 2), d_front), ((2, 4), d_rear),
                        ((0, 4), d_front + d_rear)):
        out = model.apply({"params": params}, x, t, skip_blocks=skip,
                          block_delta=delta)
        np.testing.assert_allclose(np.asarray(out), plain,
                                   rtol=1e-4, atol=1e-5)


def test_reuse_step_never_runs_skipped_blocks(model_and_params):
    """Functional proof that skipped blocks don't execute: a reuse forward is
    BITWISE invariant to arbitrary perturbation of their params, while the
    same perturbation on an executed block changes the output."""
    model, params = model_and_params
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16, 3))
    t = jnp.array([10, 10], jnp.int32)
    delta = jnp.zeros((2, model.num_patches + 1, model.embed_dim),
                      model.dtype)

    def wreck(p, block_name):
        return jax.tree_util.tree_map_with_path(
            lambda path, a: a + 1e3 if any(
                getattr(k, "key", None) == block_name for k in path) else a, p)

    base = np.asarray(model.apply({"params": params}, x, t,
                                  skip_blocks=(2, 4), block_delta=delta))
    for name in ("blocks_2", "blocks_3"):
        out = np.asarray(model.apply({"params": wreck(params, name)}, x, t,
                                     skip_blocks=(2, 4), block_delta=delta))
        np.testing.assert_array_equal(out, base)
    # sanity: the same perturbation on an EXECUTED block must show up
    out = np.asarray(model.apply({"params": wreck(params, "blocks_0")}, x, t,
                                 skip_blocks=(2, 4), block_delta=delta))
    assert np.abs(out - base).max() > 0


def test_hook_validation(model_and_params):
    model, params = model_and_params
    x = jnp.zeros((1, 16, 16, 3))
    t = jnp.zeros((1,), jnp.int32)
    with pytest.raises(ValueError, match="block_delta"):
        model.apply({"params": params}, x, t, skip_blocks=(0, 2))
    with pytest.raises(ValueError, match="capture_split"):
        model.apply({"params": params}, x, t, capture_split=0)
    with pytest.raises(ValueError):
        model.apply({"params": params}, x, t, skip_blocks=(0, 2),
                    block_delta=jnp.zeros(
                        (1, model.num_patches + 1, model.embed_dim)),
                    capture_split=2)
    scan_model = DiffusionViT(scan_blocks=True, **TINY4)
    sp = scan_model.init(jax.random.PRNGKey(0), x, t)["params"]
    with pytest.raises(ValueError, match="scan_blocks"):
        scan_model.apply({"params": sp}, x, t, capture_split=2)


# ------------------------------------------------------------------ samplers

def test_interval_one_is_bitwise_exact(model_and_params):
    model, params = model_and_params
    rng = jax.random.PRNGKey(5)
    plain = sampling.ddim_sample(model, params, rng, k=400, n=2)
    routed = sampling.ddim_sample(model, params, rng, k=400, n=2,
                                  cache_interval=1, cache_mode="full")
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(routed))
    cold_plain = sampling.cold_sample(model, params, rng, n=2, levels=4)
    cold_routed = sampling.cold_sample(model, params, rng, n=2, levels=4,
                                       cache_interval=1)
    np.testing.assert_array_equal(np.asarray(cold_plain),
                                  np.asarray(cold_routed))


@pytest.mark.parametrize("mode", ["delta", "full"])
def test_cached_ddim_close_to_exact(model_and_params, mode):
    """interval=2 on a tiny random-init model: the cached sampler must stay
    in range and near the exact one (the quantitative FID bound is bench's
    cached_quality section; here we pin basic sanity + determinism)."""
    model, params = model_and_params
    rng = jax.random.PRNGKey(6)
    exact = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2))
    cached = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2,
                                             cache_interval=2,
                                             cache_mode=mode))
    assert np.isfinite(cached).all()
    assert cached.min() >= 0.0 and cached.max() <= 1.0
    assert np.abs(cached - exact).max() < 0.25  # near, not equal
    again = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2,
                                            cache_interval=2,
                                            cache_mode=mode))
    np.testing.assert_array_equal(cached, again)  # deterministic


def test_cached_sequence_last_frame_matches_image(model_and_params):
    model, params = model_and_params
    rng = jax.random.PRNGKey(7)
    seq = sampling.ddim_sample(model, params, rng, k=500, n=2,
                               return_sequence=True, cache_interval=2)
    img = sampling.ddim_sample(model, params, rng, k=500, n=2,
                               cache_interval=2)
    assert seq.shape[0] == 5  # init + 4 steps
    np.testing.assert_allclose(np.asarray(seq[-1]), np.asarray(img),
                               rtol=1e-5, atol=1e-6)


def test_cached_cold_and_eta_paths(model_and_params):
    model, params = model_and_params
    rng = jax.random.PRNGKey(8)
    cold = np.asarray(sampling.cold_sample(model, params, rng, n=2, levels=4,
                                           cache_interval=2))
    assert np.isfinite(cold).all() and cold.min() >= 0.0 and cold.max() <= 1.0
    stoch = np.asarray(sampling.ddim_sample(model, params, rng, k=500, n=2,
                                            eta=0.5, cache_interval=2))
    assert np.isfinite(stoch).all()


def test_one_compile_per_schedule(model_and_params):
    """The refresh/reuse pattern is a scanned input, not a trace condition:
    re-sampling with new rngs must not re-trace, and only (k, interval,
    mode) changes may add compilation cache entries."""
    model, params = model_and_params
    fn = sampling._ddim_scan_cached
    fn.clear_cache()
    for seed in (10, 11, 12):
        sampling.ddim_sample(model, params, jax.random.PRNGKey(seed),
                             k=400, n=2, cache_interval=2)
    assert fn._cache_size() == 1
    sampling.ddim_sample(model, params, jax.random.PRNGKey(10), k=400, n=2,
                         cache_interval=3)
    assert fn._cache_size() == 2
    sampling.ddim_sample(model, params, jax.random.PRNGKey(10), k=400, n=2,
                         cache_interval=2, cache_mode="full")
    assert fn._cache_size() == 3


# ------------------------------------------------- adaptive / token: statics

def test_branch_sequence_adaptive_and_token():
    # adaptive reuses the delta pattern verbatim — it is the static
    # worst-case bound the drift gate can only tighten toward refresh
    np.testing.assert_array_equal(
        schedule.cache_branch_sequence(10, 2, "adaptive"),
        schedule.cache_branch_sequence(10, 2, "delta"))
    # token alternates refresh with the single token-reuse branch id
    assert list(schedule.cache_branch_sequence(6, 2, "token")) == [
        schedule.CACHE_REFRESH, schedule.CACHE_REUSE_TOKEN] * 3


def test_adaptive_token_spec_validation():
    spec = step_cache.cache_spec(4, 10, 2, "adaptive", threshold=0.05)
    assert spec.threshold == 0.05
    hash(spec)
    tok = step_cache.cache_spec(4, 10, 2, "token", token_k=2, n_tokens=5)
    assert tok.token_k == 2 and tok.n_tokens == 5
    with pytest.raises(ValueError):  # adaptive needs a threshold
        step_cache.cache_spec(4, 10, 2, "adaptive")
    with pytest.raises(ValueError):  # negative (and NaN) thresholds rejected
        step_cache.cache_spec(4, 10, 2, "adaptive", threshold=-0.1)
    with pytest.raises(ValueError):  # threshold outside its mode
        step_cache.cache_spec(4, 10, 2, "delta", threshold=0.1)
    with pytest.raises(ValueError):  # token needs n_tokens
        step_cache.cache_spec(4, 10, 2, "token", token_k=2)
    with pytest.raises(ValueError):  # k out of range
        step_cache.cache_spec(4, 10, 2, "token", token_k=6, n_tokens=5)
    with pytest.raises(ValueError):  # k=0 is not "unset", it's invalid
        step_cache.cache_spec(4, 10, 2, "token", token_k=0, n_tokens=5)
    with pytest.raises(ValueError):  # token knobs outside their mode
        step_cache.cache_spec(4, 10, 2, "delta", token_k=2, n_tokens=5)


def test_flops_saved_fraction_token_accounting():
    # 10 steps, 5 reuse; each reuse runs 1 of 5 tokens → saves 4/5 per step
    spec = step_cache.cache_spec(4, 10, 2, "token", token_k=1, n_tokens=5)
    assert step_cache.flops_saved_fraction(spec) == pytest.approx(0.4)
    # k = all tokens: the degenerate exact sampler saves nothing
    spec = step_cache.cache_spec(4, 10, 2, "token", token_k=5, n_tokens=5)
    assert step_cache.flops_saved_fraction(spec) == 0.0


def test_adaptive_init_cache_has_xref_leaf():
    cache = step_cache.init_cache(2, 5, 32, jnp.float32, mode="adaptive",
                                  img_shape=(16, 16, 3))
    assert len(cache) == 3 and cache[2].shape == (2, 16, 16, 3)
    assert cache[2].dtype == jnp.float32
    with pytest.raises(ValueError):
        step_cache.init_cache(2, 5, 32, jnp.float32, mode="adaptive")
    assert len(step_cache.init_cache(2, 5, 32, jnp.float32,
                                     mode="token")) == 2


# --------------------------------------------- token hooks: model-level

def test_token_capture_then_k_all_is_bitwise_plain(model_and_params):
    """k = N+1 elides the gather/scatter at trace time: the reuse forward is
    op-for-op the plain trunk (bitwise), and the carry it emits matches a
    capture_tokens refresh bitwise."""
    model, params = model_and_params
    x = jax.random.normal(jax.random.PRNGKey(20), (2, 16, 16, 3))
    t = jnp.array([100, 100], jnp.int32)
    n_tok = model.num_patches + 1
    plain = np.asarray(model.apply({"params": params}, x, t))
    out_cap, (ref, delta) = model.apply({"params": params}, x, t,
                                        capture_tokens=True)
    np.testing.assert_array_equal(np.asarray(out_cap), plain)
    out_all, (nr, nd) = model.apply({"params": params}, x, t,
                                    token_cache=(ref, delta), token_k=n_tok)
    np.testing.assert_array_equal(np.asarray(out_all), plain)
    np.testing.assert_array_equal(np.asarray(nr), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(nd), np.asarray(delta))


def test_token_gather_scatter_round_trip(model_and_params):
    """Perturb exactly one patch: with token_k=2 the live set is CLS + that
    patch's token; the carry must be updated at EXACTLY those rows (new
    reference stream) and bit-preserved everywhere else."""
    model, params = model_and_params
    x0 = jax.random.normal(jax.random.PRNGKey(21), (2, 16, 16, 3))
    # patch grid is 2×2 (16px/ps8); patch 0 ↔ token 1 (CLS is token 0)
    x1 = x0.at[:, :8, :8, :].add(0.5)
    t = jnp.array([100, 100], jnp.int32)
    _, (ref0, delta0) = model.apply({"params": params}, x0, t,
                                    capture_tokens=True)
    _, (ref1, _) = model.apply({"params": params}, x1, t,
                               capture_tokens=True)
    out, (nr, nd) = model.apply({"params": params}, x1, t,
                                token_cache=(ref0, delta0), token_k=2)
    assert np.isfinite(np.asarray(out)).all()
    # live rows re-referenced from x1's embed stream, dead rows untouched
    np.testing.assert_array_equal(np.asarray(nr[:, :2]),
                                  np.asarray(ref1[:, :2]))
    np.testing.assert_array_equal(np.asarray(nr[:, 2:]),
                                  np.asarray(ref0[:, 2:]))
    np.testing.assert_array_equal(np.asarray(nd[:, 2:]),
                                  np.asarray(delta0[:, 2:]))


def test_token_hook_validation(model_and_params):
    model, params = model_and_params
    x = jnp.zeros((1, 16, 16, 3))
    t = jnp.zeros((1,), jnp.int32)
    cache = (jnp.zeros((1, model.num_patches + 1, model.embed_dim)),) * 2
    with pytest.raises(ValueError, match="token_k"):
        model.apply({"params": params}, x, t, token_cache=cache)
    with pytest.raises(ValueError, match="token_k"):
        model.apply({"params": params}, x, t, token_cache=cache,
                    token_k=model.num_patches + 2)
    with pytest.raises(ValueError, match="token_k"):
        model.apply({"params": params}, x, t, token_k=2)
    with pytest.raises(ValueError):
        model.apply({"params": params}, x, t, capture_tokens=True,
                    token_cache=cache, token_k=2)
    with pytest.raises(ValueError):
        model.apply({"params": params}, x, t, capture_tokens=True,
                    capture_split=2)


# ------------------------------------------- adaptive / token: sampler level

def test_degenerate_settings_are_bitwise_exact(model_and_params):
    """The collapse contracts: threshold=0 forces every step to refresh and
    token_k=n_tokens recomputes every token — both must be BITWISE the
    plain (uncached) sampler, not merely close."""
    model, params = model_and_params
    rng = jax.random.PRNGKey(22)
    exact = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2))
    adapt0 = sampling.ddim_sample(model, params, rng, k=200, n=2,
                                  cache_interval=2, cache_mode="adaptive",
                                  cache_threshold=0.0)
    np.testing.assert_array_equal(np.asarray(adapt0), exact)
    tok_all = sampling.ddim_sample(model, params, rng, k=200, n=2,
                                   cache_interval=2, cache_mode="token",
                                   cache_tokens=model.num_patches + 1)
    np.testing.assert_array_equal(np.asarray(tok_all), exact)


def test_adaptive_inf_threshold_is_bitwise_static_delta(model_and_params):
    """A gate that never fires must follow the static worst-case schedule
    exactly — bitwise the fixed-interval delta sampler."""
    model, params = model_and_params
    rng = jax.random.PRNGKey(23)
    static = sampling.ddim_sample(model, params, rng, k=200, n=2,
                                  cache_interval=2, cache_mode="delta")
    gated = sampling.ddim_sample(model, params, rng, k=200, n=2,
                                 cache_interval=2, cache_mode="adaptive",
                                 cache_threshold=1e30)
    np.testing.assert_array_equal(np.asarray(gated), np.asarray(static))


@pytest.mark.parametrize("kw", [
    dict(cache_mode="adaptive", cache_threshold=0.05),
    dict(cache_mode="token", cache_tokens=3),
])
def test_adaptive_token_midrange_sane_and_deterministic(model_and_params, kw):
    model, params = model_and_params
    rng = jax.random.PRNGKey(24)
    exact = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2))
    out = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2,
                                          cache_interval=2, **kw))
    assert np.isfinite(out).all()
    assert out.min() >= 0.0 and out.max() <= 1.0
    assert np.abs(out - exact).max() < 0.25
    again = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2,
                                            cache_interval=2, **kw))
    np.testing.assert_array_equal(out, again)
    cold = np.asarray(sampling.cold_sample(model, params, rng, n=2, levels=4,
                                           cache_interval=2, **kw))
    assert np.isfinite(cold).all()


def test_one_compile_per_adaptive_token_config(model_and_params):
    """The drift gate is a data-dependent branch INDEX inside one program:
    new rngs never retrace, and only the static knobs (threshold, token_k)
    key new cache entries."""
    model, params = model_and_params
    fn = sampling._ddim_scan_cached
    fn.clear_cache()
    for seed in (30, 31, 32):
        sampling.ddim_sample(model, params, jax.random.PRNGKey(seed),
                             k=400, n=2, cache_interval=2,
                             cache_mode="adaptive", cache_threshold=0.05)
    assert fn._cache_size() == 1
    sampling.ddim_sample(model, params, jax.random.PRNGKey(30), k=400, n=2,
                         cache_interval=2, cache_mode="token", cache_tokens=3)
    assert fn._cache_size() == 2
    sampling.ddim_sample(model, params, jax.random.PRNGKey(31), k=400, n=2,
                         cache_interval=2, cache_mode="token", cache_tokens=2)
    assert fn._cache_size() == 3


# --------------------------------------------------- engine composition

def test_engine_adaptive_token_two_buckets_bitwise_zero_compiles():
    """The served form of both adaptive modes at 2 buckets: bitwise equal to
    the direct sampler calls (adaptive padding uses row-0 replicas so the
    batch-max drift gate can't see the pad) with zero compiles after
    warmup. Token mode's bitwise claim is per dispatch SHAPE: an
    exact-bucket token dispatch is bitwise the own-n direct call; a PADDED
    token dispatch is bitwise a direct call at the same padded shape."""
    from ddim_cold_tpu import serve

    model = DiffusionViT(**TINY4)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    adapt = serve.SamplerConfig(k=500, cache_interval=2,
                                cache_mode="adaptive", cache_threshold=0.05)
    tok = serve.SamplerConfig(k=500, cache_interval=2, cache_mode="token",
                              cache_tokens=3)
    assert adapt.batch_coupled and not tok.batch_coupled
    eng = serve.Engine(model, params, buckets=(4, 8))
    report = serve.warmup(eng, [adapt, tok], persistent_cache=False)
    assert report["new_compiles"] == 4
    t1 = eng.submit(seed=7, n=3, config=adapt)   # padded (row-0 replicas)
    t2 = eng.submit(seed=9, n=8, config=adapt)   # exact bucket
    t3 = eng.submit(seed=11, n=4, config=tok)    # exact bucket
    stats = eng.run()
    assert stats["compiles"] == 0
    for task, seed, n, kw in (
            (t1, 7, 3, dict(cache_mode="adaptive", cache_threshold=0.05)),
            (t2, 9, 8, dict(cache_mode="adaptive", cache_threshold=0.05)),
            (t3, 11, 4, dict(cache_mode="token", cache_tokens=3))):
        direct = np.asarray(sampling.ddim_sample(
            model, params, jax.random.PRNGKey(seed), k=500, n=n,
            cache_interval=2, **kw))
        np.testing.assert_array_equal(np.asarray(task.result()), direct)

    # Padded token dispatch (second drain so the two token requests cannot
    # coalesce into one plan): n=5 lands in bucket 8 with 3 zero-pad rows.
    # The guarantee here is bitwise equality with a direct call at the SAME
    # padded shape — identical program on identical inputs. Equality with
    # the own-n direct call is NOT guaranteed for token mode: the reuse
    # step's gathered sub-sequence trunk is a fresh executable per batch
    # shape, and XLA's GEMM tiling at short sequence lengths rounds
    # per-row differently across batch shapes (the full-trunk modes above
    # don't run a shape-k subset, which is why their padded dispatches
    # stay bitwise vs own-n). Own-n agreement is float-level only.
    t4 = eng.submit(seed=13, n=5, config=tok)
    stats = eng.run()
    assert stats["compiles"] == 0
    got = np.asarray(t4.result())
    x5 = jax.random.normal(jax.random.PRNGKey(13), (5, 16, 16, 3),
                           jnp.float32)
    x8 = jnp.concatenate([x5, jnp.zeros((3, 16, 16, 3), jnp.float32)])
    same_shape = np.asarray(sampling.ddim_sample(
        model, params, k=500, x_init=x8, cache_interval=2,
        cache_mode="token", cache_tokens=3))
    np.testing.assert_array_equal(got, same_shape[:5])
    own_n = np.asarray(sampling.ddim_sample(
        model, params, jax.random.PRNGKey(13), k=500, n=5,
        cache_interval=2, cache_mode="token", cache_tokens=3))
    np.testing.assert_allclose(got, own_n, rtol=0, atol=1e-5)


def test_sampler_config_adaptive_token_validation():
    from ddim_cold_tpu import serve

    with pytest.raises(ValueError):  # adaptive needs a threshold
        serve.SamplerConfig(k=500, cache_interval=2, cache_mode="adaptive")
    with pytest.raises(ValueError):  # NaN is not a threshold
        serve.SamplerConfig(k=500, cache_interval=2, cache_mode="adaptive",
                            cache_threshold=float("nan"))
    with pytest.raises(ValueError):  # threshold outside its mode
        serve.SamplerConfig(k=500, cache_interval=2,
                            cache_threshold=0.1)
    with pytest.raises(ValueError):  # token needs cache_tokens
        serve.SamplerConfig(k=500, cache_interval=2, cache_mode="token")
    with pytest.raises(ValueError):  # tokens outside their mode
        serve.SamplerConfig(k=500, cache_interval=2, cache_tokens=3)
    # inpaint + caching is now a served product (the cached inpaint scan)
    cfg = serve.SamplerConfig(task="inpaint", k=500, cache_interval=2)
    assert cfg.cached


def test_plan_batches_adaptive_never_coalesces():
    """Batch-coupled (adaptive) requests get one batch each — the drift
    gate's batch max couples rows, so coalescing or splitting would break
    the bitwise-vs-direct contract."""
    from ddim_cold_tpu import serve
    from ddim_cold_tpu.serve.batching import Request, plan_batches

    cfg = serve.SamplerConfig(k=500, cache_interval=2, cache_mode="adaptive",
                              cache_threshold=0.05)
    reqs = [Request(config=cfg, n=3), Request(config=cfg, n=2)]
    plans = plan_batches(reqs, (4, 8))
    assert [p.bucket for p in plans] == [4, 4]
    assert all(len(p.entries) == 1 for p in plans)
    assert [p.rows for p in plans] == [3, 2]
    with pytest.raises(ValueError, match="bucket"):
        plan_batches([Request(config=cfg, n=9)], (4, 8))


def test_mesh_sharded_cached_sampling_matches_single_device(model_and_params):
    """SPMD cached sampling: the cache shards ride the data axis next to the
    batch (step_cache.shard_cache) and reproduce the single-device result."""
    from ddim_cold_tpu.parallel.mesh import make_mesh

    model, params = model_and_params
    mesh = make_mesh({"data": 8})
    rng = jax.random.PRNGKey(9)
    single = np.asarray(sampling.ddim_sample(model, params, rng, k=500, n=8,
                                             cache_interval=2))
    sharded = sampling.ddim_sample(model, params, rng, k=500, n=8,
                                   cache_interval=2, mesh=mesh)
    assert len(sharded.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(sharded), single,
                               rtol=2e-5, atol=2e-6)
