"""Step-cache subsystem tests (ops/step_cache.py + the vit.py hooks).

The contract under test, in order of strictness:
* interval=1 routes around the cache machinery entirely — BITWISE equal to
  the plain sampler (the dispatch in sampling.ddim_sample/cold_sample);
* a refresh forward (capture_split) computes the exact plain forward while
  emitting the half-trunk deltas (bitwise on the image output);
* a reuse forward never executes the skipped blocks — proven functionally:
  its output is invariant to arbitrary perturbation of their params;
* the refresh→reuse round trip reproduces the plain forward to float
  round-off (a + (b − a) ≠ b bitwise, so this one is allclose, not equal);
* the schedule is static: one XLA compile per (k, interval, mode);
* SPMD cached sampling over a data mesh matches single-device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import sampling, schedule, step_cache

T = 2000
# depth 4: distinct front (0,1) / rear (2,3) halves, so a delta-mode reuse
# still runs real blocks and param-invariance has something to bite on
TINY4 = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=4,
             num_heads=4, total_steps=T)


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY4)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


# ---------------------------------------------------------------- schedule

def test_branch_sequence_delta_phase_split():
    seq = schedule.cache_branch_sequence(10, 2, "delta")
    assert seq.dtype == np.int32
    # refreshes at every interval-th step, reuse between; early half reuses
    # the REAR delta (branch 1), late half the FRONT (branch 2)
    assert list(seq) == [0, 1, 0, 1, 0, 2, 0, 2, 0, 2]


def test_branch_sequence_full_mode_and_intervals():
    assert list(schedule.cache_branch_sequence(6, 2, "full")) == [0, 1] * 3
    assert list(schedule.cache_branch_sequence(7, 3, "full")) == [
        0, 1, 1, 0, 1, 1, 0]
    # interval <= 1: every step refreshes (the exact sampler)
    assert list(schedule.cache_branch_sequence(4, 1)) == [0] * 4
    assert list(schedule.cache_branch_sequence(4, 0)) == [0] * 4
    with pytest.raises(ValueError):
        schedule.cache_branch_sequence(4, 2, "bogus")


def test_cache_spec_validation():
    spec = step_cache.cache_spec(4, 10, 2, "delta")
    assert spec.split == 2 and spec.n_steps == 10 and spec.interval == 2
    hash(spec)  # must stay hashable — it rides jit static args
    with pytest.raises(ValueError):
        step_cache.cache_spec(1, 10, 2)  # no half to skip
    with pytest.raises(ValueError):
        step_cache.cache_spec(4, 10, 2, split=0)
    with pytest.raises(ValueError):
        step_cache.cache_spec(4, 10, 2, split=4)


def test_flops_saved_fraction():
    # interval=2, 10 steps: 5 reuse steps skipping half the trunk → 25%
    assert step_cache.flops_saved_fraction(
        step_cache.cache_spec(4, 10, 2, "delta")) == pytest.approx(0.25)
    # full mode skips the whole trunk on reuse steps → 50%
    assert step_cache.flops_saved_fraction(
        step_cache.cache_spec(4, 10, 2, "full")) == pytest.approx(0.5)
    assert step_cache.flops_saved_fraction(
        step_cache.cache_spec(4, 10, 1)) == 0.0
    assert not step_cache.enabled(1) and step_cache.enabled(2)


# ---------------------------------------------------------- model-level hooks

def test_capture_split_forward_is_bitwise_plain(model_and_params):
    """A refresh step must cost nothing in exactness: same blocks, same
    order, deltas read off the already-computed token stream."""
    model, params = model_and_params
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    t = jnp.array([100, 100], jnp.int32)
    plain = model.apply({"params": params}, x, t)
    out, (d_front, d_rear) = model.apply({"params": params}, x, t,
                                         capture_split=2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(plain))
    assert d_front.shape == d_rear.shape == (2, model.num_patches + 1,
                                             model.embed_dim)


def test_skip_with_true_delta_matches_plain(model_and_params):
    """Refresh → reuse round trip: skipping a half and adding its captured
    delta reproduces the plain forward to float round-off."""
    model, params = model_and_params
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 16, 3))
    t = jnp.array([50, 50], jnp.int32)
    plain = np.asarray(model.apply({"params": params}, x, t))
    _, (d_front, d_rear) = model.apply({"params": params}, x, t,
                                       capture_split=2)
    for skip, delta in (((0, 2), d_front), ((2, 4), d_rear),
                        ((0, 4), d_front + d_rear)):
        out = model.apply({"params": params}, x, t, skip_blocks=skip,
                          block_delta=delta)
        np.testing.assert_allclose(np.asarray(out), plain,
                                   rtol=1e-4, atol=1e-5)


def test_reuse_step_never_runs_skipped_blocks(model_and_params):
    """Functional proof that skipped blocks don't execute: a reuse forward is
    BITWISE invariant to arbitrary perturbation of their params, while the
    same perturbation on an executed block changes the output."""
    model, params = model_and_params
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16, 3))
    t = jnp.array([10, 10], jnp.int32)
    delta = jnp.zeros((2, model.num_patches + 1, model.embed_dim),
                      model.dtype)

    def wreck(p, block_name):
        return jax.tree_util.tree_map_with_path(
            lambda path, a: a + 1e3 if any(
                getattr(k, "key", None) == block_name for k in path) else a, p)

    base = np.asarray(model.apply({"params": params}, x, t,
                                  skip_blocks=(2, 4), block_delta=delta))
    for name in ("blocks_2", "blocks_3"):
        out = np.asarray(model.apply({"params": wreck(params, name)}, x, t,
                                     skip_blocks=(2, 4), block_delta=delta))
        np.testing.assert_array_equal(out, base)
    # sanity: the same perturbation on an EXECUTED block must show up
    out = np.asarray(model.apply({"params": wreck(params, "blocks_0")}, x, t,
                                 skip_blocks=(2, 4), block_delta=delta))
    assert np.abs(out - base).max() > 0


def test_hook_validation(model_and_params):
    model, params = model_and_params
    x = jnp.zeros((1, 16, 16, 3))
    t = jnp.zeros((1,), jnp.int32)
    with pytest.raises(ValueError, match="block_delta"):
        model.apply({"params": params}, x, t, skip_blocks=(0, 2))
    with pytest.raises(ValueError, match="capture_split"):
        model.apply({"params": params}, x, t, capture_split=0)
    with pytest.raises(ValueError):
        model.apply({"params": params}, x, t, skip_blocks=(0, 2),
                    block_delta=jnp.zeros(
                        (1, model.num_patches + 1, model.embed_dim)),
                    capture_split=2)
    scan_model = DiffusionViT(scan_blocks=True, **TINY4)
    sp = scan_model.init(jax.random.PRNGKey(0), x, t)["params"]
    with pytest.raises(ValueError, match="scan_blocks"):
        scan_model.apply({"params": sp}, x, t, capture_split=2)


# ------------------------------------------------------------------ samplers

def test_interval_one_is_bitwise_exact(model_and_params):
    model, params = model_and_params
    rng = jax.random.PRNGKey(5)
    plain = sampling.ddim_sample(model, params, rng, k=400, n=2)
    routed = sampling.ddim_sample(model, params, rng, k=400, n=2,
                                  cache_interval=1, cache_mode="full")
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(routed))
    cold_plain = sampling.cold_sample(model, params, rng, n=2, levels=4)
    cold_routed = sampling.cold_sample(model, params, rng, n=2, levels=4,
                                       cache_interval=1)
    np.testing.assert_array_equal(np.asarray(cold_plain),
                                  np.asarray(cold_routed))


@pytest.mark.parametrize("mode", ["delta", "full"])
def test_cached_ddim_close_to_exact(model_and_params, mode):
    """interval=2 on a tiny random-init model: the cached sampler must stay
    in range and near the exact one (the quantitative FID bound is bench's
    cached_quality section; here we pin basic sanity + determinism)."""
    model, params = model_and_params
    rng = jax.random.PRNGKey(6)
    exact = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2))
    cached = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2,
                                             cache_interval=2,
                                             cache_mode=mode))
    assert np.isfinite(cached).all()
    assert cached.min() >= 0.0 and cached.max() <= 1.0
    assert np.abs(cached - exact).max() < 0.25  # near, not equal
    again = np.asarray(sampling.ddim_sample(model, params, rng, k=200, n=2,
                                            cache_interval=2,
                                            cache_mode=mode))
    np.testing.assert_array_equal(cached, again)  # deterministic


def test_cached_sequence_last_frame_matches_image(model_and_params):
    model, params = model_and_params
    rng = jax.random.PRNGKey(7)
    seq = sampling.ddim_sample(model, params, rng, k=500, n=2,
                               return_sequence=True, cache_interval=2)
    img = sampling.ddim_sample(model, params, rng, k=500, n=2,
                               cache_interval=2)
    assert seq.shape[0] == 5  # init + 4 steps
    np.testing.assert_allclose(np.asarray(seq[-1]), np.asarray(img),
                               rtol=1e-5, atol=1e-6)


def test_cached_cold_and_eta_paths(model_and_params):
    model, params = model_and_params
    rng = jax.random.PRNGKey(8)
    cold = np.asarray(sampling.cold_sample(model, params, rng, n=2, levels=4,
                                           cache_interval=2))
    assert np.isfinite(cold).all() and cold.min() >= 0.0 and cold.max() <= 1.0
    stoch = np.asarray(sampling.ddim_sample(model, params, rng, k=500, n=2,
                                            eta=0.5, cache_interval=2))
    assert np.isfinite(stoch).all()


def test_one_compile_per_schedule(model_and_params):
    """The refresh/reuse pattern is a scanned input, not a trace condition:
    re-sampling with new rngs must not re-trace, and only (k, interval,
    mode) changes may add compilation cache entries."""
    model, params = model_and_params
    fn = sampling._ddim_scan_cached
    fn.clear_cache()
    for seed in (10, 11, 12):
        sampling.ddim_sample(model, params, jax.random.PRNGKey(seed),
                             k=400, n=2, cache_interval=2)
    assert fn._cache_size() == 1
    sampling.ddim_sample(model, params, jax.random.PRNGKey(10), k=400, n=2,
                         cache_interval=3)
    assert fn._cache_size() == 2
    sampling.ddim_sample(model, params, jax.random.PRNGKey(10), k=400, n=2,
                         cache_interval=2, cache_mode="full")
    assert fn._cache_size() == 3


def test_mesh_sharded_cached_sampling_matches_single_device(model_and_params):
    """SPMD cached sampling: the cache shards ride the data axis next to the
    batch (step_cache.shard_cache) and reproduce the single-device result."""
    from ddim_cold_tpu.parallel.mesh import make_mesh

    model, params = model_and_params
    mesh = make_mesh({"data": 8})
    rng = jax.random.PRNGKey(9)
    single = np.asarray(sampling.ddim_sample(model, params, rng, k=500, n=8,
                                             cache_interval=2))
    sharded = sampling.ddim_sample(model, params, rng, k=500, n=8,
                                   cache_interval=2, mesh=mesh)
    assert len(sharded.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(sharded), single,
                               rtol=2e-5, atol=2e-6)
