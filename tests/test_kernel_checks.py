"""GRAFT-P self-tests: violating pallas_call fixtures per rule (the odd
block, the dynamic grid, the oversized scratch, the wasteful block), the
Mosaic legality sweep of ``ops/tiling.legal_block`` at the exact 200px
geometries, and the clean run over the first-class 200px kernel entries.

The fixtures trace on CPU — ``jax.make_jaxpr`` of a ``pallas_call`` never
lowers through Mosaic, which is precisely why the static pass exists: CPU
CI cannot reject these geometries at runtime, so graftcheck must."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddim_cold_tpu.analysis import entries, kernel_checks
from ddim_cold_tpu.analysis.findings import load_baseline, write_baseline
from ddim_cold_tpu.ops import tiling


def _rules_of(findings):
    return sorted({f.rule for f in findings})


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _trace(shape, dtype, block, grid):
    """A minimal one-operand pallas_call traced abstractly."""
    x = jax.ShapeDtypeStruct(shape, dtype)

    def f(x):
        return pl.pallas_call(
            _copy_kernel, out_shape=jax.ShapeDtypeStruct(shape, dtype),
            grid=grid,
            in_specs=[pl.BlockSpec(block, lambda i: (i, 0))],
            out_specs=pl.BlockSpec(block, lambda i: (i, 0)))(x)

    return jax.make_jaxpr(f)(x)


def _check(closed, **kw):
    return kernel_checks.check_program(closed, "fix", "fix.py", **kw)


# --------------------------------------------------------------- P001


def test_p001_odd_block_at_200px_token_count():
    # the r04 killer: a hand-tuned block that neither hits the f32 min
    # tile (8) nor divides the padded token axis — interpret mode runs it,
    # Mosaic rejects it on chip
    closed = _trace((2504, 128), jnp.float32, (100, 128), (26,))
    fs = _check(closed)
    assert _rules_of(fs) == ["GRAFT-P001"]
    assert {f.subject for f in fs} == {"fix:_copy_kernel#1:in0",
                                       "fix:_copy_kernel#1:out0"}
    assert "min-tile unit 8" in fs[0].message
    assert "not a multiple of block" in fs[0].message


def test_p001_sub16_sublane_block_on_bf16():
    closed = _trace((2504, 128), jnp.bfloat16, (8, 128), (313,))
    fs = _check(closed)
    assert _rules_of(fs) == ["GRAFT-P001"]
    assert "min-tile unit 16" in fs[0].message


def test_p001_non_static_grid():
    # np.int64 grid entries (np.gcd-promoted block arithmetic) become
    # DynamicGridDim at trace time — the in-tree legal_block bug this
    # pass's first run caught
    closed = _trace((2504, 128), jnp.float32, (8, 128), (np.int64(313),))
    fs = _check(closed)
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-P001", "fix:_copy_kernel#1:grid")]
    assert "non-static grid" in fs[0].message


def test_p001_whole_dim_span_is_legal():
    # a block spanning the whole array dim is exempt from the min-tile
    # multiple rule (Mosaic's whole-dim escape hatch)
    closed = _trace((4, 128), jnp.float32, (4, 128), (1,))
    assert _check(closed) == []


# --------------------------------------------------------------- P002


def test_p002_oversized_vmem_scratch():
    def kernel(x_ref, o_ref, acc_ref):
        o_ref[...] = x_ref[...]

    def f(x):
        return pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct((256, 128), jnp.float32),
            grid=(1,),
            in_specs=[pl.BlockSpec((256, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((256, 128), lambda i: (0, 0)),
            scratch_shapes=[pltpu.VMEM((4096, 4096), jnp.float32)])(x)

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((256, 128), jnp.float32))
    fs = _check(closed)
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-P002", "fix:kernel#1:vmem")]
    assert "64.5 MiB" in fs[0].message
    # a roomier explicit budget clears it
    assert _check(closed, vmem_budget=128 << 20) == []


def test_p002_budget_counts_double_buffering():
    call = kernel_checks.KernelCall(
        name="k", path="fix.py", line=1, grid=(1,),
        blocks=[kernel_checks.BlockInfo("in", 0, (512, 128), (512, 128),
                                        np.dtype(np.float32))])
    assert call.vmem_bytes() == 2 * 512 * 128 * 4


# --------------------------------------------------------------- P003


def test_p003_wasteful_block_at_logical_tokens():
    # array pre-padded to the block multiple (P001-clean) but the block
    # charges 4096 rows of compute against 2501 logical tokens
    closed = _trace((4096, 128), jnp.float32, (2048, 128), (2,))
    fs = _check(closed, logical=2501)
    assert [(f.rule, f.subject) for f in fs] == [
        ("GRAFT-P003", "fix:_copy_kernel#1:pad")]
    assert "64%" in fs[0].message
    # without the registered logical extent the same geometry is exact
    assert _check(closed) == []


def test_p003_in_tree_worst_case_passes():
    # the streamed-kv sweep worst case: bkv=1024 pads 2504 → 3072 over
    # 2501 logical (1.228) — under the 1.25 threshold by design
    closed = _trace((2504, 128), jnp.float32, (1024, 128), (3,))
    fs = _check(closed, logical=2501)
    assert _rules_of(fs) == ["GRAFT-P001"]  # 2504 % 1024 only; no P003
    assert not [f for f in fs if f.rule == "GRAFT-P003"]


# ------------------------------------------------- legal_block vs Mosaic


def test_min_tile_table_matches_tiling():
    # the pass keeps an independent copy of the tile table so a legalizer
    # regression is caught — but the two must agree on the rule itself
    for dt in (np.float32, jnp.bfloat16, np.int8):
        sub, lane = kernel_checks.MIN_TILE[np.dtype(dt).itemsize]
        assert sub == tiling.sublane_unit(dt)
        assert lane == tiling.LANE


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16, np.int8])
@pytest.mark.parametrize("dim", [2501, 2504, 3072, 64, 128, 40016])
def test_legal_block_sweep_is_mosaic_legal(dtype, dim):
    """Exhaustive request sweep at the 200px shapes: every returned block
    is a Python int (np.int64 would make the grid dynamic — P001), a
    min-tile multiple, and pads the dim to a block multiple."""
    for lane in (False, True):
        unit = tiling.LANE if lane else tiling.sublane_unit(dtype)
        for req in (1, 7, 8, 100, 256, 511, 512, 2048, dim, 2 * dim):
            blk = tiling.legal_block(req, dim, dtype, lane=lane)
            assert type(blk) is int, (req, dim, blk)
            assert blk % unit == 0
            assert blk <= tiling.round_up(dim, unit)
            padded = tiling.round_up(dim, blk)
            assert padded % blk == 0 and padded >= dim


def test_legal_block_dual_dtype_min_unit():
    # the dequant K block: activation lane dim AND int8 weight sublane dim
    blk = tiling.legal_block(512, 256, jnp.bfloat16, lane=True,
                             min_unit=tiling.sublane_unit(np.int8))
    assert type(blk) is int and blk % 128 == 0 and blk % 32 == 0


# ------------------------------------------------- baseline + clean tree


def test_p_finding_keys_are_stable_and_round_trip(tmp_path):
    closed = _trace((2504, 128), jnp.float32, (100, 128), (26,))
    fs = _check(closed)
    base = tmp_path / "baseline.txt"
    write_baseline(str(base), fs)
    assert load_baseline(str(base)) == {f.key for f in fs}
    # identity survives a re-trace (line numbers are display-only)
    assert {f.key for f in _check(_trace((2504, 128), jnp.float32,
                                         (100, 128), (26,)))} == \
        {f.key for f in fs}


def test_kernel_entries_cover_the_northstar_geometry():
    names = [e.name for e in entries.kernel_entries()]
    for required in ("ns200_f32", "ns200_bf16", "ns200_w8a16"):
        assert required in names, required
    assert any(n.startswith("flash200_grad_") for n in names)
    assert any(n.startswith("dequant200_") for n in names)


def test_clean_in_tree_kernels(kernel_traces):
    """The acceptance gate: every in-tree pallas_call at the registered
    200px geometries (f32/bf16/w8a16 samplers, the flash fwd/grad block
    sweep, the dequant matmuls) proves tile-legal, VMEM-fitting, and
    waste-free — and some calls actually exist to prove it on."""
    fs = kernel_checks.run_kernel_checks(serve_traces={}, entry_traces={},
                                         kernel_traces=kernel_traces)
    assert [f.render() for f in fs] == []
    n_calls = sum(
        len(list(kernel_checks.iter_kernel_calls(c, e.path)))
        for e, c in kernel_traces.values())
    assert n_calls >= 10, n_calls
