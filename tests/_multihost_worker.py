"""Worker for test_multihost.py — one simulated host in an N-process run.

Run as: python _multihost_worker.py <coordinator> <num_procs> <proc_id> \
            <out_dir> [mode]

Each process gets its virtual CPU devices (xla_force_host_platform_device_count,
set by the parent), initializes `jax.distributed` over the local coordinator
(the DCN-rendezvous path, parallel/mesh.py:28-36), builds a global mesh,
feeds its process-local shard of the global batch through ``shard_batch``
(make_array_from_process_local_data — the multi-host branch,
parallel/mesh.py:74-77), runs one train step, and writes the loss it saw to
``<out_dir>/loss_<proc_id>.txt`` for the parent to compare.

Modes:

* ``dp`` (default) — pure data-parallel over all devices, plus a grouped
  steps_per_dispatch=2 step and a collective orbax save (the 2-process
  matrix entry);
* ``dptpsp`` — the composed {data, model, seq} mesh: tensor-parallel params
  over 'model', ring attention over 'seq', grouped steps_per_dispatch
  dispatch — the layout the virtual-mesh dryrun compiles, here under REAL
  processes over DCN (VERDICT r4 item 7). Two processes share each data
  shard, so the worker derives its shard index from its addressable
  devices' mesh coordinates rather than from proc_id.
* ``spsample`` — sequence-parallel SAMPLING (the serving tentpole's
  (data, seq) mesh) with the 'seq' axis ACROSS the process boundary:
  {seq:2, data:4} over 2 processes × 4 devices, ulysses all-to-alls over
  DCN, k-step ddim scan, dense-local-reference parity asserted in-worker
  and a global-mean digest written for the parent's cross-process check.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def data_shard_bounds(mesh, batch_rows: int) -> tuple[int, int]:
    """[lo, hi) rows of the global batch held by THIS process, from the mesh
    coordinates of its addressable devices along 'data' (the general form of
    the 2-proc test's proc_id*rows slicing — correct even when several
    processes replicate one data shard across 'model'/'seq')."""
    axis = list(mesh.axis_names).index("data")
    coords = {
        int(np.argwhere(np.asarray(mesh.devices) == d)[0][axis])
        for d in mesh.local_devices
    }
    assert len(coords) == 1, (
        f"process spans data shards {sorted(coords)} — the P('data') batch "
        "contract needs each process inside one shard")
    n = int(mesh.shape["data"])
    rows = batch_rows // n
    lo = coords.pop() * rows
    return lo, lo + rows


def main():
    coordinator, num_procs, proc_id, out_dir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else "dp"

    import jax

    # the site jax config can override the JAX_PLATFORMS env var (it does on
    # the axon bench host) — force the virtual-CPU platform programmatically,
    # exactly like tests/conftest.py
    jax.config.update("jax_platforms", "cpu")

    from ddim_cold_tpu.parallel.mesh import (
        initialize_distributed, make_mesh, shard_batch,
    )

    initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs, jax.process_count()

    import jax.numpy as jnp

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step
    from ddim_cold_tpu.utils import checkpoint as ckpt

    if mode == "dptpsp":
        run_dptpsp(jax, jnp, out_dir, proc_id)
        jax.distributed.shutdown()
        return
    if mode == "pipemoe":
        run_pipemoe(jax, jnp, out_dir, proc_id)
        jax.distributed.shutdown()
        return
    if mode == "spsample":
        run_spsample(jax, jnp, out_dir, proc_id)
        jax.distributed.shutdown()
        return
    assert jax.local_device_count() == 4, jax.local_device_count()

    mesh = make_mesh({"data": jax.device_count()})

    model = DiffusionViT(img_size=(8, 8), patch_size=4, embed_dim=16,
                         depth=1, num_heads=2, total_steps=10)
    # deterministic per-process shard of a notional global batch of 16:
    # process r holds rows [r*8, r*8+8) — identical data either way the
    # global array is assembled, so the loss must agree across processes.
    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8, 8, 3).astype(np.float32)
    gy = rng.randn(16, 8, 8, 3).astype(np.float32)
    gt = rng.randint(1, 4, size=(16,)).astype(np.int32)
    lo, hi = proc_id * 8, proc_id * 8 + 8
    local = (gx[lo:hi], gy[lo:hi], gt[lo:hi])

    batch = shard_batch(local, mesh)
    assert not batch[0].is_fully_addressable  # genuinely multi-host global

    state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                               total_steps=10, sample_batch=local)
    train_step = make_train_step(model)
    state, loss, _ = train_step(state, batch, jax.random.PRNGKey(1),
                                jnp.float32(5.0))
    loss = float(loss)  # global-mean loss: identical on both processes

    # grouped (steps_per_dispatch) sharding across REAL processes: each host
    # contributes its (n, local_B, …) stack and the P(None, 'data') global
    # assembles — the multi-host form of the grouped-dispatch batch contract
    grouped_local = tuple(np.stack([a, a]) for a in local)
    gbatch = shard_batch(grouped_local, mesh, grouped=True)
    assert not gbatch[0].is_fully_addressable
    multi_step = make_train_step(model, steps_per_dispatch=2)
    state, gloss, _ = multi_step(state, gbatch, jax.random.PRNGKey(1),
                                 jnp.float32(5.0))
    assert np.isfinite(float(gloss)), gloss

    # collective orbax save: every process calls save (trainer.py:284-287)
    ckpt.save_checkpoint(os.path.join(out_dir, "ckpt"), state.params)

    with open(os.path.join(out_dir, f"loss_{proc_id}.txt"), "w") as f:
        f.write(repr(loss))
    jax.distributed.shutdown()


def run_dptpsp(jax, jnp, out_dir: str, proc_id: int):
    """The composed {data:2, model:2, seq:2} layout under REAL processes
    (VERDICT r4 item 7): 4 processes × 2 local devices = 8 global devices —
    tensor-parallel params over 'model' (param_partition_specs), ring
    attention over 'seq', and ONE grouped steps_per_dispatch=2 dispatch.
    Mirrors __graft_entry__.dryrun_multichip's dp×tp×sp recipe, swapping the
    virtual single-process mesh for a DCN-rendezvoused one."""
    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.ops import degrade
    from ddim_cold_tpu.parallel import (
        make_mesh, param_partition_specs, shard_batch, shard_train_state,
    )
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    assert jax.local_device_count() == 2, jax.local_device_count()
    mesh = make_mesh({"data": 2, "model": 2, "seq": 2})

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32,
                         depth=2, num_heads=4, total_steps=10,
                         seq_mesh=mesh, seq_axis="seq", batch_axis="data",
                         head_axis="model", attn_drop_rate=0.0)
    # deterministic global batch; THIS process's rows come from its
    # addressable devices' 'data' coordinate (two processes per shard here —
    # proc_id arithmetic from the dp worker would be wrong)
    rng = np.random.RandomState(0)
    B = 8
    gu = rng.randint(0, 256, size=(B, 16, 16, 3)).astype(np.uint8)
    gt = rng.randint(1, 5, size=(B,)).astype(np.int32)
    lo, hi = data_shard_bounds(mesh, B)
    local = (gu[lo:hi], gt[lo:hi])

    state = create_train_state(
        model, jax.random.PRNGKey(0), lr=1e-3, total_steps=10,
        sample_batch=(np.zeros((2, 16, 16, 3), np.float32),
                      np.zeros((2, 16, 16, 3), np.float32),
                      np.ones((2,), np.int32)))
    state = shard_train_state(state, mesh,
                              param_partition_specs(state.params))
    prepare = degrade.make_cold_prepare(size=16, max_step=4, chain=True,
                                        mesh=mesh)
    step = make_train_step(model, prepare=prepare)
    batch = shard_batch(local, mesh)
    assert not batch[0].is_fully_addressable
    state, loss, _ = step(state, batch, jax.random.PRNGKey(1),
                          jnp.float32(5.0))
    loss = float(loss)
    assert np.isfinite(loss), loss

    # grouped dispatch: 2 stacked optimizer steps, scan axis unsharded,
    # 'data' on the per-step batch dim — under real processes
    g_step = make_train_step(model, prepare=prepare, steps_per_dispatch=2)
    grouped = tuple(np.stack([a, a]) for a in local)
    gbatch = shard_batch(grouped, mesh, grouped=True)
    assert not gbatch[0].is_fully_addressable
    state, gloss, _ = g_step(state, gbatch, jax.random.PRNGKey(1),
                             jnp.float32(5.0))
    assert np.isfinite(float(gloss)), gloss

    with open(os.path.join(out_dir, f"loss_{proc_id}.txt"), "w") as f:
        f.write(repr(loss))


def run_spsample(jax, jnp, out_dir: str, proc_id: int):
    """Sequence-parallel k-step SAMPLING over DCN: mesh {seq:2, data:4} over
    2 processes × 4 local devices puts the 'seq' coordinate on the PROCESS
    index — every ulysses all-to-all crosses the process boundary — while
    the batch stays data-sharded among each host's four devices. The same
    (data, seq) geometry the serve engine warms, minus the engine (whose
    device_put/assemble path is host-local by design); the scan family and
    attention front are exactly the served code."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddim_cold_tpu.models import DiffusionViT, sp_clone
    from ddim_cold_tpu.ops import sampling
    from ddim_cold_tpu.parallel import make_mesh, shard_batch

    assert jax.local_device_count() == 4, jax.local_device_count()
    mesh = make_mesh({"seq": 2, "data": 4})
    # the claim under test is the all-to-all CROSSING DCN: this process must
    # own exactly one seq shard (and hence span every data shard). If device
    # enumeration ever stops being process-major, fail loud instead of
    # green-lighting an intra-process reshard.
    seq_ax = list(mesh.axis_names).index("seq")
    coords = {
        int(np.argwhere(np.asarray(mesh.devices) == d)[0][seq_ax])
        for d in mesh.local_devices
    }
    assert len(coords) == 1, (
        f"process spans seq shards {sorted(coords)} — the DCN-crossing "
        "all-to-all claim needs one seq shard per process")

    base = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32,
                        depth=2, num_heads=4, total_steps=2000,
                        attn_drop_rate=0.0)
    sp = sp_clone(base, mesh, sp_mode="ulysses")
    assert sp.sp_mode == "ulysses", sp.sp_mode  # 4 heads % 2 — no fallback
    # params replicated as ONE global placement (every process runs the same
    # init under out_shardings — the multi-host analogue of shard_params)
    init = jax.jit(base.init, out_shardings=NamedSharding(mesh, P()))
    params = init(jax.random.PRNGKey(0),
                  np.zeros((2, 16, 16, 3), np.float32),
                  np.array([0, 1], np.int32))["params"]

    rng = np.random.RandomState(0)
    B = 8
    x0 = rng.randn(B, 16, 16, 3).astype(np.float32)  # same on both procs
    x_init = shard_batch(x0, mesh)  # every data shard is addressable here
    assert not x_init.is_fully_addressable
    out = sampling.ddim_sample(sp, params, jax.random.PRNGKey(1), k=500,
                               x_init=x_init, mesh=mesh)
    digest = float(jnp.mean(out))  # replicated scalar — a true global mean

    # dense local reference: replicated params are fully-replicated global
    # arrays, so each process can pull a host copy and run the plain model
    # on its own device 0 — reduction reordering is the only difference
    params_host = jax.tree.map(np.asarray, params)
    ref = sampling.ddim_sample(base, params_host, jax.random.PRNGKey(1),
                               k=500, x_init=x0)
    ref_digest = float(jnp.mean(ref))
    assert abs(digest - ref_digest) < 5e-4, (digest, ref_digest)

    with open(os.path.join(out_dir, f"loss_{proc_id}.txt"), "w") as f:
        f.write(repr(digest))


def run_pipemoe(jax, jnp, out_dir: str, proc_id: int):
    """GPipe ACROSS PROCESSES + the pipe×MoE aux path (round 5): mesh
    {pipe: 2, data: 2} over 2 processes × 2 local devices puts stage 0 on
    process 0 and stage 1 on process 1, so every schedule ppermute and the
    aux psum cross the DCN boundary; the Switch aux loss rides the
    pipelined apply's mutable=["losses"] path into the step objective."""
    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.parallel import (
        make_mesh, make_pipelined_apply, pipeline_param_specs,
        shard_batch, shard_train_state,
    )
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    assert jax.local_device_count() == 2, jax.local_device_count()
    mesh = make_mesh({"pipe": 2, "data": 2})
    # the claim under test is GPipe ppermute CROSSING the process boundary:
    # this process must own exactly one pipe stage (and hence span both data
    # shards). If device enumeration ever stops being process-major, fail
    # loud here instead of green-lighting a vacuous single-process pipeline.
    pipe_ax = list(mesh.axis_names).index("pipe")
    stages = {
        int(np.argwhere(np.asarray(mesh.devices) == d)[0][pipe_ax])
        for d in mesh.local_devices
    }
    assert len(stages) == 1, (
        f"process spans pipe stages {sorted(stages)} — the DCN-crossing "
        "ppermute claim needs one stage per process")

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32,
                         depth=2, num_heads=4, total_steps=10,
                         scan_blocks=True, num_experts=2)
    rng = np.random.RandomState(0)
    B = 8
    gx = rng.randn(B, 16, 16, 3).astype(np.float32)
    gy = rng.randn(B, 16, 16, 3).astype(np.float32)
    gt = rng.randint(1, 5, size=(B,)).astype(np.int32)
    # the pipe axis crosses processes here, so EACH process addresses a
    # device in every data shard — its process-local slab is the full
    # batch (data_shard_bounds' one-shard contract applies to dp-style
    # layouts where a process sits inside a single shard)
    local = (gx, gy, gt)

    state = create_train_state(
        model, jax.random.PRNGKey(0), lr=1e-3, total_steps=10,
        sample_batch=(np.zeros((2, 16, 16, 3), np.float32),
                      np.zeros((2, 16, 16, 3), np.float32),
                      np.ones((2,), np.int32)))
    state = shard_train_state(state, mesh, pipeline_param_specs(state.params))
    step = make_train_step(
        model, moe_aux_weight=0.01,
        apply_fn=make_pipelined_apply(model, mesh, n_microbatch=2))
    batch = shard_batch(local, mesh)
    assert not batch[0].is_fully_addressable
    state, loss, _ = step(state, batch, jax.random.PRNGKey(1),
                          jnp.float32(5.0))
    loss = float(loss)
    assert np.isfinite(loss), loss

    with open(os.path.join(out_dir, f"loss_{proc_id}.txt"), "w") as f:
        f.write(repr(loss))


if __name__ == "__main__":
    main()
