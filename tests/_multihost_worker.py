"""Worker for test_multihost.py — one simulated host in a 2-process run.

Run as: python _multihost_worker.py <coordinator> <num_procs> <proc_id> <out_dir>

Each process gets 4 virtual CPU devices (xla_force_host_platform_device_count,
set by the parent), initializes `jax.distributed` over the local coordinator
(the DCN-rendezvous path, parallel/mesh.py:28-36), builds an 8-device global
mesh, feeds its process-local half of the global batch through
``shard_batch`` (make_array_from_process_local_data — the multi-host branch,
parallel/mesh.py:74-77), runs one train step, and participates in a
collective orbax save (train/trainer.py save path). Writes the loss it saw to
``<out_dir>/loss_<proc_id>.txt`` for the parent to compare.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    coordinator, num_procs, proc_id, out_dir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])

    import jax

    # the site jax config can override the JAX_PLATFORMS env var (it does on
    # the axon bench host) — force the virtual-CPU platform programmatically,
    # exactly like tests/conftest.py
    jax.config.update("jax_platforms", "cpu")

    from ddim_cold_tpu.parallel.mesh import (
        initialize_distributed, make_mesh, shard_batch,
    )

    initialize_distributed(coordinator, num_procs, proc_id)
    assert jax.process_count() == num_procs, jax.process_count()
    assert jax.local_device_count() == 4, jax.local_device_count()

    import jax.numpy as jnp
    import numpy as np

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step
    from ddim_cold_tpu.utils import checkpoint as ckpt

    mesh = make_mesh({"data": jax.device_count()})

    model = DiffusionViT(img_size=(8, 8), patch_size=4, embed_dim=16,
                         depth=1, num_heads=2, total_steps=10)
    # deterministic per-process shard of a notional global batch of 16:
    # process r holds rows [r*8, r*8+8) — identical data either way the
    # global array is assembled, so the loss must agree across processes.
    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8, 8, 3).astype(np.float32)
    gy = rng.randn(16, 8, 8, 3).astype(np.float32)
    gt = rng.randint(1, 4, size=(16,)).astype(np.int32)
    lo, hi = proc_id * 8, proc_id * 8 + 8
    local = (gx[lo:hi], gy[lo:hi], gt[lo:hi])

    batch = shard_batch(local, mesh)
    assert not batch[0].is_fully_addressable  # genuinely multi-host global

    state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                               total_steps=10, sample_batch=local)
    train_step = make_train_step(model)
    state, loss, _ = train_step(state, batch, jax.random.PRNGKey(1),
                                jnp.float32(5.0))
    loss = float(loss)  # global-mean loss: identical on both processes

    # grouped (steps_per_dispatch) sharding across REAL processes: each host
    # contributes its (n, local_B, …) stack and the P(None, 'data') global
    # assembles — the multi-host form of the grouped-dispatch batch contract
    grouped_local = tuple(np.stack([a, a]) for a in local)
    gbatch = shard_batch(grouped_local, mesh, grouped=True)
    assert not gbatch[0].is_fully_addressable
    multi_step = make_train_step(model, steps_per_dispatch=2)
    state, gloss, _ = multi_step(state, gbatch, jax.random.PRNGKey(1),
                                 jnp.float32(5.0))
    assert np.isfinite(float(gloss)), gloss

    # collective orbax save: every process calls save (trainer.py:284-287)
    ckpt.save_checkpoint(os.path.join(out_dir, "ckpt"), state.params)

    with open(os.path.join(out_dir, f"loss_{proc_id}.txt"), "w") as f:
        f.write(repr(loss))
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
