"""watch_tpu.py — the standing recovery watcher the evidence chain hangs off
(SURVEY.md §5 failure-detect/recovery). These tests drive the real probe and
main loop on the CPU backend: a live backend must fire the one-shot hook and
refresh the probe marker; a dead platform must keep polling, not crash."""

import importlib.util
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_marker(tmp_path, monkeypatch):
    """Never read or leave the real shared probe marker (same isolation
    contract as test_platform.py's _no_probe_cache): probe_marker_path
    resolves through tempfile.gettempdir(), so point it at tmp_path."""
    import tempfile

    monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
    # subprocess CLI runs honor TMPDIR for the same isolation
    monkeypatch.setenv("TMPDIR", str(tmp_path))
    yield


def _load_watcher():
    spec = importlib.util.spec_from_file_location(
        "watch_tpu", os.path.join(REPO, "scripts", "watch_tpu.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_once_alive_on_cpu():
    w = _load_watcher()
    alive, detail = w.probe_once("cpu", timeout_s=120.0)
    assert alive and detail == "probe ok"


def test_probe_once_dead_platform_fails_not_hangs():
    w = _load_watcher()
    alive, detail = w.probe_once("no_such_platform", timeout_s=120.0)
    assert not alive and detail.startswith("rc=")


def test_once_exec_fires_on_recovery_and_refreshes_marker(tmp_path):
    """End-to-end: watcher probes (cpu → immediately alive), writes the
    shared probe marker keyed by the effective first platform, runs the hook
    exactly once, and exits with the hook's return code."""
    w = _load_watcher()
    from ddim_cold_tpu.utils.platform import probe_marker_path

    marker = probe_marker_path("cpu")
    assert not os.path.exists(marker)  # isolated tempdir starts clean
    sentinel = tmp_path / "fired"
    log = tmp_path / "watch.log"
    # bound the in-process run: main() loops forever if the probe fails (a
    # broken jax/CPU backend must fail the test, not wedge the whole suite)
    signal.alarm(150)
    try:
        rc = w.main(["--interval", "1", "--timeout", "120",
                     "--platforms", "cpu", "--log", str(log),
                     "--once-exec", f"touch {sentinel} && exit 7"])
    finally:
        signal.alarm(0)
    assert rc == 7  # the watcher's exit code is the hook's
    assert sentinel.exists()  # hook ran
    assert os.path.exists(marker)  # CLIs now skip their own probes
    text = log.read_text()
    assert "ALIVE" in text and "recovery hook" in text


def test_watcher_cli_entrypoint(tmp_path):
    """`python scripts/watch_tpu.py --once-exec …` as the chain invokes it."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "watch_tpu.py"),
         "--interval", "1", "--platforms", "cpu", "--once-exec", "true"],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "ALIVE" in proc.stdout
