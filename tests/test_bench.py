"""bench.py smoke: the driver-facing record must always parse and carry the
headline keys (a bench regression silently loses the round's BENCH record)."""

import json

import numpy as np


def test_bench_smoke_record(capsys):
    import bench

    bench.main(["--smoke", "--cpu", "--steps", "3", "--batch", "4",
                "--skip-sampler"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "train_throughput_vit_tiny64_b32"
    assert np.isfinite(rec["value"]) and rec["value"] > 0
    assert rec["unit"] == "img/s"
    assert np.isfinite(rec["vs_baseline"])
    assert rec["chip"] == "cpu"
    assert "submetrics" in rec and isinstance(rec["submetrics"], dict)
    assert np.isfinite(rec["ms_per_step"]) and rec["ms_per_step"] > 0


def test_bench_stall_watchdog_emits_partial_record():
    """A wedged RPC mid-run (tunnel drop: the call blocks forever, no
    exception) must still produce a parseable record: the watchdog emits the
    partial JSON and exits (nonzero, so callers never log the partial run
    as success) instead of hanging until an outer kill — which
    would both lose the round's BENCH record and wedge the tunnel for the
    next client (utils/platform.py)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(DDIM_COLD_BENCH_STALL_S="2", DDIM_COLD_BENCH_TEST_HANG_S="3600",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--cpu", "--steps", "2",
         "--batch", "2", "--skip-sampler"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-2000:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "aborted" in rec["submetrics"], rec
    # the stall hit before the headline ran; the record says so honestly
    assert rec["value"] is None
    assert rec["metric"] == "train_throughput_vit_tiny64_b32"


def test_bench_fatal_error_still_emits_partial_record():
    """An exception escaping the try body (here: a headline failure forced by
    an invalid batch) must emit the partial record with a fatal_error note
    and exit nonzero — never crash recordless."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--cpu", "--steps", "2",
         "--batch", "-1", "--skip-sampler"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode != 0
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "fatal_error" in rec["submetrics"], rec
    assert rec["metric"] == "train_throughput_vit_tiny64_b32"
