"""bench.py smoke: the driver-facing record must always parse and carry the
headline keys (a bench regression silently loses the round's BENCH record)."""

import json

import numpy as np


def test_bench_smoke_record(capsys):
    import bench

    bench.main(["--smoke", "--cpu", "--steps", "3", "--batch", "4",
                "--skip-sampler"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "train_throughput_vit_tiny64_b32"
    assert np.isfinite(rec["value"]) and rec["value"] > 0
    assert rec["unit"] == "img/s"
    assert np.isfinite(rec["vs_baseline"])
    assert rec["chip"] == "cpu"
    assert "submetrics" in rec and isinstance(rec["submetrics"], dict)
    assert np.isfinite(rec["ms_per_step"]) and rec["ms_per_step"] > 0
