"""bench.py smoke: the driver-facing record must always parse and carry the
headline keys (a bench regression silently loses the round's BENCH record)."""

import json

import numpy as np


def test_bench_smoke_record(capsys):
    import bench

    bench.main(["--smoke", "--cpu", "--steps", "3", "--batch", "4",
                "--skip-sampler"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "train_throughput_vit_tiny64_b32"
    assert np.isfinite(rec["value"]) and rec["value"] > 0
    assert rec["unit"] == "img/s"
    assert np.isfinite(rec["vs_baseline"])
    assert rec["chip"] == "cpu"
    assert "submetrics" in rec and isinstance(rec["submetrics"], dict)
    assert np.isfinite(rec["ms_per_step"]) and rec["ms_per_step"] > 0


def test_bench_serving_smoke_record(capsys):
    """The --serving leg must record the serving submetrics the driver
    compares round over round — sustained img/s, one-shot baseline, latency
    percentiles, and a zero compiles-after-warmup count (the engine's whole
    point). Same --batch/--steps as the plain smoke test so the in-process
    jit caches keep the train half nearly free."""
    import bench

    bench.main(["--smoke", "--cpu", "--steps", "3", "--batch", "4",
                "--skip-sampler", "--no-ksweep", "--serving"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    srv = rec["submetrics"]["serving"]
    assert srv["compiles_after_warmup"] == 0
    assert srv["warmup"]["new_compiles"] >= 1
    assert np.isfinite(srv["img_per_sec"]) and srv["img_per_sec"] > 0
    assert np.isfinite(srv["oneshot_img_per_sec"]) and srv["oneshot_img_per_sec"] > 0
    # vs_oneshot is recorded for the driver's >= 0.9 acceptance gate; CPU CI
    # timing is too noisy to assert the ratio itself here
    assert np.isfinite(srv["vs_oneshot"]) and srv["vs_oneshot"] > 0
    assert srv["p95_latency_s"] >= srv["p50_latency_s"] > 0
    assert srv["rows"] > 0 and srv["batches"] > 0
    assert srv["padded_rows"] == 0  # smoke sizes are built to tile exactly
    assert srv["max_queue_depth"] >= 1


def test_bench_faults_smoke_record(capsys):
    """The --faults robustness leg: a disarmed drain (zero
    compiles-after-warmup, the zero-overhead guarantee) then the fixed
    seeded chaos schedule — the record must carry degraded-mode throughput
    and the recovery counters the driver compares round over round."""
    import bench

    bench.main(["--smoke", "--cpu", "--steps", "3", "--batch", "4",
                "--skip-sampler", "--no-ksweep", "--faults"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    fl = rec["submetrics"]["faults"]
    assert fl["compiles_after_warmup"] == 0  # clean AND chaos drains
    assert fl["warmup_new_compiles"] >= 1
    assert np.isfinite(fl["clean_img_per_sec"]) and fl["clean_img_per_sec"] > 0
    assert np.isfinite(fl["chaos_img_per_sec"]) and fl["chaos_img_per_sec"] > 0
    assert fl["degraded_ratio"] > 0
    # the fixed schedule always quarantines its one poisoned request, and
    # the permanent fault fired at least once to cause it
    assert fl["quarantined"] == 1 and fl["failed_tickets"] == 1
    assert fl["injected"] >= 1 and fl["by_site"]
    assert fl["rows"] > 0


def test_bench_quant_smoke_record(capsys):
    """The --quant 64px leg must record both dequant-matmul modes with
    paired drift + the param-byte saving, and stamp quant_rev next to
    kernel_rev (stale-record protection keys off both)."""
    import bench
    from ddim_cold_tpu.ops.quant import QUANT_REV

    bench.main(["--smoke", "--cpu", "--steps", "3", "--batch", "4",
                "--skip-sampler", "--no-ksweep", "--quant"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    sub = rec["submetrics"]
    assert sub["quant_rev"] == QUANT_REV and "kernel_rev" in sub
    q = sub["sampler_64px_w8a16"]
    assert q["param_bytes_quant"] < q["param_bytes"]
    assert q["float_img_per_sec"] > 0
    for mode in ("xla", "pallas"):
        leg = q["modes"][mode]
        assert np.isfinite(leg["img_per_sec"]) and leg["img_per_sec"] > 0
        assert np.isfinite(leg["speedup_vs_float"])
        # bf16 model: quant noise rides under the bf16 activation noise
        assert leg["max_abs_pixel_delta"] < 0.1


def test_bench_stall_watchdog_emits_partial_record():
    """A wedged RPC mid-run (tunnel drop: the call blocks forever, no
    exception) must still produce a parseable record: the watchdog emits the
    partial JSON and exits (nonzero, so callers never log the partial run
    as success) instead of hanging until an outer kill — which
    would both lose the round's BENCH record and wedge the tunnel for the
    next client (utils/platform.py)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(DDIM_COLD_BENCH_STALL_S="2", DDIM_COLD_BENCH_TEST_HANG_S="3600",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--cpu", "--steps", "2",
         "--batch", "2", "--skip-sampler"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-2000:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "aborted" in rec["submetrics"], rec
    # the stall hit before the headline ran; the record says so honestly
    assert rec["value"] is None
    assert rec["metric"] == "train_throughput_vit_tiny64_b32"


def test_reuse_round_record(tmp_path, monkeypatch):
    """Wedged-at-driver-time fallback (VERDICT r3 item 2): when the live
    probe fails but this round's chain already committed a TPU record into
    results/, bench emits THAT record (labeled captured_earlier), not a
    meaningless CPU smoke. Round N is inferred as max(BENCH_r*.json) + 1."""
    import os

    import bench

    # the recovery chain exports DDIM_COLD_ROUND for its whole process
    # tree; the inference-path assertions need it absent
    monkeypatch.delenv("DDIM_COLD_ROUND", raising=False)

    root = str(tmp_path)
    os.makedirs(os.path.join(root, "results"))
    for n in (1, 2, 3):  # three prior driver records → current round = 4
        with open(os.path.join(root, f"BENCH_r{n:02d}.json"), "w") as f:
            f.write("{}")
    # no same-round record yet → no reuse (falls through to CPU smoke)
    assert bench._reuse_round_record("probe hung", root=root) is None
    rec = {"metric": "train_throughput_vit_tiny64_b32", "value": 4089.0,
           "chip": "TPU v5 lite", "submetrics": {"mfu": 0.054}}
    path = os.path.join(root, "results", "bench_r04_tpu.json")
    with open(path, "w") as f:  # non-JSON noise line: last parseable wins
        f.write("not json\n" + json.dumps(rec) + "\n")
    got = bench._reuse_round_record("probe hung", root=root)
    assert got and got["captured_earlier"] is True
    assert got["value"] == 4089.0
    assert got["submetrics"]["captured_earlier"]["live_probe"] == "probe hung"
    assert got["submetrics"]["captured_earlier"]["file"].endswith(
        "bench_r04_tpu.json")
    # a CPU-fallback or value-less record must never be reused
    with open(path, "w") as f:
        f.write(json.dumps(dict(rec, chip="cpu")) + "\n")
    assert bench._reuse_round_record("probe hung", root=root) is None
    with open(path, "w") as f:
        f.write(json.dumps(dict(rec, value=None)) + "\n")
    assert bench._reuse_round_record("probe hung", root=root) is None
    # tunnel down the WHOLE round (no r04 record at all): the newest prior
    # round's committed record is reused, loudly labeled stale
    os.remove(path)
    with open(os.path.join(root, "results", "bench_r03_tpu.json"), "w") as f:
        f.write(json.dumps(dict(rec, value=613.0)) + "\n")
    got = bench._reuse_round_record("probe hung", root=root)
    assert got and got["value"] == 613.0
    assert got["submetrics"]["captured_earlier"]["stale_round"] == 3
    assert "not a fresh measurement" in got["submetrics"]["captured_earlier"]["note"]
    # sticky staleness: if that reused record later sits in a same-round
    # file, re-reusing it must PRESERVE the stale provenance, not relabel
    # it as a plain same-round capture
    with open(path, "w") as f:
        f.write(json.dumps(got) + "\n")
    again = bench._reuse_round_record("probe hung again", root=root)
    ce = again["submetrics"]["captured_earlier"]
    assert ce["stale_round"] == 3 and "not a fresh measurement" in ce["note"]
    assert ce["file"].endswith("bench_r03_tpu.json")  # original provenance
    assert ce["live_probe"] == "probe hung again"


def test_reuse_round_record_env_override(tmp_path, monkeypatch):
    """DDIM_COLD_ROUND (exported by the recovery chain, which KNOWS its
    round) overrides the max(BENCH_r*)+1 inference (ADVICE r4: a bench
    re-run after the driver's same-round snapshot landed would otherwise
    infer one round too high and mislabel its own chain record stale)."""
    import os

    import bench

    root = str(tmp_path)
    os.makedirs(os.path.join(root, "results"))
    rec = {"metric": "train_throughput_vit_tiny64_b32", "value": 4089.0,
           "chip": "TPU v5 lite", "submetrics": {}}
    # driver snapshots through r05 exist (so inference would say round 6)…
    for n in (4, 5):
        with open(os.path.join(root, f"BENCH_r{n:02d}.json"), "w") as f:
            f.write("{}")
    with open(os.path.join(root, "results", "bench_r05_tpu.json"), "w") as f:
        f.write(json.dumps(rec) + "\n")
    # …without the override: conservative direction — r05's record is
    # treated as prior-round and labeled stale (never laundered, only
    # over-labeled)
    monkeypatch.delenv("DDIM_COLD_ROUND", raising=False)
    got = bench._reuse_round_record("probe hung", root=root)
    assert got["submetrics"]["captured_earlier"]["stale_round"] == 5
    # with the chain's override the same file is a same-round record: no
    # stale label
    monkeypatch.setenv("DDIM_COLD_ROUND", "5")
    got = bench._reuse_round_record("probe hung", root=root)
    assert got and got["value"] == 4089.0
    assert "stale_round" not in got["submetrics"]["captured_earlier"]
    # a STALER override (a round-5 chain constant leaking into a later
    # round's process tree) may correct inference by at most one round:
    # with r06's snapshot also present, "5" is two behind and is ignored
    with open(os.path.join(root, "BENCH_r06.json"), "w") as f:
        f.write("{}")
    got = bench._reuse_round_record("probe hung", root=root)
    assert got["submetrics"]["captured_earlier"]["stale_round"] == 5
    # degenerate "0" never disables reuse
    monkeypatch.setenv("DDIM_COLD_ROUND", "0")
    got = bench._reuse_round_record("probe hung", root=root)
    assert got is not None


def test_bench_e2e_section_runs_on_cpu():
    """The e2e section (H2D probe + grouped dispatch loop) must run end to
    end — it is only exercised on hardware otherwise, and a shape bug there
    would burn the round's chip window."""
    import argparse

    import jax
    import jax.numpy as jnp

    import bench
    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state

    model = DiffusionViT(dtype=jnp.bfloat16, **MODEL_CONFIGS["vit_tiny"])
    r = np.random.RandomState(0)
    batch = (jnp.asarray(r.randn(4, 64, 64, 3), jnp.float32),
             jnp.asarray(r.randn(4, 64, 64, 3), jnp.float32),
             jnp.asarray(r.randint(1, 7, size=(4,)), jnp.int32))
    state = create_train_state(model, jax.random.PRNGKey(0), lr=2e-4,
                               total_steps=100, sample_batch=batch)
    args = argparse.Namespace(smoke=True, batch=4)
    out = bench._bench_e2e(args, model, state, lambda m: None)
    assert out["h2d_bandwidth_mib_s"] > 0
    for label in ("cold", "warm"):
        row = out[f"e2e_train_throughput_{label}"]
        assert np.isfinite(row["value"]) and row["value"] > 0
        assert row["steps_per_dispatch"] == 1  # cpu backend: nothing to amortize

    # the grouped loop (the accelerator default, spd=8 on chip) must also
    # run before its first hardware execution — forced via the env override.
    # Fresh state: the first call's train steps DONATED the old one's buffers.
    import os

    state2 = create_train_state(model, jax.random.PRNGKey(0), lr=2e-4,
                                total_steps=100, sample_batch=batch)
    os.environ["DDIM_COLD_E2E_SPD"] = "2"
    try:
        out2 = bench._bench_e2e(args, model, state2, lambda m: None)
    finally:
        del os.environ["DDIM_COLD_E2E_SPD"]
    for label in ("cold", "warm"):
        row = out2[f"e2e_train_throughput_{label}"]
        assert np.isfinite(row["value"]) and row["value"] > 0
        assert row["steps_per_dispatch"] == 2


def test_bench_fatal_error_still_emits_partial_record():
    """An exception escaping the try body (here: a headline failure forced by
    an invalid batch) must emit the partial record with a fatal_error note
    and exit nonzero — never crash recordless."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--cpu", "--steps", "2",
         "--batch", "-1", "--skip-sampler"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode != 0
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "fatal_error" in rec["submetrics"], rec
    assert rec["metric"] == "train_throughput_vit_tiny64_b32"


def test_bench_fleet_smoke_record(capsys):
    """The --fleet leg: a 2-replica router serves the stream clean, then
    under the seeded chaos schedule that kills r0 and sprays transients —
    the record must show the fleet surviving (throughput, not outage), the
    replica replacement, and ZERO compiles after warmup including the
    replacement's service life."""
    import bench

    bench.main(["--smoke", "--cpu", "--steps", "3", "--batch", "4",
                "--skip-sampler", "--no-ksweep", "--fleet"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    fl = rec["submetrics"]["fleet"]
    assert fl["compiles_after_warmup"] == 0  # replacement included
    assert np.isfinite(fl["clean_img_per_sec"]) and fl["clean_img_per_sec"] > 0
    assert np.isfinite(fl["chaos_img_per_sec"]) and fl["chaos_img_per_sec"] > 0
    assert fl["survivors"] >= 1  # the kill degraded, never silenced, serving
    assert fl["survivors"] + fl["failed_tickets"] == len(fl["stream_sizes"])
    # r0's permanent kill fired, and the lifecycle ran: retire + respawn
    assert fl["injected"] >= 1 and "serve.dispatch" in fl["by_site"]
    assert fl["replicas_retired"] >= 1
    assert fl["replicas_spawned"] >= 3  # 2 initial + the replacement
