"""W8A16 trunk quantization tests (ops/quant.py + the vit/serve wiring).

The contract ladder, strictest first:
* codec round-trip error ≤ scale/2 per output channel (symmetric [−127, 127]
  codes — the −128 code must stay unused);
* ``quant=None`` is a BITWISE no-op — the quant field may not perturb the
  float path it gates;
* the w8a16 forward matches the float forward allclose at the documented
  tolerance (per-channel int8 on a trained-scale random-init trunk);
* the Pallas fused kernel agrees with the XLA dequant form (both accumulate
  f32 and apply scale in the epilogue);
* the step cache COMPOSES: a capture_split refresh over quantized params is
  bitwise the plain quantized forward — block-delta capture is a trunk
  structure hook, independent of how each dense computes;
* the serving engine serves a quant config bitwise-equal to the direct
  quantized sampler, ships int8 trunk buffers, and a warmed engine stays at
  ZERO compiles over mixed quant/non-quant request streams.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu import serve
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import quant, sampling

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
            num_heads=4, total_steps=2000)
K = 500  # 4 reverse steps (tests/test_serve.py's budget)

#: documented w8a16-vs-float forward tolerance on the 16×16 smoke model
#: (observed max |Δ| ≈ 8e-5; PERF.md "Quantization" quotes this bound)
W8A16_ATOL = 1e-3


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def quantized(model_and_params):
    model, params = model_and_params
    return model.clone(quant="xla"), quant.quantize_params(params)


def _xt():
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    return x, jnp.array([100, 100], jnp.int32)


# ------------------------------------------------------------------- codec

def test_roundtrip_error_within_half_scale():
    """Per-channel symmetric codec: |w − dequant(quant(w))| ≤ scale/2 for
    every entry (round-to-nearest with the max value mapping exactly to
    ±127), codes in [−127, 127] — −128 unused."""
    w = jax.random.normal(jax.random.PRNGKey(2), (64, 48)) * jnp.exp(
        jax.random.normal(jax.random.PRNGKey(3), (48,)))  # ragged col scales
    w_int8, scale = quant.quantize_weight(w)
    assert w_int8.dtype == jnp.int8 and scale.dtype == jnp.float32
    assert int(jnp.min(w_int8)) >= -127 and int(jnp.max(w_int8)) <= 127
    err = np.abs(np.asarray(w, np.float32)
                 - np.asarray(quant.dequantize_weight(w_int8, scale)))
    bound = np.asarray(scale) / 2 + 1e-7
    assert (err <= bound[None, :]).all(), float((err / bound).max())


def test_zero_column_and_calibrate(model_and_params):
    """All-zero output channels get scale 1.0 / zero codes (no 0/0), and
    calibrate's per-layer relative error stays ≤ 0.5 — the codec bound —
    for every trunk dense, keyed by addressable path."""
    w_int8, scale = quant.quantize_weight(jnp.zeros((8, 4)))
    np.testing.assert_array_equal(np.asarray(scale), np.ones(4, np.float32))
    np.testing.assert_array_equal(np.asarray(w_int8), np.zeros((8, 4)))

    _, params = model_and_params
    cal = quant.calibrate(params)
    # depth 2 × (qkv, proj, fc1, fc2) = 8 trunk denses
    assert len(cal) == 8
    assert "blocks_0/attn/qkv" in cal and "blocks_1/mlp/fc2" in cal
    for path, st in cal.items():
        assert st["max_err_over_scale"] <= 0.5 + 1e-6, (path, st)
        assert st["scale_min"] > 0


def test_quantize_params_topology_and_bytes(model_and_params):
    """The tree transform: trunk kernels become {w_int8, scale} IN PLACE
    (same module paths — sharding rules and engine param flow see the same
    structure), biases bitwise-untouched, patch_embed/head/embeds stay
    float, and the trunk itself ships ≈4× fewer bytes."""
    _, params = model_and_params
    qp = quant.quantize_params(params)
    assert not quant.is_quantized(params) and quant.is_quantized(qp)

    for b in ("blocks_0", "blocks_1"):
        for mod, leaves in (("attn", ("qkv", "proj")), ("mlp", ("fc1", "fc2"))):
            for leaf in leaves:
                d = qp[b][mod][leaf]
                assert "kernel" not in d
                assert d["w_int8"].dtype == jnp.int8
                assert d["scale"].dtype == jnp.float32
                assert d["scale"].shape == (d["w_int8"].shape[-1],)
                np.testing.assert_array_equal(
                    np.asarray(d["bias"]),
                    np.asarray(params[b][mod][leaf]["bias"]))
    # the OTHER "proj" — patch_embed's — must stay a float kernel
    assert "kernel" in qp["patch_embed"]["proj"]
    assert "w_int8" not in qp["patch_embed"]["proj"]
    jax.tree_util.tree_map(np.testing.assert_array_equal,
                           qp["head"], params["head"])

    def codec_bytes(tree, leaves):
        return sum(quant.param_bytes(tree[b][m][d][leaf])
                   for b in ("blocks_0", "blocks_1")
                   for m, ds in (("attn", ("qkv", "proj")),
                                 ("mlp", ("fc1", "fc2")))
                   for d in ds for leaf in leaves)

    # f32 kernel → int8 codes + one f32 scale per column: ≈4× on the codec
    # itself (biases are shared by both trees and excluded — at this toy
    # width they'd dilute the ratio, on the real 384-wide trunk they don't)
    ratio = (codec_bytes(params, ("kernel",))
             / codec_bytes(qp, ("w_int8", "scale")))
    assert 3.5 < ratio <= 4.0, ratio
    assert quant.param_bytes(qp) < quant.param_bytes(params)


# ----------------------------------------------------------------- matmuls

@pytest.mark.parametrize("shape", [(7, 33, 50), (16, 128, 256)])
def test_pallas_matches_xla(shape):
    """The fused kernel (padding paths included: odd M/K/N) reproduces the
    XLA dequant matmul to f32 round-off — either mode can stand in for the
    other."""
    M, Kd, N = shape
    x = jax.random.normal(jax.random.PRNGKey(4), (M, Kd))
    w_int8, scale = quant.quantize_weight(
        jax.random.normal(jax.random.PRNGKey(5), (Kd, N)))
    a = np.asarray(quant.dequant_matmul(x, w_int8, scale, mode="xla"))
    b = np.asarray(quant.dequant_matmul(x, w_int8, scale, mode="pallas"))
    assert a.dtype == b.dtype == np.float32
    np.testing.assert_allclose(b, a, rtol=1e-6, atol=1e-6)


def test_pallas_multichunk_k_accumulation():
    """K streamed through the VMEM accumulator in several chunks (the TPU
    schedule for real trunk shapes) must match a single-pass dot."""
    x = jax.random.normal(jax.random.PRNGKey(6), (16, 300))
    w_int8, scale = quant.quantize_weight(
        jax.random.normal(jax.random.PRNGKey(7), (300, 64)))
    got = np.asarray(quant._dequant_matmul_pallas(
        x, w_int8, scale, block_m=8, block_n=128, block_k=128))  # 3 k-chunks
    want = np.asarray(quant._dequant_matmul_xla(x, w_int8, scale))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_odd_requested_blocks_legalized_at_200px(monkeypatch):
    """Regression for the 200px tile-legality bug, quant edition: odd
    hand-tuned (block_m, block_n, block_k) used to reach the BlockSpecs via
    ``min(block, dim)`` — fine under CPU interpret, a Mosaic reject on chip.
    K is the hardest dim: it is the activation's LANE dim and the int8
    weight's SUBLANE dim (unit 32) at the same time. Shapes are the exact
    200px trunk matmuls: p8 tokens (626, 384) @ fc1, p4 tokens 2501."""
    from test_flash_attention import _tile_rule_spy

    calls = _tile_rule_spy(monkeypatch, quant)  # only uses the shared pl
    cases = [((626, 384, 1536), jnp.bfloat16, (100, 300, 100)),
             ((2501, 384, 384), jnp.float32, (300, 100, 384))]
    for (M, Kd, N), dtype, (bm, bn, bk) in cases:
        x = jax.random.normal(jax.random.PRNGKey(8), (M, Kd), dtype)
        w_int8, scale = quant.quantize_weight(
            jax.random.normal(jax.random.PRNGKey(9), (Kd, N)))
        got = np.asarray(quant._dequant_matmul_pallas(
            x, w_int8, scale, block_m=bm, block_n=bn, block_k=bk),
            np.float32)
        want = np.asarray(quant._dequant_matmul_xla(
            x.astype(jnp.float32), w_int8, scale))
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    assert len(calls) == len(cases), calls


def test_dequant_matmul_validation():
    x = jnp.zeros((2, 4))
    w_int8, scale = quant.quantize_weight(jnp.ones((4, 3)))
    with pytest.raises(ValueError, match="mode"):
        quant.dequant_matmul(x, w_int8, scale, mode="int4")
    with pytest.raises(ValueError, match="int8"):
        quant.dequant_matmul(x, jnp.ones((4, 3)), scale)


# ------------------------------------------------------------- model level

def test_quant_none_is_bitwise_noop(model_and_params):
    """The quant field gates, never perturbs: quant=None runs the identical
    float program."""
    model, params = model_and_params
    x, t = _xt()
    base = np.asarray(model.apply({"params": params}, x, t))
    routed = np.asarray(model.clone(quant=None).apply({"params": params}, x, t))
    np.testing.assert_array_equal(routed, base)


@pytest.mark.parametrize("mode", ["xla", "pallas"])
def test_w8a16_forward_close_to_float(model_and_params, mode):
    """The headline numerics contract: the quantized forward matches the
    float forward at the documented tolerance, for both matmul modes."""
    model, params = model_and_params
    x, t = _xt()
    want = np.asarray(model.apply({"params": params}, x, t))
    got = np.asarray(model.clone(quant=mode).apply(
        {"params": quant.quantize_params(params)}, x, t))
    np.testing.assert_allclose(got, want, atol=W8A16_ATOL, rtol=0)


def test_quant_model_validation(model_and_params):
    model, params = model_and_params
    x, t = _xt()
    with pytest.raises(ValueError, match="quant"):
        model.clone(quant="int4").apply({"params": params}, x, t)
    scan = DiffusionViT(scan_blocks=True, **TINY)
    sp = scan.init(jax.random.PRNGKey(0), x, t)["params"]
    with pytest.raises(ValueError, match="scan_blocks"):
        scan.clone(quant="xla").apply({"params": sp}, x, t)
    moe = DiffusionViT(num_experts=2, **TINY)
    mp = moe.init(jax.random.PRNGKey(0), x, t)["params"]
    with pytest.raises(ValueError, match="dense trunk"):
        moe.clone(quant="xla").apply({"params": mp}, x, t)


# ----------------------------------------------------- step-cache composition

def test_capture_split_refresh_is_bitwise_plain_quantized(quantized):
    """Composition with the step cache: a refresh forward (capture_split)
    over QUANTIZED params is bitwise the plain quantized forward — the
    delta-capture hook reads the token stream the w8a16 trunk already
    computed, exactly as on the float path."""
    qmodel, qparams = quantized
    x, t = _xt()
    plain = np.asarray(qmodel.apply({"params": qparams}, x, t))
    out, (d_front, d_rear) = qmodel.apply({"params": qparams}, x, t,
                                          capture_split=1)
    np.testing.assert_array_equal(np.asarray(out), plain)
    assert d_front.shape == d_rear.shape


def test_cached_quantized_sampler_paired_drift(model_and_params, quantized):
    """interval=2 full-mode quantized sampling stays paired-close to the
    exact float sampler (the composed shift the PERF.md table reports), and
    the composed path is deterministic."""
    model, params = model_and_params
    qmodel, qparams = quantized
    rng = jax.random.PRNGKey(8)
    exact = np.asarray(sampling.ddim_sample(model, params, rng, k=K, n=2))
    composed = np.asarray(sampling.ddim_sample(
        qmodel, qparams, rng, k=K, n=2, cache_interval=2, cache_mode="full"))
    assert np.isfinite(composed).all()
    assert np.abs(composed - exact).max() < 0.25
    again = np.asarray(sampling.ddim_sample(
        qmodel, qparams, rng, k=K, n=2, cache_interval=2, cache_mode="full"))
    np.testing.assert_array_equal(composed, again)


def test_quantized_sampler_guard_smoke(model_and_params):
    """The paired Fréchet guard runs end to end (proxy extractor) and its
    pixel delta obeys the sampler tolerance; composed cache_interval rides
    the same call."""
    from ddim_cold_tpu.eval import fid

    model, params = model_and_params
    rep = fid.quantized_sampler_guard(model, params,
                                      rng=jax.random.PRNGKey(9),
                                      n_samples=2, sample_batch=2, k=K)
    assert rep["quant_rev"] == quant.QUANT_REV
    assert np.isfinite(rep["fid_exact_vs_quant"])
    assert rep["max_abs_pixel_delta"] < 5e-3  # 4-step drift of an 8e-5 eps gap
    assert rep["calibration_worst_layer"] is not None


# ----------------------------------------------------------------- serving

@pytest.fixture(scope="module")
def warmed_quant(model_and_params):
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    cfg_f = serve.SamplerConfig(k=K)
    cfg_q = serve.SamplerConfig(k=K, quant="xla")
    report = serve.warmup(eng, [cfg_f, cfg_q], persistent_cache=False)
    assert report["new_compiles"] == 2  # one program per (config, bucket)
    return eng, cfg_f, cfg_q


def test_engine_quant_bitwise_vs_direct(model_and_params, quantized,
                                        warmed_quant):
    """Acceptance: the engine serves a quant config bitwise-equal to the
    direct quantized sampler, ships int8 trunk buffers (device dtype, not a
    dequantized copy), and reports the ≈4×-smaller param-byte footprint."""
    qmodel, qparams = quantized
    eng, _, cfg_q = warmed_quant
    compiles = eng.stats["compiles"]
    t = eng.submit(seed=101, n=3, config=cfg_q)
    eng.run()
    assert eng.stats["compiles"] == compiles
    want = np.asarray(sampling.ddim_sample(
        qmodel, qparams, jax.random.PRNGKey(101), k=K, n=3))
    np.testing.assert_array_equal(t.result(timeout=5), want)
    # the engine's own tree carries int8 leaves — H2D shipped int8, once
    assert eng._qparams["blocks_0"]["attn"]["qkv"]["w_int8"].dtype == jnp.int8
    assert eng.stats["param_bytes_quant"] < eng.stats["param_bytes"]


def test_zero_compiles_mixed_quant_streams(model_and_params, warmed_quant):
    """After warmup over BOTH configs, interleaved quant and float requests
    at many sizes — across several drains — trigger zero program builds, and
    the two streams never coalesce into one batch."""
    from ddim_cold_tpu.serve.batching import Request, plan_batches

    eng, cfg_f, cfg_q = warmed_quant
    compiles = eng.stats["compiles"]
    for sizes in ([1, 2], [3, 4], [2, 1, 3]):
        tickets = [eng.submit(seed=110 + n, n=n,
                              config=(cfg_q if i % 2 else cfg_f))
                   for i, n in enumerate(sizes)]
        eng.run()
        for t in tickets:
            assert t.done
    assert eng.stats["compiles"] == compiles

    plans = plan_batches([Request(config=cfg_f, n=2),
                          Request(config=cfg_q, n=2)], (4,))
    assert len(plans) == 2  # quant and float programs differ — no sharing
