"""Device-side corruption data path (ops/degrade.make_cold_prepare +
ShardedLoader raw mode + train/step prepare hook + device_prefetch).

The host ships ``(base, t)`` and the jitted step rebuilds the reference
contract ``(D(x,t), target, t)`` on device; these tests pin that the rebuilt
batch is bit-identical to the host/C++ pipeline (diffusion_loader.py:79-97
semantics) and that the trainer trains the same under either path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddim_cold_tpu.data import ColdDownSampleDataset, DiffusionDataset, ShardedLoader
from ddim_cold_tpu.data.loader import device_prefetch
from ddim_cold_tpu.ops import degrade


@pytest.fixture(scope="module", params=["chain", "direct"])
def cold_sets(request, synthetic_image_dir):
    """(host-path dataset, raw-path dataset) over the same files/seed."""
    mk = lambda: ColdDownSampleDataset(  # noqa: E731
        synthetic_image_dir, imgSize=(64, 64), target_mode=request.param)
    return mk(), mk(), request.param


def test_raw_batch_contract(cold_sets):
    host_ds, raw_ds, _ = cold_sets
    idxs = np.arange(8)
    base, ts = raw_ds.get_raw_batch(idxs, num_threads=2)
    assert base.shape == (8, 64, 64, 3) and base.dtype == np.float32
    assert ts.shape == (8,) and ts.dtype == np.int32
    assert (1 <= ts).all() and (ts <= host_ds.max_step).all()
    # same per-(seed, epoch, index) t stream as the host path
    _, _, host_ts = host_ds.get_batch(idxs, num_threads=2)
    np.testing.assert_array_equal(ts, host_ts)
    # bases are the clean decoded images
    np.testing.assert_array_equal(base[3], raw_ds._base(3))


def test_prepare_rebuilds_host_batch_bitexact(cold_sets):
    host_ds, raw_ds, mode = cold_sets
    idxs = np.arange(10)
    noisy, target, ts = host_ds.get_batch(idxs, num_threads=2)
    base, raw_ts = raw_ds.get_raw_batch(idxs, num_threads=2)
    prepare = degrade.make_cold_prepare(
        size=64, max_step=host_ds.max_step, chain=(mode == "chain"))
    d_noisy, d_target, d_ts = prepare(
        (jnp.asarray(base), jnp.asarray(raw_ts)), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(d_noisy), noisy)
    np.testing.assert_array_equal(np.asarray(d_target), target)
    np.testing.assert_array_equal(np.asarray(d_ts), ts)


def test_cold_prepare_pins_batch_sharding_under_mesh():
    """Under a dp×tp×sp mesh the degrade gathers must stay batch-sharded —
    left to the partitioner they can land W-sharded and trigger XLA's
    "Involuntary full rematerialization" replicate-all fallback on the
    reshard into the attention layout (MULTICHIP_r02 tail)."""
    from ddim_cold_tpu.parallel import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 2, "model": 2, "seq": 2})
    prepare = degrade.make_cold_prepare(size=16, max_step=4, chain=True,
                                        mesh=mesh)
    base = jnp.zeros((8, 16, 16, 3), jnp.uint8)
    t = jnp.ones((8,), jnp.int32)
    noisy, target, _ = jax.jit(
        lambda b: prepare(b, jax.random.PRNGKey(0)))((base, t))
    for arr in (noisy, target):
        spec = arr.sharding.spec
        assert spec and spec[0] == "data", spec
        assert all(s is None for s in spec[1:]), spec


def test_uint8_base_normalizes_bitexact(rng):
    """uint8-shipped bases must normalize to the exact host float pipeline
    (÷255 then ·2−1, datasets._load_base order)."""
    u8 = rng.randint(0, 256, size=(4, 16, 16, 3)).astype(np.uint8)
    want = (u8.astype(np.float32) / 255.0) * 2.0 - 1.0
    got = np.asarray(degrade.normalize_base(jnp.asarray(u8)))
    np.testing.assert_array_equal(got, want)
    # float input passes through untouched
    f = want[:2]
    np.testing.assert_array_equal(np.asarray(degrade.normalize_base(jnp.asarray(f))), f)


@pytest.fixture(scope="module")
def exact_size_image_dir(tmp_path_factory):
    """jpgs whose native size IS the dataset img_size (64×64) — the uint8
    ship-raw-bytes fast path (no resize anywhere)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("exact64_jpgs")
    rs = np.random.RandomState(7)
    for i in range(8):
        arr = rs.randint(0, 255, size=(64, 64, 3), dtype=np.uint8)
        Image.fromarray(arr).save(root / f"{i}.jpg")
    return str(root)


def test_raw_batch_ships_uint8_when_exact_size(exact_size_image_dir):
    """Identity-resize datasets ship raw uint8 bytes (4× less transfer), and
    the in-jit normalize+degrade rebuilds the host batch bit-exactly."""
    mk = lambda: ColdDownSampleDataset(  # noqa: E731
        exact_size_image_dir, imgSize=(64, 64), target_mode="chain")
    raw_ds, host_ds = mk(), mk()
    idxs = np.arange(8)
    base, ts = raw_ds.get_raw_batch(idxs, num_threads=2)
    assert base.dtype == np.uint8, "exact-size files must ship as uint8"
    noisy, target, host_ts = host_ds.get_batch(idxs, num_threads=2)
    np.testing.assert_array_equal(ts, host_ts)
    prepare = degrade.make_cold_prepare(size=64, max_step=6, chain=True)
    d_noisy, d_target, _ = prepare(
        (jnp.asarray(base), jnp.asarray(ts)), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(d_noisy), noisy)
    np.testing.assert_array_equal(np.asarray(d_target), target)
    # the float32 view through the same cache matches the PIL pipeline
    from ddim_cold_tpu.data.datasets import _load_base
    import os

    want = _load_base(os.path.join(exact_size_image_dir,
                                   sorted(os.listdir(exact_size_image_dir))[0]),
                      (64, 64), use_native=False)
    np.testing.assert_array_equal(raw_ds._base(0), want)


def test_raw_dtype_stable_for_mixed_size_dataset(tmp_path):
    """One off-size file pins the WHOLE dataset to float32 — batch dtype must
    not flip with batch composition (jit retraces; multi-host SPMD hosts must
    agree on the global array dtype)."""
    from PIL import Image

    rs = np.random.RandomState(3)
    for i in range(6):
        Image.fromarray(rs.randint(0, 255, (64, 64, 3), np.uint8)).save(
            tmp_path / f"exact_{i}.jpg")
    Image.fromarray(rs.randint(0, 255, (65, 64, 3), np.uint8)).save(
        tmp_path / "odd.jpg")
    ds = ColdDownSampleDataset(str(tmp_path), imgSize=(64, 64))
    assert not ds._uniform_u8
    # a batch containing ONLY exact-size files still ships float32
    base, _ = ds.get_raw_batch([0, 1, 2], num_threads=1)
    assert base.dtype == np.float32


def test_raw_dtype_drift_raises_not_silent_flip(tmp_path):
    """A file mutated on disk AFTER the header probe pinned the dataset uint8
    must raise, not silently ship a float32 batch (jit retrace; multi-host
    global-dtype divergence)."""
    from PIL import Image

    from ddim_cold_tpu.data import native

    if not native.available():
        pytest.skip("uint8 pinning requires the native decoder")
    rs = np.random.RandomState(5)
    for i in range(4):
        Image.fromarray(rs.randint(0, 255, (64, 64, 3), np.uint8)).save(
            tmp_path / f"img_{i}.jpg")
    ds = ColdDownSampleDataset(str(tmp_path), imgSize=(64, 64),
                               target_mode="chain")
    assert ds._uniform_u8
    Image.fromarray(rs.randint(0, 255, (80, 80, 3), np.uint8)).save(
        tmp_path / "img_1.jpg")  # now needs a resize → float32 decode path
    with pytest.raises(RuntimeError, match="pinned uint8"):
        ds.get_raw_batch([0, 1, 2], num_threads=1)


def test_native_decode_batch_parity(exact_size_image_dir):
    """Raw C++ u8 decode == PIL bytes; size-mismatched files flag failed."""
    import os

    from PIL import Image

    from ddim_cold_tpu.data import native

    if not native.available():
        pytest.skip("native library unavailable")
    paths = [os.path.join(exact_size_image_dir, n)
             for n in sorted(os.listdir(exact_size_image_dir))]
    res = native.decode_batch(paths, (64, 64), num_threads=2)
    assert res is not None
    u8, failed = res
    assert not failed.any()
    for j, p in enumerate(paths[:3]):
        np.testing.assert_array_equal(u8[j], np.asarray(Image.open(p).convert("RGB")))
    # wrong expected size → failed mask, no crash
    res = native.decode_batch(paths[:2], (32, 32), num_threads=1)
    assert res is not None and res[1].all()


def test_loader_raw_mode_yields_pairs(cold_sets):
    _, raw_ds, _ = cold_sets
    loader = ShardedLoader(raw_ds, 4, shuffle=False, drop_last=True, raw=True)
    batches = list(loader)
    assert len(batches) == len(raw_ds) // 4
    for base, ts in batches:
        assert base.shape == (4, 64, 64, 3) and ts.shape == (4,)


def test_gaussian_raw_batch_and_prepare(synthetic_image_dir):
    """Gaussian raw path: same t stream as the host pipeline, clean x₀ bases,
    and the in-jit forward noising implements √ᾱ·x₀ + √(1−ᾱ)·ε with
    device-drawn unit-normal ε (deterministic per rng)."""
    ds = DiffusionDataset(synthetic_image_dir, imgSize=(32, 32), max_step=2000)
    idxs = np.arange(10)
    base, ts = ds.get_raw_batch(idxs, num_threads=2)
    noisy_h, x0_h, ts_h = ds.get_batch(idxs, num_threads=2)
    np.testing.assert_array_equal(ts, ts_h)
    np.testing.assert_array_equal(base, x0_h)

    prepare = degrade.make_gaussian_prepare(2000)
    rng = jax.random.PRNGKey(5)
    noisy, target, t_out = prepare((jnp.asarray(base), jnp.asarray(ts)), rng)
    np.testing.assert_array_equal(np.asarray(target), base)
    np.testing.assert_array_equal(np.asarray(t_out), ts)
    # recover ε and check it is the exact device-normal draw
    alpha = 1.0 - np.sqrt((ts.astype(np.float32) + 1.0) / 2000.0)
    alpha = alpha[:, None, None, None]
    eps = (np.asarray(noisy) - np.sqrt(alpha) * base) / np.sqrt(1.0 - alpha)
    want_eps = np.asarray(jax.random.normal(rng, base.shape, jnp.float32))
    np.testing.assert_allclose(eps, want_eps, atol=1e-4)
    # deterministic: same rng → same batch
    noisy2, _, _ = prepare((jnp.asarray(base), jnp.asarray(ts)), rng)
    np.testing.assert_array_equal(np.asarray(noisy), np.asarray(noisy2))


@pytest.mark.isolated
def test_trainer_gaussian_device_path_smoke(tmp_path, synthetic_image_dir):
    """Gaussian + device_degrade trains (device-noised train loader) while
    the val loader stays on the deterministic host path."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    cfg = ExperimentConfig(
        exp_name="g", framework="dd", batch_size=4, epoch=(0, 1),
        base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
        image_size=(32, 32), patch_size=8, embed_dim=32, depth=2, head=2,
        num_devices=1, dataset="gaussian", device_degrade=True,
    )
    result = run(cfg, str(tmp_path), max_steps=3)
    assert np.isfinite(result.best_loss)


def test_loader_raw_requires_capable_dataset(synthetic_image_dir):
    class NoRaw:
        def __len__(self):
            return 4

    with pytest.raises(ValueError, match="get_raw_batch"):
        ShardedLoader(NoRaw(), 4, shuffle=False, raw=True)


def test_train_step_equivalent_under_device_degrade(cold_sets):
    """One optimizer step from identical inits must produce the same loss and
    (numerically) the same params whether corruption ran on host or device."""
    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    host_ds, raw_ds, mode = cold_sets
    model = DiffusionViT(img_size=(64, 64), patch_size=8, embed_dim=32,
                         depth=2, num_heads=2)
    idxs = np.arange(8)
    host_batch = tuple(map(jnp.asarray, host_ds.get_batch(idxs, num_threads=2)))
    raw_batch = tuple(map(jnp.asarray, raw_ds.get_raw_batch(idxs, num_threads=2)))
    prepare = degrade.make_cold_prepare(
        size=64, max_step=host_ds.max_step, chain=(mode == "chain"))

    def one_step(step_fn, batch):
        state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                                   total_steps=100, sample_batch=host_batch)
        state, loss, _ = step_fn(state, batch, jax.random.PRNGKey(7),
                                 jnp.float32(5.0))
        return state, float(loss)

    s_host, l_host = one_step(make_train_step(model), host_batch)
    s_dev, l_dev = one_step(make_train_step(model, prepare=prepare), raw_batch)
    np.testing.assert_allclose(l_dev, l_host, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s_host.params), jax.tree.leaves(s_dev.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_device_prefetch_order_and_abandon():
    placed = []

    def place(x):
        placed.append(x)
        return x * 10

    out = list(device_prefetch(range(6), place, depth=2))
    assert out == [0, 10, 20, 30, 40, 50]

    # abandoning the generator stops the producer promptly
    gen = device_prefetch(range(1000), place, depth=2)
    assert next(gen) == 0
    gen.close()
    assert len(placed) < 6 + 20  # bounded work after close


def test_device_prefetch_propagates_errors():
    def place(x):
        if x == 3:
            raise RuntimeError("boom")
        return x

    gen = device_prefetch(range(6), place, depth=2)
    got = [next(gen), next(gen), next(gen)]
    assert got == [0, 1, 2]
    with pytest.raises(RuntimeError, match="boom"):
        list(gen)


@pytest.mark.isolated
def test_trainer_device_path_matches_host_path(tmp_path, synthetic_image_dir):
    """Two 3-step trainer runs — host corruption vs device corruption — land
    on the same loss trajectory, and the async saver leaves both checkpoints."""
    import os

    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    def go(tag, device_degrade):
        cfg = ExperimentConfig(
            exp_name=tag, framework="dd", batch_size=4, epoch=(0, 1),
            base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
            image_size=(32, 32), patch_size=8, embed_dim=32, depth=2, head=2,
            num_devices=1, device_degrade=device_degrade,
        )
        return run(cfg, str(tmp_path / tag), max_steps=3)

    r_host = go("host", False)
    r_dev = go("dev", True)
    np.testing.assert_allclose(r_dev.last_val_loss, r_host.last_val_loss, rtol=1e-5)
    np.testing.assert_allclose(r_dev.best_loss, r_host.best_loss, rtol=1e-5)
    for name in ("bestloss.ckpt", "lastepoch.ckpt"):
        assert os.path.isdir(os.path.join(r_dev.run_dir, name)), name
