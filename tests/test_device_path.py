"""Device-side corruption data path (ops/degrade.make_cold_prepare +
ShardedLoader raw mode + train/step prepare hook + device_prefetch).

The host ships ``(base, t)`` and the jitted step rebuilds the reference
contract ``(D(x,t), target, t)`` on device; these tests pin that the rebuilt
batch is bit-identical to the host/C++ pipeline (diffusion_loader.py:79-97
semantics) and that the trainer trains the same under either path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddim_cold_tpu.data import ColdDownSampleDataset, DiffusionDataset, ShardedLoader
from ddim_cold_tpu.data.loader import device_prefetch
from ddim_cold_tpu.ops import degrade


@pytest.fixture(scope="module", params=["chain", "direct"])
def cold_sets(request, synthetic_image_dir):
    """(host-path dataset, raw-path dataset) over the same files/seed."""
    mk = lambda: ColdDownSampleDataset(  # noqa: E731
        synthetic_image_dir, imgSize=(64, 64), target_mode=request.param)
    return mk(), mk(), request.param


def test_raw_batch_contract(cold_sets):
    host_ds, raw_ds, _ = cold_sets
    idxs = np.arange(8)
    base, ts = raw_ds.get_raw_batch(idxs, num_threads=2)
    assert base.shape == (8, 64, 64, 3) and base.dtype == np.float32
    assert ts.shape == (8,) and ts.dtype == np.int32
    assert (1 <= ts).all() and (ts <= host_ds.max_step).all()
    # same per-(seed, epoch, index) t stream as the host path
    _, _, host_ts = host_ds.get_batch(idxs, num_threads=2)
    np.testing.assert_array_equal(ts, host_ts)
    # bases are the clean decoded images
    np.testing.assert_array_equal(base[3], raw_ds._base(3))


def test_prepare_rebuilds_host_batch_bitexact(cold_sets):
    host_ds, raw_ds, mode = cold_sets
    idxs = np.arange(10)
    noisy, target, ts = host_ds.get_batch(idxs, num_threads=2)
    base, raw_ts = raw_ds.get_raw_batch(idxs, num_threads=2)
    prepare = degrade.make_cold_prepare(
        size=64, max_step=host_ds.max_step, chain=(mode == "chain"))
    d_noisy, d_target, d_ts = prepare(
        (jnp.asarray(base), jnp.asarray(raw_ts)), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(d_noisy), noisy)
    np.testing.assert_array_equal(np.asarray(d_target), target)
    np.testing.assert_array_equal(np.asarray(d_ts), ts)


def test_uint8_base_normalizes_bitexact(rng):
    """uint8-shipped bases must normalize to the exact host float pipeline
    (÷255 then ·2−1, datasets._load_base order)."""
    u8 = rng.randint(0, 256, size=(4, 16, 16, 3)).astype(np.uint8)
    want = (u8.astype(np.float32) / 255.0) * 2.0 - 1.0
    got = np.asarray(degrade.normalize_base(jnp.asarray(u8)))
    np.testing.assert_array_equal(got, want)
    # float input passes through untouched
    f = want[:2]
    np.testing.assert_array_equal(np.asarray(degrade.normalize_base(jnp.asarray(f))), f)


def test_loader_raw_mode_yields_pairs(cold_sets):
    _, raw_ds, _ = cold_sets
    loader = ShardedLoader(raw_ds, 4, shuffle=False, drop_last=True, raw=True)
    batches = list(loader)
    assert len(batches) == len(raw_ds) // 4
    for base, ts in batches:
        assert base.shape == (4, 64, 64, 3) and ts.shape == (4,)


def test_loader_raw_requires_capable_dataset(synthetic_image_dir):
    gauss = DiffusionDataset(synthetic_image_dir, imgSize=(32, 32))
    with pytest.raises(ValueError, match="get_raw_batch"):
        ShardedLoader(gauss, 4, shuffle=False, raw=True)


def test_train_step_equivalent_under_device_degrade(cold_sets):
    """One optimizer step from identical inits must produce the same loss and
    (numerically) the same params whether corruption ran on host or device."""
    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    host_ds, raw_ds, mode = cold_sets
    model = DiffusionViT(img_size=(64, 64), patch_size=8, embed_dim=32,
                         depth=2, num_heads=2)
    idxs = np.arange(8)
    host_batch = tuple(map(jnp.asarray, host_ds.get_batch(idxs, num_threads=2)))
    raw_batch = tuple(map(jnp.asarray, raw_ds.get_raw_batch(idxs, num_threads=2)))
    prepare = degrade.make_cold_prepare(
        size=64, max_step=host_ds.max_step, chain=(mode == "chain"))

    def one_step(step_fn, batch):
        state = create_train_state(model, jax.random.PRNGKey(0), lr=1e-3,
                                   total_steps=100, sample_batch=host_batch)
        state, loss, _ = step_fn(state, batch, jax.random.PRNGKey(7),
                                 jnp.float32(5.0))
        return state, float(loss)

    s_host, l_host = one_step(make_train_step(model), host_batch)
    s_dev, l_dev = one_step(make_train_step(model, prepare=prepare), raw_batch)
    np.testing.assert_allclose(l_dev, l_host, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s_host.params), jax.tree.leaves(s_dev.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_device_prefetch_order_and_abandon():
    placed = []

    def place(x):
        placed.append(x)
        return x * 10

    out = list(device_prefetch(range(6), place, depth=2))
    assert out == [0, 10, 20, 30, 40, 50]

    # abandoning the generator stops the producer promptly
    gen = device_prefetch(range(1000), place, depth=2)
    assert next(gen) == 0
    gen.close()
    assert len(placed) < 6 + 20  # bounded work after close


def test_device_prefetch_propagates_errors():
    def place(x):
        if x == 3:
            raise RuntimeError("boom")
        return x

    gen = device_prefetch(range(6), place, depth=2)
    got = [next(gen), next(gen), next(gen)]
    assert got == [0, 1, 2]
    with pytest.raises(RuntimeError, match="boom"):
        list(gen)


def test_trainer_device_path_matches_host_path(tmp_path, synthetic_image_dir):
    """Two 3-step trainer runs — host corruption vs device corruption — land
    on the same loss trajectory, and the async saver leaves both checkpoints."""
    import os

    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    def go(tag, device_degrade):
        cfg = ExperimentConfig(
            exp_name=tag, framework="dd", batch_size=4, epoch=(0, 1),
            base_lr=0.005, data_storage=(synthetic_image_dir, synthetic_image_dir),
            image_size=(32, 32), patch_size=8, embed_dim=32, depth=2, head=2,
            num_devices=1, device_degrade=device_degrade,
        )
        return run(cfg, str(tmp_path / tag), max_steps=3)

    r_host = go("host", False)
    r_dev = go("dev", True)
    np.testing.assert_allclose(r_dev.last_val_loss, r_host.last_val_loss, rtol=1e-5)
    np.testing.assert_allclose(r_dev.best_loss, r_host.best_loss, rtol=1e-5)
    for name in ("bestloss.ckpt", "lastepoch.ckpt"):
        assert os.path.isdir(os.path.join(r_dev.run_dir, name)), name
