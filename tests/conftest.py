"""Test harness: 8 virtual CPU devices (SURVEY.md §4 'distributed without a cluster').

Must set XLA flags before jax is imported anywhere; pytest loads conftest
before collecting test modules, so this is the single chokepoint.
"""

import os

# NOTE: this environment pre-sets JAX_PLATFORMS=axon (TPU tunnel) and the
# config survives env-var overrides — the jax.config.update below is the one
# that actually forces CPU. The XLA flag must still be set pre-import to get
# the 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Parity tests compare against float32 torch/numpy oracles; this JAX build's
# default matmul precision is reduced (the TPU-friendly default the framework
# keeps for training/bench), so pin full f32 dots for the test suite.
jax.config.update("jax_default_matmul_precision", "float32")

# The suite's wall time is dominated by ~30 jit compiles of tiny models; a
# persistent compilation cache makes re-runs (the common local case) start
# nearly compile-free. Fresh clones still pay the first-compile cost once.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import re  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# In-process `trainer.run` exercises the full composite (pjit train steps +
# loader threads + logging + checkpoint I/O) inside the pytest interpreter.
# On some hosts that composite flakily corrupts the native heap and takes the
# whole pytest process down with SIGSEGV/SIGABRT, losing every result after
# it. Tests marked `isolated` therefore run in a fresh subprocess: a native
# crash becomes an ordinary test failure and the rest of the suite survives.
# The same corruption occasionally DEADLOCKS the child instead of crashing
# it; the subprocess timeout below exists to turn that wedge into the same
# ordinary failure before it eats the tier-1 wall budget (ROADMAP's 870 s
# outer timeout), so it must stay well under budget/2.
_ISOLATED_CHILD_ENV = "DDIM_COLD_TPU_ISOLATED_CHILD"
_ISOLATED_TIMEOUT_S = float(os.environ.get("DDIM_COLD_ISOLATED_TIMEOUT_S", "150"))
# Suite-wide cap on signal-death retries. A single flaky crash gets its one
# retry; a host where the native crash is DETERMINISTIC (dozens of isolated
# tests die every run) must not pay 2× child runtime per crash — that alone
# can blow the 870 s tier-1 budget. Once the budget is spent, further signal
# deaths fail immediately, exactly as before the retry existed.
_retry_budget = int(os.environ.get("DDIM_COLD_ISOLATED_RETRIES", "3"))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "isolated: run this test in a fresh pytest subprocess so a native "
        "crash in the in-process trainer cannot kill the whole suite",
    )
    config.addinivalue_line("markers", "slow: long-running test (tier-2)")


def pytest_runtest_protocol(item, nextitem):
    if item.get_closest_marker("isolated") is None:
        return None
    if os.environ.get(_ISOLATED_CHILD_ENV):
        return None  # already inside the child; run normally
    hook = item.ihook
    hook.pytest_runtest_logstart(nodeid=item.nodeid, location=item.location)
    start = time.time()
    env = dict(os.environ, **{_ISOLATED_CHILD_ENV: "1"})
    cmd = [sys.executable, "-m", "pytest", "-q", "-x",
           "-p", "no:cacheprovider", item.nodeid]

    def attempt():
        """Run the child once → (returncode, output, timed_out)."""
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, env=env,
                cwd=str(item.config.rootpath), timeout=_ISOLATED_TIMEOUT_S,
            )
            return proc.returncode, (proc.stdout or "") + (proc.stderr or ""), False
        except subprocess.TimeoutExpired as exc:
            out = ((exc.stdout or b"").decode(errors="replace")
                   + f"\nisolated subprocess timed out after {_ISOLATED_TIMEOUT_S:g}s")
            return -1, out, True

    rc, out, timed_out = attempt()
    flaky_note = None
    global _retry_budget
    if rc < 0 and not timed_out and _retry_budget > 0:
        # The documented flaky-host class: the child was KILLED BY A SIGNAL
        # (SIGSEGV/SIGABRT from the native-heap corruption this runner exists
        # to contain). Retry exactly once — a real regression that crashes
        # deterministically crashes the retry too and still fails; ordinary
        # assertion failures (rc > 0) and deadlocks (the timeout path) are
        # never retried, so nothing real is masked.
        _retry_budget -= 1
        flaky_note = (f"first attempt died with signal {-rc}; "
                      "retried once (flaky-host native-crash class)")
        rc, out, timed_out = attempt()
    duration = time.time() - start
    if rc == 0 and re.search(r"\b1 skipped\b", out) and not re.search(r"\b1 passed\b", out):
        outcome = "skipped"
        longrepr = (str(item.path), item.location[1] or 0,
                    "skipped inside isolated subprocess")
    elif rc == 0:
        outcome, longrepr = "passed", None
    else:
        outcome = "failed"
        tail = "\n".join(out.splitlines()[-40:])
        why = (f"isolated subprocess died with signal {-rc}" if rc < 0
               else f"isolated subprocess exited with code {rc}")
        if flaky_note:
            why = f"{flaky_note}; retry then {why}"
        longrepr = f"{why}\n{tail}"
    keywords = {item.name: 1}
    sections = []
    if flaky_note:
        keywords["flaky-retry"] = 1
        sections.append(("flaky-retry", flaky_note))
    report = pytest.TestReport(
        nodeid=item.nodeid, location=item.location,
        keywords=keywords, outcome=outcome, longrepr=longrepr,
        when="call", sections=sections, duration=duration,
        start=start, stop=start + duration,
    )
    hook.pytest_runtest_logreport(report=report)
    # The in-process setup/teardown cycle was skipped, but earlier items'
    # module/class finalizers are still parked on the SetupState stack waiting
    # for "the next item" to tear them down. Pop everything nextitem doesn't
    # need, or the next in-process test errors at setup with "previous item
    # was not torn down properly".
    item.session._setupstate.teardown_exact(nextitem)
    hook.pytest_runtest_logfinish(nodeid=item.nodeid, location=item.location)
    return True


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(0)


@pytest.fixture(scope="session")
def kernel_traces():
    """The 200px kernel-entry traces (graftcheck's kernels/memory layers),
    built once per session — test_kernel_checks and test_memory_checks
    both walk them, and the abstract trace is the expensive part."""
    from ddim_cold_tpu.analysis import entries

    return entries.kernel_traces()


@pytest.fixture(scope="session")
def synthetic_image_dir(tmp_path_factory):
    """A 10-image jpg folder (the integration-test dataset, SURVEY.md §4)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("synthetic_jpgs")
    rs = np.random.RandomState(42)
    for i in range(10):
        arr = rs.randint(0, 255, size=(96, 80, 3), dtype=np.uint8)
        Image.fromarray(arr).save(root / f"{i}.jpg")
    return str(root)
