"""Test harness: 8 virtual CPU devices (SURVEY.md §4 'distributed without a cluster').

Must set XLA flags before jax is imported anywhere; pytest loads conftest
before collecting test modules, so this is the single chokepoint.
"""

import os

# NOTE: this environment pre-sets JAX_PLATFORMS=axon (TPU tunnel) and the
# config survives env-var overrides — the jax.config.update below is the one
# that actually forces CPU. The XLA flag must still be set pre-import to get
# the 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Parity tests compare against float32 torch/numpy oracles; this JAX build's
# default matmul precision is reduced (the TPU-friendly default the framework
# keeps for training/bench), so pin full f32 dots for the test suite.
jax.config.update("jax_default_matmul_precision", "float32")

# The suite's wall time is dominated by ~30 jit compiles of tiny models; a
# persistent compilation cache makes re-runs (the common local case) start
# nearly compile-free. Fresh clones still pay the first-compile cost once.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(0)


@pytest.fixture(scope="session")
def synthetic_image_dir(tmp_path_factory):
    """A 10-image jpg folder (the integration-test dataset, SURVEY.md §4)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("synthetic_jpgs")
    rs = np.random.RandomState(42)
    for i in range(10):
        arr = rs.randint(0, 255, size=(96, 80, 3), dtype=np.uint8)
        Image.fromarray(arr).save(root / f"{i}.jpg")
    return str(root)
