"""Few-step distilled sampling tests (the k∈{1,2,4} serving family).

Covers the full stack the feature spans: the fewstep scan's BITWISE
contract against manually-indexed per-step DDIM updates (same traced
arithmetic, no scan), the progressive-distillation loop (loss decreases,
checkpoint/resume round-trip restores finished students bit-for-bit), the
engine's first-class ``SamplerConfig(steps=k)`` programs (bitwise vs the
direct sampler at two buckets, step-cache and w8a16 composition, student
param routing), warmup fingerprint dedup (a student config aliases the
teacher's executable instead of compiling), config validation at both
layers, and the graftcheck J006 sweep registration.

The bitwise reference deliberately runs ONE JITTED HELPER PER STEP with the
schedule coefficients passed as TRACED scalars: that reproduces the scan
body's exact fma contraction points. An eager python loop (or a fully
unrolled jit with the coefficients baked as constants) differs by ~1 ulp at
steps=4 — constant folding changes the contraction order — and would turn
this into a flaky allclose test.
"""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from ddim_cold_tpu import serve
from ddim_cold_tpu.analysis import entries
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.ops import quant as quant_mod
from ddim_cold_tpu.ops import sampling, schedule
from ddim_cold_tpu.train import distill

TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
            num_heads=4, total_steps=2000)


@pytest.fixture(scope="module")
def model_and_params():
    model = DiffusionViT(**TINY)
    x = jnp.zeros((2, 16, 16, 3))
    params = model.init(jax.random.PRNGKey(0), x,
                        jnp.array([0, 1], jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def student_params(model_and_params):
    """A 'student' tree distinguishable from the teacher — the routing
    tests need outputs that differ, not a real distilled checkpoint."""
    _, params = model_and_params
    return jax.tree.map(lambda a: a + 1e-3, params)


@pytest.fixture(scope="module")
def warmed(model_and_params, student_params):
    """One engine with every plain few-step program warmed at two buckets,
    shared by the bitwise/routing tests (AOT compiles dominate runtime)."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4, 8),
                       student_params=student_params)
    cfgs = [serve.SamplerConfig(steps=s) for s in (1, 2, 4)]
    report = serve.warmup(eng, cfgs, persistent_cache=False)
    assert report["new_compiles"] == 6  # 3 step counts x 2 buckets
    return eng


def _direct_fewstep(model, params, seed, steps, n, **kw):
    return np.asarray(sampling.ddim_sample_fewstep(
        model, params, jax.random.PRNGKey(seed), steps=steps, n=n, **kw))


# ------------------------------------------------------------ scan bitwise


@pytest.mark.parametrize("steps", [1, 2, 4])
def test_fewstep_scan_bitwise_vs_manual_steps(model_and_params, steps):
    """The compiled scan program equals steps-many manually-indexed DDIM
    updates (final jump-to-clean hoisted as a bare forward), bit for bit."""
    model, params = model_and_params
    n = 2
    coeffs = schedule.fewstep_coefficients(model.total_steps, steps)

    @jax.jit
    def one_update(p, x, t, c1, c2):
        x0 = jnp.clip(model.apply({"params": p}, x,
                                  jnp.full((x.shape[0],), t, jnp.int32)),
                      -1.0, 1.0)
        return c1 * x + c2 * x0

    @jax.jit
    def final_forward(p, x, t):
        x0 = jnp.clip(model.apply({"params": p}, x,
                                  jnp.full((x.shape[0],), t, jnp.int32)),
                      -1.0, 1.0)
        return (x0 + 1.0) / 2.0

    rng = jax.random.PRNGKey(7)
    H, W = model.img_size
    x = jax.random.normal(rng, (n, H, W, model.in_chans), jnp.float32)
    cx = jnp.asarray(coeffs.cx)
    cx0 = jnp.asarray(coeffs.cx0)
    t_seq = jnp.asarray(coeffs.t_seq)
    for j in range(steps - 1):
        x = one_update(params, x, t_seq[j], cx[j], cx0[j])
    ref = np.asarray(final_forward(params, x, t_seq[steps - 1]))
    out = _direct_fewstep(model, params, 7, steps, n)
    assert np.array_equal(out, ref)
    assert out.min() >= 0.0 and out.max() <= 1.0


def test_fewstep_halving_schedule_nests():
    """Every other level of the 2s-step sequence IS the s-step sequence —
    the invariant progressive distillation (two teacher steps = one student
    step) banks on."""
    for s in (1, 2):
        t2 = schedule.fewstep_time_sequence(2000, 2 * s)
        t1 = schedule.fewstep_time_sequence(2000, s)
        assert np.array_equal(t2[::2], t1)


# -------------------------------------------------------------- distill


def test_distill_ddim_loss_decreases():
    model = DiffusionViT(**TINY)
    teacher = model.init(jax.random.PRNGKey(1), jnp.zeros((2, 16, 16, 3)),
                         jnp.array([0, 1], jnp.int32))["params"]
    cfg = distill.DistillConfig(start_steps=2, target_steps=1, iters=40,
                                batch_size=4, lr=1e-3, variant="ddim",
                                log_every=10, seed=3)
    out = distill.distill(model, teacher, cfg)
    assert set(out["students"]) == {2, 1}
    assert out["final_steps"] == 1
    for steps, losses in out["history"].items():
        assert len(losses) == 4
        assert losses[-1] < losses[0], (
            f"k={steps} distill loss did not decrease: {losses}")
    # the k=1 student is servable through the few-step program
    img = sampling.ddim_sample_fewstep(model, out["students"][1],
                                       jax.random.PRNGKey(0), steps=1, n=2)
    assert img.shape == (2, 16, 16, 3)


def test_distill_checkpoint_resume_roundtrip(tmp_path):
    model = DiffusionViT(**TINY)
    teacher = model.init(jax.random.PRNGKey(1), jnp.zeros((2, 16, 16, 3)),
                         jnp.array([0, 1], jnp.int32))["params"]
    cfg = distill.DistillConfig(start_steps=2, target_steps=1, iters=6,
                                batch_size=2, variant="ddim", log_every=0,
                                checkpoint_dir=str(tmp_path), seed=5)
    first = distill.distill(model, teacher, cfg)
    again = distill.distill(model, teacher, cfg)
    for steps, params in first["students"].items():
        a = jax.tree.leaves(params)
        b = jax.tree.leaves(again["students"][steps])
        assert all(np.array_equal(x, y) for x, y in zip(a, b))
    # every round was restored from its finished checkpoint, not retrained
    assert all(not v for v in again["history"].values())


def test_distillconfig_validation():
    with pytest.raises(ValueError):  # 4 -> 3 is not a halving chain
        distill.DistillConfig(start_steps=4, target_steps=3)
    with pytest.raises(ValueError):
        distill.DistillConfig(variant="sde")
    with pytest.raises(ValueError):  # cold teacher needs 2*s | levels
        distill.DistillConfig(start_steps=4, target_steps=1, variant="cold",
                              cold_levels=6)
    with pytest.raises(ValueError):
        distill.DistillConfig(iters=0)


# ------------------------------------------------------------ serving


def test_engine_fewstep_bitwise_vs_direct_two_buckets(model_and_params,
                                                      warmed):
    model, params = model_and_params
    eng = warmed
    for steps in (1, 2, 4):
        cfg = serve.SamplerConfig(steps=steps)
        for n in (4, 8):  # one request per bucket
            t = eng.submit(seed=40 + n, n=n, config=cfg)
            report = eng.run()
            assert report["compiles"] == 0
            out = np.asarray(t.result(timeout=120))
            assert np.array_equal(
                out, _direct_fewstep(model, params, 40 + n, steps, n))


def test_engine_fewstep_student_routing(model_and_params, student_params,
                                        warmed):
    """student=True dispatches the SAME program over the student tree —
    bitwise the direct sampler on those params, and no new compile."""
    model, params = model_and_params
    eng = warmed
    cfg = serve.SamplerConfig(steps=2, student=True)
    serve.warmup(eng, [cfg], persistent_cache=False)  # aliases, no compile
    t = eng.submit(seed=51, n=4, config=cfg)
    report = eng.run()
    assert report["compiles"] == 0
    out = np.asarray(t.result(timeout=120))
    assert np.array_equal(out, _direct_fewstep(model, student_params, 51,
                                               2, 4))
    assert not np.array_equal(out, _direct_fewstep(model, params, 51, 2, 4))


def test_engine_fewstep_without_student_params_raises(model_and_params):
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    with pytest.raises(ValueError, match="student_params"):
        eng.ensure_program(serve.SamplerConfig(steps=2, student=True), 4)


def test_engine_fewstep_cached_and_quant_composition(model_and_params):
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4,))
    cfg_c = serve.SamplerConfig(steps=4, cache_interval=2, cache_mode="full")
    cfg_q = serve.SamplerConfig(steps=2, quant="xla")
    serve.warmup(eng, [cfg_c, cfg_q], persistent_cache=False)
    t_c = eng.submit(seed=60, n=4, config=cfg_c)
    t_q = eng.submit(seed=61, n=4, config=cfg_q)
    report = eng.run()
    assert report["compiles"] == 0
    assert np.array_equal(
        np.asarray(t_c.result(timeout=120)),
        _direct_fewstep(model, params, 60, 4, 4, cache_interval=2,
                        cache_mode="full"))
    assert np.array_equal(
        np.asarray(t_q.result(timeout=120)),
        _direct_fewstep(model.clone(quant="xla"),
                        quant_mod.quantize_params(params), 61, 2, 4))


def test_warmup_dedup_aliases_student_config(model_and_params,
                                             student_params):
    """The student config's trace fingerprints identical to the teacher's
    (same jaxpr, same consts — params are call arguments), so warmup
    compiles ONE program per bucket and aliases the other key."""
    model, params = model_and_params
    eng = serve.Engine(model, params, buckets=(4, 8),
                       student_params=student_params)
    cfgs = [serve.SamplerConfig(steps=2),
            serve.SamplerConfig(steps=2, student=True)]
    report = serve.warmup(eng, cfgs, persistent_cache=False)
    assert report["new_compiles"] == 2
    assert report["deduped"] == 2
    assert report["programs"] == 4
    assert eng.stats["program_aliases"] == 2
    # dedup=False restores one compile per key
    eng2 = serve.Engine(model, params, buckets=(4,),
                        student_params=student_params)
    report2 = serve.warmup(eng2, cfgs, persistent_cache=False, dedup=False)
    assert report2["new_compiles"] == 2
    assert report2["deduped"] == 0


def test_samplerconfig_fewstep_validation():
    with pytest.raises(ValueError, match="steps"):
        serve.SamplerConfig(steps=-1)
    with pytest.raises(ValueError, match="student"):
        serve.SamplerConfig(student=True)
    with pytest.raises(ValueError, match="few-step"):
        serve.SamplerConfig(steps=2, sampler="cold")
    with pytest.raises(ValueError, match="task"):
        serve.SamplerConfig(steps=2, task="inpaint")
    with pytest.raises(ValueError, match="telemetry"):
        serve.SamplerConfig(steps=2, telemetry=True,
                            cache_interval=2)
    # the valid family
    for s in (1, 2, 4):
        assert serve.SamplerConfig(steps=s).steps == s


def test_j006_sweep_registers_fewstep_programs():
    labels = {label for label, _, _ in entries.serve_sweep()}
    assert {"ddim_fs1", "ddim_fs2", "ddim_fs4", "ddim_fs4_ci2",
            "ddim_fs2_pv1", "ddim_fs1_qxla"} <= labels
