"""Packaging metadata vs the on-disk tree (the PR-2 lesson: serve/ shipped
in the repo but not in the wheel — imports worked from a checkout and broke
on install). Python 3.10 has no tomllib, so the packages list is parsed
with a regex pinned to pyproject's literal layout."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "ddim_cold_tpu"


def _pyproject() -> str:
    with open(os.path.join(REPO, "pyproject.toml")) as f:
        return f.read()


def _declared_packages(text: str) -> set:
    block = re.search(r"packages\s*=\s*\[(.*?)\]", text, re.S)
    assert block, "pyproject.toml lost its [tool.setuptools] packages list"
    return set(re.findall(r'"([^"]+)"', block.group(1)))


def _on_disk_packages() -> set:
    pkgs = set()
    base = os.path.join(REPO, PKG)
    for dirpath, dirnames, files in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if "__init__.py" in files:
            rel = os.path.relpath(dirpath, REPO)
            pkgs.add(rel.replace(os.sep, "."))
    return pkgs


def test_packages_list_matches_tree():
    declared = _declared_packages(_pyproject())
    on_disk = _on_disk_packages()
    missing = on_disk - declared   # in the repo, absent from the wheel
    stale = declared - on_disk     # in the wheel list, gone from the repo
    assert not missing, f"packages missing from pyproject.toml: {sorted(missing)}"
    assert not stale, f"pyproject.toml lists nonexistent packages: {sorted(stale)}"


def test_graftcheck_console_script():
    text = _pyproject()
    assert re.search(
        r'graftcheck\s*=\s*"ddim_cold_tpu\.analysis\.cli:main"', text), \
        "graftcheck console script missing from [project.scripts]"


def test_console_script_target_importable():
    from ddim_cold_tpu.analysis.cli import main

    assert callable(main)
