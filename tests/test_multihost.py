"""Multi-host (DCN) path: 2 real processes × 4 virtual CPU devices.

The reference is single-node only (hardcoded localhost NCCL rendezvous,
multi_gpu_trainer.py:28); this build claims multi-host via
``jax.distributed`` + per-process data shards (SURVEY.md §1 target layering).
Round 1 never exercised that branch — this test spawns two OS processes that
rendezvous over a local coordinator, assemble a global batch with
``make_array_from_process_local_data``, take one identical training step, and
perform a collective orbax save (tests/_multihost_worker.py)."""

import os
import socket
import subprocess
import sys


WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_workers(tmp_path, n_procs: int, local_devices: int, mode: str,
                   timeout: float):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={local_devices}",
        JAX_ENABLE_X64="0",
        # share the suite's persistent compile cache (conftest.py) so rerun
        # workers skip their XLA compiles
        JAX_COMPILATION_CACHE_DIR=os.path.join(
            os.path.dirname(__file__), ".jax_cache"),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, str(n_procs), str(r),
             str(tmp_path), mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for r in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
    losses = []
    for r in range(n_procs):
        with open(tmp_path / f"loss_{r}.txt") as f:
            losses.append(float(f.read()))
    return losses


def test_four_process_dp_tp_sp_grouped_step(tmp_path):
    """The composed dp×tp×sp layout under REAL DCN processes (VERDICT r4
    item 7): 4 processes × 2 virtual devices = {data:2, model:2, seq:2} —
    tensor-parallel params, ring attention over 'seq', and a grouped
    steps_per_dispatch=2 dispatch. Everything beyond 2 processes previously
    ran only on single-process virtual meshes."""
    losses = _spawn_workers(tmp_path, n_procs=4, local_devices=2,
                            mode="dptpsp", timeout=600)
    # gradient psum ⇒ one global-mean loss, identical on every process —
    # including the two process pairs that REPLICATE each data shard
    assert len(set(losses)) == 1, losses
    assert 0.0 < losses[0] < 10.0


def test_two_process_pipeline_moe_step(tmp_path):
    """GPipe ACROSS PROCESSES (round 5): {pipe:2, data:2} over 2 real DCN
    processes puts stage 0 on process 0 and stage 1 on process 1 — every
    schedule ppermute and the re-sown Switch aux psum cross the process
    boundary. The pipelined apply threads the MoE aux into the step, so one
    test pins BOTH round-5 capabilities (pipe×MoE, pipe over DCN)."""
    losses = _spawn_workers(tmp_path, n_procs=2, local_devices=2,
                            mode="pipemoe", timeout=600)
    assert len(set(losses)) == 1, losses
    assert 0.0 < losses[0] < 10.0


def test_two_process_sequence_parallel_sampling(tmp_path):
    """The serving tentpole's (data, seq) mesh under REAL DCN processes:
    {seq:2, data:4} over 2 processes × 4 virtual devices puts the ulysses
    all-to-alls ACROSS the process boundary while each host keeps the batch
    data-sharded among its own devices. The k-step sp sampler must match
    the dense local reference at float tolerance (asserted in-worker) and
    produce ONE identical global-mean digest on every process."""
    import pytest

    try:
        digests = _spawn_workers(tmp_path, n_procs=2, local_devices=4,
                                 mode="spsample", timeout=600)
    except AssertionError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            # some jaxlib CPU builds rendezvous over DCN but cannot EXECUTE
            # a cross-process program (the same wall every mode in this
            # harness hits there) — nothing sp-specific to learn, skip
            pytest.skip("jaxlib CPU backend lacks multiprocess execution")
        raise
    assert len(set(digests)) == 1, digests
    assert 0.0 <= digests[0] <= 1.0  # the sampler delivers in [0, 1]


def test_two_process_distributed_train_step(tmp_path):
    losses = _spawn_workers(tmp_path, n_procs=2, local_devices=4, mode="dp",
                            timeout=240)
    # the gradient psum makes the loss a global mean — identical across hosts
    assert losses[0] == losses[1]
    assert 0.0 < losses[0] < 10.0
    # the collective orbax save produced one complete checkpoint, readable
    # by a plain single-process consumer (restore needs a target tree: the
    # saved shardings name devices from the 2-process world)
    assert (tmp_path / "ckpt").is_dir()
    import jax
    import numpy as np

    from ddim_cold_tpu.models import DiffusionViT
    from ddim_cold_tpu.utils.checkpoint import restore_checkpoint

    model = DiffusionViT(img_size=(8, 8), patch_size=4, embed_dim=16,
                         depth=1, num_heads=2, total_steps=10)
    template = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 8, 8, 3), np.float32),
        np.zeros((1,), np.int32))["params"]
    params = restore_checkpoint(str(tmp_path / "ckpt"), template)
    # structure preserved; values finite and post-step (≠ the shared init)
    assert jax.tree.structure(params) == jax.tree.structure(template)
    leaves, init_leaves = jax.tree.leaves(params), jax.tree.leaves(template)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    assert any(not np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(leaves, init_leaves))
