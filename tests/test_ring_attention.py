"""Ring attention vs dense softmax attention on the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.parallel import make_mesh
from ddim_cold_tpu.parallel.ring_attention import ring_self_attention

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


def dense_attention(q, k, v, scale):
    logits = jnp.einsum("bnhd,bmhd->bhnm", q, k) * scale
    attn = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhnm,bmhd->bnhd", attn, v)


@pytest.mark.parametrize("N", [64, 65, 257])  # divisible, cls-token sizes
def test_ring_matches_dense(N):
    rng = np.random.RandomState(0)
    B, H, D = 2, 4, 8
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    scale = D**-0.5
    mesh = make_mesh({"data": 8, "model": 1})
    want = np.asarray(dense_attention(q, k, v, scale))
    ring = jax.jit(lambda q, k, v: ring_self_attention(  # jit: eager shard_map
        q, k, v, mesh, axis="data", scale=scale))       # dispatch is ~10× slower
    got = np.asarray(ring(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_bf16_inputs():
    rng = np.random.RandomState(1)
    B, N, H, D = 1, 40, 2, 8
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    mesh = make_mesh({"data": 8, "model": 1})
    out = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh))(q, k, v)
    assert out.dtype == jnp.bfloat16 and out.shape == (B, N, H, D)
    want = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), 8**-0.5)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(want),
                               rtol=0.05, atol=0.02)


def test_ring_under_jit():
    rng = np.random.RandomState(2)
    B, N, H, D = 2, 16, 2, 4
    q, k, v = (jnp.asarray(rng.randn(B, N, H, D), jnp.float32) for _ in range(3))
    mesh = make_mesh({"data": 4, "model": 2})
    f = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh, axis="data"))
    got = np.asarray(f(q, k, v))
    want = np.asarray(dense_attention(q, k, v, D**-0.5))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_composed_batch_axis():
    """data×seq composed mesh: batch stays dp-sharded while the ring rotates
    over seq only."""
    rng = np.random.RandomState(3)
    B, N, H, D = 4, 33, 2, 8
    q, k, v = (jnp.asarray(rng.randn(B, N, H, D), jnp.float32) for _ in range(3))
    mesh = make_mesh({"data": 2, "seq": 4})
    got = np.asarray(jax.jit(lambda q, k, v: ring_self_attention(
        q, k, v, mesh, axis="seq", batch_axis="data"))(q, k, v))
    want = np.asarray(dense_attention(q, k, v, D**-0.5))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("N", [257, 321])
def test_ring_gradient_matches_dense(N):
    """Reverse-mode through the ring (ppermute rotation + blockwise online
    softmax under shard_map) ≡ autodiff through dense attention — the
    training path of every sp config. Forward parity alone would miss a
    wrong VJP (the rotation transposes to the inverted permutation)."""
    rng = np.random.RandomState(5)
    B, H, D = 1, 4, 16
    q, k, v = (jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
               for _ in range(3))
    scale = D**-0.5
    mesh = make_mesh({"seq": 8})

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_self_attention(q, k, v, mesh, axis="seq", scale=scale) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), scale) ** 2)

    g_ours = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for name, ours, want in zip("qkv", g_ours, g_want):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_ring_gradient_composed_tp_matches_dense():
    """Gradients through the FULL composed layout — ring over 'seq', heads
    sharded over 'model', batch over 'data' — match plain dense autodiff."""
    rng = np.random.RandomState(6)
    B, N, H, D = 2, 65, 4, 8
    q, k, v = (jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
               for _ in range(3))
    scale = D**-0.5
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(
            q, k, v, mesh, axis="seq", batch_axis="data",
            head_axis="model", scale=scale) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, scale) ** 2)

    g_ours = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for name, ours, want in zip("qkv", g_ours, g_want):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want),
                                   rtol=2e-4, atol=2e-5, err_msg=f"d{name}")


def test_model_with_seq_parallel_matches_dense():
    """DiffusionViT with seq_mesh/seq_axis set produces the same outputs (and
    param tree — ring adds no params) as the plain model."""
    from ddim_cold_tpu.models import DiffusionViT

    mesh = make_mesh({"data": 2, "seq": 4})
    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=2, num_heads=4)
    plain = DiffusionViT(**cfg)
    ringed = DiffusionViT(seq_mesh=mesh, seq_axis="seq", batch_axis="data", **cfg)
    x = jnp.asarray(np.random.RandomState(4).randn(4, 16, 16, 3), jnp.float32)
    t = jnp.array([0, 5, 100, 1999], jnp.int32)
    params = jax.jit(plain.init)(jax.random.PRNGKey(0), x, t)["params"]
    rparams = jax.jit(ringed.init)(jax.random.PRNGKey(0), x, t)["params"]
    assert jax.tree.structure(params) == jax.tree.structure(rparams)
    a = np.asarray(jax.jit(plain.apply)({"params": params}, x, t))
    b = np.asarray(jax.jit(ringed.apply)({"params": params}, x, t))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)


def test_trainer_builds_seq_parallel_model():
    """config.mesh with a 'seq' axis turns on ring attention and zeroes
    attention dropout (the weightless path cannot apply it)."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import build_model

    mesh = make_mesh({"data": 2, "seq": 4})
    cfg = ExperimentConfig(exp_name="t", image_size=(16, 16), patch_size=4,
                           embed_dim=32, depth=1, head=2,
                           mesh={"data": 2, "seq": 4})
    model = build_model(cfg, mesh=mesh)
    assert model.seq_axis == "seq" and model.batch_axis == "data"
    assert model.attn_drop_rate == 0.0
    plain = build_model(cfg, mesh=make_mesh({"data": 8}))
    assert plain.seq_mesh is None


@pytest.mark.isolated
def test_seq_parallel_training_end_to_end(tmp_path, synthetic_image_dir):
    """Full trainer run on mesh {data:4, seq:2} (regression: init crashed when
    the sample batch wasn't divisible over the data axis) and {seq:8} (pure sp,
    no data axis)."""
    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.train.trainer import run

    for mesh_shape in ({"data": 4, "seq": 2}, {"seq": 8}):
        cfg = ExperimentConfig(
            exp_name="sp", framework=f"ring{len(mesh_shape)}",
            batch_size=1, epoch=(0, 1), base_lr=0.005,
            data_storage=(synthetic_image_dir, synthetic_image_dir),
            image_size=(16, 16), patch_size=8, embed_dim=32, depth=1, head=2,
            mesh=mesh_shape,
        )
        result = run(cfg, str(tmp_path), max_steps=2)
        assert np.isfinite(result.best_loss)


def test_seq_parallel_head_axis_and_dropout_guard():
    """tp-composed ring keeps heads sharded (head_axis) and a seq-parallel
    model with active attention-dropout raises instead of silently densifying."""
    from ddim_cold_tpu.models import DiffusionViT

    mesh = make_mesh({"data": 2, "model": 2, "seq": 2})
    cfg = dict(img_size=(16, 16), patch_size=4, embed_dim=32, depth=1, num_heads=4)
    sharded = DiffusionViT(seq_mesh=mesh, seq_axis="seq", batch_axis="data",
                           head_axis="model", attn_drop_rate=0.0, **cfg)
    x = jnp.asarray(np.random.RandomState(5).randn(2, 16, 16, 3), jnp.float32)
    t = jnp.array([1, 2], jnp.int32)
    params = jax.jit(sharded.init)(jax.random.PRNGKey(0), x, t)["params"]
    plain = DiffusionViT(**cfg)
    a = np.asarray(jax.jit(plain.apply)({"params": params}, x, t))
    b = np.asarray(jax.jit(sharded.apply)({"params": params}, x, t))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)

    bad = DiffusionViT(seq_mesh=mesh, seq_axis="seq", batch_axis="data", **cfg)
    with pytest.raises(ValueError, match="attention-dropout"):
        bad.apply({"params": params}, x, t, deterministic=False,
                  rngs={"dropout": jax.random.PRNGKey(1)})
