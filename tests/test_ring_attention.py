"""Ring attention vs dense softmax attention on the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddim_cold_tpu.parallel import make_mesh
from ddim_cold_tpu.parallel.ring_attention import ring_self_attention

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


def dense_attention(q, k, v, scale):
    logits = jnp.einsum("bnhd,bmhd->bhnm", q, k) * scale
    attn = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhnm,bmhd->bnhd", attn, v)


@pytest.mark.parametrize("N", [64, 65, 257])  # divisible, cls-token sizes
def test_ring_matches_dense(N):
    rng = np.random.RandomState(0)
    B, H, D = 2, 4, 8
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, N, H, D), jnp.float32)
    scale = D**-0.5
    mesh = make_mesh({"data": 8, "model": 1})
    want = np.asarray(dense_attention(q, k, v, scale))
    got = np.asarray(ring_self_attention(q, k, v, mesh, axis="data", scale=scale))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_bf16_inputs():
    rng = np.random.RandomState(1)
    B, N, H, D = 1, 40, 2, 8
    q = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, N, H, D), jnp.bfloat16)
    mesh = make_mesh({"data": 8, "model": 1})
    out = ring_self_attention(q, k, v, mesh)
    assert out.dtype == jnp.bfloat16 and out.shape == (B, N, H, D)
    want = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), 8**-0.5)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(want),
                               rtol=0.05, atol=0.02)


def test_ring_under_jit():
    rng = np.random.RandomState(2)
    B, N, H, D = 2, 16, 2, 4
    q, k, v = (jnp.asarray(rng.randn(B, N, H, D), jnp.float32) for _ in range(3))
    mesh = make_mesh({"data": 4, "model": 2})
    f = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh, axis="data"))
    got = np.asarray(f(q, k, v))
    want = np.asarray(dense_attention(q, k, v, D**-0.5))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
