#!/usr/bin/env python
"""Dataset module shim — the reference's ``diffusion_loader.py`` import surface.

Reference users do ``from diffusion_loader import ColdDownSampleDataset`` (so does
its trainer, multi_gpu_trainer.py:5); this module re-exports the TPU-native
implementations from ``ddim_cold_tpu.data`` under the reference names, including
the ``_au`` paper-variant class (diffusion_loader.py:99-138: targets the clean
x₀ directly instead of the one-level-up chain target).

``python diffusion_loader.py [image_dir]`` runs the dataset visual check
(reference diffusion_loader.py:141-154): for each level t = 1..max_step it
renders the ``(D(x,t), target)`` pair of the first item and writes
``degradation_pairs.png`` — headless-friendly (saved, not shown). Without an
argument it degrades a synthetic gradient image so the check runs out of the box.
"""

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from ddim_cold_tpu.data import (  # noqa: E402,F401
    ColdDownSampleDataset,
    DiffusionDataset,
    pil_loader,
)


class ColdDownSampleDataset_au(ColdDownSampleDataset):
    """Paper variant: ``(D(x,t), x₀, t)`` (reference diffusion_loader.py:99-138)."""

    def __init__(self, root, imgSize=(32, 32), **kwargs):
        kwargs.pop("target_mode", None)
        super().__init__(root, imgSize=imgSize, target_mode="direct", **kwargs)


def _synthetic_dir(size: int = 64) -> str:
    import tempfile

    import numpy as np
    from PIL import Image

    root = tempfile.mkdtemp(prefix="ddim_cold_viz_")
    y, x = np.mgrid[0:size, 0:size].astype(np.float32) / (size - 1)
    arr = np.stack([x, y, 0.5 * (x + y)], axis=-1)
    Image.fromarray((arr * 255).astype(np.uint8)).save(os.path.join(root, "grad.png"))
    return root


def main(argv):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    root = argv[1] if len(argv) > 1 else _synthetic_dir()
    out = argv[2] if len(argv) > 2 else os.path.join(HERE, "degradation_pairs.png")
    ds = ColdDownSampleDataset(root, imgSize=(64, 64))
    fig, axes = plt.subplots(2, ds.max_step, figsize=(2 * ds.max_step, 4.2))
    for t in range(1, ds.max_step + 1):
        noisy, target, _ = ds.__getitem__(0, t=t)
        for row, img, label in ((0, noisy, f"D(x,{t})"), (1, target, f"D(x,{t - 1})")):
            ax = axes[row][t - 1]
            ax.imshow(np.clip((np.asarray(img) + 1) / 2, 0, 1))
            ax.set_title(label, fontsize=8)
            ax.axis("off")
    fig.tight_layout()
    fig.savefig(out, dpi=110)
    print(f"degradation pairs (t=1..{ds.max_step}) → {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
