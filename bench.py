#!/usr/bin/env python
"""Benchmark: training throughput on the reference's one recorded config.

Measures images/sec for the vit_tiny 64px cold-diffusion training step at the
reference's effective batch 32 with AMP (bf16 compute here), and compares to
the train.log steady state: 4.56 s / 100 steps ≈ 702 img/s on one RTX 3090
(BASELINE.md). Runs on whatever the default JAX platform is — the real TPU
chip under the driver.

Prints ONE JSON line:
    {"metric": ..., "value": ..., "unit": "img/s", "vs_baseline": ...}

``--smoke`` shrinks the measurement for CPU sanity runs. ``--sampler`` also
reports DDIM k=20 sampling throughput (the north-star metric path) to stderr.
"""

import argparse
import json
import sys
import time

BASELINE_IMG_PER_SEC = 702.0  # train.log steady state, 1×3090 (BASELINE.md)


def main(argv=None):
    """``argv=None`` → sys.argv; scripts (tpu_validate) pass a list to reuse
    this harness as the single source of timing truth."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny quick run (CI/CPU)")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--sampler", action="store_true",
                    help="also time DDIM k=20 sampling (stderr)")
    ap.add_argument("--ksweep", action="store_true",
                    help="also sweep sampler stride k over {1,5,20,50} (stderr)")
    ap.add_argument("--northstar", action="store_true",
                    help="also time the north-star path: 200px DDIM k=20 "
                         "img/s/chip (BASELINE.md; stderr)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (env JAX_PLATFORMS can be "
                         "overridden by site config; this flag always wins)")
    args = ap.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    if args.smoke:
        args.steps = 10

    model = DiffusionViT(dtype=jnp.bfloat16, **MODEL_CONFIGS["vit_tiny"])
    rng = np.random.RandomState(0)
    B = args.batch
    batch = (
        jnp.asarray(rng.randn(B, 64, 64, 3), jnp.float32),
        jnp.asarray(rng.randn(B, 64, 64, 3), jnp.float32),
        jnp.asarray(rng.randint(1, 7, size=(B,)), jnp.int32),
    )
    state = create_train_state(model, jax.random.PRNGKey(0), lr=2e-4,
                               total_steps=51200, sample_batch=batch)
    train_step = make_train_step(model)
    ema = jnp.float32(5.0)

    # warmup / compile. Syncs go through float()/np.asarray — a real D2H
    # transfer — because block_until_ready can return early through the
    # remote-TPU tunnel, silently timing only the dispatch.
    t0 = time.time()
    state, _, ema = train_step(state, batch, jax.random.PRNGKey(1), ema)
    float(ema)
    compile_s = time.time() - t0
    for _ in range(3):
        state, _, ema = train_step(state, batch, jax.random.PRNGKey(1), ema)
    float(ema)

    t0 = time.time()
    for _ in range(args.steps):
        state, _, ema = train_step(state, batch, jax.random.PRNGKey(1), ema)
    float(ema)
    dt = time.time() - t0

    img_per_sec = B * args.steps / dt
    print(
        f"[bench] platform={jax.default_backend()} devices={jax.device_count()} "
        f"compile={compile_s:.1f}s {args.steps} steps in {dt:.2f}s "
        f"({1000*dt/args.steps:.2f} ms/step)", file=sys.stderr)

    def time_ddim(smodel, sparams, k, n, label):
        """Compile+sync one sampling run, then time a second — syncing via a
        real host transfer (see the block_until_ready note above). Returns
        seconds; results are memoized per (model, k) by jit's cache, so
        overlapping flags don't re-measure."""
        from ddim_cold_tpu.ops import sampling

        key = (id(smodel), k, n)
        if key not in timed:
            img = sampling.ddim_sample(smodel, sparams, jax.random.PRNGKey(2), k=k, n=n)
            np.asarray(img)
            t0 = time.time()
            img = sampling.ddim_sample(smodel, sparams, jax.random.PRNGKey(3), k=k, n=n)
            np.asarray(img)
            timed[key] = time.time() - t0
        sdt = timed[key]
        print(f"[bench] {label} DDIM k={k:3d} N={n}: {sdt:6.2f}s → "
              f"{n/sdt:8.2f} img/s/chip", file=sys.stderr)
        return sdt

    timed = {}
    n_sample = 8 if args.smoke else 64
    if args.sampler:
        time_ddim(model, state.params, 20, n_sample, "sampler")
    if args.ksweep:
        for k in (5, 20, 50) if args.smoke else (1, 5, 20, 50):
            time_ddim(model, state.params, k, n_sample, "k-sweep")
    if args.northstar:
        n, k = (4, 100) if args.smoke else (16, 20)
        ns_params = None
        for flash in (False, True):
            ns_model = DiffusionViT(dtype=jnp.bfloat16, use_flash=flash,
                                    **MODEL_CONFIGS["oxford_flower_200_p4"])
            if ns_params is None:
                ns_params = ns_model.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((1, 200, 200, 3)), jnp.zeros((1,), jnp.int32))["params"]
            time_ddim(ns_model, ns_params, k, n,
                      f"north-star 200px flash={int(flash)}")

    print(json.dumps({
        "metric": "train_throughput_vit_tiny64_b32",
        "value": round(img_per_sec, 1),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
